"""Headline benchmark: M5-scale end-to-end batched fit wall-clock.

Driver metric (BASELINE.json:2): "M5 (30k series) end-to-end fit wall-clock;
sMAPE parity vs CPU".  Target: all 30,490 series in < 60 s on a TPU v5e-8
(BASELINE.json:5).  This machine exposes ONE v5e chip, so the printed
``vs_baseline`` is target_seconds / measured_seconds on a single chip —
values >= 1.0 mean the 8-chip target is beaten with 1/8th of the hardware.
``extra.vs_chip_seconds_budget`` additionally reports the chip-second
framing (480 chip-s budget / single-chip seconds spent) — an extrapolation
over the embarrassingly-parallel series axis, kept out of the headline.

Resilience: the single TPU chip sits behind an experimental stdio-tunneled
relay whose worker can crash on large programs (observed: single input
buffers over ~64 MB kill it, and the envelope shrinks after a crash).  A
dead worker takes the whole JAX client with it, so the benchmark is split
into processes:

  parent (this file, no JAX)  — caches generated data across runs keyed by
                                shape, spawns fit workers, retries crashed
                                ranges (halving the chunk only when a
                                phase-1 attempt made zero progress), resumes
                                from completed per-chunk result files,
                                watches per-dispatch heartbeats so long
                                compiles / the chunk-less phase-2 pass are
                                not killed as stalls, then runs a CPU eval
                                worker and prints the ONE summary JSON line.
  --_fit child (TPU)          — phase 1: every chunk at a short lockstep
                                depth (prefetching the next chunk's host
                                prep), saved as it lands; phase 2: the
                                unconverged tail across ALL chunks is
                                compacted into one batch, finished at full
                                depth with the GN-diagonal metric, and the
                                chunk files patched in place (idempotent).
  --_eval child (CPU)         — in-sample sMAPE on a subsample from the
                                saved states (accuracy gate, not the metric).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Usage: python bench.py [--series N] [--days N] [--chunk N] [--smoke]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

TARGET_S = 60.0        # driver target: 60 s on a v5e-8 (BASELINE.json:5)
TARGET_CHIPS = 8       # ... which is a 480 chip-second budget
MIN_CHUNK = 512
# Total wall budget.  The driver harness kills the whole process on ITS
# timeout (observed ~20 min); staying under it is the only way the summary
# line reaches stdout.  Overridable for longer local runs.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "900"))
# Reserve at the end of the budget for the eval child + summary print.
RESERVE_S = 150.0


# Bump when a bench.py change alters fit NUMERICS (solver args, phase
# policy, data handling).  Orchestration-only changes (probing, retries,
# logging) must NOT bump it: the whole point of the numerics-scoped
# fingerprint below is that resume state survives them.
BENCH_NUMERICS_REV = 6


def _code_fingerprint() -> str:
    """Hash of the numerics-affecting sources only — keys the resumable
    scratch dir.  Round 3 hashed every package .py plus bench.py itself, so
    ANY commit (even docstring-only) discarded cross-run resume state; now
    only modules on the fit path rotate it: model math (models/), the
    solver (ops/), backend chunking policy (backends/), the config schema,
    and the data generator."""
    import hashlib

    h = hashlib.md5()
    h.update(str(BENCH_NUMERICS_REV).encode())
    pats = [
        os.path.join(REPO, "tsspark_tpu", "models", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "ops", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "backends", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "config.py"),
        os.path.join(REPO, "tsspark_tpu", "data", "datasets.py"),
    ]
    files = sorted(f for p in pats for f in glob.glob(p, recursive=True))
    for f in files:
        with open(f, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:10]


def _datagen_fingerprint() -> str:
    """Hash of the data generator alone — keys the shared datagen cache so
    a generator change can never serve stale arrays to a new code version."""
    import hashlib

    with open(os.path.join(REPO, "tsspark_tpu", "data", "datasets.py"),
              "rb") as fh:
        return hashlib.md5(fh.read()).hexdigest()[:8]


def _model_config():
    from tsspark_tpu.config import (
        ProphetConfig,
        RegressorConfig,
        SeasonalityConfig,
    )

    # Eval config 3 (BASELINE.json:9): holiday regressors + external features.
    return ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )


def _host_cpu_tag() -> str:
    from tsspark_tpu.utils.platform import host_cpu_tag

    return host_cpu_tag()


def _setup_jax_child():
    """Child-process JAX config: persistent compile cache."""
    import jax

    from tsspark_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(REPO, f".jax_cache_{_host_cpu_tag()}"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return jax


# --------------------------------------------------------------------------
# fit worker (TPU)
# --------------------------------------------------------------------------

def _prep_path(out_dir: str, lo: int, hi: int) -> str:
    return os.path.join(out_dir, f"prep_{lo:06d}_{hi:06d}.npz")


def _save_prep_atomic(out_dir, lo, hi, b_real, packed, meta) -> None:
    """Persist one chunk's packed device payload (host numpy) so a CPU-side
    prep worker can build it while the TPU tunnel is wedged and the fit
    worker can later skip its own prep.  NamedTuple fields are flattened
    with prefixes; the dotfile + rename makes readers never see a torn
    file (same convention as chunk saves)."""
    import numpy as np

    arrays = {"b_real": np.asarray(b_real)}
    for k, v in packed._asdict().items():
        arrays[f"packed_{k}"] = np.asarray(v)
    for k, v in meta._asdict().items():
        arrays[f"meta_{k}"] = np.asarray(v)
    tmp = os.path.join(out_dir, f".tmp_prep_{lo:06d}_{hi:06d}.npz")
    np.savez(tmp, **arrays)
    os.replace(tmp, _prep_path(out_dir, lo, hi))


def _load_prep(out_dir, lo, hi, chunk=None):
    """(b_real, PackedFitData, ScalingMeta) or None if absent/corrupt.

    ``chunk``: reject payloads whose padded batch width differs — a tail
    range keeps its (lo, hi) name across a chunk-halving retry, and
    serving the old wider payload would re-dispatch exactly the program
    size that just crashed the worker."""
    import numpy as np

    from tsspark_tpu.models.prophet.design import PackedFitData, ScalingMeta

    path = _prep_path(out_dir, lo, hi)
    if not os.path.exists(path):
        return None
    try:
        z = np.load(path)
        packed = PackedFitData(**{
            k: z[f"packed_{k}"] for k in PackedFitData._fields
        })
        meta = ScalingMeta(**{
            k: z[f"meta_{k}"] for k in ScalingMeta._fields
        })
        if chunk is not None and packed.y.shape[0] != chunk:
            return None
        return int(z["b_real"]), packed, meta
    except Exception:
        return None


def prep_worker(args) -> int:
    """CPU-side chunk prep: build the packed device payloads for up to
    ``--max-ahead`` pending chunks and save them next to the chunk results.

    Runs overlapped with the parent's tunnel-probe loop (JAX_PLATFORMS=cpu,
    so a wedged TPU tunnel cannot block it): when the tunnel recovers, the
    fit worker finds its first chunks pre-packed and goes straight to
    device work instead of paying host prep on the critical path."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _setup_jax_child()
    import numpy as np

    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.models.prophet.design import (
        _indicator_reg_cols, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")
    model = ProphetModel(_model_config(), SolverConfig(max_iters=args.max_iters))
    u8_cols = _indicator_reg_cols(reg)

    # Completed COVERAGE, not exact chunk-file names: after a mid-run
    # chunk halving, regions fitted under the old wider grid have no file
    # at the new (lo, hi) spacing, and pre-packing them would burn the
    # bounded --max-ahead budget on payloads no fit worker will read.
    done = _completed_ranges(args.out)

    def _covered(lo: int, hi: int) -> bool:
        cur = lo
        for dlo, dhi in done:
            if dhi <= cur:
                continue
            if dlo > cur:
                return False
            cur = dhi
            if cur >= hi:
                return True
        return cur >= hi

    made = 0
    for lo in range(0, args.series, args.chunk):
        if made >= args.max_ahead:
            break
        hi = min(lo + args.chunk, args.series)
        if _covered(lo, hi) or os.path.exists(_prep_path(args.out, lo, hi)):
            continue
        b_real = hi - lo
        y_c = np.zeros((args.chunk, y.shape[1]), np.float32)
        m_c = np.zeros((args.chunk, y.shape[1]), np.float32)
        r_c = np.zeros((args.chunk,) + reg.shape[1:], np.float32)
        y_c[:b_real] = y[lo:hi]
        m_c[:b_real] = mask[lo:hi]
        r_c[:b_real] = reg[lo:hi]
        data, meta = model.prepare(
            ds, y_c, mask=m_c, regressors=r_c, as_numpy=True
        )
        packed, _ = pack_fit_data(data, meta, ds, reg_u8_cols=u8_cols,
                                  collapse_cap=True)
        _save_prep_atomic(args.out, lo, hi, b_real, packed, meta)
        made += 1
    return 0


def _save_chunk_atomic(out_dir, lo, hi, state, extra_arrays=None):
    import numpy as np

    # Dotfile prefix so a half-written file can never match the
    # chunk_*.npz resume/eval glob.
    tmp = os.path.join(out_dir, f".tmp_{lo:06d}_{hi:06d}.npz")
    arrays = dict(
        theta=np.asarray(state.theta),
        loss=np.asarray(state.loss),
        grad_norm=np.asarray(state.grad_norm),
        converged=np.asarray(state.converged),
        n_iters=np.asarray(state.n_iters),
        status=np.asarray(state.status) if state.status is not None
        else np.zeros(len(np.asarray(state.converged)), np.int32),
        y_scale=np.asarray(state.meta.y_scale),
        floor=np.asarray(state.meta.floor),
        ds_start=np.asarray(state.meta.ds_start),
        ds_span=np.asarray(state.meta.ds_span),
        reg_mean=np.asarray(state.meta.reg_mean),
        reg_std=np.asarray(state.meta.reg_std),
        changepoints=np.asarray(state.meta.changepoints),
    )
    arrays.update(extra_arrays or {})
    np.savez(tmp, **arrays)
    os.replace(tmp, os.path.join(out_dir, f"chunk_{lo:06d}_{hi:06d}.npz"))


def fit_worker(args) -> int:
    """Phase 1: every chunk at a short lockstep depth (phase1 iters), saved
    as it lands.  Phase 2 (once no chunk is missing over the whole range):
    gather the unconverged tail across ALL chunks into one compacted batch,
    finish it at full depth warm-started from phase-1 parameters, and patch
    the chunk files in place (idempotent; resumable after any crash).

    Rationale: the batched solver is lockstep, so pre-compaction every chunk
    paid max_iters for its slowest series while the measured mean iterations
    to converge is ~3 (VERDICT round 2).  See TpuBackend.fit_twophase for
    the same logic as an in-memory API.
    """
    jax = _setup_jax_child()
    import numpy as np

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.backends.tpu import patch_state
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.models.prophet.design import (
        ScalingMeta, _indicator_reg_cols, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import (
        FitState, fit_core_packed, fitstate_from_packed,
    )

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")

    # Liveness for the parent's stall watchdog: every completed solver
    # dispatch touches this file, so long legitimate work (a fresh compile,
    # the chunk-less phase-2 straggler fit) is distinguishable from a
    # wedged tunnel without any new chunk result appearing.
    hb_path = os.path.join(args.out, "heartbeat")

    def heartbeat():
        with open(hb_path, "w") as fh:
            fh.write(str(time.time()))

    backend = get_backend(
        "tpu", _model_config(), SolverConfig(max_iters=args.max_iters),
        chunk_size=args.chunk, iter_segment=args.segment or None,
        on_segment=heartbeat,
    )
    # phase1 depth >= full depth degenerates to a single-phase run.
    two_phase = 0 < args.phase1_iters < args.max_iters
    phase1 = backend._phase1(args.phase1_iters) if two_phase else backend

    # Phase 1 drives the model layer directly with a bounded prefetch pool:
    # upcoming chunks' host-side design builds (~0.6-1.4 s of numpy each)
    # run while earlier chunks occupy the device.  Device time per chunk is
    # now ~0.6 s (gather-free trend), so a one-deep prefetch left prep on
    # the critical path every other chunk (measured alternating 0.6 s /
    # 2.2 s chunk walls); two prep workers and a three-deep window keep the
    # device continuously fed while bounding buffered chunks (~60 MB each).
    # Chunks are padded to the full chunk size with inert all-masked rows
    # (same convention as TpuBackend._fit_padded) so every fit hits one
    # compiled shape.
    from concurrent.futures import ThreadPoolExecutor

    # The packed mode drives ONE compiled program for both phases: the
    # static solver carries the full depth, while the per-phase differences
    # (solve depth, GN-metric switch, warm-start-vs-ridge-init) are TRACED
    # scalars (fit_core's *_dynamic args).  Phase 2 previously compiled and
    # warmed a second program (different static solver + init presence) at
    # ~10 s per run through the tunnel.
    model = backend._model
    n_params = model.config.num_params
    zeros_theta = np.zeros((args.chunk, n_params), np.float32)

    # Segmented mode (--segment < phase-1 depth) keeps the FitData path:
    # per-segment dispatches with a heartbeat after each, for runs where
    # bounding single-dispatch time matters more than transfer bytes.
    # Default mode runs each chunk as ONE packed-transfer program.
    segmented = bool(
        phase1.iter_segment
        and phase1.iter_segment < phase1._model.solver_config.max_iters
    )
    # Indicator-column split for the packed path, decided ONCE on the full
    # dataset: per-chunk auto-detection would let a chunk whose continuous
    # column is coincidentally all-0/1 flip the static argument and
    # silently recompile mid-run.
    u8_cols = _indicator_reg_cols(reg)

    def prep(lo: int, hi: int):
        if not segmented:
            # A CPU prep worker may have pre-packed this chunk while the
            # tunnel was down (same prepare/pack code path, so numerics
            # are identical); corrupt/absent files fall through to local
            # prep.
            cached = _load_prep(args.out, lo, hi, chunk=args.chunk)
            if cached is not None:
                return lo, hi, cached[0], cached[1], cached[2]
        b_real = hi - lo
        y_c = np.zeros((args.chunk, y.shape[1]), np.float32)
        m_c = np.zeros((args.chunk, y.shape[1]), np.float32)
        r_c = np.zeros((args.chunk,) + reg.shape[1:], np.float32)
        y_c[:b_real] = y[lo:hi]
        m_c[:b_real] = mask[lo:hi]
        r_c[:b_real] = reg[lo:hi]
        # as_numpy: a prep thread must not issue device transfers — on the
        # single-chip tunnel they queue behind the in-flight fit program
        # and re-serialize the pipeline the prefetch exists to overlap.
        # pack_fit_data then cuts the shipped bytes ~3x (mask folded into
        # y as NaN, bit-packed indicator columns, device-side t
        # reconstruction, elided cap; design.PackedFitData).
        data, meta = model.prepare(
            ds, y_c, mask=m_c, regressors=r_c, as_numpy=True
        )
        if segmented:
            return lo, hi, b_real, data, meta
        packed, _ = pack_fit_data(data, meta, ds, reg_u8_cols=u8_cols,
                                  collapse_cap=True)
        return lo, hi, b_real, packed, meta

    todo = []
    for lo in range(args.lo, args.hi, args.chunk):
        hi = min(lo + args.chunk, args.hi)
        if not os.path.exists(
            os.path.join(args.out, f"chunk_{lo:06d}_{hi:06d}.npz")
        ):
            todo.append((lo, hi))
    prefetch_depth = 3
    # Adaptive phase-1 depth: depth is a TRACED value of the one compiled
    # program, so it can change per chunk for free.  One adjustment after
    # chunk 0 keeps runs predictable.  The deepen branch fires only on a
    # PATHOLOGICAL first chunk (a quarter still progressing): measured on
    # the M5 shape, the unconverged set is depth-FLAT (124/122/122/120/114
    # stragglers per 1024 at depths 8/12/16/24/32) — it is the
    # ill-conditioned tail that needs phase 2's GN metric, not more plain
    # lockstep iterations, so the old 3% trigger doubled every chunk's
    # device time for ~2 rescued series per 1024.  If virtually everything
    # converges early, shallow out.
    depth = {"v": args.phase1_iters if two_phase else args.max_iters,
             "tuned": not two_phase or getattr(args, "no_phase1_tune", False)}

    def tune_depth(state, b_real):
        if depth["tuned"]:
            return
        depth["tuned"] = True
        frac_unconv = float(
            (~np.asarray(state.converged)[:b_real]).mean()
        )
        if frac_unconv > 0.25:
            depth["v"] = min(int(depth["v"]) * 2, args.max_iters)
        elif frac_unconv < 0.005 and depth["v"] > 8:
            depth["v"] = max(8, int(depth["v"]) * 2 // 3)

    def save_and_log(lo, hi, state, fit_s, t_wait, t_put, t_dev, t1):
        """Chunk save + prep-file cleanup + one times.jsonl row (shared by
        the packed writer path and the segmented inline path)."""
        _save_chunk_atomic(args.out, lo, hi, state)
        try:  # prep payload served its purpose; bound scratch disk
            os.remove(_prep_path(args.out, lo, hi))
        except OSError:
            pass
        with open(os.path.join(args.out, "times.jsonl"), "a") as fh:
            fh.write(json.dumps({
                "lo": lo, "hi": hi, "fit_s": round(fit_s, 3),
                "wait_s": round(t_wait, 3), "put_s": round(t_put, 3),
                "dev_s": round(t_dev, 3),
                "read_s": round(time.time() - t1, 3),
                "chunk": args.chunk, "device": str(jax.devices()[0]),
            }) + "\n")

    # Post-fit host work (device->host readback of the small result
    # buffers, FitState assembly, chunk-file save) rides a single writer
    # thread so the main thread's next device_put starts immediately after
    # the fit dispatch completes — the readbacks (~0.4 MB) overlap the next
    # chunk's multi-MB upload instead of serializing ahead of it.  One
    # worker keeps times.jsonl appends race-free.  ``fit_s`` is captured
    # on the MAIN thread at hand-off so it measures the chunk's actual
    # wall (wait+put+dev); read_s alone reflects writer-side readback,
    # which may overlap the next chunk's upload.
    def finish_chunk(lo, hi, b_real, theta, stats, meta, fit_s, t_wait,
                     t_put, t_dev):
        t1 = time.time()
        state = fitstate_from_packed(
            np.asarray(theta)[:b_real],
            np.asarray(stats)[:, :b_real],
            jax.tree.map(lambda a: np.asarray(a)[:b_real], meta),
        )
        save_and_log(lo, hi, state, fit_s, t_wait, t_put, t_dev, t1)
        return state

    # Device-resident chunk payloads: phase 1 keeps every uploaded packed
    # payload alive on device (~16.6 MB x 30 chunks = ~500 MB HBM) so
    # phase 2 can gather its straggler rows ON DEVICE instead of
    # re-prepping and re-uploading them over the serial tunnel.  Falls
    # back to the host path whenever coverage is partial (resume,
    # chunk-halving retries).  Retained bytes are CAPPED (ADVICE r4):
    # HBM cost is linear in series count, so a much-larger-than-M5 run
    # would otherwise OOM phase 1; past the budget we stop inserting and
    # the partial-coverage check routes phase 2 to the host path.
    resident = {}
    resident_bytes = 0
    resident_budget = int(
        os.environ.get("BENCH_RESIDENT_MB", "4096")
    ) * (1 << 20)
    with ThreadPoolExecutor(max_workers=2) as pool, \
            ThreadPoolExecutor(max_workers=1) as writer:
        write_futs = []
        futs = {
            j: pool.submit(prep, *todo[j])
            for j in range(min(prefetch_depth, len(todo)))
        }
        for i in range(len(todo)):
            t0 = time.time()
            lo, hi, b_real, payload, meta = futs.pop(i).result()
            t_wait = time.time() - t0
            nxt = i + prefetch_depth
            if nxt < len(todo):
                futs[nxt] = pool.submit(prep, *todo[nxt])
            t1 = time.time()
            # One device_put call for the whole pytree (not per-leaf
            # tree.map): the runtime can batch the per-buffer dispatches.
            payload = jax.device_put(payload)
            jax.block_until_ready(jax.tree.leaves(payload))
            t_put = time.time() - t1
            t1 = time.time()
            if segmented:
                state = phase1._model._fit_prepared(
                    payload, meta, None, phase1.iter_segment,
                    on_segment=heartbeat,
                )
                jax.block_until_ready(state.theta)
                t_dev = time.time() - t1
                t1 = time.time()
                state = jax.tree.map(
                    lambda a: np.asarray(a)[:b_real], state
                )
                save_and_log(lo, hi, state, time.time() - t0,
                             t_wait, t_put, t_dev, t1)
            else:
                theta, stats = fit_core_packed(
                    payload, zeros_theta, model.config, model.solver_config,
                    reg_u8_cols=u8_cols,
                    max_iters_dynamic=np.int32(depth["v"]),
                    gn_precond_dynamic=np.bool_(False),
                    use_theta0_dynamic=np.bool_(False),
                )
                jax.block_until_ready(theta)
                heartbeat()
                if two_phase and not os.environ.get("BENCH_NO_RESIDENT"):
                    # Real [lo, hi) recorded: rows past hi - lo are inert
                    # padding that phase 2 must never gather (a padding
                    # row "converges" instantly and would silently patch
                    # garbage into a real series' slot).
                    nb = sum(
                        a.nbytes for a in jax.tree.leaves(payload)
                    )
                    if resident_bytes + nb <= resident_budget:
                        resident[lo] = (hi, payload)
                        resident_bytes += nb
                t_dev = time.time() - t1
                fit_s = time.time() - t0
                if not depth["tuned"]:
                    # Depth must settle before chunk 1 dispatches, so
                    # chunk 0 finalizes inline.
                    state = finish_chunk(lo, hi, b_real, theta, stats,
                                         meta, fit_s, t_wait, t_put, t_dev)
                    tune_depth(state, b_real)
                else:
                    write_futs.append(writer.submit(
                        finish_chunk, lo, hi, b_real, theta, stats, meta,
                        fit_s, t_wait, t_put, t_dev,
                    ))
        for f in write_futs:
            f.result()  # surface writer-thread failures before phase 2

    # ---- phase 2: compacted straggler pass over the whole series range ----
    if not two_phase:
        return 0
    done = _completed_ranges(args.out)
    if _missing_ranges(done, args.series):
        return 0  # another worker attempt still owes phase-1 chunks
    marker = os.path.join(args.out, "phase2_done")
    if os.path.exists(marker):
        return 0

    t0 = time.time()
    straggler_idx, straggler_theta, straggler_gn = [], [], []
    files = {}
    for lo, hi in done:
        f = os.path.join(args.out, f"chunk_{lo:06d}_{hi:06d}.npz")
        z = dict(np.load(f))
        files[(lo, hi)] = z
        # Already-patched chunks (resume after a phase-2 crash) are final.
        if z.get("phase2") is not None:
            continue
        # Unconverged only.  TpuBackend.fit's rescue pass additionally
        # refits stuck exits (status FLOOR/STALLED) — measured on the eval
        # configs it trims the CPU-parity tail (p99 1.24 -> 0.86 sMAPE) —
        # but on bench-shaped data the same widening costs ~60% more fit
        # wall for <= 0.1 nats/series, so the HEADLINE run keeps the fast
        # selection; parity is graded through the eval path, which uses
        # the rescue-enabled fit.
        bad = np.flatnonzero(~z["converged"])
        straggler_idx.extend(int(lo + i) for i in bad)
        straggler_theta.append(z["theta"][bad])
        straggler_gn.append(z["grad_norm"][bad])
    phase2_mode = "none"
    if straggler_idx:
        heartbeat()  # phase 2 starts: reset the stall clock
        idx = np.asarray(straggler_idx)
        # Difficulty-sorted compaction (see backends.tpu.difficulty_order;
        # the chunk-file patch below indexes by idx, so order is free).
        from tsspark_tpu.backends.tpu import difficulty_order
        order = difficulty_order(np.concatenate(straggler_gn))
        idx = idx[order]
        theta_cat = np.concatenate(straggler_theta, axis=0)[order]
        # Stragglers get the GN-diagonal initial metric (ill-conditioned
        # tail; see SolverConfig.precond) and the full solve depth, through
        # THE SAME compiled program as phase 1: the batch is padded to the
        # fixed phase-1 chunk size (inert all-masked rows) and the phase
        # differences ride the traced *_dynamic args, so no second program
        # is ever compiled or warmed.
        n_s = len(straggler_idx)
        pad = (-n_s) % args.chunk
        pad_rows = lambda a: np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
        ) if pad else a

        def host_gather():
            """(y, mask, reg, init) rows for the host-side phase-2 paths
            (~45 MB of copies the device-resident path never makes)."""
            return (
                pad_rows(np.ascontiguousarray(y[idx], np.float32)),
                pad_rows(np.ascontiguousarray(mask[idx], np.float32)),
                pad_rows(np.ascontiguousarray(reg[idx], np.float32)),
                pad_rows(theta_cat.astype(np.float32)),
            )

        if segmented:
            phase2_mode = "segmented"
            y_s, m_s, r_s, init_s = host_gather()
            # Bounded-dispatch mode: phase 2 keeps --segment's short
            # per-segment dispatches (the reason segmented mode exists),
            # via the static straggler backend.
            state2 = backend._straggler_backend().fit(
                ds, y_s, mask=m_s, regressors=r_s, init=init_s,
            )
            state2 = jax.tree.map(lambda a: np.asarray(a)[:n_s], state2)
            jax.block_until_ready(jax.tree.leaves(state2)[0])
        elif resident and all(
            any(l2 <= int(g) < h2 for l2, (h2, _) in resident.items())
            for g in idx
        ):
            phase2_mode = "resident"
            # Device-resident gather: every straggler's chunk payload is
            # still on device from phase 1, so the deep refit gathers its
            # rows there — per sub-chunk the tunnel carries only a (c,)
            # index vector and a (c, P) warm-start instead of a ~16 MB
            # re-packed payload, and no host re-prep runs at all.  Only
            # the ~n_s straggler rows are ever concatenated (per-chunk
            # takes first, each chunk freed as it is consumed), so peak
            # HBM stays near phase-1 levels.
            import jax.numpy as jnp

            from tsspark_tpu.models.prophet.design import (
                PACKED_PER_SERIES_FIELDS,
            )

            def map_batch(p, fn):
                upd = {
                    k: fn(getattr(p, k)) for k in PACKED_PER_SERIES_FIELDS
                }
                if p.X_season.ndim == 3:  # per-series (conditional seas.)
                    upd["X_season"] = fn(p.X_season)
                return p._replace(**upd)

            smalls, grouped, gather_ranges = [], [], []
            for l2 in sorted(resident):
                h2, payload2 = resident[l2]
                sel = idx[(idx >= l2) & (idx < h2)]
                if sel.size:
                    local = jnp.asarray((sel - l2).astype(np.int32))
                    smalls.append(map_batch(
                        payload2,
                        lambda a: jnp.take(a, local, axis=0),
                    ))
                    grouped.extend(int(g) for g in sel)
                    gather_ranges.append((l2, h2))
                del resident[l2]
            cat_fields = PACKED_PER_SERIES_FIELDS + (
                ("X_season",) if smalls[0].X_season.ndim == 3 else ()
            )
            strag = smalls[0]._replace(**{
                k: jnp.concatenate(
                    [getattr(s, k) for s in smalls], axis=0
                ) for k in cat_fields
            })
            del smalls
            pos_of = {g: i for i, g in enumerate(grouped)}
            row_idx = np.asarray(
                [pos_of[int(g)] for g in idx], np.int32
            )

            def gather_fit(ix, th):
                # Eager device-side row gathers (a few small dispatches),
                # then THE SAME compiled fit program as phase 1 — the
                # gathered payload has phase 1's exact shapes/dtypes, so
                # no new executable is ever compiled for phase 2.
                packed_g = map_batch(
                    strag, lambda a: jnp.take(a, ix, axis=0)
                )
                return fit_core_packed(
                    packed_g, th, model.config, model.solver_config,
                    reg_u8_cols=u8_cols,
                    max_iters_dynamic=np.int32(args.max_iters),
                    gn_precond_dynamic=np.bool_(True),
                    use_theta0_dynamic=np.bool_(True),
                )
            th_parts, st_parts = [], []
            for lo2 in range(0, n_s, args.chunk):
                hi2 = min(lo2 + args.chunk, n_s)
                ix = row_idx[lo2:hi2]
                th = theta_cat[lo2:hi2].astype(np.float32)
                if hi2 - lo2 < args.chunk:
                    # Pad by repeating the first row: a duplicate of a row
                    # already being solved adds no lockstep depth (unlike
                    # arbitrary data) and its result is sliced away.
                    rep = args.chunk - (hi2 - lo2)
                    ix = np.concatenate([ix, np.repeat(ix[:1], rep)])
                    th = np.concatenate(
                        [th, np.repeat(th[:1], rep, axis=0)]
                    )
                th2, st2 = gather_fit(jnp.asarray(ix), jnp.asarray(th))
                jax.block_until_ready(th2)
                heartbeat()
                th_parts.append(np.asarray(th2)[:hi2 - lo2])
                st_parts.append(np.asarray(st2)[:, :hi2 - lo2])
            del strag
            # Scaling meta for the straggler rows comes from the chunk
            # files — deterministic per series, so these are the exact
            # values a host re-prep would recompute.  Rows are selected
            # inside each file via its own (lo, hi) (no full-dataset
            # concatenation, no positional-alignment assumption), in
            # grouped order, then mapped back to difficulty order with
            # the same row_idx the solves used.
            meta_keys = ("y_scale", "floor", "ds_start", "ds_span",
                         "reg_mean", "reg_std", "changepoints")
            meta_grouped = {
                k: np.concatenate([
                    files[(l2, h2)][k][idx[(idx >= l2) & (idx < h2)] - l2]
                    for (l2, h2) in gather_ranges
                ]) for k in meta_keys
            }
            state2 = fitstate_from_packed(
                np.concatenate(th_parts, axis=0),
                np.concatenate(st_parts, axis=1),
                ScalingMeta(**{
                    k: v[row_idx[:n_s]] for k, v in meta_grouped.items()
                }),
            )
        else:
            # Straggler sub-chunk prep (numpy design build + packing,
            # ~1 s each) prefetched on threads so it overlaps the deep
            # device solves, same pattern as the phase-1 loop.
            phase2_mode = "host"
            # Partial-coverage fallback: the retained payloads (~500 MB
            # HBM) serve no purpose here — release them before the deep
            # solves raise peak memory.
            resident.clear()
            y_s, m_s, r_s, init_s = host_gather()
            lows = list(range(0, n_s + pad, args.chunk))

            def prep2(lo2):
                hi2 = lo2 + args.chunk
                data2, meta2 = model.prepare(
                    ds, y_s[lo2:hi2], mask=m_s[lo2:hi2],
                    regressors=r_s[lo2:hi2], as_numpy=True,
                )
                packed2, _ = pack_fit_data(
                    data2, meta2, ds, reg_u8_cols=u8_cols,
                    collapse_cap=True,
                )
                return packed2, meta2

            subs = []
            with ThreadPoolExecutor(max_workers=2) as pool2:
                futs2 = {
                    j: pool2.submit(prep2, lows[j])
                    for j in range(min(prefetch_depth, len(lows)))
                }
                for j, lo2 in enumerate(lows):
                    packed2, meta2 = futs2.pop(j).result()
                    nxt = j + prefetch_depth
                    if nxt < len(lows):
                        futs2[nxt] = pool2.submit(prep2, lows[nxt])
                    # Warm continuation only: phase 2's set is series
                    # still PROGRESSING at the phase-1 cap (stuck exits
                    # carry status FLOOR/STALLED and are the rescue
                    # path's job, not phase 2's) — measured round 4, a
                    # fresh-ridge restart won 0/120 of these with zero
                    # total gain, so the second solve bought nothing at
                    # double the phase-2 cost.
                    th2, st2 = fit_core_packed(
                        packed2, init_s[lo2:lo2 + args.chunk],
                        model.config, model.solver_config,
                        reg_u8_cols=u8_cols,
                        max_iters_dynamic=np.int32(args.max_iters),
                        gn_precond_dynamic=np.bool_(True),
                        use_theta0_dynamic=np.bool_(True),
                    )
                    jax.block_until_ready(th2)
                    heartbeat()
                    subs.append(fitstate_from_packed(
                        np.asarray(th2), st2, meta2
                    ))
            state2 = jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0)[:n_s], *subs
            )
        for (lo, hi), z in files.items():
            if z.get("phase2") is not None:
                continue
            in_chunk = np.flatnonzero((idx >= lo) & (idx < hi))
            local = idx[in_chunk] - lo
            state = FitState(
                theta=z["theta"], loss=z["loss"], grad_norm=z["grad_norm"],
                converged=z["converged"], n_iters=z["n_iters"],
                status=z["status"],
                meta=ScalingMeta(
                    y_scale=z["y_scale"], floor=z["floor"],
                    ds_start=z["ds_start"], ds_span=z["ds_span"],
                    reg_mean=z["reg_mean"], reg_std=z["reg_std"],
                    changepoints=z["changepoints"],
                ),
            )
            sub = jax.tree.map(lambda a: np.asarray(a)[in_chunk], state2)
            patched = patch_state(state, local, sub)
            _save_chunk_atomic(
                args.out, lo, hi, patched,
                extra_arrays={"phase2": np.asarray(1)},
            )
    with open(os.path.join(args.out, "times.jsonl"), "a") as fh:
        fh.write(json.dumps({
            "phase2_s": round(time.time() - t0, 3),
            "stragglers": len(straggler_idx),
            "phase2_mode": phase2_mode,
        }) + "\n")
    with open(marker, "w") as fh:
        fh.write("ok\n")
    return 0


# --------------------------------------------------------------------------
# profile mode: trace one solver segment at bench shape
# --------------------------------------------------------------------------

def profile_main(args) -> None:
    """Capture an XLA trace of the steady-state fit at 1024x1941 and print a
    wall-clock breakdown (prep / transfer / init / per-segment / per-iter /
    per-objective-eval).  The trace goes to --profile-dir for TensorBoard's
    profile plugin; the breakdown answers "where do the milliseconds go"
    without opening it (round-2 verdict item 3)."""
    jax = _setup_jax_child()
    import numpy as np

    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets
    from tsspark_tpu.models.prophet.model import (
        ProphetModel, fit_init_core, fit_segment_core,
    )
    from tsspark_tpu.utils import profiling

    cfg = _model_config()
    solver = SolverConfig(max_iters=120)
    model = ProphetModel(cfg, solver)
    b, t_len, seg = 1024, args.days, args.segment or 24
    timers = profiling.Timers()
    batch = datasets.m5_like(n_series=b, n_days=t_len)
    with timers.section("prepare_host"):
        data, meta = model.prepare(
            np.asarray(batch.ds, np.float32),
            np.nan_to_num(batch.y).astype(np.float32),
            mask=batch.mask.astype(np.float32),
            regressors=batch.regressors.astype(np.float32),
        )
    with timers.section("transfer"):
        data = jax.tree.map(jax.device_put, data)
        jax.block_until_ready(jax.tree.leaves(data))
    with timers.section("init_incl_compile"):
        st = fit_init_core(data, None, cfg, solver)
        jax.block_until_ready(st.theta)
    with timers.section("segment_warmup_incl_compile"):
        st = fit_segment_core(data, st, cfg, solver, seg)
        jax.block_until_ready(st.theta)
    with timers.section("segment_traced"):
        with profiling.trace(args.profile_dir):
            with profiling.annotate("fit_segment_steady"):
                st = fit_segment_core(data, st, cfg, solver, seg)
                jax.block_until_ready(st.theta)
    seg_s = timers.summary()["segment_traced"]["total_s"]
    # Objective-eval cost: one fan line search evaluates ls_max_steps+1
    # trial rows + 1 value-and-grad per iteration.
    evals_per_iter = solver.ls_max_steps + 2
    print(json.dumps({
        "metric": f"profile_segment_{b}x{t_len}",
        "value": round(seg_s / seg, 4),
        "unit": "s/iter",
        "vs_baseline": 0.0,
        "extra": {
            "timers": timers.summary(),
            "segment_iters": seg,
            "per_objective_eval_ms": round(
                1e3 * seg_s / seg / evals_per_iter, 2
            ),
            "ls_max_steps": solver.ls_max_steps,
            "device": str(jax.devices()[0]),
            "trace_dir": args.profile_dir,
        },
    }), flush=True)


# --------------------------------------------------------------------------
# eval worker (CPU)
# --------------------------------------------------------------------------

def eval_worker(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax = _setup_jax_child()
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tsspark_tpu.eval import metrics
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState, ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")

    # Gather enough leading chunks to cover n_eval series.
    files = sorted(glob.glob(os.path.join(args.out, "chunk_*.npz")))
    parts, covered = [], 0
    for f in files:
        parts.append(np.load(f))
        covered = int(os.path.basename(f).split("_")[2].split(".")[0])
        if covered >= args.n_eval:
            break
    n = min(args.n_eval, covered)
    cat = lambda k: jnp.asarray(
        np.concatenate([p[k] for p in parts], axis=0)[:n]
    )
    # Meta stays host numpy float64 (ScalingMeta contract).
    catn = lambda k: np.concatenate([p[k] for p in parts], axis=0)[:n]
    state = FitState(
        theta=cat("theta"),
        meta=ScalingMeta(
            y_scale=catn("y_scale"), floor=catn("floor"),
            ds_start=catn("ds_start"), ds_span=catn("ds_span"),
            reg_mean=catn("reg_mean"), reg_std=catn("reg_std"),
            changepoints=catn("changepoints"),
        ),
        loss=cat("loss"), grad_norm=cat("grad_norm"),
        converged=cat("converged"), n_iters=cat("n_iters"),
    )
    model = ProphetModel(_model_config())
    fc = model.predict(
        state, jnp.asarray(ds),
        regressors=jnp.asarray(np.ascontiguousarray(reg[:n])),
        num_samples=0,
    )
    y_n = jnp.asarray(np.nan_to_num(np.ascontiguousarray(y[:n])))
    smape = float(np.mean(np.asarray(
        metrics.smape(y_n, fc["yhat"], mask=jnp.asarray(
            np.ascontiguousarray(mask[:n])))
    )))
    with open(os.path.join(args.out, "eval.json"), "w") as fh:
        json.dump({"smape_insample_mean": round(smape, 3), "n_eval": n}, fh)
    return 0


# --------------------------------------------------------------------------
# parent orchestrator (no JAX)
# --------------------------------------------------------------------------

# Live worker subprocesses: the SIGTERM handler must kill them or an orphan
# fit child keeps holding the TPU tunnel after the parent is gone.
_CHILDREN: set = set()


def _tunnel_preflight(timeout: float = 90.0) -> bool:
    """Client-creation watchdog: a wedged TPU tunnel blocks ``jax.devices()``
    forever (observed repeatedly on this image).  Probe it in a disposable
    subprocess so the decision takes <= ``timeout`` seconds instead of a
    fit-worker stall cycle."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "jax.devices()\n"
        "x = jnp.ones((128, 128))\n"
        "(x @ x).block_until_ready()\n"
        "print('tunnel-ok', flush=True)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False
    return "tunnel-ok" in (r.stdout or "")


def _spawn(mode: str, args, extra: list, timeout: Optional[float] = None,
           progress_timeout: Optional[float] = None) -> int:
    """Run a worker; kill it on overall timeout OR when no new chunk result
    has appeared for ``progress_timeout`` seconds (a wedged TPU tunnel blocks
    client creation forever — stalling is indistinguishable from working
    except by watching the output directory)."""
    cmd = [sys.executable, os.path.abspath(__file__), mode,
           "--data", args._data_dir, "--out", args._out_dir] + extra
    env = dict(os.environ)
    if mode == "--_eval":
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(cmd, stdout=sys.stderr, env=env)
    _CHILDREN.add(proc)
    start = time.time()
    last_progress = start
    n_start = len(_completed_ranges(args._out_dir))
    n_chunks = n_start
    hb_path = os.path.join(args._out_dir, "heartbeat")
    hb_last = os.path.getmtime(hb_path) if os.path.exists(hb_path) else 0.0
    any_progress = False
    try:
        while True:
            try:
                return proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
            now = time.time()
            n_now = len(_completed_ranges(args._out_dir))
            if n_now > n_chunks:
                n_chunks, last_progress = n_now, now
                any_progress = True
            # Per-dispatch heartbeats from the fit worker also count: the
            # phase-2 straggler pass rewrites existing chunks (no new files),
            # and a fresh compile shows nothing for minutes — both are
            # liveness, not a stall.
            hb_now = os.path.getmtime(hb_path) if os.path.exists(hb_path) \
                else 0.0
            if hb_now > hb_last:
                hb_last, last_progress = hb_now, now
                any_progress = True
            timed_out = timeout is not None and now - start > timeout
            # Until THIS worker shows its first sign of life it may be
            # cold-compiling its first dispatch — give it triple the steady
            # allowance, but no more (round 2 lost 680 s to a silent stall).
            allowance = (progress_timeout if any_progress
                         else None if progress_timeout is None
                         else 3.0 * progress_timeout)
            stalled = (allowance is not None
                       and now - last_progress > allowance)
            if timed_out or stalled:
                why = "timed out" if timed_out else "stalled (no new chunk)"
                print(f"[bench] worker {why} after {round(now - start)}s",
                      file=sys.stderr)
                proc.kill()
                proc.wait()
                return -9
    finally:
        _CHILDREN.discard(proc)


def _completed_ranges(out_dir: str):
    done = []
    for f in sorted(glob.glob(os.path.join(out_dir, "chunk_*.npz"))):
        base = os.path.basename(f)[len("chunk_"):-len(".npz")]
        lo, hi = base.split("_")
        done.append((int(lo), int(hi)))
    return done


def _missing_ranges(done, total):
    missing, cur = [], 0
    for lo, hi in sorted(done):
        if lo > cur:
            missing.append((cur, lo))
        cur = max(cur, hi)
    if cur < total:
        missing.append((cur, total))
    return missing


def _build_summary(args, t_wall0, gen_s, chunk, retries, note=None,
                   probes=None):
    """Summary JSON from whatever is on disk RIGHT NOW — callable at any
    point (including from the SIGTERM handler mid-fit)."""
    import numpy as np

    # Every read guards against files truncated by a killed child: the
    # summary line must come out no matter what state the scratch dir is in.
    times = []
    tpath = os.path.join(args._out_dir, "times.jsonl")
    if os.path.exists(tpath):
        try:
            with open(tpath) as fh:
                for line in fh:
                    if line.strip():
                        times.append(json.loads(line))
        except Exception:
            pass
    phase2_s = sum(t.get("phase2_s", 0.0) for t in times)
    stragglers = sum(t.get("stragglers", 0) for t in times)
    fit_s = sum(t.get("fit_s", 0.0) for t in times) + phase2_s
    done = _completed_ranges(args._out_dir)
    n_done = sum(hi - lo for lo, hi in done)

    smape = None
    epath = os.path.join(args._out_dir, "eval.json")
    if os.path.exists(epath):
        try:
            with open(epath) as fh:
                smape = json.load(fh)["smape_insample_mean"]
        except Exception:
            pass

    conv, n_iters_max, status_counts = [], 0, {}
    for f in glob.glob(os.path.join(args._out_dir, "chunk_*.npz")):
        try:
            z = np.load(f)
            conv.append(float(z["converged"].mean()))
            n_iters_max = max(n_iters_max, int(z["n_iters"].max()))
            if "status" in z.files:
                vals, counts = np.unique(z["status"], return_counts=True)
                for v, c in zip(vals, counts):
                    status_counts[int(v)] = status_counts.get(int(v), 0) + int(c)
        except Exception:
            pass

    complete = n_done >= args.series
    # Honest headline semantics (round-2 verdict): ``value`` is the fit wall
    # for the COMPLETED series; when partial, the full-workload projection is
    # reported alongside and vs_baseline is computed against the projection
    # so a partial run can never read better than a finished one.
    projected = fit_s * args.series / n_done if n_done else 0.0
    extra = {
        "smape_insample_mean": smape,
        "converged_frac": round(float(np.mean(conv)), 4) if conv else 0.0,
        "n_iters_max": n_iters_max,
        "status_counts": status_counts,  # keys: ops/lbfgs.STATUS_*
        "series_done": n_done,
        "series_requested": args.series,
        "complete": complete,
        "series_per_s": round(n_done / fit_s, 2) if fit_s else 0.0,
        "projected_full_fit_s": round(projected, 1),
        "phase2_s": round(phase2_s, 2),
        "stragglers": stragglers,
        "datagen_s": round(gen_s, 2),
        "wall_s": round(time.time() - t_wall0, 1),
        "device": next(
            (t["device"] for t in reversed(times) if "device" in t), None
        ),
        "chunk_final": chunk,
        "resumed": bool(getattr(args, "_resumed", False)),
        "worker_retries": retries,
        "max_iters": args.max_iters,
        "phase1_iters": args.phase1_iters,
    }
    if note:
        extra["note"] = note
    if probes and probes.get("n"):
        # Wedge-resilience audit trail: how many tunnel probes ran, how
        # many failed, and the wall-offset of the last one — proof the
        # probe loop ran to the reserve on a fully-wedged budget.
        extra["tunnel_probes"] = probes["n"]
        extra["tunnel_probe_fails"] = probes["fails"]
        extra["last_probe_at_s"] = probes["last_t"]
    # vs_baseline keeps the STRICT round-1/2 definition — 60 s target /
    # measured single-chip seconds, i.e. >= 1.0 means the whole 8-chip
    # target is beaten on one chip — so the headline stays conservative
    # and comparable across rounds.  The chip-second framing (the 60 s
    # v5e-8 target = 480 chip-seconds; the workload is embarrassingly
    # parallel over series chunks, multi-chip path exercised by
    # tests/test_sharding.py + dryrun_multichip) is reported alongside in
    # ``extra`` — it is an extrapolation this one-chip machine cannot
    # measure, so it must not be the headline ratio.
    extra["chip_seconds_budget"] = TARGET_S * TARGET_CHIPS
    extra["vs_chip_seconds_budget"] = (
        round(TARGET_S * TARGET_CHIPS / projected, 3) if projected else 0.0
    )
    return {
        "metric": f"m5_{args.series}x{args.days}_fit_wall_clock",
        "value": round(fit_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / projected, 3) if projected else 0.0,
        "extra": extra,
    }


_EMITTED = False


def _emit(summary) -> None:
    """Print the ONE summary line exactly once."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(summary), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=30490)
    ap.add_argument("--days", type=int, default=1941)
    # 1024 is the largest chunk that has survived the TPU tunnel's crash
    # envelope in practice; 2048 has never completed a driver run.
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--max-iters", type=int, default=120)
    ap.add_argument("--segment", type=int, default=24,
                    help="solver iterations per XLA dispatch (0 = one "
                         "program for the full solve)")
    ap.add_argument("--phase1-iters", type=int, default=12,
                    help="lockstep depth of the main pass; unconverged "
                         "series are compacted into one full-depth "
                         "follow-up batch (0 = single-phase)")
    ap.add_argument("--no-phase1-tune", action="store_true",
                    help="pin phase-1 depth to --phase1-iters instead of "
                         "adapting it from chunk 0's convergence (A/B "
                         "instrument: the tuner deepens 12 -> 24 on the "
                         "M5 shape and the payoff is under measurement)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a quick pipeline check")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir (debugging)")
    ap.add_argument("--profile", action="store_true",
                    help="trace one steady-state solver segment instead of "
                         "running the benchmark")
    ap.add_argument("--profile-dir", default=os.path.join(REPO, "profiles"))
    args = ap.parse_args()
    if args.profile:
        profile_main(args)
        return
    if args.smoke:
        args.series, args.days, args.chunk = 512, 256, 512

    t_wall0 = time.time()
    deadline = t_wall0 + BUDGET_S
    import numpy as np

    from tsspark_tpu.data import datasets

    # Persistent, code-fingerprinted scratch: a run killed by the harness
    # timeout (or a wedged tunnel) resumes from its completed chunk files on
    # the next invocation instead of starting over — per-chunk saves and the
    # phase-2 marker are already idempotent.  Any source change rotates the
    # fingerprint so stale results can never leak across code versions.
    scratch = os.path.join(
        "/tmp",
        f"tsbench_run_{args.series}x{args.days}_c{args.chunk}"
        f"_p{args.phase1_iters}{'f' if args.no_phase1_tune else ''}"
        f"_{_code_fingerprint()}",
    )
    args._out_dir = os.path.join(scratch, "out")
    resumed = os.path.isdir(args._out_dir) and bool(
        glob.glob(os.path.join(args._out_dir, "chunk_*.npz"))
    )
    args._resumed = resumed
    if resumed:
        print(f"[bench] resuming from {args._out_dir}", file=sys.stderr)
    # Stale scratch dirs (other fingerprints / shapes) have no resume value
    # — but only reap ones untouched for hours: a CONCURRENT bench with a
    # different shape owns a freshly-modified dir, and deleting it would
    # destroy that run's chunk files mid-flight.
    for d in glob.glob("/tmp/tsbench_run_*"):
        if os.path.abspath(d) == os.path.abspath(scratch):
            continue
        try:
            newest = max(
                (os.path.getmtime(p) for p in
                 glob.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError:
            continue
        if time.time() - newest > 6 * 3600:
            shutil.rmtree(d, ignore_errors=True)
    os.makedirs(args._out_dir, exist_ok=True)

    # From here on a SIGTERM/SIGINT (harness timeout) still produces the one
    # summary line from whatever chunks have landed; the scratch dir is
    # KEPT on signal so the next run resumes.
    state = {"chunk": args.chunk, "retries": 0, "gen_s": 0.0,
             "probes": {"n": 0, "fails": 0, "last_t": 0.0}}

    def _on_signal(signum, frame):
        for proc in list(_CHILDREN):  # free the TPU tunnel before exiting
            try:
                proc.kill()
            except OSError:
                pass
        _emit(_build_summary(args, t_wall0, state["gen_s"], state["chunk"],
                             state["retries"], note=f"signal {signum}",
                             probes=state["probes"]))
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # Generated data is cached across runs/retries keyed by shape (the
    # generator is seed-deterministic): round-2 burned ~47 s of every
    # budgeted run regenerating identical arrays.
    gen0 = time.time()
    cache = os.path.join(
        tempfile.gettempdir(),
        f"tsbench_data_{args.series}x{args.days}_{_datagen_fingerprint()}",
    )
    if not os.path.exists(os.path.join(cache, "ok")):
        # Private temp dir + atomic rename: concurrent bench processes can
        # race to publish, but each writes its own dir and the loser keeps
        # (or falls back to) a complete copy — a half-written cache can
        # never appear under the "ok"-marked path.
        tmp_cache = tempfile.mkdtemp(
            prefix="tsbench_datagen_", dir=tempfile.gettempdir()
        )
        batch = datasets.m5_like(n_series=args.series, n_days=args.days)
        np.save(os.path.join(tmp_cache, "ds.npy"),
                batch.ds.astype(np.float32))
        np.save(os.path.join(tmp_cache, "y.npy"),
                np.nan_to_num(batch.y).astype(np.float32))
        np.save(os.path.join(tmp_cache, "mask.npy"),
                batch.mask.astype(np.float32))
        np.save(os.path.join(tmp_cache, "reg.npy"),
                batch.regressors.astype(np.float32))
        del batch
        with open(os.path.join(tmp_cache, "ok"), "w") as fh:
            fh.write("ok\n")
        try:
            os.rename(tmp_cache, cache)
        except OSError:
            # Someone else published first (or a stale dir exists): use
            # theirs if complete, else fall back to our private copy.
            if not os.path.exists(os.path.join(cache, "ok")):
                cache = tmp_cache
            else:
                shutil.rmtree(tmp_cache, ignore_errors=True)
    args._data_dir = cache
    state["gen_s"] = gen_s = time.time() - gen0

    note = None
    side = {"eval": None, "prep": None}  # overlapped CPU-side children
    probes = state["probes"]

    def _probe_log(ok: bool, dur: float) -> None:
        probes["n"] += 1
        probes["fails"] += 0 if ok else 1
        probes["last_t"] = round(time.time() - t_wall0, 1)
        try:
            with open(os.path.join(args._out_dir, "probes.jsonl"), "a") as fh:
                fh.write(json.dumps({
                    "t": probes["last_t"], "ok": ok, "dur_s": round(dur, 1),
                }) + "\n")
        except OSError:
            pass

    def _eval_covered() -> bool:
        """eval.json exists AND covers the series the final eval would:
        an overlapped eval started mid-wedge may have scored only the
        chunks landed at that moment, and must not satisfy the end-of-run
        obligation for a run that went on to complete more."""
        try:
            with open(os.path.join(args._out_dir, "eval.json")) as fh:
                have = json.load(fh).get("n_eval", 0)
        except (OSError, ValueError):
            return False
        n_done = sum(
            hi - lo for lo, hi in _completed_ranges(args._out_dir)
        )
        return n_done > 0 and have >= min(512, n_done)

    def _reserve() -> float:
        """End-of-run time to protect.  Shrinks as the remaining exit
        obligations shrink: with a covering eval.json on disk (or nothing
        evaluable) only the summary print is left, so the probe/fit loop
        may run nearly to the deadline — the round-3 failure mode was
        surrendering with ~500 s left while a fixed 150 s reserve sat
        unused."""
        if _eval_covered():
            return 25.0
        if not _completed_ranges(args._out_dir):
            return 25.0  # nothing to eval; probing is the best use of time
        if side["eval"] is not None and side["eval"].poll() is None:
            return 60.0  # eval already running concurrently
        return RESERVE_S

    def _side_child(kind: str, extra: list) -> None:
        """Nonblocking CPU child (--_eval / --_prep), JAX forced to CPU so
        a wedged TPU tunnel cannot block it.  At most one of each kind."""
        proc = side.get(kind)
        if proc is not None and proc.poll() is None:
            return
        cmd = [sys.executable, os.path.abspath(__file__), f"--_{kind}",
               "--data", args._data_dir, "--out", args._out_dir] + extra
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        side[kind] = subprocess.Popen(cmd, stdout=sys.stderr, env=env)
        _CHILDREN.add(side[kind])

    def _overlap_cpu_work() -> None:
        """Tunnel-down time is spent on the CPU-side work the run needs
        anyway: eval of already-landed chunks and pre-packing pending chunk
        payloads, so a late tunnel recovery converts into chunks instantly."""
        done = _completed_ranges(args._out_dir)
        n_done = sum(hi - lo for lo, hi in done)
        if n_done and not _eval_covered():
            _side_child("eval", ["--n-eval", str(min(512, n_done))])
        if _missing_ranges(done, args.series):
            _side_child("prep", [
                "--series", str(args.series),
                "--chunk", str(state["chunk"]),
                "--max-iters", str(args.max_iters),
                "--max-ahead", "6",
            ])

    # Probe before the first attempt (tunnel health unknown) and after any
    # attempt that died without progress; a worker that just produced
    # chunks has proven the tunnel alive, so skip the probe then.
    check_tunnel = os.environ.get("JAX_PLATFORMS", "") not in ("cpu",)
    probe_sleep = 5.0
    while True:
        missing = _missing_ranges(_completed_ranges(args._out_dir), args.series)
        phase2_pending = (
            0 < args.phase1_iters < args.max_iters
            and not os.path.exists(
                os.path.join(args._out_dir, "phase2_done")
            )
        )
        if not missing and not phase2_pending:
            break
        remaining = deadline - time.time()
        if remaining < _reserve():
            note = "fit budget exhausted; partial"
            print(f"[bench] {note}", file=sys.stderr)
            break
        # Client-creation watchdog: don't hand the range to a fit worker
        # that will hang in jax.devices() for the whole stall allowance.
        # A wedged tunnel recovers on its own schedule, so probing NEVER
        # gives up while budget remains (round-3 verdict: quitting after
        # three probes threw away ~500 s of a 900 s budget) — cheap ~30 s
        # probes loop until deadline - reserve, with the wait overlapped
        # by the CPU-side eval/prep children.
        if check_tunnel:
            t_probe = time.time()
            # Escalating timeout: cheap 30 s probes while wedged, but a
            # healthy tunnel whose client creation is merely SLOW (30-90 s
            # has been observed) must not fail every probe forever — each
            # consecutive failure buys the next probe more patience, up
            # to the old 90 s allowance.
            patience = min(30.0 + 15.0 * probes.get("consec", 0), 90.0)
            ok = _tunnel_preflight(
                timeout=min(patience, max(10.0, remaining - _reserve()))
            )
            probes["consec"] = 0 if ok else probes.get("consec", 0) + 1
            _probe_log(ok, time.time() - t_probe)
            if not ok:
                print(
                    f"[bench] tunnel probe failed "
                    f"({probes['fails']}/{probes['n']} probes failed, "
                    f"{round(deadline - time.time())}s of budget left; "
                    f"probing until the reserve)",
                    file=sys.stderr,
                )
                _overlap_cpu_work()
                time.sleep(min(
                    probe_sleep,
                    max(0.0, deadline - time.time() - _reserve()),
                ))
                probe_sleep = min(probe_sleep * 1.5, 30.0)
                continue
            probe_sleep = 5.0
            check_tunnel = False
        remaining = deadline - time.time()
        budget = max(60.0, remaining - _reserve())
        before = len(_completed_ranges(args._out_dir))
        lo = missing[0][0] if missing else 0
        hi = missing[-1][1] if missing else args.series
        rc = _spawn("--_fit", args, [
            "--lo", str(lo), "--hi", str(hi),
            "--chunk", str(state["chunk"]), "--max-iters", str(args.max_iters),
            "--segment", str(args.segment),
            "--series", str(args.series),
            "--phase1-iters", str(args.phase1_iters),
        ] + (["--no-phase1-tune"] if args.no_phase1_tune else []),
            timeout=budget, progress_timeout=90.0)
        if rc == 0:
            continue  # re-scan; loop exits when nothing is missing
        state["retries"] += 1
        made_progress = len(_completed_ranges(args._out_dir)) > before
        # A death with zero progress puts the tunnel itself under suspicion.
        check_tunnel = (not made_progress and
                        os.environ.get("JAX_PLATFORMS", "") not in ("cpu",))
        # Halve the chunk only when a PHASE-1 attempt made no progress at
        # all — halving targets too-big-program crashes.  A straggler crash
        # mid-run keeps the size that was evidently working, and a death in
        # the phase-2 pass (all chunks already exist) says nothing about
        # chunk size (changing it would only force a fresh compile shape).
        chunk = state["chunk"]
        new_chunk = chunk if (made_progress or not missing) \
            else max(chunk // 2, MIN_CHUNK)
        print(f"[bench] fit worker died (rc={rc}), chunk {chunk} -> "
              f"{new_chunk}, retry {state['retries']}", file=sys.stderr)
        # No retry cap: a crash loop is re-probed (check_tunnel above) and
        # retried until the budget's reserve — the driver deadline, not a
        # counter, decides when to stop (round-3 verdict item 1).
        state["chunk"] = new_chunk
        time.sleep(10.0)  # let the crashed TPU worker restart cleanly

    n_done = sum(hi - lo for lo, hi in _completed_ranges(args._out_dir))
    ep = side.get("eval")
    if ep is not None and ep.poll() is None:
        # An overlapped eval is already in flight; give it the remaining
        # budget instead of starting a duplicate.
        try:
            ep.wait(timeout=max(15.0, deadline - time.time() - 15.0))
        except subprocess.TimeoutExpired:
            ep.kill()
    # Re-run when coverage grew past what an overlapped mid-wedge eval
    # scored (eval.json records its n_eval; the worker overwrites it).
    if n_done and not _eval_covered():
        eval_budget = max(60.0, deadline - time.time() - 15.0)
        _spawn("--_eval", args, ["--n-eval", str(min(512, n_done))],
               timeout=eval_budget)
    pp = side.get("prep")
    if pp is not None and pp.poll() is None:
        pp.kill()

    summary = _build_summary(args, t_wall0, gen_s, state["chunk"],
                             state["retries"], note=note,
                             probes=state["probes"])
    _emit(summary)
    # Remove the scratch only after a COMPLETE run: partial results are the
    # resume state for the next invocation (fingerprint-keyed, so a code
    # change invalidates them anyway).
    if not args.keep and summary["extra"].get("complete"):
        shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] in ("--_fit", "--_eval", "--_prep"):
        mode = sys.argv.pop(1)
        ap = argparse.ArgumentParser()
        ap.add_argument("--data", required=True)
        ap.add_argument("--out", required=True)
        ap.add_argument("--lo", type=int, default=0)
        ap.add_argument("--hi", type=int, default=0)
        ap.add_argument("--chunk", type=int, default=2048)
        ap.add_argument("--max-iters", type=int, default=120)
        ap.add_argument("--segment", type=int, default=24)
        ap.add_argument("--series", type=int, default=0)
        ap.add_argument("--phase1-iters", type=int, default=0)
        ap.add_argument("--no-phase1-tune", action="store_true")
        ap.add_argument("--n-eval", type=int, default=512)
        ap.add_argument("--max-ahead", type=int, default=6)
        a = ap.parse_args()
        sys.exit({"--_fit": fit_worker, "--_eval": eval_worker,
                  "--_prep": prep_worker}[mode](a))
    main()
