"""Headline benchmark: M5-scale end-to-end batched fit wall-clock.

Driver metric (BASELINE.json:2): "M5 (30k series) end-to-end fit wall-clock;
sMAPE parity vs CPU".  Target: all 30,490 series in < 60 s on a TPU v5e-8
(BASELINE.json:5).  This machine exposes ONE v5e chip, so the printed
``vs_baseline`` is target_seconds / measured_seconds on a single chip —
values >= 1.0 mean the 8-chip target is beaten with 1/8th of the hardware.

Resilience: the single TPU chip sits behind an experimental stdio-tunneled
relay whose worker can crash on large programs (observed: single input
buffers over ~64 MB kill it, and the envelope shrinks after a crash).  A
dead worker takes the whole JAX client with it, so the benchmark is split
into processes:

  parent (this file, no JAX)  — generates data once to .npy files, spawns
                                fit workers, retries crashed ranges with a
                                halved chunk size, resumes from completed
                                per-chunk result files, then runs a CPU eval
                                worker and prints the ONE summary JSON line.
  --_fit child (TPU)          — fits [lo, hi) in chunks, saving each chunk's
                                FitState + timing to disk the moment it
                                completes, so a crash loses at most a chunk.
  --_eval child (CPU)         — in-sample sMAPE on a subsample from the
                                saved states (accuracy gate, not the metric).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Usage: python bench.py [--series N] [--days N] [--chunk N] [--smoke]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

TARGET_S = 60.0
MIN_CHUNK = 512
# Total wall budget.  The driver harness kills the whole process on ITS
# timeout (observed ~20 min); staying under it is the only way the summary
# line reaches stdout.  Overridable for longer local runs.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "900"))
# Reserve at the end of the budget for the eval child + summary print.
RESERVE_S = 150.0


def _model_config():
    from tsspark_tpu.config import (
        ProphetConfig,
        RegressorConfig,
        SeasonalityConfig,
    )

    # Eval config 3 (BASELINE.json:9): holiday regressors + external features.
    return ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )


def _setup_jax_child():
    """Child-process JAX config: persistent compile cache."""
    import jax

    from tsspark_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return jax


# --------------------------------------------------------------------------
# fit worker (TPU)
# --------------------------------------------------------------------------

def fit_worker(args) -> int:
    jax = _setup_jax_child()
    import jax.numpy as jnp
    import numpy as np

    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import SolverConfig

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")

    backend = get_backend(
        "tpu", _model_config(), SolverConfig(max_iters=args.max_iters),
        chunk_size=args.chunk, iter_segment=args.segment or None,
    )

    for lo in range(args.lo, args.hi, args.chunk):
        hi = min(lo + args.chunk, args.hi)
        out_path = os.path.join(args.out, f"chunk_{lo:06d}_{hi:06d}.npz")
        if os.path.exists(out_path):
            continue
        t0 = time.time()
        # Host arrays in: prepare_fit_data computes scalings host-side and
        # ships only the final f32 design tensors over the tunnel once.
        state = backend.fit(
            ds,
            np.ascontiguousarray(y[lo:hi]),
            mask=np.ascontiguousarray(mask[lo:hi]),
            regressors=np.ascontiguousarray(reg[lo:hi]),
        )
        jax.block_until_ready(state.theta)
        fit_s = time.time() - t0
        # Dotfile prefix so a half-written file can never match the
        # chunk_*.npz resume/eval glob.
        tmp = os.path.join(args.out, f".tmp_{lo:06d}_{hi:06d}.npz")
        np.savez(
            tmp,
            theta=np.asarray(state.theta),
            loss=np.asarray(state.loss),
            grad_norm=np.asarray(state.grad_norm),
            converged=np.asarray(state.converged),
            n_iters=np.asarray(state.n_iters),
            y_scale=np.asarray(state.meta.y_scale),
            floor=np.asarray(state.meta.floor),
            ds_start=np.asarray(state.meta.ds_start),
            ds_span=np.asarray(state.meta.ds_span),
            reg_mean=np.asarray(state.meta.reg_mean),
            reg_std=np.asarray(state.meta.reg_std),
        )
        os.replace(tmp, out_path)
        with open(os.path.join(args.out, "times.jsonl"), "a") as fh:
            fh.write(json.dumps({
                "lo": lo, "hi": hi, "fit_s": round(fit_s, 3),
                "chunk": args.chunk, "device": str(jax.devices()[0]),
            }) + "\n")
    return 0


# --------------------------------------------------------------------------
# eval worker (CPU)
# --------------------------------------------------------------------------

def eval_worker(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax = _setup_jax_child()
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tsspark_tpu.eval import metrics
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState, ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")

    # Gather enough leading chunks to cover n_eval series.
    files = sorted(glob.glob(os.path.join(args.out, "chunk_*.npz")))
    parts, covered = [], 0
    for f in files:
        parts.append(np.load(f))
        covered = int(os.path.basename(f).split("_")[2].split(".")[0])
        if covered >= args.n_eval:
            break
    n = min(args.n_eval, covered)
    cat = lambda k: jnp.asarray(
        np.concatenate([p[k] for p in parts], axis=0)[:n]
    )
    # Meta stays host numpy float64 (ScalingMeta contract).
    catn = lambda k: np.concatenate([p[k] for p in parts], axis=0)[:n]
    state = FitState(
        theta=cat("theta"),
        meta=ScalingMeta(
            y_scale=catn("y_scale"), floor=catn("floor"),
            ds_start=catn("ds_start"), ds_span=catn("ds_span"),
            reg_mean=catn("reg_mean"), reg_std=catn("reg_std"),
        ),
        loss=cat("loss"), grad_norm=cat("grad_norm"),
        converged=cat("converged"), n_iters=cat("n_iters"),
    )
    model = ProphetModel(_model_config())
    fc = model.predict(
        state, jnp.asarray(ds),
        regressors=jnp.asarray(np.ascontiguousarray(reg[:n])),
        num_samples=0,
    )
    y_n = jnp.asarray(np.nan_to_num(np.ascontiguousarray(y[:n])))
    smape = float(np.mean(np.asarray(
        metrics.smape(y_n, fc["yhat"], mask=jnp.asarray(
            np.ascontiguousarray(mask[:n])))
    )))
    with open(os.path.join(args.out, "eval.json"), "w") as fh:
        json.dump({"smape_insample_mean": round(smape, 3), "n_eval": n}, fh)
    return 0


# --------------------------------------------------------------------------
# parent orchestrator (no JAX)
# --------------------------------------------------------------------------

# Live worker subprocesses: the SIGTERM handler must kill them or an orphan
# fit child keeps holding the TPU tunnel after the parent is gone.
_CHILDREN: set = set()


def _tunnel_preflight(timeout: float = 90.0) -> bool:
    """Client-creation watchdog: a wedged TPU tunnel blocks ``jax.devices()``
    forever (observed repeatedly on this image).  Probe it in a disposable
    subprocess so the decision takes <= ``timeout`` seconds instead of a
    fit-worker stall cycle."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "jax.devices()\n"
        "x = jnp.ones((128, 128))\n"
        "(x @ x).block_until_ready()\n"
        "print('tunnel-ok', flush=True)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False
    return "tunnel-ok" in (r.stdout or "")


def _spawn(mode: str, args, extra: list, timeout: Optional[float] = None,
           progress_timeout: Optional[float] = None) -> int:
    """Run a worker; kill it on overall timeout OR when no new chunk result
    has appeared for ``progress_timeout`` seconds (a wedged TPU tunnel blocks
    client creation forever — stalling is indistinguishable from working
    except by watching the output directory)."""
    cmd = [sys.executable, os.path.abspath(__file__), mode,
           "--data", args._data_dir, "--out", args._out_dir] + extra
    env = dict(os.environ)
    if mode == "--_eval":
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(cmd, stdout=sys.stderr, env=env)
    _CHILDREN.add(proc)
    start = time.time()
    last_progress = start
    n_start = len(_completed_ranges(args._out_dir))
    n_chunks = n_start
    try:
        while True:
            try:
                return proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
            now = time.time()
            n_now = len(_completed_ranges(args._out_dir))
            if n_now > n_chunks:
                n_chunks, last_progress = n_now, now
            timed_out = timeout is not None and now - start > timeout
            # Until THIS worker lands its first chunk it may legitimately be
            # cold-compiling (a halved chunk is a fresh XLA shape, minutes
            # with nothing to show) — give it triple the steady allowance.
            allowance = (progress_timeout if n_chunks > n_start
                         else None if progress_timeout is None
                         else 3.0 * progress_timeout)
            stalled = (allowance is not None
                       and now - last_progress > allowance)
            if timed_out or stalled:
                why = "timed out" if timed_out else "stalled (no new chunk)"
                print(f"[bench] worker {why} after {round(now - start)}s",
                      file=sys.stderr)
                proc.kill()
                proc.wait()
                return -9
    finally:
        _CHILDREN.discard(proc)


def _completed_ranges(out_dir: str):
    done = []
    for f in sorted(glob.glob(os.path.join(out_dir, "chunk_*.npz"))):
        base = os.path.basename(f)[len("chunk_"):-len(".npz")]
        lo, hi = base.split("_")
        done.append((int(lo), int(hi)))
    return done


def _missing_ranges(done, total):
    missing, cur = [], 0
    for lo, hi in sorted(done):
        if lo > cur:
            missing.append((cur, lo))
        cur = max(cur, hi)
    if cur < total:
        missing.append((cur, total))
    return missing


def _build_summary(args, t_wall0, gen_s, chunk, retries, note=None):
    """Summary JSON from whatever is on disk RIGHT NOW — callable at any
    point (including from the SIGTERM handler mid-fit)."""
    import numpy as np

    # Every read guards against files truncated by a killed child: the
    # summary line must come out no matter what state the scratch dir is in.
    times = []
    tpath = os.path.join(args._out_dir, "times.jsonl")
    if os.path.exists(tpath):
        try:
            with open(tpath) as fh:
                for line in fh:
                    if line.strip():
                        times.append(json.loads(line))
        except Exception:
            pass
    fit_s = sum(t["fit_s"] for t in times)
    done = _completed_ranges(args._out_dir)
    n_done = sum(hi - lo for lo, hi in done)

    smape = None
    epath = os.path.join(args._out_dir, "eval.json")
    if os.path.exists(epath):
        try:
            with open(epath) as fh:
                smape = json.load(fh)["smape_insample_mean"]
        except Exception:
            pass

    conv = []
    for f in glob.glob(os.path.join(args._out_dir, "chunk_*.npz")):
        try:
            conv.append(float(np.load(f)["converged"].mean()))
        except Exception:
            pass

    extra = {
        "smape_insample_mean": smape,
        "converged_frac": round(float(np.mean(conv)), 4) if conv else 0.0,
        "series_done": n_done,
        "series_requested": args.series,
        "datagen_s": round(gen_s, 2),
        "wall_s": round(time.time() - t_wall0, 1),
        "device": times[-1]["device"] if times else None,
        "chunk_final": chunk,
        "worker_retries": retries,
        "max_iters": args.max_iters,
    }
    if note:
        extra["note"] = note
    return {
        "metric": f"m5_{args.series}x{args.days}_fit_wall_clock",
        "value": round(fit_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / fit_s, 3) if fit_s else 0.0,
        "extra": extra,
    }


_EMITTED = False


def _emit(summary) -> None:
    """Print the ONE summary line exactly once."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(summary), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=30490)
    ap.add_argument("--days", type=int, default=1941)
    # 1024 is the largest chunk that has survived the TPU tunnel's crash
    # envelope in practice; 2048 has never completed a driver run.
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--max-iters", type=int, default=120)
    ap.add_argument("--segment", type=int, default=24,
                    help="solver iterations per XLA dispatch (0 = one "
                         "program for the full solve)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a quick pipeline check")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir (debugging)")
    args = ap.parse_args()
    if args.smoke:
        args.series, args.days, args.chunk = 512, 256, 512

    t_wall0 = time.time()
    deadline = t_wall0 + BUDGET_S
    import numpy as np

    from tsspark_tpu.data import datasets

    scratch = tempfile.mkdtemp(prefix="tsbench_", dir="/tmp")
    args._data_dir = os.path.join(scratch, "data")
    args._out_dir = os.path.join(scratch, "out")
    os.makedirs(args._data_dir)
    os.makedirs(args._out_dir)

    # From here on a SIGTERM/SIGINT (harness timeout) still produces the one
    # summary line from whatever chunks have landed.
    state = {"chunk": args.chunk, "retries": 0, "gen_s": 0.0}

    def _on_signal(signum, frame):
        for proc in list(_CHILDREN):  # free the TPU tunnel before exiting
            try:
                proc.kill()
            except OSError:
                pass
        _emit(_build_summary(args, t_wall0, state["gen_s"], state["chunk"],
                             state["retries"], note=f"signal {signum}"))
        if not args.keep:
            shutil.rmtree(scratch, ignore_errors=True)
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    gen0 = time.time()
    batch = datasets.m5_like(n_series=args.series, n_days=args.days)
    np.save(os.path.join(args._data_dir, "ds.npy"),
            batch.ds.astype(np.float32))
    np.save(os.path.join(args._data_dir, "y.npy"),
            np.nan_to_num(batch.y).astype(np.float32))
    np.save(os.path.join(args._data_dir, "mask.npy"),
            batch.mask.astype(np.float32))
    np.save(os.path.join(args._data_dir, "reg.npy"),
            batch.regressors.astype(np.float32))
    del batch
    state["gen_s"] = gen_s = time.time() - gen0

    note = None
    preflight_fails = 0  # CONSECUTIVE failures; reset on success
    # Probe before the first attempt (tunnel health unknown) and after any
    # attempt that died without progress; a worker that just produced
    # chunks has proven the tunnel alive, so skip the probe then.
    check_tunnel = os.environ.get("JAX_PLATFORMS", "") not in ("cpu",)
    while True:
        missing = _missing_ranges(_completed_ranges(args._out_dir), args.series)
        if not missing:
            break
        remaining = deadline - time.time()
        if remaining < RESERVE_S:
            note = "fit budget exhausted; partial"
            print(f"[bench] {note}", file=sys.stderr)
            break
        # Client-creation watchdog: don't hand the range to a fit worker
        # that will hang in jax.devices() for the whole stall allowance.
        if check_tunnel:
            if not _tunnel_preflight(timeout=min(90.0, remaining / 3)):
                preflight_fails += 1
                state["retries"] += 1
                print(f"[bench] tunnel preflight failed ({preflight_fails})",
                      file=sys.stderr)
                if preflight_fails >= 3:
                    note = "tpu tunnel wedged (client creation never returned)"
                    print(f"[bench] {note}", file=sys.stderr)
                    break
                time.sleep(
                    min(30.0, max(0.0, deadline - time.time() - RESERVE_S))
                )
                continue
            preflight_fails = 0
            check_tunnel = False
        remaining = deadline - time.time()
        budget = max(60.0, remaining - RESERVE_S)
        before = len(_completed_ranges(args._out_dir))
        rc = _spawn("--_fit", args, [
            "--lo", str(missing[0][0]), "--hi", str(missing[-1][1]),
            "--chunk", str(state["chunk"]), "--max-iters", str(args.max_iters),
            "--segment", str(args.segment),
        ], timeout=budget, progress_timeout=120.0)
        if rc == 0:
            continue  # re-scan; loop exits when nothing is missing
        state["retries"] += 1
        made_progress = len(_completed_ranges(args._out_dir)) > before
        # A death with zero progress puts the tunnel itself under suspicion.
        check_tunnel = (not made_progress and
                        os.environ.get("JAX_PLATFORMS", "") not in ("cpu",))
        # Halve the chunk only when the attempt made no progress at all —
        # a straggler crash (or budget timeout) mid-run keeps the size that
        # was evidently working.
        chunk = state["chunk"]
        new_chunk = chunk if made_progress else max(chunk // 2, MIN_CHUNK)
        print(f"[bench] fit worker died (rc={rc}), chunk {chunk} -> "
              f"{new_chunk}, retry {state['retries']}", file=sys.stderr)
        if chunk <= MIN_CHUNK and state["retries"] > 8 and not made_progress:
            note = "fit worker kept dying at minimum chunk; partial"
            break
        state["chunk"] = new_chunk
        time.sleep(10.0)  # let the crashed TPU worker restart cleanly

    n_done = sum(hi - lo for lo, hi in _completed_ranges(args._out_dir))
    if n_done:
        eval_budget = max(60.0, deadline - time.time() - 15.0)
        _spawn("--_eval", args, ["--n-eval", str(min(512, n_done))],
               timeout=eval_budget)

    _emit(_build_summary(args, t_wall0, gen_s, state["chunk"],
                         state["retries"], note=note))
    if not args.keep:
        shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] in ("--_fit", "--_eval"):
        mode = sys.argv.pop(1)
        ap = argparse.ArgumentParser()
        ap.add_argument("--data", required=True)
        ap.add_argument("--out", required=True)
        ap.add_argument("--lo", type=int, default=0)
        ap.add_argument("--hi", type=int, default=0)
        ap.add_argument("--chunk", type=int, default=2048)
        ap.add_argument("--max-iters", type=int, default=120)
        ap.add_argument("--segment", type=int, default=24)
        ap.add_argument("--n-eval", type=int, default=512)
        a = ap.parse_args()
        sys.exit(fit_worker(a) if mode == "--_fit" else eval_worker(a))
    main()
