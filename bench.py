"""Headline benchmark: M5-scale end-to-end batched fit wall-clock.

Driver metric (BASELINE.json:2): "M5 (30k series) end-to-end fit wall-clock;
sMAPE parity vs CPU".  Target: all 30,490 series in < 60 s on a TPU v5e-8
(BASELINE.json:5).  This machine exposes ONE v5e chip, so the printed
``vs_baseline`` is target_seconds / measured_seconds on a single chip —
values >= 1.0 mean the 8-chip target is beaten with 1/8th of the hardware.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Usage: python bench.py [--series N] [--days N] [--chunk N] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

from tsspark_tpu.utils.platform import honor_env_platforms

# sitecustomize force-selects the axon TPU platform; honor an explicit
# JAX_PLATFORMS env override (e.g. CPU pipeline smoke checks).
honor_env_platforms()

# Persistent compile cache: repeat benches skip XLA compilation, matching the
# steady-state serving pattern (the reference's JVM also amortizes JIT).
_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=30490)
    ap.add_argument("--days", type=int, default=1941)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--max-iters", type=int, default=120)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a quick pipeline check")
    args = ap.parse_args()
    if args.smoke:
        args.series, args.days, args.chunk = 512, 256, 512

    from tsspark_tpu.config import (
        ProphetConfig,
        RegressorConfig,
        SeasonalityConfig,
        SolverConfig,
    )
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.data import datasets
    from tsspark_tpu.eval import metrics

    # Eval config 3 (BASELINE.json:9): holiday regressors + external features.
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )
    solver = SolverConfig(max_iters=args.max_iters)

    gen0 = time.time()
    batch = datasets.m5_like(n_series=args.series, n_days=args.days)
    gen_s = time.time() - gen0

    backend = get_backend("tpu", cfg, solver, chunk_size=args.chunk)

    t0 = time.time()
    y = jnp.asarray(np.nan_to_num(batch.y))
    mask = jnp.asarray(batch.mask)
    reg = jnp.asarray(batch.regressors)
    state = backend.fit(jnp.asarray(batch.ds), y, mask=mask, regressors=reg)
    jax.block_until_ready(state.theta)
    fit_s = time.time() - t0

    # In-sample sMAPE sanity on a subsample (accuracy gate, not the metric).
    n_eval = min(512, args.series)
    fc = backend.predict(
        jax.tree.map(lambda a: a[:n_eval], state),
        jnp.asarray(batch.ds),
        regressors=reg[:n_eval],
        num_samples=0,
    )
    smape = float(
        np.mean(
            np.asarray(
                metrics.smape(y[:n_eval], fc["yhat"], mask=mask[:n_eval])
            )
        )
    )

    target_s = 60.0
    print(
        json.dumps(
            {
                "metric": f"m5_{args.series}x{args.days}_fit_wall_clock",
                "value": round(fit_s, 3),
                "unit": "s",
                "vs_baseline": round(target_s / fit_s, 3),
                "extra": {
                    "smape_insample_mean": round(smape, 3),
                    "converged_frac": round(
                        float(np.asarray(state.converged).mean()), 4
                    ),
                    "datagen_s": round(gen_s, 2),
                    "device": str(jax.devices()[0]),
                    "chunk": args.chunk,
                    "max_iters": args.max_iters,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
