"""Headline benchmark: M5-scale end-to-end batched fit wall-clock.

Driver metric (BASELINE.json:2): "M5 (30k series) end-to-end fit wall-clock;
sMAPE parity vs CPU".  Target: all 30,490 series in < 60 s on a TPU v5e-8
(BASELINE.json:5).  This machine exposes ONE v5e chip, so the printed
``vs_baseline`` is target_seconds / measured_seconds on a single chip —
values >= 1.0 mean the 8-chip target is beaten with 1/8th of the hardware.
``extra.vs_chip_seconds_budget`` additionally reports the chip-second
framing (480 chip-s budget / single-chip seconds spent) — an extrapolation
over the embarrassingly-parallel series axis, kept out of the headline.

Resilience: the process isolation, stall watchdog, tunnel probe loop,
chunk-halving retries, and crash-resumable two-phase fit all live in
``tsspark_tpu.orchestrate`` (they are a LIBRARY capability —
``fit_resilient`` / ``Forecaster(..., resilient=True)``); this file is a
thin caller that adds only the benchmark-specific pieces:

  * the M5-shaped dataset via the shared columnar data plane
    (tsspark_tpu.data.plane: warm cache = pure memmap reads; cold cache
    = background shard ingestion overlapped with the fit, docs/DATA.md),
  * the numerics-scoped resumable scratch key,
  * the CPU eval child (in-sample sMAPE accuracy gate),
  * budget/reserve accounting against the driver's harness timeout, with
    tunnel-down time spent on overlapped CPU eval/prep children,
  * the ONE summary JSON line (also emitted from the SIGTERM handler).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Usage: python bench.py [--series N] [--days N] [--chunk N] [--smoke]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from tsspark_tpu import orchestrate
from tsspark_tpu.perf import load_learned_chunk, summarize_times

TARGET_S = 60.0        # driver target: 60 s on a v5e-8 (BASELINE.json:5)
TARGET_CHIPS = 8       # ... which is a 480 chip-second budget
MIN_CHUNK = 512
# Total wall budget.  The driver harness kills the whole process on ITS
# timeout (observed ~20 min); staying under it is the only way the summary
# line reaches stdout.  Overridable for longer local runs.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "900"))
# Reserve at the end of the budget for the eval child + summary print.
RESERVE_S = 150.0
# The accelerator probe/backoff phase may consume at most this fraction
# of the budget while ZERO chunks have landed; past it the run degrades
# to CPU fit workers so it always banks (and reports) real progress —
# BENCH_r05 spent its full 875 s probing and flushed nothing.
PROBE_BUDGET_FRACTION = 0.3


# The package-wide fit-numerics revision (bump policy documented at the
# constant): one shared value keys BOTH this bench's resumable scratch
# fingerprint and the serve registry's manifest guard, so the two can
# never drift apart.
from tsspark_tpu.config import NUMERICS_REV as BENCH_NUMERICS_REV


def _code_fingerprint() -> str:
    """Hash of the numerics-affecting sources only — keys the resumable
    scratch dir.  Round 3 hashed every package .py plus bench.py itself, so
    ANY commit (even docstring-only) discarded cross-run resume state; now
    only modules on the fit path rotate it: model math (models/), the
    solver (ops/), backend chunking policy (backends/), the config schema,
    and the WHOLE data package (datasets + loaders + plane + ingest — a
    loader/plane change must never resume against stale cached arrays)."""
    import hashlib

    h = hashlib.md5()
    h.update(str(BENCH_NUMERICS_REV).encode())
    pats = [
        os.path.join(REPO, "tsspark_tpu", "models", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "ops", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "backends", "**", "*.py"),
        os.path.join(REPO, "tsspark_tpu", "config.py"),
        os.path.join(REPO, "tsspark_tpu", "data", "**", "*.py"),
    ]
    files = sorted(f for p in pats for f in glob.glob(p, recursive=True))
    for f in files:
        with open(f, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:10]


def _model_config():
    from tsspark_tpu.config import (
        ProphetConfig,
        RegressorConfig,
        SeasonalityConfig,
    )

    # Eval config 3 (BASELINE.json:9): holiday regressors + external features.
    return ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )


# --------------------------------------------------------------------------
# profile mode: trace one solver segment at bench shape
# --------------------------------------------------------------------------

def profile_main(args) -> None:
    """Capture an XLA trace of the steady-state fit at 1024x1941 and print a
    wall-clock breakdown (prep / transfer / init / per-segment / per-iter /
    per-objective-eval).  The trace goes to --profile-dir for TensorBoard's
    profile plugin; the breakdown answers "where do the milliseconds go"
    without opening it (round-2 verdict item 3)."""
    jax = orchestrate._setup_jax_child()
    import numpy as np

    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import m5_rows
    from tsspark_tpu.models.prophet.model import (
        ProphetModel, fit_init_core, fit_segment_core,
    )
    from tsspark_tpu.utils import profiling

    cfg = _model_config()
    solver = SolverConfig(max_iters=120)
    model = ProphetModel(cfg, solver)
    b, t_len, seg = 1024, args.days, args.segment or 24
    timers = profiling.Timers()
    batch = m5_rows(0, b, n_days=t_len)
    with timers.section("prepare_host"):
        data, meta = model.prepare(
            np.asarray(batch.ds, np.float32),
            np.nan_to_num(batch.y).astype(np.float32),
            mask=batch.mask.astype(np.float32),
            regressors=batch.regressors.astype(np.float32),
        )
    with timers.section("transfer"):
        data = jax.tree.map(jax.device_put, data)
        jax.block_until_ready(jax.tree.leaves(data))
    with timers.section("init_incl_compile"):
        st = fit_init_core(data, None, cfg, solver)
        jax.block_until_ready(st.theta)
    with timers.section("segment_warmup_incl_compile"):
        st = fit_segment_core(data, st, cfg, solver, seg)
        jax.block_until_ready(st.theta)
    with timers.section("segment_traced"):
        with profiling.trace(args.profile_dir):
            with profiling.annotate("fit_segment_steady"):
                st = fit_segment_core(data, st, cfg, solver, seg)
                jax.block_until_ready(st.theta)
    seg_s = timers.summary()["segment_traced"]["total_s"]
    # Objective-eval cost: one fan line search evaluates ls_max_steps+1
    # trial rows + 1 value-and-grad per iteration.
    evals_per_iter = solver.ls_max_steps + 2
    print(json.dumps({
        "metric": f"profile_segment_{b}x{t_len}",
        "value": round(seg_s / seg, 4),
        "unit": "s/iter",
        "vs_baseline": 0.0,
        "extra": {
            "timers": timers.summary(),
            "segment_iters": seg,
            "per_objective_eval_ms": round(
                1e3 * seg_s / seg / evals_per_iter, 2
            ),
            "ls_max_steps": solver.ls_max_steps,
            "device": str(jax.devices()[0]),
            "trace_dir": args.profile_dir,
        },
    }), flush=True)


# --------------------------------------------------------------------------
# eval worker (CPU)
# --------------------------------------------------------------------------

def eval_worker(args) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax = orchestrate._setup_jax_child()
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tsspark_tpu.eval import metrics
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState, ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"), mmap_mode="r")
    mask = np.load(os.path.join(args.data, "mask.npy"), mmap_mode="r")
    reg = np.load(os.path.join(args.data, "reg.npy"), mmap_mode="r")

    # Gather enough leading chunks to cover n_eval series.
    files = sorted(glob.glob(os.path.join(args.out, "chunk_*.npz")))
    parts, covered = [], 0
    for f in files:
        parts.append(np.load(f))
        covered = int(os.path.basename(f).split("_")[2].split(".")[0])
        if covered >= args.n_eval:
            break
    n = min(args.n_eval, covered)
    cat = lambda k: jnp.asarray(
        np.concatenate([p[k] for p in parts], axis=0)[:n]
    )
    # Meta stays host numpy float64 (ScalingMeta contract).
    catn = lambda k: np.concatenate([p[k] for p in parts], axis=0)[:n]
    state = FitState(
        theta=cat("theta"),
        meta=ScalingMeta(
            y_scale=catn("y_scale"), floor=catn("floor"),
            ds_start=catn("ds_start"), ds_span=catn("ds_span"),
            reg_mean=catn("reg_mean"), reg_std=catn("reg_std"),
            changepoints=catn("changepoints"),
        ),
        loss=cat("loss"), grad_norm=cat("grad_norm"),
        converged=cat("converged"), n_iters=cat("n_iters"),
    )
    model = ProphetModel(_model_config())
    fc = model.predict(
        state, jnp.asarray(ds),
        regressors=jnp.asarray(np.ascontiguousarray(reg[:n])),
        num_samples=0,
    )
    y_n = jnp.asarray(np.nan_to_num(np.ascontiguousarray(y[:n])))
    smape = float(np.mean(np.asarray(
        metrics.smape(y_n, fc["yhat"], mask=jnp.asarray(
            np.ascontiguousarray(mask[:n])))
    )))
    with open(os.path.join(args.out, "eval.json"), "w") as fh:
        json.dump({"smape_insample_mean": round(smape, 3), "n_eval": n}, fh)
    return 0


# --------------------------------------------------------------------------
# parent orchestrator (no JAX): benchmark-specific wiring only
# --------------------------------------------------------------------------

# Side (nonblocking CPU) children the bench runs during tunnel-down time;
# the SIGTERM handler must kill them along with orchestrate's workers.
_SIDE: dict = {"eval": None, "prep": None}


def _build_summary(args, t_wall0, gen_s, chunk, retries, note=None,
                   probes=None):
    """Summary JSON from whatever is on disk RIGHT NOW — callable at any
    point (including from the SIGTERM handler mid-fit)."""
    import numpy as np

    # Every read guards against files truncated by a killed child: the
    # summary line must come out no matter what state the scratch dir is in.
    times = []
    tpath = os.path.join(args._out_dir, "times.jsonl")
    if os.path.exists(tpath):
        try:
            with open(tpath) as fh:
                for line in fh:
                    if line.strip():
                        times.append(json.loads(line))
        except Exception:
            pass
    phase2_s = sum(t.get("phase2_s", 0.0) for t in times)
    stragglers = sum(t.get("stragglers", 0) for t in times)
    fit_s = sum(t.get("fit_s", 0.0) for t in times) + phase2_s
    done = orchestrate.completed_ranges(args._out_dir)
    n_done = sum(hi - lo for lo, hi in done)

    smape = None
    epath = os.path.join(args._out_dir, "eval.json")
    if os.path.exists(epath):
        try:
            with open(epath) as fh:
                smape = json.load(fh)["smape_insample_mean"]
        except Exception:
            pass

    conv, n_iters_max, status_counts = [], 0, {}
    for f in glob.glob(os.path.join(args._out_dir, "chunk_*.npz")):
        try:
            z = np.load(f)
            conv.append(float(z["converged"].mean()))
            n_iters_max = max(n_iters_max, int(z["n_iters"].max()))
            if "status" in z.files:
                vals, counts = np.unique(z["status"], return_counts=True)
                for v, c in zip(vals, counts):
                    status_counts[int(v)] = status_counts.get(int(v), 0) + int(c)
        except Exception:
            pass

    complete = n_done >= args.series
    # Honest headline semantics (round-2 verdict): ``value`` is the fit wall
    # for the COMPLETED series; when partial, the full-workload projection is
    # reported alongside and vs_baseline is computed against the projection
    # so a partial run can never read better than a finished one.
    projected = fit_s * args.series / n_done if n_done else 0.0
    from tsspark_tpu.obs import context as obs

    from tsspark_tpu.obs.history import git_rev

    wall = time.time() - t_wall0
    extra = {
        "trace_id": obs.trace_id(),
        # Cross-run identity for the history index (obs.history): the
        # regression sentinel only baselines rows with a matching
        # numerics revision, and the git rev names the commit to bisect
        # when a breach fires.
        "numerics_rev": BENCH_NUMERICS_REV,
        "git_rev": git_rev(REPO),
        "config_fingerprint": _code_fingerprint(),
        "smape_insample_mean": smape,
        "converged_frac": round(float(np.mean(conv)), 4) if conv else 0.0,
        "n_iters_max": n_iters_max,
        "status_counts": status_counts,  # keys: ops/lbfgs.STATUS_*
        "series_done": n_done,
        "series_requested": args.series,
        "complete": complete,
        # The fit path that produced this run's coverage ("resident" =
        # mesh-resident single-program, "fileproto" = chunk-file
        # workers).  The history index folds it into the workload key so
        # the regression sentinel never baselines one path's throughput
        # against the other's.
        "fit_path": getattr(args, "_fit_path", "fileproto"),
        "series_per_s": round(n_done / fit_s, 2) if fit_s else 0.0,
        "projected_full_fit_s": round(projected, 1),
        "phase2_s": round(phase2_s, 2),
        "stragglers": stragglers,
        "datagen_s": round(gen_s, 2),
        "datagen_share": round(gen_s / wall, 4) if wall else 0.0,
        "wall_s": round(wall, 1),
        "device": next(
            (t["device"] for t in reversed(times) if "device" in t), None
        ),
        "chunk_final": chunk,
        "resumed": bool(getattr(args, "_resumed", False)),
        "worker_retries": retries,
        "max_iters": args.max_iters,
        "phase1_iters": args.phase1_iters,
    }
    if note:
        extra["note"] = note
    if extra["fit_path"] == "resident" and n_done and fit_s:
        # Path-scoped throughput metric: rides its own
        # [tool.tsspark.slo.bench] budget (resident_series_per_s) so the
        # resident path's series/s is gated on its own baseline history.
        extra["resident_series_per_s"] = extra["series_per_s"]
    # Ingest-overlap accounting (docs/DATA.md): ``datagen_s`` above is
    # the wall the bench actually BLOCKED on data; the ingest driver's
    # own wall ran concurrent with the fit, and the difference is the
    # overlap the plane bought.  Only stamped when THIS run ingested —
    # a warm-cache run must not report the original cold ingest's wall.
    if getattr(args, "_ingest", None) is not None:
        from tsspark_tpu.data.ingest import read_ingest_report

        rep = read_ingest_report(args._data_dir)
        if rep:
            extra["ingest_wall_s"] = rep.get("wall_s")
            extra["ingest_overlap_s"] = round(
                max(0.0, float(rep.get("wall_s") or 0.0) - gen_s), 2
            )
            extra["ingest_processes"] = rep.get("processes")
    # Per-segment perf telemetry (docs/PERF.md): per-chunk width/live/
    # series-per-s/compile-miss rows plus the autotuner's learned state —
    # the block ``python -m tsspark_tpu.perf BENCH_*.json`` prints.
    autotune_state = None
    apath = os.path.join(args._out_dir, "autotune.json")
    if os.path.exists(apath):
        try:
            with open(apath) as fh:
                autotune_state = json.load(fh)
        except Exception:
            pass
    extra["perf"] = summarize_times(times, autotune_state)
    if probes and probes.get("n"):
        # Wedge-resilience audit trail: how many tunnel probes ran, how
        # many failed, and the wall-offset of the last one — proof the
        # probe loop ran to the reserve on a fully-wedged budget.
        extra["tunnel_probes"] = probes["n"]
        extra["tunnel_probe_fails"] = probes["fails"]
        extra["last_probe_at_s"] = probes["last_t"]
    # vs_baseline keeps the STRICT round-1/2 definition — 60 s target /
    # measured single-chip seconds, i.e. >= 1.0 means the whole 8-chip
    # target is beaten on one chip — so the headline stays conservative
    # and comparable across rounds.  The chip-second framing (the 60 s
    # v5e-8 target = 480 chip-seconds; the workload is embarrassingly
    # parallel over series chunks, multi-chip path exercised by
    # tests/test_sharding.py + dryrun_multichip) is reported alongside in
    # ``extra`` — it is an extrapolation this one-chip machine cannot
    # measure, so it must not be the headline ratio.
    extra["chip_seconds_budget"] = TARGET_S * TARGET_CHIPS
    extra["vs_chip_seconds_budget"] = (
        round(TARGET_S * TARGET_CHIPS / projected, 3) if projected else 0.0
    )
    return {
        "metric": f"m5_{args.series}x{args.days}_fit_wall_clock",
        "value": round(fit_s, 3),
        "unit": "s",
        "vs_baseline": round(TARGET_S / projected, 3) if projected else 0.0,
        "extra": extra,
    }


_EMITTED = False


def _emit(summary) -> None:
    """Print the ONE summary line exactly once."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(summary), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--series", type=int, default=30490)
    ap.add_argument("--days", type=int, default=1941)
    # 1024 is the largest chunk that has survived the TPU tunnel's crash
    # envelope in practice; 2048 has never completed a driver run.
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--max-iters", type=int, default=120)
    ap.add_argument("--segment", type=int, default=24,
                    help="solver iterations per XLA dispatch (0 = one "
                         "program for the full solve)")
    ap.add_argument("--phase1-iters", type=int, default=12,
                    help="lockstep depth of the main pass; unconverged "
                         "series are compacted into one full-depth "
                         "follow-up batch (0 = single-phase)")
    ap.add_argument("--no-phase1-tune", action="store_true",
                    help="pin phase-1 depth to --phase1-iters instead of "
                         "adapting it from chunk 0's convergence (A/B "
                         "instrument: the tuner deepens 12 -> 24 on the "
                         "M5 shape and the payoff is under measurement)")
    ap.add_argument("--no-autotune", action="store_true",
                    help="pin the chunk size to --chunk instead of "
                         "hill-climbing it online from measured series/s "
                         "(tsspark_tpu.perf.ChunkAutotuner)")
    ap.add_argument("--resident", action="store_true",
                    help="mesh-resident single-program fit "
                         "(tsspark_tpu.resident): when a device mesh is "
                         "usable, run the whole fit as sharded in-process "
                         "dispatches fed from the plane memmaps — no "
                         "per-chunk process spawn or prep files; falls "
                         "back to the chunk-file protocol on a meshless "
                         "box (docs/PERF.md \"Mesh-resident fit\")")
    ap.add_argument("--scale", default=None, metavar="RUNG",
                    help="run the million-series scale ladder instead "
                         "of the M5 fit benchmark: one rung "
                         "('smoke'/'30k'/'100k'/'1m') or 'ladder' for "
                         "30k -> 100k -> 1m — ingest -> resident fit "
                         "-> mmap-snapshot publish -> pool serve "
                         "against one data plane, emitting "
                         "SCALE_*.json (docs/SERVING.md, 'Snapshot "
                         "plane & memory model')")
    ap.add_argument("--delta", nargs="?", const="smoke", default=None,
                    metavar="RUNG",
                    help="delta-refit churn sweep (tsspark_tpu.refit) "
                         "at a scale-ladder rung ('smoke' default, or "
                         "'30k'): cold resident fit + publish once, "
                         "then per churn fraction land a synthetic "
                         "row-advance, run one warm delta-refit cycle "
                         "(detect -> fit changed set -> copy-forward "
                         "delta publish -> materialized flip), and "
                         "stamp delta_series_per_s / delta_wall_frac "
                         "into BENCH_delta_* reports (docs/PERF.md "
                         "\"Delta refit\")")
    ap.add_argument("--churns", default=None,
                    help="comma-separated churn fractions for --delta "
                         "(default 0.01,0.1,0.3)")
    ap.add_argument("--freshness", nargs="?", const="smoke",
                    default=None, metavar="RUNG",
                    help="sustained-churn freshness stream "
                         "(tsspark_tpu.sched) at a scale rung ('smoke' "
                         "default, or '30k'): land a hot-biased delta "
                         "stream while the always-on scheduler runs "
                         "serialized then pipelined cycles, measuring "
                         "steady-state data-to-forecast freshness "
                         "p50/p95 (docs/PERF.md \"Continuous refit & "
                         "freshness\"); emits BENCH_freshness_*")
    ap.add_argument("--serveplane", nargs="?", const=48, default=None,
                    type=int, metavar="N_SERIES",
                    help="forecast-plane serve benchmark "
                         "(tsspark_tpu.serve.planebench): hot-read "
                         "req/s served from the materialized plane vs "
                         "the compute path, the zero-dispatch read "
                         "p50/p99, and 1-replica TTFR cold vs "
                         "AOT-bank-warmed; emits BENCH_serveplane_* "
                         "judged under [tool.tsspark.slo.serve] "
                         "(docs/SERVE.md \"Forecast plane\")")
    ap.add_argument("--serveplane-requests", type=int, default=2000,
                    help="--serveplane: hot reads through the plane "
                         "engine (the dispatch arm replays 1/8th)")
    ap.add_argument("--uncertainty", nargs="?", const=24, default=None,
                    type=int, metavar="N_SERIES",
                    help="uncertainty-tier calibration benchmark "
                         "(tsspark_tpu.uncertainty.calibrate): ADVI "
                         "fit throughput, quantile-plane publish + "
                         "mmap interval-read p50/p99, empirical-vs-"
                         "nominal coverage on held-out data, and a "
                         "small NUTS gold audit; emits "
                         "BENCH_uncertainty_* judged under "
                         "[tool.tsspark.slo.calibration] "
                         "(docs/UNCERTAINTY.md)")
    ap.add_argument("--reuse-cold", default=None, metavar="DIR",
                    help="for --delta/--freshness: reuse (or record) "
                         "the cold fit+publish reference under DIR so "
                         "repeated sweeps amortize the cold fit")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for a quick pipeline check")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir (debugging)")
    ap.add_argument("--profile", action="store_true",
                    help="trace one steady-state solver segment instead of "
                         "running the benchmark")
    ap.add_argument("--profile-dir", default=os.path.join(REPO, "profiles"))
    args = ap.parse_args()
    if args.profile:
        profile_main(args)
        return
    if args.delta:
        # Same mesh forcing as --resident/--scale: the delta cycles run
        # the resident fit path in-process.
        from tsspark_tpu.resident import force_virtual_host_mesh

        force_virtual_host_mesh()
        from tsspark_tpu import refit

        reports = refit.run_delta_bench(
            args.delta, churns=refit.parse_churns(args.churns),
            reuse_cold=args.reuse_cold,
        )
        sys.exit(0 if refit.sweep_ok(reports) else 1)
    if args.serveplane:
        # Same device pinning as `python -m tsspark_tpu.serve`: the
        # serve bench must never block on a wedged accelerator tunnel.
        if os.environ.get("TSSPARK_SERVE_DEVICE", "cpu") == "cpu":
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            import jax

            jax.config.update("jax_platforms", "cpu")
        import argparse as _argparse

        from tsspark_tpu.serve import planebench

        sys.exit(planebench.run_serveplane_bench(_argparse.Namespace(
            series=args.serveplane,
            requests=args.serveplane_requests,
            seed=0, dir=None, report=None, data_root=None,
        )))
    if args.uncertainty:
        # Same device pinning as --serveplane: the calibration smoke is
        # a serve-tier workload and must not block on an accelerator.
        if os.environ.get("TSSPARK_SERVE_DEVICE", "cpu") == "cpu":
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            import jax

            jax.config.update("jax_platforms", "cpu")
        import argparse as _argparse

        from tsspark_tpu.uncertainty import calibrate

        sys.exit(calibrate.run_uncertainty_bench(_argparse.Namespace(
            series=args.uncertainty, seed=0, dir=None, report=None,
            data_root=None,
        )))
    if args.freshness:
        from tsspark_tpu.resident import force_virtual_host_mesh

        force_virtual_host_mesh()
        from tsspark_tpu import refit, sched

        reports = sched.run_freshness_bench(
            args.freshness, reuse_cold=args.reuse_cold,
        )
        sys.exit(0 if refit.sweep_ok(reports) else 1)
    if args.scale:
        # The ladder needs the virtual host mesh for the resident fit
        # path, same forcing as --resident (before anything imports
        # jax).
        from tsspark_tpu.resident import force_virtual_host_mesh

        force_virtual_host_mesh()
        from tsspark_tpu import bench_scale

        if args.scale == "ladder":
            reports = bench_scale.run_ladder()
        else:
            reports = [bench_scale.run_rung(args.scale)]
        sys.exit(0 if all(r.get("complete")
                          and r.get("sentinel_ok", True)
                          for r in reports) else 1)
    if args.smoke:
        args.series, args.days, args.chunk = 512, 256, 512
    if args.resident:
        # The resident path needs a mesh; on a CPU-pinned run that is
        # the virtual host-device mesh (same forcing as tests/chaos).
        # Must land in os.environ before anything imports jax — the
        # bench parent stays jax-free until run_resident (importing
        # tsspark_tpu.resident is jax-free at module level).
        from tsspark_tpu.resident import force_virtual_host_mesh

        force_virtual_host_mesh()
        if args.segment:
            # run_resident has no segmented mode: each wave is ONE
            # sharded dispatch, with per-wave flushes/heartbeats giving
            # the bounded-progress signal --segment buys the file
            # protocol.  Say so instead of silently dropping the flag.
            print(
                "[bench] --resident ignores --segment (waves are single "
                "dispatches; per-wave flushes bound progress instead)",
                file=sys.stderr,
            )

    t_wall0 = time.time()
    deadline = t_wall0 + BUDGET_S
    import numpy as np

    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import plane
    from tsspark_tpu.data.ingest import IngestDriver

    # Persistent, code-fingerprinted scratch: a run killed by the harness
    # timeout (or a wedged tunnel) resumes from its completed chunk files on
    # the next invocation instead of starting over — per-chunk saves and the
    # phase-2 marker are already idempotent.  Any source change rotates the
    # fingerprint so stale results can never leak across code versions.
    scratch = os.path.join(
        "/tmp",
        f"tsbench_run_{args.series}x{args.days}_c{args.chunk}"
        f"_p{args.phase1_iters}{'f' if args.no_phase1_tune else ''}"
        f"{'na' if args.no_autotune else ''}"
        f"{'res' if args.resident else ''}"
        f"_{_code_fingerprint()}",
    )
    args._out_dir = os.path.join(scratch, "out")
    resumed = os.path.isdir(args._out_dir) and bool(
        glob.glob(os.path.join(args._out_dir, "chunk_*.npz"))
    )
    args._resumed = resumed
    if resumed:
        print(f"[bench] resuming from {args._out_dir}", file=sys.stderr)
    # Stale scratch dirs (other fingerprints / shapes) have no resume value
    # — but only reap ones untouched for hours: a CONCURRENT bench with a
    # different shape owns a freshly-modified dir, and deleting it would
    # destroy that run's chunk files mid-flight.
    # /tmp/tsbench_data_* is the RETIRED private datagen cache (replaced
    # by the shared plane, docs/DATA.md) — nothing writes it anymore, so
    # leftovers from older code are reaped with the stale scratch dirs.
    for d in glob.glob("/tmp/tsbench_run_*") + \
            glob.glob("/tmp/tsbench_data_*") + \
            glob.glob("/tmp/tsbench_datagen_*"):
        if os.path.abspath(d) == os.path.abspath(scratch):
            continue
        try:
            newest = max(
                (os.path.getmtime(p) for p in
                 glob.glob(os.path.join(d, "**"), recursive=True)),
                default=os.path.getmtime(d),
            )
        except OSError:
            continue
        if time.time() - newest > 6 * 3600:
            shutil.rmtree(d, ignore_errors=True)
    os.makedirs(args._out_dir, exist_ok=True)
    # One observability trace per bench run: worker claim/fit/land spans
    # land in the scratch's spans.jsonl, and the summary is stamped with
    # the trace id so BENCH artifacts join the run ledger
    # (python -m tsspark_tpu.obs report <out dir>).
    from tsspark_tpu.obs import context as obs

    obs.start_run(os.path.join(args._out_dir, "spans.jsonl"))
    orchestrate.save_run_config(
        args._out_dir, _model_config(),
        SolverConfig(max_iters=args.max_iters),
    )

    # From here on a SIGTERM/SIGINT (harness timeout) still produces the one
    # summary line from whatever chunks have landed; the scratch dir is
    # KEPT on signal so the next run resumes.
    state = {"chunk": args.chunk, "retries": 0, "gen_s": 0.0,
             "probes": {"n": 0, "fails": 0, "last_t": 0.0}}

    def _on_signal(signum, frame):
        orchestrate.kill_children()  # free the TPU tunnel before exiting
        if getattr(args, "_ingest", None) is not None:
            args._ingest.kill()  # landed shards persist; ingest resumes
        for proc in _SIDE.values():
            if proc is not None:
                try:
                    proc.kill()
                except OSError:
                    pass
        _emit(_build_summary(args, t_wall0, state["gen_s"], state["chunk"],
                             state["retries"], note=f"signal {signum}",
                             probes=state["probes"]))
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # Data rides the shared columnar plane (tsspark_tpu.data.plane;
    # docs/DATA.md) — the ad-hoc /tmp npy cache this block used to
    # maintain is gone.  Warm cache: the manifest hits and the fit
    # starts on pure memmap reads.  Cold cache: a background ingest
    # pool produces shards while the fit workers consume already-landed
    # coverage, so generation OVERLAPS fitting instead of preceding it
    # (BENCH_builder_r06 spent 74% of its wall generating data first).
    gen0 = time.time()
    spec = plane.DatasetSpec(
        generator="m5", n_series=args.series, n_timesteps=args.days,
        seed=2,
    )
    args._data_dir = plane.dataset_dir(spec)
    args._ingest = None
    if not plane.is_complete(args._data_dir):
        from tsspark_tpu.obs.metrics import DEFAULT as _METRICS

        _METRICS.counter("tsspark_datagen_cache_misses_total").inc()
        args._ingest = IngestDriver.start(spec)
        print(f"[bench] cold data cache; ingesting {spec.cache_key()} "
              f"overlapped with the fit", file=sys.stderr)
    else:
        from tsspark_tpu.obs.metrics import DEFAULT as _METRICS

        _METRICS.counter("tsspark_datagen_cache_hits_total").inc()
    # gen_s is the time the BENCH was blocked on data (the warm path's
    # manifest check is ~ms); the ingest wall itself lands in extras as
    # ingest_wall_s, overlapped with fitting.
    state["gen_s"] = gen_s = time.time() - gen0

    def _eval_covered() -> bool:
        """eval.json exists AND covers the series the final eval would:
        an overlapped eval started mid-wedge may have scored only the
        chunks landed at that moment, and must not satisfy the end-of-run
        obligation for a run that went on to complete more."""
        try:
            with open(os.path.join(args._out_dir, "eval.json")) as fh:
                have = json.load(fh).get("n_eval", 0)
        except (OSError, ValueError):
            return False
        n_done = sum(
            hi - lo
            for lo, hi in orchestrate.completed_ranges(args._out_dir)
        )
        return n_done > 0 and have >= min(512, n_done)

    def _reserve() -> float:
        """End-of-run time to protect.  Shrinks as the remaining exit
        obligations shrink: with a covering eval.json on disk (or nothing
        evaluable) only the summary print is left, so the probe/fit loop
        may run nearly to the deadline — the round-3 failure mode was
        surrendering with ~500 s left while a fixed 150 s reserve sat
        unused."""
        if _eval_covered():
            return 25.0
        if not orchestrate.completed_ranges(args._out_dir):
            return 25.0  # nothing to eval; probing is the best use of time
        ep = _SIDE.get("eval")
        if ep is not None and ep.poll() is None:
            return 60.0  # eval already running concurrently
        return RESERVE_S

    def _side_child(kind: str, cmd: list) -> None:
        """Nonblocking CPU child, JAX forced to CPU so a wedged TPU tunnel
        cannot block it.  At most one of each kind."""
        proc = _SIDE.get(kind)
        if proc is not None and proc.poll() is None:
            return
        _SIDE[kind] = subprocess.Popen(
            cmd, stdout=sys.stderr,
            env=orchestrate._child_env(force_cpu=True),
        )

    def _overlap_cpu_work() -> None:
        """Tunnel-down time is spent on the CPU-side work the run needs
        anyway: eval of already-landed chunks and pre-packing pending chunk
        payloads, so a late tunnel recovery converts into chunks instantly."""
        done = orchestrate.completed_ranges(args._out_dir)
        n_done = sum(hi - lo for lo, hi in done)
        if n_done and not _eval_covered():
            _side_child("eval", [
                sys.executable, os.path.abspath(__file__), "--_eval",
                "--data", args._data_dir, "--out", args._out_dir,
                "--n-eval", str(min(512, n_done)),
            ])
        if orchestrate.missing_ranges(done, args.series):
            # Pre-pack at the width the fit worker will actually request
            # (it rejects width-mismatched prep payloads): the tuner's
            # learned width when one exists, else — when autotuning — the
            # tuner's starting floor (a fresh run's first claims are
            # floor-sized, so cap-width payloads would all be rejected).
            # Clamped to the current (possibly crash-halved) chunk cap,
            # above which the tuner can never dispatch.
            learned = load_learned_chunk(
                os.path.join(args._out_dir, "autotune.json")
            )
            if learned:
                prep_chunk = min(learned, state["chunk"])
            elif not args.no_autotune:
                prep_chunk = min(128, state["chunk"])
            else:
                prep_chunk = state["chunk"]
            _side_child("prep", [
                sys.executable, "-m", "tsspark_tpu.orchestrate", "--_prep",
                "--data", args._data_dir, "--out", args._out_dir,
                "--series", str(args.series),
                "--chunk", str(prep_chunk),
                "--max-ahead", "6",
            ])

    args._fit_path = "fileproto"
    if args.resident:
        # Stamp the path BEFORE the run: a SIGTERM mid-fit emits the
        # summary from the handler, and a resident run's partial row
        # must never land under the fileproto workload key (the
        # cross-path baseline mixing the key exists to prevent).  The
        # meshless fallback corrects it after the run returns.
        args._fit_path = "resident"
        # Mesh-resident single-program fit (tsspark_tpu.resident): runs
        # IN-PROCESS (this parent imports JAX), checkpoints through the
        # same chunk/lease protocol — a crash resumes from the landed
        # flushes on the next invocation; a meshless box degrades to the
        # chunk-file workers inside run_resident with one warning.
        from tsspark_tpu import resident as resident_mod

        try:
            result = resident_mod.run_resident(
                data_dir=args._data_dir,
                out_dir=args._out_dir,
                series=args.series,
                chunk=args.chunk,
                phase1_iters=args.phase1_iters,
                no_phase1_tune=args.no_phase1_tune,
                autotune=not args.no_autotune,
                deadline=deadline,
                reserve=_reserve,
                state=state,
                # A meshless/wedged box degrades to the file protocol
                # WITH the bench's usual resilience wiring (probe
                # budget, overlapped CPU work, budget-decides-retries)
                # — not the library defaults.
                fallback_opts=dict(
                    min_chunk=MIN_CHUNK,
                    segment=args.segment,
                    probe_budget_s=BUDGET_S * PROBE_BUDGET_FRACTION,
                    on_idle=_overlap_cpu_work,
                    progress_timeout=90.0,
                    max_fruitless_retries=None,
                ),
            )
        except Exception as e:  # the one-JSON-line contract must hold
            print(f"[bench] resident fit failed: {e!r}; summary covers "
                  f"the landed coverage", file=sys.stderr)
            result = dict(state, complete=False, fit_path="resident")
        args._fit_path = result.get("fit_path", "resident")
    else:
        result = orchestrate.run_resilient(
            data_dir=args._data_dir,
            out_dir=args._out_dir,
            series=args.series,
            chunk=args.chunk,
            min_chunk=MIN_CHUNK,
            segment=args.segment,
            phase1_iters=args.phase1_iters,
            no_phase1_tune=args.no_phase1_tune,
            # Online chunk autotuner: start small (first chunk flushes in
            # seconds, whatever the runtime), hill-climb series/s along
            # the pow-2 ladder up to --chunk, persist the learned size
            # for resumes (tsspark_tpu.perf.ChunkAutotuner).
            autotune=not args.no_autotune,
            # Bound the probe/backoff phase: a tunnel-down run degrades
            # to CPU workers after this share of the budget instead of
            # probing to the reserve with nothing flushed (BENCH_r05).
            probe_budget_s=BUDGET_S * PROBE_BUDGET_FRACTION,
            deadline=deadline,
            reserve=_reserve,
            on_idle=_overlap_cpu_work,
            progress_timeout=90.0,
            state=state,
            # The BUDGET decides when this run stops (round-3 verdict
            # item 1: a crash loop is re-probed and retried until the
            # reserve), never a retry counter — and an uncaught
            # RuntimeError here would break the one-JSON-line contract.
            max_fruitless_retries=None,
        )
    note = None if result.get("complete") else "fit budget exhausted; partial"
    if result.get("degraded_cpu"):
        note = ((note + "; ") if note else "") + \
            "degraded to CPU workers after probe budget"
    if note:
        print(f"[bench] {note}", file=sys.stderr)

    n_done = sum(
        hi - lo for lo, hi in orchestrate.completed_ranges(args._out_dir)
    )
    ep = _SIDE.get("eval")
    if ep is not None and ep.poll() is None:
        # An overlapped eval is already in flight; give it the remaining
        # budget instead of starting a duplicate.
        try:
            ep.wait(timeout=max(15.0, deadline - time.time() - 15.0))
        except subprocess.TimeoutExpired:
            ep.kill()
            ep.wait()  # reap, or _side_child sees it as still running
    # Re-run when coverage grew past what an overlapped mid-wedge eval
    # scored (eval.json records its n_eval; the worker overwrites it) —
    # through the same _side_child plumbing, waited on with the leftover
    # budget.
    if n_done and not _eval_covered():
        _side_child("eval", [
            sys.executable, os.path.abspath(__file__), "--_eval",
            "--data", args._data_dir, "--out", args._out_dir,
            "--n-eval", str(min(512, n_done)),
        ])
        ep = _SIDE.get("eval")
        try:
            ep.wait(timeout=max(60.0, deadline - time.time() - 15.0))
        except subprocess.TimeoutExpired:
            ep.kill()
            ep.wait()
    pp = _SIDE.get("prep")
    if pp is not None and pp.poll() is None:
        pp.kill()
    ing = getattr(args, "_ingest", None)
    if ing is not None and ing.alive():
        # A complete fit implies every consumed shard landed; whatever
        # the driver still owes (the tail past --series, the manifest)
        # finishes in seconds — give it a short grace, then kill (the
        # sentinel-gated cache resumes next run either way).
        t_block0 = time.time()
        if ing.wait(timeout=min(30.0,
                                max(5.0, deadline - time.time() - 10.0))
                    ) is None:
            ing.kill()
        gen_s += time.time() - t_block0
        state["gen_s"] = gen_s

    summary = _build_summary(args, t_wall0, gen_s, state["chunk"],
                             state["retries"], note=note,
                             probes=state["probes"])
    _emit(summary)
    # Remove the scratch only after a COMPLETE run: partial results are the
    # resume state for the next invocation (fingerprint-keyed, so a code
    # change invalidates them anyway).
    if not args.keep and summary["extra"].get("complete"):
        shutil.rmtree(scratch, ignore_errors=True)
    # Regression sentinel post-step (docs/OBSERVABILITY.md "Trajectory
    # & SLOs"): the summary joins RUNHISTORY.jsonl and is judged
    # against the rolling baseline; a throughput/first-flush/accuracy
    # breach exits nonzero AFTER the one summary line is out, so the
    # run that introduced a regression fails loudly while the artifact
    # contract stays intact.  TSSPARK_SENTINEL=0 opts out; sentinel
    # machinery failures only warn — they must never mask the summary.
    if os.environ.get("TSSPARK_SENTINEL", "1") != "0":
        try:
            from tsspark_tpu.obs import regress

            verdict = regress.sentinel_report(
                summary, source=f"bench:{summary['metric']}"
            )
            if verdict is not None:
                print(f"[bench] {regress.summarize(verdict)}",
                      file=sys.stderr)
                if not verdict["ok"]:
                    sys.exit(1)
        except SystemExit:
            raise
        except Exception as e:
            print(f"[bench] sentinel skipped: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--_eval":
        sys.argv.pop(1)
        ap = argparse.ArgumentParser()
        ap.add_argument("--data", required=True)
        ap.add_argument("--out", required=True)
        ap.add_argument("--n-eval", type=int, default=512)
        sys.exit(eval_worker(ap.parse_args()))
    main()
