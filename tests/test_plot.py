"""Plotting module: figures render with the right artists (Agg backend)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pandas as pd
import pytest

from tsspark_tpu import Forecaster, ProphetConfig, SeasonalityConfig
from tsspark_tpu import plot as plot_mod


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(1)
    n = 180
    ds = pd.date_range("2024-01-01", periods=n, freq="D")
    t = np.arange(n)
    df = pd.concat([
        pd.DataFrame({"series_id": f"s{i}", "ds": ds,
                      "y": 6 + 0.03 * t + 2 * np.sin(2 * np.pi * t / 7)
                           + rng.normal(0, 0.3, n)})
        for i in range(2)
    ], ignore_index=True)
    fc = Forecaster(ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=4,
    ))
    fc.fit(df)
    return fc, df


def test_plot_forecast(fitted):
    fc, df = fitted
    out = fc.predict(horizon=21, include_history=True)
    ax = plot_mod.plot_forecast(out, history_df=df, series_id="s1")
    assert ax.get_title() == "s1"
    # forecast line + interval band + observed points all present
    assert len(ax.lines) >= 2
    assert len(ax.collections) >= 1
    ax.figure.canvas.draw()  # renders without error
    import matplotlib.pyplot as plt

    plt.close(ax.figure)


def test_plot_forecast_unknown_series(fitted):
    fc, _ = fitted
    out = fc.predict(horizon=7)
    with pytest.raises(ValueError, match="not present"):
        plot_mod.plot_forecast(out, series_id="nope")


def test_plot_components(fitted):
    fc, _ = fitted
    ds, comps = fc.components(horizon=14)
    assert "weekly" in comps
    assert comps["weekly"].shape[0] == 2
    fig = plot_mod.plot_components(comps, ds, series_index=0)
    labels = [ax.get_ylabel() for ax in fig.axes]
    assert "weekly" in labels
    fig.canvas.draw()
    import matplotlib.pyplot as plt

    plt.close(fig)


def test_plot_cross_validation_metric(tmp_path):
    import pandas as pd
    from tsspark_tpu import plot

    rng = np.random.default_rng(3)
    n = 60
    cv = pd.DataFrame({
        "series_id": "s0",
        "ds": np.tile(np.arange(10.0, 10.0 + n // 3), 3),
        "cutoff": np.repeat([9.0, 8.0, 7.0], n // 3),
        "y": rng.normal(10, 1, n),
        "yhat": rng.normal(10, 1, n),
        "yhat_lower": np.full(n, 5.0),
        "yhat_upper": np.full(n, 15.0),
    })
    ax = plot.plot_cross_validation_metric(cv, metric="smape")
    assert ax.get_ylabel() == "smape"
    ax2 = plot.plot_cross_validation_metric(cv, metric="coverage")
    assert ax2.get_ylabel() == "coverage"
    with pytest.raises(ValueError, match="unknown metric"):
        plot.plot_cross_validation_metric(cv, metric="nope")


def test_add_changepoints_to_plot():
    import pandas as pd

    from tsspark_tpu.config import ProphetConfig, SolverConfig
    from tsspark_tpu.frame import Forecaster
    from tsspark_tpu import plot

    rng = np.random.default_rng(3)
    n = 200
    ds = pd.date_range("2022-01-01", periods=n, freq="D")
    t = np.arange(n)
    y = 5 + 0.05 * t - 0.12 * np.maximum(t - 100, 0) + rng.normal(0, 0.1, n)
    df = pd.DataFrame({"series_id": "a", "ds": ds, "y": y})
    fc = Forecaster(
        ProphetConfig(seasonalities=(), n_changepoints=8,
                      changepoint_prior_scale=0.5),
        SolverConfig(max_iters=60), backend="tpu",
    ).fit(df)
    cps = fc.changepoints_df()
    assert len(cps) == 8 and (cps["ds"] > df["ds"].min()).all()
    # The induced break is large; at least one changepoint is significant.
    assert cps["abs_delta"].max() > 0.01
    out = fc.predict(horizon=10)
    ax = plot.plot_forecast(out, history_df=df)
    plot.add_changepoints_to_plot(ax, fc)
    assert len(ax.lines) > 1  # forecast line + at least one changepoint
