"""Prophet capability parity: auto-seasonality selection, conditional
seasonalities, and observed-quantile changepoint placement (round-3 feature
set; upstream Prophet semantics, TPU-first batched implementation)."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from tsspark_tpu import Forecaster, ProphetConfig, SeasonalityConfig
from tsspark_tpu.config import DAILY, WEEKLY, YEARLY, SolverConfig
from tsspark_tpu.models.prophet import seasonality as seas_mod
from tsspark_tpu.models.prophet.design import (
    prepare_fit_data,
    quantile_changepoints,
)
from tsspark_tpu.models.prophet.model import ProphetModel


# -- auto-seasonality ---------------------------------------------------------

def test_auto_seasonalities_rule():
    daily_3y = np.arange(0, 1100.0)
    assert seas_mod.auto_seasonalities(daily_3y) == (YEARLY, WEEKLY)
    daily_1m = np.arange(0, 30.0)
    assert seas_mod.auto_seasonalities(daily_1m) == (WEEKLY,)
    hourly_3d = np.arange(0, 3.0, 1 / 24)
    assert seas_mod.auto_seasonalities(hourly_3d) == (DAILY,)
    hourly_3w = np.arange(0, 21.0, 1 / 24)
    assert seas_mod.auto_seasonalities(hourly_3w) == (WEEKLY, DAILY)
    weekly_5y = np.arange(0, 1900.0, 7.0)  # spacing 7d: no weekly component
    assert seas_mod.auto_seasonalities(weekly_5y) == (YEARLY,)
    assert seas_mod.auto_seasonalities(np.asarray([0.0])) == ()


def test_forecaster_auto_seasonality_resolves_at_fit():
    rng = np.random.default_rng(0)
    t = np.arange(800.0)
    y = 10 + 2 * np.sin(2 * np.pi * t / 7) + rng.normal(0, 0.1, t.size)
    df = pd.DataFrame({"series_id": "s0", "ds": t, "y": y})
    fc = Forecaster(
        ProphetConfig(n_changepoints=5), backend="tpu", auto_seasonality=True
    )
    fc.fit(df)
    # 800 daily points: yearly (span >= 730) + weekly (spacing < 7).
    assert tuple(s.name for s in fc.config.seasonalities) == (
        "yearly", "weekly",
    )
    out = fc.predict(horizon=7)
    assert np.isfinite(out["yhat"].to_numpy()).all()


# -- conditional seasonalities ------------------------------------------------

def test_conditional_seasonality_gates_component():
    # Weekly pattern exists ONLY in the "on" regime (first half).  A gated
    # weekly seasonality must (a) fit it there and (b) contribute exactly
    # zero where the condition is off.
    rng = np.random.default_rng(1)
    n = 400
    t = np.arange(float(n))
    on = (t < n // 2).astype(float)
    y = 5.0 + 0.01 * t + on * 2.0 * np.sin(2 * np.pi * t / 7) \
        + rng.normal(0, 0.05, n)
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("weekly_on", 7.0, 3, condition_name="on"),
        ),
        n_changepoints=4,
    )
    model = ProphetModel(cfg, SolverConfig(max_iters=200))
    ds = jnp.asarray(t, jnp.float32)
    y_j = jnp.asarray(y[None, :], jnp.float32)
    cond = {"on": on[None, :]}
    state = model.fit(ds, y_j, conditions=cond)
    comps = model.components(state, ds, conditions=cond)
    weekly = np.asarray(comps["weekly_on"])[0]
    np.testing.assert_allclose(weekly[n // 2:], 0.0, atol=1e-6)
    assert np.abs(weekly[: n // 2]).max() > 1.0
    # The fit must actually capture the on-regime pattern.
    fc = model.predict(state, ds, conditions=cond)
    resid = np.asarray(fc["yhat"])[0] - y
    assert np.abs(resid).mean() < 0.15


def test_conditional_seasonality_requires_values():
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("weekly_on", 7.0, 2, condition_name="on"),
        ),
        n_changepoints=2,
    )
    model = ProphetModel(cfg)
    ds = jnp.arange(50, dtype=jnp.float32)
    y = jnp.ones((1, 50))
    with pytest.raises(ValueError, match="condition"):
        model.fit(ds, y)


def test_conditional_seasonality_through_forecaster():
    rng = np.random.default_rng(2)
    n = 300
    t = np.arange(float(n))
    weekend = ((t.astype(int) % 7) >= 5).astype(float)
    y = 3.0 + weekend * 1.5 * np.sin(2 * np.pi * t / 7) \
        + rng.normal(0, 0.05, n)
    df = pd.DataFrame(
        {"series_id": "s0", "ds": t, "y": y, "is_weekend": weekend}
    )
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("wk_weekend", 7.0, 3,
                              condition_name="is_weekend"),
        ),
        n_changepoints=3,
    )
    fc = Forecaster(cfg, backend="tpu").fit(df)
    # horizon-only predict cannot know future condition values.
    with pytest.raises(ValueError, match="condition"):
        fc.predict(horizon=7)
    fut_t = np.arange(float(n), float(n) + 14)
    fut = pd.DataFrame({
        "series_id": "s0", "ds": fut_t,
        "is_weekend": ((fut_t.astype(int) % 7) >= 5).astype(float),
    })
    out = fc.predict(future_df=fut)
    assert np.isfinite(out["yhat"].to_numpy()).all()


# -- observed-quantile changepoints ------------------------------------------

def test_quantile_changepoints_follow_observation_density():
    # 200 observations in the first 10% of scaled time, 20 in the rest:
    # quantile placement must concentrate changepoints where the data is.
    t = np.concatenate([
        np.linspace(0.0, 0.1, 200), np.linspace(0.1, 1.0, 20),
    ])[None, :]
    mask = np.ones_like(t)
    cps = quantile_changepoints(t, mask, 10, changepoint_range=0.9)
    assert cps.shape == (1, 10)
    assert (np.diff(cps[0]) >= 0).all()
    # ~90% of the observations sit below t=0.1, so most changepoints must.
    assert (cps[0] < 0.11).sum() >= 7
    # Uniform placement would put at most 2 of 10 there.


def test_quantile_placement_matches_uniform_on_regular_grid():
    rng = np.random.default_rng(3)
    n = 300
    t = np.arange(float(n))
    y = (4 + 0.02 * t + np.sin(2 * np.pi * t / 7)
         + rng.normal(0, 0.1, (2, n))).astype(np.float32)
    base = dict(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=6,
    )
    m_u = ProphetModel(ProphetConfig(**base))
    m_q = ProphetModel(
        ProphetConfig(changepoint_placement="quantile", **base)
    )
    ds = jnp.asarray(t, jnp.float32)
    st_u = m_u.fit(ds, jnp.asarray(y))
    st_q = m_q.fit(ds, jnp.asarray(y))
    # On a regular fully-observed grid the placements coincide up to one
    # grid step, so the optima must agree closely.
    np.testing.assert_allclose(
        np.asarray(st_q.loss), np.asarray(st_u.loss), rtol=5e-3, atol=0.5
    )
    # And prediction must round-trip the quantile grid through ScalingMeta.
    fc = m_q.predict(st_q, ds)
    assert np.isfinite(np.asarray(fc["yhat"])).all()


def test_quantile_changepoints_respect_mask():
    # Observations only in the middle third; changepoints must live there.
    t = np.linspace(0.0, 1.0, 300)[None, :]
    mask = ((t > 0.33) & (t < 0.67)).astype(np.float64)
    cps = quantile_changepoints(t, mask, 5, changepoint_range=1.0)
    assert (cps > 0.32).all() and (cps < 0.68).all()
