"""Convergence-compacting segment scheduler: bitwise parity + width policy.

The scheduler (``models.prophet.model._run_segments_compacted``) shrinks
the lockstep batch to its unconverged set between solver segments.  What
makes it safe to enable by default is that every per-series quantity in
the solver and the design tensors is row-local, so the compacted
schedule must reproduce the full-width segmented solve BITWISE per
series — these tests pin exactly that, on mixed easy/hard batches,
through the model API, the chunked TpuBackend, and (as composition: the
mesh path has no segments to compact) the mesh-chunked backend.  The
slow micro-bench pins the throughput claim the scheduler exists for.
"""

import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tsspark_tpu.config import (  # noqa: E402
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.models.prophet.model import ProphetModel  # noqa: E402

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 3),), n_changepoints=5
)

STATE_FIELDS = ("theta", "loss", "grad_norm", "converged", "n_iters",
                "status")


def _mixed_batch(b=96, t=160, hard_every=4, seed=0, easy="trend",
                 hard_scale=1.0):
    """Mixed difficulty: most series converge well before the iteration
    cap, every ``hard_every``-th is a noisy random walk (amplified by
    ``hard_scale``) that needs the full depth — the shape compaction
    targets."""
    rng = np.random.default_rng(seed)
    ds = np.arange(t, dtype=np.float64)
    y = np.empty((b, t), np.float32)
    for i in range(b):
        if i % hard_every == 0:
            y[i] = (hard_scale * np.cumsum(rng.normal(0, 1.0, t))
                    + 5 * np.sin(ds / 7 * 2 * np.pi))
        elif easy == "const":
            y[i] = 1.0 + 0.001 * i
        else:
            y[i] = 0.01 * i + 0.05 * ds + rng.normal(0, 0.01, t)
    return ds, y


def _assert_states_equal(a, b):
    for f in STATE_FIELDS:
        xa, xb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        np.testing.assert_array_equal(xa, xb, err_msg=f)


def test_model_segmented_compaction_bitwise_parity():
    ds, y = _mixed_batch()
    model = ProphetModel(CFG, SolverConfig(max_iters=48))
    full = model.fit(ds, y, iter_segment=8, compact=False)
    comp = model.fit(ds, y, iter_segment=8, compact=True)
    _assert_states_equal(full, comp)


def test_compaction_shrinks_live_width():
    from tsspark_tpu.perf import PerfRecorder

    ds, y = _mixed_batch(b=128, hard_every=8)
    model = ProphetModel(CFG, SolverConfig(max_iters=48))
    rec = PerfRecorder()
    model.fit(ds, y, iter_segment=8, compact=True, recorder=rec)
    rep = rec.report()
    widths = rep.widths
    assert len(widths) >= 2
    # The batch must actually shrink (the mixed batch converges its easy
    # majority well before the cap) and widths stay on the pow-2/32 grid.
    assert min(widths) < widths[0]
    assert all(w >= 32 and (w & (w - 1)) == 0 for w in widths)
    # live never exceeds the dispatched width and is non-increasing.
    lives = [s.live for s in rep.segments]
    assert all(s.live <= s.width for s in rep.segments)
    assert lives == sorted(lives, reverse=True)


def test_compaction_parity_with_warm_start_and_regressors():
    rng = np.random.default_rng(5)
    ds, y = _mixed_batch(b=80, t=128, hard_every=5, seed=5)
    reg = rng.normal(size=(80, 128, 1)).astype(np.float32)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
        regressors=(RegressorConfig("x0"),),
    )
    model = ProphetModel(cfg, SolverConfig(max_iters=40))
    init = 0.01 * rng.normal(size=(80, cfg.num_params)).astype(np.float32)
    full = model.fit(ds, y, regressors=reg, init=init, iter_segment=6,
                     compact=False)
    comp = model.fit(ds, y, regressors=reg, init=init, iter_segment=6,
                     compact=True)
    _assert_states_equal(full, comp)


def test_backend_chunked_compaction_parity():
    """The TpuBackend path: chunking (with a padded tail chunk) composes
    with compaction; compact=True is the default."""
    from tsspark_tpu.backends.tpu import TpuBackend

    ds, y = _mixed_batch(b=150, hard_every=6, seed=2)
    solver = SolverConfig(max_iters=48)
    full = TpuBackend(CFG, solver, chunk_size=64, iter_segment=8,
                      compact=False).fit(ds, y)
    comp = TpuBackend(CFG, solver, chunk_size=64, iter_segment=8).fit(ds, y)
    _assert_states_equal(full, comp)
    np.testing.assert_array_equal(
        np.asarray(full.meta.y_scale), np.asarray(comp.meta.y_scale)
    )


def test_compacted_width_policy():
    from tsspark_tpu.parallel.sharding import compacted_width

    assert compacted_width(0) == 32          # floor
    assert compacted_width(1) == 32
    assert compacted_width(33) == 64         # next pow2
    assert compacted_width(64) == 64         # exact pow2 stays
    assert compacted_width(65) == 128
    assert compacted_width(5, floor=8) == 8
    # Mesh composition: widths pad up to the series-shard multiple.
    assert compacted_width(5, floor=8, multiple=8) == 8
    assert compacted_width(33, multiple=8) == 64
    assert compacted_width(33, floor=32, multiple=48) == 96
    assert compacted_width(200, multiple=3) == 258  # 256 -> multiple of 3


def test_mesh_chunked_fit_composes_with_compaction():
    """Compaction is a no-op under a mesh (the sharded solve has no
    segment boundary), but the default compact=True backend must still
    run the mesh-chunked path and match the single-device chunked fit —
    and the width it WOULD compact to always divides the series shards
    (compacted_width's ``multiple``)."""
    import jax

    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.data import datasets
    from tsspark_tpu.parallel import mesh as mesh_mod
    from tsspark_tpu.parallel.sharding import compacted_width

    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    batch = datasets.m4_hourly_like(n_series=64, max_len=240, seed=11)
    ds, y = batch.ds, batch.y
    solver = SolverConfig(max_iters=60)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    ref = TpuBackend(cfg, solver, chunk_size=16, compact=True).fit(ds, y)
    bk = TpuBackend(cfg, solver, chunk_size=16, mesh=m, compact=True)
    assert bk._compact_multiple() == 8
    for n_live in (1, 5, 9, 33):
        assert compacted_width(n_live, multiple=bk._compact_multiple()) % 8 \
            == 0
    shard = bk.fit(ds, y)
    scale = np.maximum(np.abs(np.asarray(ref.loss)), 1.0)
    np.testing.assert_allclose(
        np.asarray(shard.loss) / scale, np.asarray(ref.loss) / scale,
        rtol=0, atol=2e-3,
    )
    np.testing.assert_array_equal(
        np.asarray(shard.meta.y_scale), np.asarray(ref.meta.y_scale)
    )


@pytest.mark.slow
def test_compaction_speedup_on_early_converging_batch():
    """The acceptance micro-bench: on a batch where >= 75% of series
    converge in the first segment, the compacted schedule must deliver
    >= 1.5x series/s over the full-width segmented solve — with
    bitwise-identical FitState output.  Both paths are warmed first
    (compiling every width on the compaction ladder) so the timed
    comparison measures execution, not XLA compiles.

    Shape rationale: the easy majority (noisy lines) converges via ftol
    around iteration 30-48 on the exact-t segmented path, so a 48-iter
    first segment retires > 80% of the batch; the amplified random
    walks run to (or near) the 144-iter cap, keeping the full-width
    path paying 512 lanes for a handful of live rows."""
    ds, y = _mixed_batch(b=512, t=256, hard_every=10, seed=3,
                         easy="trend", hard_scale=3.0)
    solver = SolverConfig(max_iters=144)
    model = ProphetModel(CFG, solver)

    # Warm both paths; pin the bitwise-parity contract at this shape.
    full = model.fit(ds, y, iter_segment=48, compact=False)
    comp = model.fit(ds, y, iter_segment=48, compact=True)
    _assert_states_equal(full, comp)
    # >= 75% of the batch converges within the first 48-iter segment.
    ni = np.asarray(full.n_iters)
    frac_first = float((np.asarray(full.converged) & (ni <= 48)).mean())
    assert frac_first >= 0.75, frac_first

    def timed(compact):
        t0 = time.perf_counter()
        model.fit(ds, y, iter_segment=48, compact=compact)
        return time.perf_counter() - t0

    t_full = min(timed(False) for _ in range(3))
    t_comp = min(timed(True) for _ in range(3))
    speedup = t_full / t_comp
    assert speedup >= 1.5, (
        f"compaction speedup {speedup:.2f}x < 1.5x "
        f"(full {t_full:.3f}s, compacted {t_comp:.3f}s)"
    )
