"""Closed-form ridge warm start (models/prophet/init.py).

The init is the main single-chip perf lever: it must (a) land close enough
to the optimum that L-BFGS needs an order of magnitude fewer iterations
than the endpoint heuristic, (b) not change the fitted quality, and (c)
stay finite on the degenerate inputs the chunk-padding path produces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import (
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.data import datasets
from tsspark_tpu.eval import metrics
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.init import ridge_init
from tsspark_tpu.models.prophet.loss import value_batch
from tsspark_tpu.models.prophet.model import ProphetModel
from tsspark_tpu.models.prophet.params import init_theta

CFG = ProphetConfig(
    seasonalities=(
        SeasonalityConfig("yearly", 365.25, 6),
        SeasonalityConfig("weekly", 7.0, 3),
    ),
    regressors=(RegressorConfig("promo", standardize=False),),
    n_changepoints=12,
)


def _batch(n_series=24, n_days=400):
    b = datasets.m5_like(n_series=n_series, n_days=n_days)
    return (
        jnp.asarray(b.ds, jnp.float32),
        jnp.asarray(np.nan_to_num(b.y), jnp.float32),
        jnp.asarray(b.mask, jnp.float32),
        jnp.asarray(b.regressors[..., :1], jnp.float32),
    )


def test_ridge_init_beats_heuristic_loss():
    ds, y, mask, reg = _batch()
    data, _ = prepare_fit_data(ds, y, CFG, mask=mask, regressors=reg)
    f_ridge = value_batch(ridge_init(data, CFG), data, CFG)
    f_heur = value_batch(
        init_theta(CFG, data.y, data.mask, data.t), data, CFG
    )
    assert bool(jnp.all(jnp.isfinite(f_ridge)))
    # The closed-form start must dominate the heuristic on every series.
    assert bool(jnp.all(f_ridge <= f_heur))


def test_ridge_init_cuts_iterations_same_quality():
    ds, y, mask, reg = _batch()
    out = {}
    for init in ("heuristic", "ridge"):
        m = ProphetModel(CFG, SolverConfig(max_iters=200, init=init))
        st = m.fit(ds, y, mask=mask, regressors=reg)
        fc = m.predict(st, ds, regressors=reg, num_samples=0)
        out[init] = (
            float(st.n_iters.mean()),
            np.asarray(metrics.smape(y, fc["yhat"], mask=mask)),
        )
    it_heur, sm_heur = out["heuristic"]
    it_ridge, sm_ridge = out["ridge"]
    assert it_ridge < 0.5 * it_heur  # in practice ~10x fewer
    assert abs(sm_ridge.mean() - sm_heur.mean()) < 0.1
    assert np.max(np.abs(sm_ridge - sm_heur)) < 0.5


@pytest.mark.parametrize("growth", ["logistic", "flat"])
def test_ridge_init_nonlinear_growth_finite_and_helps(growth):
    ds, y, mask, reg = _batch(n_series=8, n_days=300)
    y = jnp.abs(y) + 1.0
    cfg = ProphetConfig(
        growth=growth,
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=8,
    )
    cap = jnp.full_like(y, float(y.max()) * 1.5) if growth == "logistic" else None
    data, _ = prepare_fit_data(ds, y, cfg, mask=mask, cap=cap)
    th = ridge_init(data, cfg)
    f_ridge = value_batch(th, data, cfg)
    f_heur = value_batch(
        init_theta(cfg, data.y, data.mask, data.t), data, cfg
    )
    assert bool(jnp.all(jnp.isfinite(th))) and bool(jnp.all(jnp.isfinite(f_ridge)))
    # Betas are solved conditional on the heuristic trend: never worse.
    assert bool(jnp.all(f_ridge <= f_heur + 1e-3))


def test_ridge_init_fully_masked_rows_inert():
    ds, y, mask, reg = _batch(n_series=8, n_days=200)
    mask = mask.at[3:].set(0.0)  # padding-style dummy rows
    data, _ = prepare_fit_data(ds, y, CFG, mask=mask, regressors=reg)
    th = ridge_init(data, CFG)
    assert bool(jnp.all(jnp.isfinite(th)))
    # Pure-prior rows: linear params shrink to ~0.
    assert float(jnp.max(jnp.abs(th[3:, :2]))) < 1e-3
