"""Backtest harness + eval-config runners (smoke scale)."""

import numpy as np
import pytest

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.eval import backtest
from tsspark_tpu.eval.configs import RUNNERS


def test_make_cutoffs():
    ds = np.arange(0.0, 365.0)
    cuts = backtest.make_cutoffs(ds, horizon=30, period=30, initial=180)
    assert (cuts >= 180).all() and (cuts <= 364 - 30).all()
    assert np.allclose(np.diff(cuts), 30)


def test_make_cutoffs_too_short():
    with pytest.raises(ValueError):
        backtest.make_cutoffs(np.arange(100.0), horizon=30, period=15,
                              initial=180)


def test_cross_validation_batched():
    rng = np.random.default_rng(0)
    t = np.arange(300.0)
    b = 3
    y = (
        10.0 * (np.arange(b)[:, None] + 1)
        + 0.05 * t[None, :]
        + 2.0 * np.sin(2 * np.pi * t / 7)[None, :]
        + rng.normal(0, 0.2, (b, 300))
    )
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),), n_changepoints=5
    )
    cv = backtest.cross_validation(
        t, y, cfg, horizon=14, period=28, initial=150,
        solver_config=SolverConfig(max_iters=80),
    )
    c = len(cv["cutoffs"])
    assert c >= 3
    assert cv["smape"].shape == (b, c)
    # A clean synthetic signal must backtest accurately at every cutoff.
    assert cv["smape"].max() < 5.0, cv["smape"]
    perf = backtest.performance_metrics(cv)
    assert perf["n_windows"] == b * c
    assert 0.0 <= perf["coverage_mean"] <= 1.0


@pytest.mark.parametrize("key", ["1", "2", "4", "5"])
def test_eval_config_smoke(key):
    out = RUNNERS[key](backend="tpu", scale=0.02)
    if key == "5":
        assert out["warm_starts"] > 0
        assert out["smape_forecast"] < 10.0
    else:
        assert out["smape_train"] < 15.0
        if key != "4":
            # Logistic+multiplicative (config 4) legitimately exhausts the
            # iteration budget before the strict convergence flags trip —
            # the scipy oracle does too, at equal sMAPE — so only the
            # accuracy gate applies there.
            assert out["converged_frac"] > 0.5


def test_eval_config3_smoke():
    out = RUNNERS["3"](backend="tpu", scale=0.001)  # ~30 series
    assert out["smape_train"] < 30.0  # intermittent retail-like series
    assert out["n_series"] >= 8
