"""Structured logging, profiling timers, and checkpoint utilities."""

import json
import logging

from tsspark_tpu.utils.logging import get_logger, timed
from tsspark_tpu.utils.profiling import Timers


def _last_json_line(err: str) -> dict:
    # Other libraries (jax, absl) also write to stderr; take our JSON line.
    lines = [l for l in err.strip().splitlines() if l.startswith("{")]
    return json.loads(lines[-1])


def test_structured_logger_json_lines(capsys):
    log = get_logger("tsspark.test")
    log.info("fit_done", n_series=42, seconds=1.25)
    payload = _last_json_line(capsys.readouterr().err)
    assert payload["event"] == "fit_done"
    assert payload["n_series"] == 42
    assert payload["level"] == "info"


def test_timed_context(capsys):
    log = get_logger("tsspark.test2")
    with timed(log, "block", tag="x"):
        pass
    payload = _last_json_line(capsys.readouterr().err)
    assert payload["event"] == "block"
    assert payload["tag"] == "x"
    assert payload["seconds"] >= 0


def test_timers_accumulate():
    t = Timers()
    for _ in range(3):
        with t.section("fit"):
            pass
    s = t.summary()
    assert s["fit"]["count"] == 3
    assert s["fit"]["total_s"] >= 0


def test_persistent_compile_cache_respects_explicit_config(monkeypatch):
    """The lazy cache setup must never override an explicit user choice:
    conftest points jax_compilation_cache_dir at the suite's host-keyed
    dir, and enable_persistent_compile_cache must leave it alone."""
    import jax

    from tsspark_tpu.utils import platform as plat

    before = jax.config.jax_compilation_cache_dir
    assert before  # conftest configured the suite cache
    monkeypatch.setattr(plat, "_CACHE_ENABLED", False)
    plat.enable_persistent_compile_cache()
    assert jax.config.jax_compilation_cache_dir == before
    # Second call is a guarded no-op regardless of environment.
    plat.enable_persistent_compile_cache()
    assert jax.config.jax_compilation_cache_dir == before
