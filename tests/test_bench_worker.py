"""bench.py fit-worker: two-phase chunk files, straggler patching, and
crash-resume idempotency (driven in-process on the CPU backend)."""

import argparse
import glob
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def _args(tmp_path, series=96, days=128, chunk=32, phase1=6):
    from tsspark_tpu.data import datasets

    data_dir = tmp_path / "data"
    out_dir = tmp_path / "out"
    data_dir.mkdir()
    out_dir.mkdir()
    batch = datasets.m5_like(n_series=series, n_days=days)
    np.save(data_dir / "ds.npy", batch.ds.astype(np.float32))
    np.save(data_dir / "y.npy", np.nan_to_num(batch.y).astype(np.float32))
    np.save(data_dir / "mask.npy", batch.mask.astype(np.float32))
    np.save(data_dir / "reg.npy", batch.regressors.astype(np.float32))
    return argparse.Namespace(
        data=str(data_dir), out=str(out_dir), lo=0, hi=series, chunk=chunk,
        max_iters=120, segment=12, series=series, phase1_iters=phase1,
    )


def test_fit_worker_two_phase_and_resume(tmp_path):
    args = _args(tmp_path)
    assert bench.fit_worker(args) == 0

    files = sorted(glob.glob(os.path.join(args.out, "chunk_*.npz")))
    assert len(files) == 3
    for f in files:
        z = np.load(f)
        # Phase 2 ran: every chunk is flagged patched and fully converged.
        assert z["phase2"] == 1
        assert z["converged"].all()
        assert z["theta"].shape[0] == 32
    assert os.path.exists(os.path.join(args.out, "phase2_done"))
    with open(os.path.join(args.out, "times.jsonl")) as fh:
        times = [json.loads(l) for l in fh if l.strip()]
    assert sum(1 for t in times if "fit_s" in t) == 3
    phase2 = [t for t in times if "phase2_s" in t]
    assert len(phase2) == 1 and phase2[0]["stragglers"] >= 0
    # Heartbeats fired (the stall watchdog's liveness signal).
    assert os.path.exists(os.path.join(args.out, "heartbeat"))

    # Fully-complete rerun: nothing refits, marker short-circuits.
    n_times = len(times)
    assert bench.fit_worker(args) == 0
    with open(os.path.join(args.out, "times.jsonl")) as fh:
        assert len([l for l in fh if l.strip()]) == n_times

    # Crash-resume: lose one chunk and the phase-2 marker mid-"crash".
    victim = files[1]
    os.remove(victim)
    os.remove(os.path.join(args.out, "phase2_done"))
    assert bench.fit_worker(args) == 0
    z = np.load(victim)
    # The missing chunk was refit AND re-patched; untouched chunks kept
    # their already-patched results (idempotent phase 2).
    assert z["phase2"] == 1 and z["converged"].all()
    for f in files:
        assert np.load(f)["phase2"] == 1
    assert os.path.exists(os.path.join(args.out, "phase2_done"))


def test_prep_worker_cache_matches_inline_prep(tmp_path):
    """The overlapped CPU --_prep worker and the fit worker's inline prep
    run the same prepare/pack code path; the cached payload must be
    BIT-identical so a chunk fit from cache reproduces the inline fit."""
    args = _args(tmp_path, series=64, days=128, chunk=32, phase1=0)
    args.max_ahead = 1
    assert bench.prep_worker(args) == 0
    cached = bench._load_prep(args.out, 0, 32)
    assert cached is not None
    b_real, packed, meta = cached
    assert b_real == 32

    # Inline reference: same construction as fit_worker.prep.
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.models.prophet.design import (
        _indicator_reg_cols, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"))
    mask = np.load(os.path.join(args.data, "mask.npy"))
    reg = np.load(os.path.join(args.data, "reg.npy"))
    model = ProphetModel(bench._model_config(), SolverConfig(max_iters=120))
    u8 = _indicator_reg_cols(reg)
    y_c = np.zeros((32, y.shape[1]), np.float32); y_c[:] = y[0:32]
    m_c = np.zeros((32, y.shape[1]), np.float32); m_c[:] = mask[0:32]
    r_c = np.zeros((32,) + reg.shape[1:], np.float32); r_c[:] = reg[0:32]
    data, meta_ref = model.prepare(
        ds, y_c, mask=m_c, regressors=r_c, as_numpy=True
    )
    packed_ref, _ = pack_fit_data(data, meta_ref, ds, reg_u8_cols=u8,
                                  collapse_cap=True)
    for k in packed._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(packed, k)),
            np.asarray(getattr(packed_ref, k)), err_msg=k,
        )
    for k in meta._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(meta, k)),
            np.asarray(getattr(meta_ref, k)), err_msg=k,
        )

    # A second prep run is a no-op (file exists), and a chunk file
    # supersedes the prep cache.
    assert bench.prep_worker(args) == 0


def test_phase2_resident_matches_host_path(tmp_path, monkeypatch):
    """The device-resident phase-2 gather and the host re-prep path must
    produce equivalent straggler refits: same convergence/status and
    thetas equal to f32 solver tolerance (the gathered payload is
    bit-identical to a re-packed one; only dispatch mechanics differ)."""
    (tmp_path / "resident").mkdir()
    (tmp_path / "host").mkdir()
    args_r = _args(tmp_path / "resident", series=96, days=128, chunk=32,
                   phase1=6)
    args_h = _args(tmp_path / "host", series=96, days=128, chunk=32,
                   phase1=6)
    # Non-segmented mode: the resident path only exists there.
    args_r.segment = 0
    args_h.segment = 0
    monkeypatch.delenv("BENCH_NO_RESIDENT", raising=False)
    assert bench.fit_worker(args_r) == 0
    monkeypatch.setenv("BENCH_NO_RESIDENT", "1")
    assert bench.fit_worker(args_h) == 0

    def mode(out):
        with open(os.path.join(out, "times.jsonl")) as fh:
            rows = [json.loads(l) for l in fh if l.strip()]
        return next(t["phase2_mode"] for t in rows if "phase2_s" in t)

    assert mode(args_r.out) == "resident"
    assert mode(args_h.out) == "host"
    fr = sorted(glob.glob(os.path.join(args_r.out, "chunk_*.npz")))
    fh_ = sorted(glob.glob(os.path.join(args_h.out, "chunk_*.npz")))
    assert len(fr) == len(fh_) == 3
    for a, b in zip(fr, fh_):
        za, zb = np.load(a), np.load(b)
        assert za["phase2"] == 1 and zb["phase2"] == 1
        np.testing.assert_array_equal(za["status"], zb["status"])
        np.testing.assert_array_equal(za["converged"], zb["converged"])
        # Same data, same warm start, same program semantics: thetas agree
        # to f32 noise.
        np.testing.assert_allclose(
            za["theta"], zb["theta"], rtol=2e-4, atol=2e-4
        )
        for k in ("y_scale", "ds_start", "ds_span"):
            np.testing.assert_array_equal(za[k], zb[k])
