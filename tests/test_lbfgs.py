"""Batched L-BFGS: convex quadratics (exact answer), Rosenbrock (hard),
and mixed batches where series converge at different rates."""

import jax
import jax.numpy as jnp
import numpy as np

from tsspark_tpu.config import SolverConfig
from tsspark_tpu.ops import lbfgs


def _batch_fun(f_single):
    """Lift a scalar objective to the (B,) losses + (B, P) grads contract."""

    def fun(theta):
        f = jax.vmap(f_single)(theta)
        g = jax.vmap(jax.grad(f_single))(theta)
        return f, g

    return fun


def test_batched_quadratic_exact():
    rng = np.random.default_rng(0)
    b, p = 16, 8
    # Random SPD quadratics with distinct conditioning per series.
    a_half = rng.normal(size=(b, p, p))
    a_mats = np.einsum("bij,bkj->bik", a_half, a_half) + 0.1 * np.eye(p)
    centers = rng.normal(size=(b, p))
    a_j = jnp.asarray(a_mats)
    c_j = jnp.asarray(centers)

    def fun(theta):
        d = theta - c_j
        ad = jnp.einsum("bij,bj->bi", a_j, d)
        f = 0.5 * jnp.sum(d * ad, axis=-1)
        return f, ad

    res = lbfgs.minimize(fun, jnp.asarray(rng.normal(size=(b, p))))
    assert bool(res.converged.all())
    np.testing.assert_allclose(np.asarray(res.theta), centers, atol=1e-3)
    assert np.asarray(res.f).max() < 1e-6


def test_rosenbrock_batch():
    def rosen(x):
        return jnp.sum(
            100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2
        )

    rng = np.random.default_rng(1)
    theta0 = jnp.asarray(rng.uniform(-1.5, 1.5, size=(8, 4)))
    res = lbfgs.minimize(
        _batch_fun(rosen), theta0, SolverConfig(max_iters=500, tol=0.0, gtol=1e-5)
    )
    # Every series must reach a stationary point (4D Rosenbrock has a genuine
    # local minimum near (-1, 1, 1, 1), so not all starts reach all-ones).
    assert np.asarray(res.grad_norm).max() < 1e-3
    # Which basin each start lands in is trajectory luck; stationarity above
    # is the real check.  Still require the global optimum to dominate.
    at_global = np.abs(np.asarray(res.theta) - 1.0).max(axis=-1) < 1e-2
    assert at_global.sum() >= 4


def test_mixed_convergence_rates_freeze_correctly():
    # Series 0: trivial 1-step quadratic; series 1: badly conditioned.
    scales = jnp.asarray([[1.0, 1.0], [1.0, 1e4]])

    def fun(theta):
        f = 0.5 * jnp.sum(scales * theta * theta, axis=-1)
        return f, scales * theta

    theta0 = jnp.full((2, 2), 3.0)
    res = lbfgs.minimize(fun, theta0, SolverConfig(max_iters=300, tol=0.0))
    assert bool(res.converged.all())
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-3)
    # The easy series must have stopped iterating earlier than the hard one.
    assert int(res.n_iters[0]) <= int(res.n_iters[1])


def test_nonfinite_trial_rejected():
    # Objective explodes to NaN outside |x| < 2: line search must shrink past it.
    def f_single(x):
        v = jnp.sum(x * x)
        return jnp.where(v > 4.0, jnp.nan, v)

    theta0 = jnp.asarray([[1.9, 0.0]])
    res = lbfgs.minimize(_batch_fun(f_single), theta0, SolverConfig(max_iters=100))
    assert np.isfinite(float(res.f[0]))
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-3)


def test_status_reports_termination_reason():
    # Clean quadratics: every series should stop on the gradient test.
    def fun(theta):
        f = 0.5 * jnp.sum(theta * theta, axis=-1)
        return f, theta

    res = lbfgs.minimize(fun, jnp.ones((4, 3)))
    assert bool(res.converged.all())
    assert np.all(np.asarray(res.status) == lbfgs.STATUS_GTOL)


def test_float32_floor_terminates_early():
    # gtol unreachable in float32 (set to 1e-12, ftol disabled): the solver
    # must detect stationarity at the f32 noise floor instead of burning the
    # whole iteration budget on last-ulp oscillation.
    rng = np.random.default_rng(3)
    scales = jnp.asarray(np.exp(rng.uniform(0.0, 6.0, size=(8, 6))), jnp.float32)

    def fun(theta):
        f = 0.5 * jnp.sum(scales * theta * theta, axis=-1)
        return f, scales * theta

    theta0 = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)
    cfg = SolverConfig(max_iters=400, tol=0.0, gtol=1e-12)
    res = lbfgs.minimize(fun, theta0, cfg)
    assert bool(res.converged.all())
    # Terminated on the noise floor (or a genuinely failed search), not gtol.
    assert np.all(
        np.isin(
            np.asarray(res.status),
            [lbfgs.STATUS_FLOOR, lbfgs.STATUS_STALLED],
        )
    )
    # ... and did so long before the cap, at a genuine minimum.
    assert int(np.asarray(res.n_iters).max()) < 100
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-3)


def test_fan_search_matches_sequential_backtracking():
    # The fan must select, per series, the FIRST (largest) ladder step that
    # passes Armijo — byte-identical to sequential backtracking.  Verify one
    # iteration against a host-side replay of the ladder.
    rng = np.random.default_rng(7)
    b, p = 16, 5
    a_half = rng.normal(size=(b, p, p))
    a_mats = np.einsum("bij,bkj->bik", a_half, a_half) + 0.5 * np.eye(p)
    a_j = jnp.asarray(a_mats)

    def fun(theta):
        ad = jnp.einsum("bij,bj->bi", a_j, theta)
        return 0.5 * jnp.sum(theta * ad, axis=-1), ad

    cfg = SolverConfig()
    theta0 = jnp.asarray(rng.normal(size=(b, p)), jnp.float32)
    state0 = lbfgs.init_state(fun, theta0, cfg)
    state1 = lbfgs.run_segment(fun, state0, cfg, num_iters=1)

    # First iteration direction is -grad (empty history), seeded step 1.0.
    f0, g0 = np.asarray(state0.f), np.asarray(state0.grad)
    direction = -g0
    dg = np.sum(direction * g0, axis=-1)
    quad = lambda i, x: 0.5 * float(
        np.float32(x) @ (a_mats[i].astype(np.float32) @ np.float32(x))
    )
    expected = np.empty(b)
    for i in range(b):
        step = min(cfg.init_step, cfg.init_step * 4.0)
        f_t = f0[i]
        for _ in range(cfg.ls_max_steps):
            trial = np.asarray(theta0)[i] + np.float32(step) * direction[i]
            f_t = quad(i, trial)
            if np.isfinite(f_t) and f_t <= f0[i] + cfg.ls_armijo_c1 * step * dg[i]:
                break
            step *= cfg.ls_shrink
        expected[i] = f_t
    np.testing.assert_allclose(np.asarray(state1.f), expected, rtol=1e-5)


def test_diag_precond_speeds_ill_conditioned_batch():
    # Diagonal quadratics with curvature spread over 6 decades: the exact
    # inverse-diagonal initial metric must converge far faster than the
    # unpreconditioned gamma*I scaling, to the same optimum.
    rng = np.random.default_rng(11)
    scales = jnp.asarray(
        np.exp(rng.uniform(0.0, 14.0, size=(8, 6))), jnp.float32
    )

    def fun(theta):
        f = 0.5 * jnp.sum(scales * theta * theta, axis=-1)
        return f, scales * theta

    theta0 = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)
    cfg = SolverConfig(max_iters=200, tol=0.0)
    plain = lbfgs.minimize(fun, theta0, cfg)
    pre = lbfgs.minimize(fun, theta0, cfg, precond=1.0 / scales)
    assert bool(pre.converged.all())
    np.testing.assert_allclose(np.asarray(pre.theta), 0.0, atol=1e-3)
    # Newton-diagonal steps solve each quadratic almost immediately.
    assert int(np.asarray(pre.n_iters).max()) <= 5
    assert int(np.asarray(pre.n_iters).sum()) < int(
        np.asarray(plain.n_iters).sum()
    )


def test_closed_form_fan_matches_stacked_trials():
    """For linear-growth additive models the closed-form ladder losses
    (loss.fan_value_closed_form) must equal evaluating each trial directly, to
    float32 rounding — and the resulting full fit must match the stacked
    path's optimum."""
    from tsspark_tpu.config import ProphetConfig, RegressorConfig, SeasonalityConfig
    from tsspark_tpu.models.prophet.design import prepare_fit_data
    from tsspark_tpu.models.prophet.loss import (
        fan_value_closed_form, has_closed_form_fan, value_batch,
    )
    from tsspark_tpu.models.prophet.model import ProphetModel
    from tsspark_tpu.models.prophet.init import initial_theta

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        regressors=(RegressorConfig("price"),),
        n_changepoints=6,
    )
    assert has_closed_form_fan(cfg)
    rng = np.random.default_rng(21)
    b, n = 5, 240
    t = np.arange(float(n))
    y = (6 + 0.03 * t + 1.2 * np.sin(2 * np.pi * t / 7)
         + rng.normal(0, 0.3, (b, n))).astype(np.float32)
    reg = rng.normal(0, 1, (b, n, 1)).astype(np.float32)
    data, _ = prepare_fit_data(
        jnp.arange(float(n)), jnp.asarray(y), cfg, regressors=reg
    )
    theta = initial_theta(data, cfg, SolverConfig())
    direction = jnp.asarray(
        rng.normal(0, 0.1, theta.shape).astype(np.float32)
    )
    ladder = jnp.asarray(
        (0.5 ** np.arange(8))[:, None] * np.ones((1, b)), jnp.float32
    )
    closed = fan_value_closed_form(theta, direction, ladder, data, cfg)
    direct = jax.vmap(
        lambda s: value_batch(theta + s[:, None] * direction, data, cfg)
    )(ladder)
    np.testing.assert_allclose(
        np.asarray(closed), np.asarray(direct), rtol=2e-4, atol=2e-3
    )
    # Multiplicative features stay eligible (quadratic-in-step closed form);
    # non-linear growth does not.
    cfg_m = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2,
                                         mode="multiplicative"),),
        n_changepoints=4,
    )
    assert has_closed_form_fan(cfg_m)
    data_m, _ = prepare_fit_data(jnp.arange(float(n)), jnp.asarray(y), cfg_m)
    theta_m = initial_theta(data_m, cfg_m, SolverConfig())
    dir_m = jnp.asarray(
        rng.normal(0, 0.1, theta_m.shape).astype(np.float32)
    )
    lad_m = jnp.asarray(
        (0.5 ** np.arange(6))[:, None] * np.ones((1, b)), jnp.float32
    )
    closed_m = fan_value_closed_form(theta_m, dir_m, lad_m, data_m, cfg_m)
    direct_m = jax.vmap(
        lambda sv: value_batch(theta_m + sv[:, None] * dir_m, data_m, cfg_m)
    )(lad_m)
    np.testing.assert_allclose(
        np.asarray(closed_m), np.asarray(direct_m), rtol=2e-4, atol=2e-3
    )
    assert not has_closed_form_fan(
        ProphetConfig(growth="logistic", seasonalities=())
    )
    # End-to-end: the fit through the closed-form search reaches the same
    # optimum as the stacked path (forced by calling minimize without
    # fan_value).
    model = ProphetModel(cfg, SolverConfig(max_iters=150))
    st = model.fit(jnp.arange(float(n)), jnp.asarray(y), regressors=jnp.asarray(reg))
    assert bool(st.converged.all())
    resid = np.asarray(st.loss)
    from tsspark_tpu.ops import lbfgs as lb
    from tsspark_tpu.models.prophet.loss import value_and_grad_batch
    stacked = lb.minimize(
        lambda th: value_and_grad_batch(th, data, cfg),
        initial_theta(data, cfg, SolverConfig()),
        SolverConfig(max_iters=150),
        fun_value=lambda th: value_batch(th, data, cfg),
    )
    np.testing.assert_allclose(
        resid, np.asarray(stacked.f), rtol=1e-3, atol=1e-2
    )


def test_jit_compatible():
    def fun(theta):
        f = 0.5 * jnp.sum(theta * theta, axis=-1)
        return f, theta

    jitted = jax.jit(lambda t0: lbfgs.minimize(fun, t0))
    res = jitted(jnp.ones((4, 3)))
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-4)


def test_dynamic_depth_matches_static():
    """fit_core's traced depth/metric/init switches reproduce the static
    configuration exactly: a full-depth static solver driven with
    max_iters_dynamic=K, gn flag off, and ridge-init selected dynamically
    lands bit-close to a static max_iters=K solver (ones preconditioner,
    theta0=None).  This is the invariant that lets the bench's two phases
    share ONE compiled program."""
    import numpy as np

    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.models.prophet.design import prepare_fit_data
    from tsspark_tpu.models.prophet.model import fit_core

    rng = np.random.default_rng(11)
    b, t_len = 16, 150
    ds = np.arange(t_len, dtype=np.float64)
    y = 4 + 0.03 * ds[None] + np.sin(2 * np.pi * ds[None] / 7.0) \
        + rng.normal(0, 0.15, (b, t_len))
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=5,
    )
    data, _ = prepare_fit_data(ds, y, cfg)

    # precond pinned to "none": the gn flag below is OFF, and the default
    # ("auto") now resolves to gn_diag, which would be a different metric.
    res_static = fit_core(
        data, None, cfg, SolverConfig(max_iters=9, precond="none")
    )
    res_dyn = fit_core(
        data,
        np.zeros_like(np.asarray(res_static.theta)),  # ignored: flag off
        cfg,
        SolverConfig(max_iters=120),
        max_iters_dynamic=np.int32(9),
        gn_precond_dynamic=np.bool_(False),
        use_theta0_dynamic=np.bool_(False),
    )
    np.testing.assert_allclose(
        np.asarray(res_dyn.theta), np.asarray(res_static.theta), atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(res_dyn.n_iters), np.asarray(res_static.n_iters)
    )
    # Warm-start selection: flag ON continues from the given thetas.
    res_warm = fit_core(
        data,
        np.asarray(res_static.theta),
        cfg,
        SolverConfig(max_iters=120),
        max_iters_dynamic=np.int32(120),
        gn_precond_dynamic=np.bool_(True),
        use_theta0_dynamic=np.bool_(True),
    )
    assert bool(np.all(np.asarray(res_warm.f) <= np.asarray(res_static.f) + 1e-5))


def test_ftol_patience_survives_single_microscopic_step():
    """A single sub-tol accepted step must NOT end a series (round-4: the
    whole M5 parity tail was single-shot ftol exits 2-3 iterations in).
    With patience=1 the first tiny accepted decrease converges the batch
    immediately; the default patience keeps iterating and reaches the
    true optimum."""
    rng = np.random.default_rng(5)
    b, p = 4, 6
    # Anisotropic SPD quadratics: one L-BFGS step cannot reach the optimum.
    a_half = rng.normal(size=(b, p, p))
    a_mats = np.einsum("bij,bkj->bik", a_half, a_half) + 0.1 * np.eye(p)
    centers = rng.normal(size=(b, p))
    a_j = jnp.asarray(a_mats)
    c_j = jnp.asarray(centers)

    def fun(theta):
        d = theta - c_j
        ad = jnp.einsum("bij,bj->bi", a_j, d)
        return 0.5 * jnp.sum(d * ad, axis=-1), ad

    theta0 = jnp.asarray(rng.normal(size=(b, p)))
    # tol=1e9 makes EVERY accepted decrease "sub-tol"; gtol/floor disabled
    # so ftol is the only live exit.
    base = dict(max_iters=50, tol=1e9, gtol=0.0, floor_patience=1 << 30)
    res1 = lbfgs.minimize(fun, theta0, SolverConfig(ftol_patience=1, **base))
    res4 = lbfgs.minimize(fun, theta0, SolverConfig(ftol_patience=4, **base))
    # Impatient: one accepted iteration then stop, far from the optimum.
    assert int(np.asarray(res1.n_iters).max()) == 1
    # Patient: runs exactly the patience budget, strictly lower objective.
    assert int(np.asarray(res4.n_iters).min()) == 4
    assert float(np.asarray(res4.f).max()) < float(np.asarray(res1.f).min())
