"""Batched L-BFGS: convex quadratics (exact answer), Rosenbrock (hard),
and mixed batches where series converge at different rates."""

import jax
import jax.numpy as jnp
import numpy as np

from tsspark_tpu.config import SolverConfig
from tsspark_tpu.ops import lbfgs


def _batch_fun(f_single):
    """Lift a scalar objective to the (B,) losses + (B, P) grads contract."""

    def fun(theta):
        f = jax.vmap(f_single)(theta)
        g = jax.vmap(jax.grad(f_single))(theta)
        return f, g

    return fun


def test_batched_quadratic_exact():
    rng = np.random.default_rng(0)
    b, p = 16, 8
    # Random SPD quadratics with distinct conditioning per series.
    a_half = rng.normal(size=(b, p, p))
    a_mats = np.einsum("bij,bkj->bik", a_half, a_half) + 0.1 * np.eye(p)
    centers = rng.normal(size=(b, p))
    a_j = jnp.asarray(a_mats)
    c_j = jnp.asarray(centers)

    def fun(theta):
        d = theta - c_j
        ad = jnp.einsum("bij,bj->bi", a_j, d)
        f = 0.5 * jnp.sum(d * ad, axis=-1)
        return f, ad

    res = lbfgs.minimize(fun, jnp.asarray(rng.normal(size=(b, p))))
    assert bool(res.converged.all())
    np.testing.assert_allclose(np.asarray(res.theta), centers, atol=1e-3)
    assert np.asarray(res.f).max() < 1e-6


def test_rosenbrock_batch():
    def rosen(x):
        return jnp.sum(
            100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2
        )

    rng = np.random.default_rng(1)
    theta0 = jnp.asarray(rng.uniform(-1.5, 1.5, size=(8, 4)))
    res = lbfgs.minimize(
        _batch_fun(rosen), theta0, SolverConfig(max_iters=500, tol=0.0, gtol=1e-5)
    )
    # Every series must reach a stationary point (4D Rosenbrock has a genuine
    # local minimum near (-1, 1, 1, 1), so not all starts reach all-ones).
    assert np.asarray(res.grad_norm).max() < 1e-3
    # Which basin each start lands in is trajectory luck; stationarity above
    # is the real check.  Still require the global optimum to dominate.
    at_global = np.abs(np.asarray(res.theta) - 1.0).max(axis=-1) < 1e-2
    assert at_global.sum() >= 4


def test_mixed_convergence_rates_freeze_correctly():
    # Series 0: trivial 1-step quadratic; series 1: badly conditioned.
    scales = jnp.asarray([[1.0, 1.0], [1.0, 1e4]])

    def fun(theta):
        f = 0.5 * jnp.sum(scales * theta * theta, axis=-1)
        return f, scales * theta

    theta0 = jnp.full((2, 2), 3.0)
    res = lbfgs.minimize(fun, theta0, SolverConfig(max_iters=300, tol=0.0))
    assert bool(res.converged.all())
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-3)
    # The easy series must have stopped iterating earlier than the hard one.
    assert int(res.n_iters[0]) <= int(res.n_iters[1])


def test_nonfinite_trial_rejected():
    # Objective explodes to NaN outside |x| < 2: line search must shrink past it.
    def f_single(x):
        v = jnp.sum(x * x)
        return jnp.where(v > 4.0, jnp.nan, v)

    theta0 = jnp.asarray([[1.9, 0.0]])
    res = lbfgs.minimize(_batch_fun(f_single), theta0, SolverConfig(max_iters=100))
    assert np.isfinite(float(res.f[0]))
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-3)


def test_jit_compatible():
    def fun(theta):
        f = 0.5 * jnp.sum(theta * theta, axis=-1)
        return f, theta

    jitted = jax.jit(lambda t0: lbfgs.minimize(fun, t0))
    res = jitted(jnp.ones((4, 3)))
    np.testing.assert_allclose(np.asarray(res.theta), 0.0, atol=1e-4)
