"""Spark adapter (duck-typed fake session) + CLI + forecaster checkpoints."""

import json
import subprocess
import sys
import os

import numpy as np
import pandas as pd
import pytest

from tsspark_tpu import Forecaster, ProphetConfig, SeasonalityConfig
from tsspark_tpu.spark import SparkForecaster, forecast_spark
from tsspark_tpu.utils import checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _long_df(b=2, n=150, seed=0):
    rng = np.random.default_rng(seed)
    ds = pd.date_range("2024-01-01", periods=n, freq="D")
    t = np.arange(n)
    frames = [
        pd.DataFrame({
            "series_id": f"s{i}",
            "ds": ds,
            "y": 8 + 0.05 * t + 2 * np.sin(2 * np.pi * t / 7)
                 + rng.normal(0, 0.3, n),
        })
        for i in range(b)
    ]
    return pd.concat(frames, ignore_index=True)


_CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 3),), n_changepoints=5
)


# -- fake Spark surface ------------------------------------------------------

class FakeSession:
    def createDataFrame(self, pdf):
        return FakeSparkFrame(pdf, self)


class FakeSparkFrame:
    def __init__(self, pdf, session=None):
        self._pdf = pdf
        self.sparkSession = session or FakeSession()

    def toPandas(self):
        return self._pdf.copy()


def test_spark_adapter_round_trip():
    sdf = FakeSparkFrame(_long_df())
    out = forecast_spark(sdf, Forecaster(_CFG), horizon=14)
    assert isinstance(out, FakeSparkFrame)
    pdf = out.toPandas()
    assert {"series_id", "ds", "yhat", "yhat_lower", "yhat_upper"} <= set(
        pdf.columns
    )
    assert len(pdf) == 2 * 14
    assert np.isfinite(pdf["yhat"]).all()


def test_spark_adapter_rejects_non_spark_input():
    with pytest.raises(TypeError, match="toPandas"):
        SparkForecaster(Forecaster(_CFG)).fit(_long_df())


def test_spark_adapter_predict_before_fit():
    with pytest.raises(RuntimeError, match="before fit"):
        SparkForecaster(Forecaster(_CFG)).predict(horizon=3)


# -- forecaster checkpoint round trip ---------------------------------------

def test_save_load_forecaster(tmp_path):
    df = _long_df()
    fc = Forecaster(_CFG)
    fc.fit(df)
    expected = fc.predict(horizon=7)

    path = str(tmp_path / "model.npz")
    checkpoint.save_forecaster(path, fc)
    fc2 = checkpoint.load_forecaster(path)
    got = fc2.predict(horizon=7)

    pd.testing.assert_frame_equal(
        expected.reset_index(drop=True), got.reset_index(drop=True)
    )


def test_save_forecaster_requires_fitted(tmp_path):
    with pytest.raises(ValueError, match="fitted"):
        checkpoint.save_forecaster(str(tmp_path / "m.npz"), Forecaster(_CFG))


# -- CLI ---------------------------------------------------------------------

def _run_cli(args, tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return subprocess.run(
        [sys.executable, "-m", "tsspark_tpu", *args],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=300,
    )


@pytest.mark.slow
def test_cli_forecast_and_backtest(tmp_path):
    _long_df().to_csv(tmp_path / "input.csv", index=False)

    r = _run_cli([
        "forecast", "--input", "input.csv", "--horizon", "7",
        "--output", "fc.csv", "--seasonality", "weekly",
        "--n-changepoints", "5", "--max-iters", "80",
    ], tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    fc = pd.read_csv(tmp_path / "fc.csv")
    assert {"series_id", "ds", "yhat"} <= set(fc.columns)
    assert len(fc) == 2 * 7

    r = _run_cli([
        "fit", "--input", "input.csv", "--model", "model.npz",
        "--seasonality", "weekly", "--n-changepoints", "5",
        "--max-iters", "80",
    ], tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    meta = json.loads(r.stdout.strip().splitlines()[-1])
    assert meta["n_series"] == 2

    r = _run_cli([
        "predict", "--model", "model.npz", "--horizon", "5",
        "--output", "pred.csv",
    ], tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(pd.read_csv(tmp_path / "pred.csv")) == 2 * 5

    r = _run_cli([
        "backtest", "--input", "input.csv", "--horizon", "7",
        "--period", "30", "--initial", "90", "--output", "pm.csv",
        "--seasonality", "weekly", "--n-changepoints", "5",
        "--max-iters", "80",
    ], tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    pm = pd.read_csv(tmp_path / "pm.csv")
    assert {"horizon", "smape", "rmse"} <= set(pm.columns)


@pytest.mark.slow
def test_cli_auto_seasonality_flag(tmp_path):
    # 100 daily points: the auto rule resolves to WEEKLY ONLY, which differs
    # from the CLI's yearly+weekly default — a silently ignored flag would
    # produce a different (larger) fitted config, caught below.
    rng = np.random.default_rng(4)
    n = 100
    t = np.arange(n, dtype=float)
    df = pd.DataFrame({
        "series_id": "s0",
        "ds": pd.date_range("2020-01-01", periods=n, freq="D"),
        "y": 7 + 2 * np.sin(2 * np.pi * t / 7) + rng.normal(0, 0.1, n),
    })
    df.to_csv(tmp_path / "input.csv", index=False)
    r = _run_cli([
        "fit", "--input", "input.csv", "--auto-seasonality",
        "--n-changepoints", "5", "--max-iters", "60",
        "--model", "model.npz",
    ], tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    from tsspark_tpu.utils import checkpoint

    fc = checkpoint.load_forecaster(str(tmp_path / "model.npz"))
    assert tuple(s.name for s in fc.config.seasonalities) == ("weekly",)
    out = fc.predict(horizon=7)
    assert len(out) == 7 and np.isfinite(out["yhat"]).all()
