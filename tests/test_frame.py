"""Long-format DataFrame front-end: pivot, datetime round-trip, and
regressions for review findings (floor alignment, standardize opt-out,
chunked per-series grids)."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

import tsspark_tpu as tt
from tsspark_tpu.config import ProphetConfig, RegressorConfig, SolverConfig, WEEKLY
from tsspark_tpu.frame import Forecaster, pivot_long
from tsspark_tpu.models.prophet.design import prepare_fit_data


def _long_df(n_days=120, n_series=2, seed=0, start="2023-01-01"):
    rng = np.random.default_rng(seed)
    dates = pd.date_range(start, periods=n_days, freq="D")
    frames = []
    for i in range(n_series):
        lvl = 10.0 * (i + 1)
        y = lvl + np.sin(2 * np.pi * np.arange(n_days) / 7) + rng.normal(
            0, 0.1, n_days
        )
        frames.append(
            pd.DataFrame({"series_id": f"s{i}", "ds": dates, "y": y})
        )
    return pd.concat(frames, ignore_index=True)


def test_pivot_long_shapes_and_holes():
    df = _long_df(n_days=10)
    df = df.drop(df[(df.series_id == "s1") & (df.ds < "2023-01-04")].index)
    batch = pivot_long(df)
    assert batch.y.shape == (2, 10)
    assert np.isnan(batch.y[1, :3]).all() and np.isfinite(batch.y[1, 3:]).all()


def test_pivot_floor_staggered_start():
    """Review finding: floor must come from each series' first OBSERVED row,
    not union-grid column 0."""
    df = _long_df(n_days=10)
    df["floor"] = np.where(df.series_id == "s0", 5.0, 8.0)
    df = df.drop(df[(df.series_id == "s1") & (df.ds < "2023-01-04")].index)
    batch = pivot_long(df, floor_col="floor")
    np.testing.assert_allclose(batch.floor, [5.0, 8.0])


def test_datetime_roundtrip_us_resolution():
    """Regression: pandas >= 2 may store datetime64 at us resolution; output
    ds must continue the training calendar, not land in 1970."""
    df = _long_df(n_days=60)
    fc = Forecaster(
        ProphetConfig(seasonalities=(WEEKLY,), n_changepoints=3), backend="tpu"
    ).fit(df)
    out = fc.predict(horizon=5, num_samples=0)
    assert out.ds.min() == pd.Timestamp("2023-03-02")
    assert out.ds.max() == pd.Timestamp("2023-03-06")


def test_numeric_ds_passthrough():
    df = _long_df(n_days=40)
    df["ds"] = (df.ds - pd.Timestamp("1970-01-01")).dt.days.astype(float)
    fc = Forecaster(
        ProphetConfig(seasonalities=(WEEKLY,), n_changepoints=3), backend="tpu"
    ).fit(df)
    out = fc.predict(horizon=3, num_samples=0)
    assert np.issubdtype(out.ds.dtype, np.floating)
    assert len(out) == 2 * 3


def test_regressor_standardize_opt_out():
    """Review finding: standardize=False must leave continuous columns raw."""
    cfg = ProphetConfig(
        seasonalities=(),
        n_changepoints=0,
        regressors=(RegressorConfig("temp", standardize=False),),
    )
    rng = np.random.default_rng(1)
    reg = rng.normal(20.0, 5.0, (1, 50, 1))
    data, meta = prepare_fit_data(
        jnp.arange(50.0), jnp.asarray(rng.normal(size=(1, 50))), cfg,
        regressors=jnp.asarray(reg),
    )
    np.testing.assert_allclose(np.asarray(data.X_reg), reg, atol=1e-5)
    np.testing.assert_allclose(np.asarray(meta.reg_std), 1.0)


def test_chunked_fit_with_per_series_grids():
    """Review finding: (B, T) ds must survive chunking + padding."""
    rng = np.random.default_rng(2)
    b, t_len = 3, 60
    ds = np.stack([np.arange(t_len, dtype=float) + 10 * i for i in range(b)])
    y = 5.0 + 0.1 * ds + rng.normal(0, 0.1, (b, t_len))
    backend = tt.get_backend(
        "tpu",
        ProphetConfig(seasonalities=(), n_changepoints=2),
        tt.SolverConfig(max_iters=50),
        chunk_size=2,
    )
    state = backend.fit(jnp.asarray(ds), jnp.asarray(y))
    assert state.theta.shape[0] == b
    assert bool(jnp.isfinite(state.loss).all())
    np.testing.assert_allclose(
        np.asarray(state.meta.ds_start), ds[:, 0], atol=1e-6
    )


def test_regressor_coefficients_recover_known_effect():
    """regressor_coefficients must report the effect per RAW unit of the
    regressor in data units, undoing both y-scaling and standardization."""
    rng = np.random.default_rng(9)
    n = 300
    t = np.arange(float(n))
    price = rng.normal(50.0, 10.0, n)
    y = 100.0 + 0.05 * t + 2.5 * price + rng.normal(0, 0.5, n)
    df = pd.DataFrame({"series_id": "s0", "ds": t, "y": y, "price": price})
    cfg = ProphetConfig(
        seasonalities=(), n_changepoints=3,
        regressors=(RegressorConfig("price"),),
    )
    fc = tt.Forecaster(cfg, regressor_cols=("price",)).fit(df)
    out = fc.regressor_coefficients()
    assert set(out.columns) == {"series_id", "regressor", "mode", "coef"}
    assert out.shape[0] == 1
    np.testing.assert_allclose(out["coef"].iloc[0], 2.5, rtol=0.05)


def test_fit_prophet_compat_namespace():
    """The reference's module path survives the rename: tsspark.fit.prophet
    -> tsspark_tpu.fit.prophet (BASELINE.json:5)."""
    from tsspark_tpu.fit import prophet

    assert prophet.ProphetModel is not None
    rng = np.random.default_rng(0)
    n = 80
    model = prophet.ProphetModel(
        prophet.ProphetConfig(seasonalities=(), n_changepoints=2),
        prophet.SolverConfig(max_iters=30),
    )
    y = (5 + 0.1 * np.arange(n) + rng.normal(0, 0.2, (1, n))).astype(np.float32)
    state = model.fit(jnp.arange(float(n)), jnp.asarray(y))
    assert np.isfinite(float(state.loss[0]))


def test_make_future_frame_and_builders():
    """Chainable config builders + make_future_frame edit-then-predict loop
    (Prophet's add_regressor / make_future_dataframe workflow)."""
    rng = np.random.default_rng(5)
    ds = pd.date_range("2022-01-01", periods=200, freq="D")
    promo = (rng.random(200) < 0.1).astype(float)
    y = 10 + 0.02 * np.arange(200) + 2.0 * promo + rng.normal(0, 0.1, 200)
    df = pd.DataFrame(
        {"series_id": "a", "ds": ds, "y": y, "promo": promo}
    )

    cfg = (
        ProphetConfig(seasonalities=(), n_changepoints=3)
        .with_seasonality("weekly", 7.0, 2)
        .with_regressor("promo", standardize=False)
    )
    assert [s.name for s in cfg.seasonalities] == ["weekly"]
    assert [r.name for r in cfg.regressors] == ["promo"]
    with pytest.raises(ValueError, match="duplicate"):
        cfg.with_regressor("promo")

    fc = Forecaster(cfg, SolverConfig(max_iters=60), backend="tpu").fit(df)
    fut = fc.make_future_frame(horizon=14)
    assert len(fut) == 14
    assert fut["ds"].min() > df["ds"].max()
    # Regressor models refuse bare horizon but accept the edited frame.
    with pytest.raises(ValueError, match="future_df"):
        fc.predict(horizon=14)
    fut["promo"] = 1.0
    hi = fc.predict(future_df=fut)
    fut2 = fc.make_future_frame(horizon=14)
    fut2["promo"] = 0.0
    lo = fc.predict(future_df=fut2)
    # The recovered promo effect separates the two futures.
    assert float((hi.yhat - lo.yhat).mean()) > 1.0


def test_explicit_changepoints():
    """Prophet's changepoints= arg: a known trend break at an explicit date
    is recovered, and the config's grid is pinned to exactly those dates."""
    rng = np.random.default_rng(9)
    n = 300
    ds = pd.date_range("2022-01-01", periods=n, freq="D")
    t = np.arange(n)
    brk = 150
    y = 5 + 0.05 * t - 0.09 * np.maximum(t - brk, 0) + rng.normal(0, 0.1, n)
    df = pd.DataFrame({"series_id": "a", "ds": ds, "y": y})

    fc = Forecaster(
        ProphetConfig(seasonalities=(), changepoint_prior_scale=1.0),
        SolverConfig(max_iters=80),
        backend="tpu",
        changepoints=[ds[brk]],
    )
    assert fc.config.n_changepoints == 1
    fc.fit(df)
    # Slope before vs after the break, from the fitted trend.
    comp = fc.predict(future_df=df[["series_id", "ds"]])
    trend = comp["trend"].to_numpy()
    pre = np.polyfit(t[20:brk], trend[20:brk], 1)[0]
    post = np.polyfit(t[brk + 20:], trend[brk + 20:], 1)[0]
    assert pre - post > 0.05, (pre, post)


def test_predictive_samples():
    """Raw draw tensor: right shape, centered on yhat, in data units."""
    df = _long_df(n_days=100, n_series=3)
    fc = Forecaster(
        ProphetConfig(seasonalities=(WEEKLY,), n_changepoints=3),
        SolverConfig(max_iters=40),
        backend="tpu",
    ).fit(df)
    out = fc.predictive_samples(horizon=10, num_samples=64, seed=1)
    s = out["yhat_samples"]
    assert s.shape == (64, 3, 10)
    assert out["ds"].shape == (10,)
    point = fc.predict(horizon=10)
    med = np.median(s, axis=0).ravel()
    np.testing.assert_allclose(
        med, point["yhat"].to_numpy(), atol=np.abs(med).mean() * 0.5 + 1.0
    )


def test_predictive_samples_guards_and_numeric_changepoints():
    df = _long_df(n_days=80, n_series=2)
    # Sampling disabled -> clear error, not KeyError.
    fc0 = Forecaster(
        ProphetConfig(seasonalities=(), n_changepoints=2,
                      uncertainty_samples=0),
        SolverConfig(max_iters=20), backend="cpu",
    ).fit(df)
    with pytest.raises(ValueError, match="uncertainty_samples"):
        fc0.predictive_samples(horizon=5)
    # Backend-independence: raw draws work through the scipy cpu backend.
    out = fc0.predictive_samples(horizon=5, num_samples=16)
    assert out["yhat_samples"].shape == (16, 2, 5)
    # numpy-integer changepoints on a NUMERIC calendar stay in day units
    # (pd.to_datetime would read them as nanoseconds).
    dfn = df.copy()
    dfn["ds"] = (
        (pd.to_datetime(df["ds"]) - pd.Timestamp("1970-01-01"))
        / pd.Timedelta(days=1)
    )
    day40 = float(dfn["ds"].iloc[40])
    fcn = Forecaster(
        ProphetConfig(seasonalities=()),
        SolverConfig(max_iters=10), backend="cpu",
        changepoints=np.array([int(day40)], dtype=np.int64),
    )
    assert fcn.config.changepoints == (float(int(day40)),)
    # Out-of-span explicit changepoint warns instead of failing the batch.
    with pytest.warns(UserWarning, match="outside their observed span"):
        Forecaster(
            ProphetConfig(seasonalities=()), SolverConfig(max_iters=5),
            backend="cpu", changepoints=[df["ds"].max() + pd.Timedelta(days=400)],
        ).fit(df)
