"""Snapshot plane (serve/snapplane.py + registry mmap path,
docs/SERVING.md "Snapshot plane & memory model"): bitwise parity of
predictions served from an mmap snapshot vs the same version's npz —
direct engine AND through the replica pool, through a version flip and
a registry fallback — plus torn-shard sentinel rejection, the bounded
forecast cache's eviction accounting, the analysis gate's bytecode
hygiene checker, and the tier-1 scale-ladder smoke rung."""

import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.serve import (
    ForecastCache,
    ParamRegistry,
    PredictionEngine,
    RegistryError,
)
from tsspark_tpu.serve import snapplane

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(3)
    t = np.arange(140.0)
    y = (12 + 0.03 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (6, 140)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    return backend, state, [f"s{i}" for i in range(6)]


def _registry(tmp_path, fitted, name="registry", **kwargs):
    backend, state, ids = fitted
    reg = ParamRegistry(str(tmp_path / name), CFG, **kwargs)
    reg.publish(state, ids, step=np.ones(len(ids)))
    return reg


def _tear(path):
    """Byte-flip several offsets of one file (same spread as
    faults.corrupt_file)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        for k in range(1, 8):
            fh.seek(size * k // 8)
            chunk = fh.read(16)
            fh.seek(size * k // 8)
            fh.write(bytes(b ^ 0xFF for b in chunk))


# ---------------------------------------------------------------------------
# plane write/attach mechanics
# ---------------------------------------------------------------------------


def test_publish_lands_plane_and_npz(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    vdir = os.path.join(reg.root, "v000001")
    names = set(os.listdir(vdir))
    assert {"snap_spec.json", "snapok.json", "state.npz",
            "state.json"} <= names
    assert {"snapcol_theta.npy", "snapcol_ids.npy",
            "snapcol_ids_sorted.npy", "snapcol_id_order.npy",
            "snapcol_extra_step.npy"} <= names
    assert snapplane.verify_plane(vdir)
    assert snapplane.snapshot_nbytes(vdir) > 0
    # The manifest records which formats landed.
    m = reg._read_manifest()
    assert m["versions"]["1"]["formats"] == ["mmap", "npz"]


def test_mmap_rows_match_dict_lookup(tmp_path, fitted):
    """The vectorized searchsorted lookup is semantically identical to
    the npz path's dict: order preserved, duplicates kept, unknown ids
    reported, empty query tolerated."""
    reg = _registry(tmp_path, fitted)
    mm = reg.load()
    npz = ParamRegistry(reg.root, CFG, snapshot_format="npz").load()
    assert mm.source == "mmap" and npz.source == "npz"
    for query in (["s3", "s1", "s1", "s5"], ["nope"], ["s0", "zzz"],
                  []):
        i_mm, miss_mm = mm.rows(query)
        i_npz, miss_npz = npz.rows(query)
        assert i_mm.tolist() == i_npz.tolist()
        assert miss_mm == miss_npz


def test_torn_plane_shard_rejected_then_npz_archival_fallback(
        tmp_path, fitted):
    """A torn plane shard must be rejected by the CRC sentinel; with
    the SAME version's archival npz intact, the registry degrades to it
    (one warning) — not to an older version."""
    reg = _registry(tmp_path, fitted)
    vdir = os.path.join(reg.root, "v000001")
    _tear(os.path.join(vdir, "snapcol_theta.npy"))
    assert not snapplane.verify_plane(vdir)
    with pytest.raises(snapplane.SnapshotPlaneError):
        snapplane.attach(vdir)
    with pytest.warns(RuntimeWarning, match="archival npz"):
        snap = reg.load()
    assert snap.source == "npz" and snap.version == 1
    assert snap.fallback_from is None  # same version, different format


def test_torn_plane_only_version_falls_back_to_previous(tmp_path,
                                                        fitted):
    """A plane-ONLY version (no npz) with a torn shard must degrade
    down the active->previous chain, exactly like a corrupt npz."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)  # v1, both formats
    v2 = reg.publish(state._replace(theta=state.theta * 1.01), ids,
                     snapshot_format="mmap")
    _tear(os.path.join(reg.root, f"v{v2:06d}", "snapcol_theta.npy"))
    with pytest.warns(RuntimeWarning, match="last good"):
        snap = reg.load()
    assert snap.version == 1 and snap.fallback_from == v2
    with pytest.raises(RegistryError) as e:
        reg.load(v2)
    assert e.value.reason == "corrupt-snapshot"


# ---------------------------------------------------------------------------
# bitwise parity: engine, flip, fallback
# ---------------------------------------------------------------------------


def _forecast_values(engine, sids, horizon):
    res = engine.forecast(sids, horizon)
    return res.version, np.asarray(res.ds), {
        k: np.asarray(v) for k, v in res.values.items()
    }


def _assert_bitwise(a, b):
    va, dsa, vala = a
    vb, dsb, valb = b
    assert va == vb
    assert np.array_equal(dsa, dsb)
    assert set(vala) == set(valb)
    for k in vala:
        assert np.array_equal(vala[k], valb[k]), k


def test_engine_predictions_bitwise_equal_across_formats(tmp_path,
                                                         fitted):
    """One registry, two engines — one on the mmap plane, one pinned to
    the npz — must serve bit-identical forecasts, including after a
    version flip and under a registry fallback."""
    backend, state, ids = fitted
    reg_mm = _registry(tmp_path, fitted)
    reg_npz = ParamRegistry(reg_mm.root, CFG, snapshot_format="npz")
    eng_mm = PredictionEngine(reg_mm, cache=ForecastCache(64))
    eng_npz = PredictionEngine(reg_npz, cache=ForecastCache(64))
    assert eng_mm.refresh().source == "mmap"
    assert eng_npz.refresh().source == "npz"
    for sids, h in ((["s0"], 7), (["s4", "s2", "s0"], 12),
                    (["s5", "s5"], 3)):
        _assert_bitwise(_forecast_values(eng_mm, sids, h),
                        _forecast_values(eng_npz, sids, h))

    # Through a version flip (each engine refreshes independently).
    v2 = reg_mm.publish(state._replace(theta=state.theta * 1.02), ids,
                        step=np.ones(len(ids)))
    a = _forecast_values(eng_mm, ["s1", "s3"], 9)
    b = _forecast_values(eng_npz, ["s1", "s3"], 9)
    assert a[0] == v2
    _assert_bitwise(a, b)

    # Through a registry fallback: v2 torn in BOTH formats -> both
    # engines degrade to v1 and still agree bit for bit.
    for name in ("state.npz", "snapcol_theta.npy"):
        _tear(os.path.join(reg_mm.root, f"v{v2:06d}", name))
    with pytest.warns(RuntimeWarning, match="last good"):
        assert eng_mm.ensure_version(1)
    with pytest.warns(RuntimeWarning, match="last good"):
        assert eng_npz.ensure_version(1)
    a = _forecast_values(eng_mm, ["s2", "s0"], 7)
    b = _forecast_values(eng_npz, ["s2", "s0"], 7)
    assert a[0] == 1
    _assert_bitwise(a, b)


def test_pool_predictions_bitwise_equal_across_formats(tmp_path,
                                                       fitted,
                                                       monkeypatch):
    """The parity contract THROUGH the replica pool: one pool of
    replicas attached to the mmap plane, one env-pinned to the npz
    format — responses (including through a pool-materialized version
    flip) are bit-identical."""
    from tsspark_tpu.serve.pool import ReplicaPool

    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    v2 = reg.publish(state._replace(theta=state.theta * 1.01), ids,
                     step=np.ones(len(ids)), activate=False)

    def collect(pool, version):
        out = []
        for sids, h in ((["s0"], 7), (["s3", "s1"], 9)):
            resp = pool.forecast(sids, h)
            assert resp.get("ok") and resp["version"] == version, resp
            out.append({k: resp[k] for k in
                        ("ds", "yhat", "series_ids", "version")
                        if k in resp})
        return out

    monkeypatch.delenv("TSSPARK_SNAPSHOT_FORMAT", raising=False)
    pool = ReplicaPool(str(tmp_path / "pool_mm"), reg.root,
                       n_replicas=1)
    pool.start()
    try:
        got_mm_v1 = collect(pool, 1)
        pool.activate(v2, hot_series=ids[:2], horizons=(7, 9))
        got_mm_v2 = collect(pool, v2)
    finally:
        pool.stop()

    reg.activate(1)  # reset the active pointer for the npz pool
    monkeypatch.setenv("TSSPARK_SNAPSHOT_FORMAT", "npz")
    pool = ReplicaPool(str(tmp_path / "pool_npz"), reg.root,
                       n_replicas=1)
    pool.start()
    try:
        got_npz_v1 = collect(pool, 1)
        pool.activate(v2, hot_series=ids[:2], horizons=(7, 9))
        got_npz_v2 = collect(pool, v2)
    finally:
        pool.stop()
    assert got_mm_v1 == got_npz_v1
    assert got_mm_v2 == got_npz_v2


# ---------------------------------------------------------------------------
# bounded forecast cache
# ---------------------------------------------------------------------------


def test_cache_lru_eviction_counted():
    cache = ForecastCache(capacity=3)
    for i in range(5):
        cache.put((1, f"s{i}", 8, 0, 0), {"yhat": np.zeros(8)})
    assert len(cache) == 3
    assert cache.evicted == 2
    assert cache.stats()["evicted"] == 2
    # LRU order: oldest two went first.
    assert cache.peek((1, "s0", 8, 0, 0)) is None
    assert cache.peek((1, "s4", 8, 0, 0)) is not None
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    text = METRICS.to_prometheus()
    assert "tsspark_serve_cache_evicted" in text


def test_cache_capacity_from_env(monkeypatch):
    monkeypatch.setenv("TSSPARK_SERVE_CACHE_CAPACITY", "17")
    assert ForecastCache().capacity == 17
    assert ForecastCache(capacity=5).capacity == 5
    monkeypatch.delenv("TSSPARK_SERVE_CACHE_CAPACITY")
    from tsspark_tpu.serve.cache import FALLBACK_CAPACITY

    assert ForecastCache().capacity == FALLBACK_CAPACITY


# ---------------------------------------------------------------------------
# hygiene checker (committed bytecode)
# ---------------------------------------------------------------------------


def test_hygiene_flags_committed_bytecode(tmp_path):
    from tsspark_tpu.analysis import hygiene

    (tmp_path / ".gitignore").write_text("__pycache__/\n*.pyc\n")
    clean = hygiene.check_hygiene(
        str(tmp_path), tracked=["tsspark_tpu/serve/engine.py"]
    )
    assert clean == []
    dirty = hygiene.check_hygiene(str(tmp_path), tracked=[
        "tsspark_tpu/serve/engine.py",
        "__pycache__/bench.cpython-310.pyc",
        "tsspark_tpu/__pycache__/config.cpython-310.pyc",
        "tsspark_tpu/native/blob.pyo",
    ])
    assert sorted(f.rule for f in dirty) == ["committed-bytecode"] * 3
    # The gitignore coverage check.
    (tmp_path / ".gitignore").write_text("*.log\n")
    gap = hygiene.check_hygiene(str(tmp_path), tracked=[])
    assert [f.rule for f in gap] == ["gitignore-gap"]


def test_repo_has_no_tracked_bytecode_and_ignores_pycache():
    """The live gate over THIS checkout: no bytecode in the index, and
    the root .gitignore keeps covering __pycache__/ (root dir
    included)."""
    from tsspark_tpu.analysis import hygiene

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert hygiene.check_hygiene(root) == []


# ---------------------------------------------------------------------------
# scale ladder: the tier-1 smoke rung
# ---------------------------------------------------------------------------


def test_scale_smoke_rung_in_process(tmp_path, monkeypatch):
    """The in-process smoke rung of ``bench --scale``: ingest -> fit
    (resident path; the test mesh is the conftest's 8 virtual devices)
    -> mmap publish -> engine serve with a mid-run flip — wired through
    the regression sentinel so ladder metrics accrue baselines under
    the scale-scoped workload key."""
    from tsspark_tpu import bench_scale
    from tsspark_tpu.obs import history

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("TSSPARK_DATA_ROOT", str(tmp_path / "plane"))
    rep = bench_scale.run_rung(
        "smoke", scratch_root=str(tmp_path / "scratch"),
        sentinel=True,
    )
    assert rep["complete"], rep
    assert rep["fit"]["fit_path"] == "resident"
    assert rep["publish"]["snapshot_mb"] > 0
    serve = rep["serve"]
    assert serve["outcomes"]["failed"] == 0
    assert serve["flip"]["version"] == 2
    assert serve["time_to_first_request_s"] is not None
    assert os.path.exists(rep["path"])
    # The sentinel ingested the rung under its scale-scoped workload
    # key — the namespace discipline that keeps 1M rows from ever
    # baselining against smoke rows.
    rows = history.read_history(str(tmp_path / "RUNHISTORY.jsonl"))
    srows = [r for r in rows if r["kind"] == "scale"]
    assert len(srows) == 1
    assert srows[0]["workload"] == "scale_smoke"
    m = srows[0]["metrics"]
    assert m["agg_requests_per_s"] > 0
    assert m["rss_mb_per_replica"] > 0
    assert rep.get("sentinel_ok", True)
    # Re-ingesting the same report is a no-op (idempotent by trace id).
    row, appended = history.ingest(
        json.load(open(rep["path"])),
        str(tmp_path / "RUNHISTORY.jsonl"),
    )
    assert row is not None and not appended
