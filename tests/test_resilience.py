"""Resilience subsystem: retry policy schedules, deterministic fault
injection, chunk integrity (CRC + quarantine), poison-series
quarantine/bisection, CPU degradation, and the crash-recovery acceptance
scenario (worker killed twice + corrupt chunk + NaN-poisoned series ->
fit completes, healthy series bit-identical to the fault-free run).

Everything runs on CPU: the fault harness (resilience.faults) provokes
the failures a real TPU deployment meets, deterministically.
"""

import glob
import os
import sys
import warnings as warnings_mod

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tsspark_tpu import orchestrate  # noqa: E402
from tsspark_tpu.resilience import faults, integrity  # noqa: E402
from tsspark_tpu.resilience.policy import (  # noqa: E402
    PROBE,
    STREAM_POLL,
    WORKER_RETRY,
    CircuitBreaker,
    CircuitOpen,
    RetryPolicy,
)
from tsspark_tpu.resilience.report import (  # noqa: E402
    STATUS_QUARANTINED,
    ResilienceWarning,
    get_report,
)

# Fast schedules for subprocess tests: real sleeps stay, but short.
FAST_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.2, backoff=1.0,
                         max_delay_s=0.5)


def _model_config():
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig

    return ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=6,
    )


def _batch(series=48, days=128):
    from tsspark_tpu.data import datasets

    b = datasets.m5_like(n_series=series, n_days=days)
    y = np.nan_to_num(b.y).astype(np.float32)
    return b.ds.astype(np.float64), y, b.mask.astype(np.float32)


def _fit(tmp_path, name, ds, y, mask, **kw):
    from tsspark_tpu.config import SolverConfig

    kw.setdefault("chunk", 16)
    kw.setdefault("phase1_iters", 4)
    kw.setdefault("no_phase1_tune", True)
    kw.setdefault("retry_policy", FAST_RETRY)
    return orchestrate.fit_resilient(
        _model_config(), SolverConfig(max_iters=60), ds, y, mask=mask,
        scratch_dir=str(tmp_path / name), **kw,
    )


# -- policy ----------------------------------------------------------------


def test_retry_policy_schedules():
    # The named defaults reproduce the historical hard-coded schedules.
    assert WORKER_RETRY.delay_s(0) == 10.0
    assert WORKER_RETRY.delay_s(7) == 10.0  # fixed sleep, no backoff
    assert WORKER_RETRY.allows(8) and not WORKER_RETRY.allows(9)
    assert [PROBE.attempt_timeout(k) for k in (0, 1, 4, 99)] == \
        [30.0, 45.0, 90.0, 90.0]
    assert PROBE.delay_s(0) == 5.0
    assert PROBE.delay_s(1) == 7.5
    assert PROBE.delay_s(50) == 30.0  # capped
    assert PROBE.allows(10 ** 9)  # probes never give up
    # Jitter is deterministic: same (seed, retry) -> same delay.
    p = RetryPolicy(base_delay_s=4.0, jitter=0.25, seed=11)
    assert p.delay_s(3) == p.delay_s(3)
    assert 3.0 <= p.delay_s(3) <= 5.0
    assert p.delay_s(3) != RetryPolicy(
        base_delay_s=4.0, jitter=0.25, seed=12
    ).delay_s(3)


def test_retry_policy_call_retries_then_raises():
    calls = {"n": 0}
    pol = RetryPolicy(max_attempts=3, base_delay_s=0.0)

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky) == "ok" and calls["n"] == 3
    calls["n"] = 0

    def always_bad():
        calls["n"] += 1
        raise OSError("persistent")

    with pytest.raises(OSError):
        pol.call(always_bad)
    assert calls["n"] == 3  # attempts bounded


# -- circuit breaker -------------------------------------------------------


def test_circuit_breaker_state_machine():
    """Closed -> open at the failure threshold, open -> half-open after
    the reset window (one trial at a time), trial success closes, trial
    failure re-opens — all on an injected clock."""
    now = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0,
                        name="dep", clock=lambda: now["t"])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed" and br.allow()  # below threshold
    br.record_failure()
    assert br.state == "open" and br.opens == 1
    assert not br.allow() and br.fast_fails == 1
    assert br.retry_after_s() == 10.0
    now["t"] = 10.0
    assert br.state == "half-open"
    assert br.allow()          # the single trial
    assert not br.allow()      # a second concurrent trial is refused
    br.record_failure()        # trial failed: re-open for a new window
    assert br.state == "open" and br.opens == 2
    now["t"] = 20.0
    assert br.allow()
    br.record_success()
    assert br.state == "closed" and br.allow()
    # A success resets the consecutive-failure count entirely.
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"


def test_retry_policy_call_respects_breaker():
    """RetryPolicy.call sheds fast through an open breaker instead of
    retrying a dead dependency to its attempt budget."""
    calls = {"n": 0}

    def always_bad():
        calls["n"] += 1
        raise OSError("down")

    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0,
                        name="broker")
    pol = RetryPolicy(max_attempts=5, base_delay_s=0.0)
    with pytest.raises(OSError):
        pol.call(always_bad, breaker=br)  # 2 attempts trip the breaker
    assert calls["n"] == 2 and br.state == "open"
    with pytest.raises(CircuitOpen):
        pol.call(always_bad, breaker=br)
    assert calls["n"] == 2  # shed BEFORE any attempt ran


def test_breaker_trial_slot_survives_foreign_exception():
    """A half-open trial that dies on a NON-retryable exception (a
    caller bug, not a dependency failure) must still resolve the trial
    slot — the breaker re-opens instead of wedging with the trial
    marked in flight forever."""
    now = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=10.0,
                        name="dep", clock=lambda: now["t"])
    pol = RetryPolicy(max_attempts=3, base_delay_s=0.0)
    with pytest.raises(OSError):
        pol.call(lambda: (_ for _ in ()).throw(OSError("down")),
                 retry_on=(OSError,), breaker=br)
    assert br.state == "open"
    now["t"] = 10.0  # half-open: next call is the trial

    def caller_bug():
        raise ValueError("not a dependency failure")

    with pytest.raises(ValueError):
        pol.call(caller_bug, retry_on=(OSError,), breaker=br)
    assert br.state == "open"  # re-opened, NOT wedged half-open
    now["t"] = 20.0
    assert br.allow()  # a fresh trial is admitted after the window


def test_resilient_source_sheds_through_open_breaker(monkeypatch,
                                                     tmp_path):
    """The streaming poll loop: a broker that keeps failing opens the
    shared breaker, and the next poll raises CircuitOpen immediately —
    no further retry sleeps against a dead dependency."""
    from tsspark_tpu.streaming.source import ResilientSource

    class DeadSource:
        polls = 0

        def poll(self):
            DeadSource.polls += 1
            raise ConnectionError("broker down")

        def commit(self):
            pass

    br = CircuitBreaker(failure_threshold=3, reset_timeout_s=60.0,
                        name="kafka")
    src = ResilientSource(
        DeadSource(),
        RetryPolicy(max_attempts=3, base_delay_s=0.0, max_delay_s=0.0),
        breaker=br,
    )
    with pytest.raises(ConnectionError):
        src.poll()
    assert DeadSource.polls == 3 and br.state == "open"
    with pytest.raises(CircuitOpen):
        src.poll()
    assert DeadSource.polls == 3  # shed fast, zero new attempts


# -- faults ----------------------------------------------------------------


def test_sleep_mode_stalls_without_failing(tmp_path, monkeypatch):
    """The slow-I/O fault class: a "sleep" rule delays the armed call
    and lets it proceed — no flag, no raise, just latency."""
    import time as time_mod

    plan = faults.FaultPlan(state_dir=str(tmp_path / "st")).fail(
        "fit_chunk", mode="sleep", attempts=1, delay_s=0.25,
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    t0 = time_mod.time()
    assert faults.inject("fit_chunk") is False  # stalled, not flagged
    assert time_mod.time() - t0 >= 0.25
    t0 = time_mod.time()
    assert faults.inject("fit_chunk") is False  # window consumed
    assert time_mod.time() - t0 < 0.2


def test_fault_plan_windows_and_series_targeting(tmp_path, monkeypatch):
    plan = (
        faults.FaultPlan(state_dir=str(tmp_path / "st"))
        .fail("a", after=1, attempts=2)
        .fail("b", series=37, attempts=5)
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    fired = 0
    for _ in range(6):
        try:
            faults.inject("a")
        except faults.FaultInjected:
            fired += 1
    assert fired == 2  # skip 1, fire 2, then spent

    faults.inject("b", lo=0, hi=32)  # series 37 not in range: no-op
    with pytest.raises(faults.FaultInjected):
        faults.inject("b", lo=32, hi=64)

    monkeypatch.delenv(faults.ENV_VAR)
    faults.inject("a")  # unarmed: pure no-op


def test_fault_plan_counts_across_processes(tmp_path, monkeypatch):
    """Call slots are claimed via the filesystem, so a respawned process
    continues the count instead of resetting it."""
    import subprocess

    plan = faults.FaultPlan(state_dir=str(tmp_path / "st")).fail(
        "x", after=1, attempts=1
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    code = (
        "from tsspark_tpu.resilience import faults\n"
        "try:\n"
        "    faults.inject('x')\n"
        "    print('clean')\n"
        "except faults.FaultInjected:\n"
        "    print('fired')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    outs = [
        subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env).stdout.strip()
        for _ in range(3)
    ]
    assert outs == ["clean", "fired", "clean"]


# -- integrity -------------------------------------------------------------


def _fake_state(n=4, p=3):
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState

    z = lambda *s: np.zeros(s)
    return FitState(
        theta=np.arange(n * p, dtype=np.float32).reshape(n, p),
        loss=z(n), grad_norm=z(n), converged=np.ones(n, bool),
        n_iters=np.ones(n, np.int32), status=np.ones(n, np.int32),
        meta=ScalingMeta(
            y_scale=np.ones(n), floor=z(n), ds_start=z(n),
            ds_span=np.ones(n), reg_mean=z(n, 1), reg_std=np.ones((n, 1)),
            changepoints=z(n, 2),
        ),
    )


def test_chunk_crc_detects_silent_corruption(tmp_path):
    out = str(tmp_path)
    orchestrate.save_chunk_atomic(out, 0, 4, _fake_state())
    path = orchestrate._chunk_path(out, 0, 4)
    assert integrity.verify_file(path)
    assert integrity.sweep_chunks(out) == []  # healthy: untouched

    # Tamper with the payload but keep the (now stale) stamp: the zip
    # layer cannot catch this — our CRC must.
    z = dict(np.load(path))
    z["theta"] = z["theta"] + 1.0
    np.savez(path, **z)
    assert not integrity.verify_file(path)
    assert integrity.sweep_chunks(out) == [(0, 4)]
    assert os.path.exists(path + ".corrupt") and not os.path.exists(path)
    assert orchestrate.completed_ranges(out) == []  # range re-queued


def test_torn_chunk_quarantined_and_requeued(tmp_path):
    out = str(tmp_path)
    orchestrate.save_chunk_atomic(out, 0, 4, _fake_state())
    path = orchestrate._chunk_path(out, 0, 4)
    with open(path, "r+b") as fh:  # torn write: truncate mid-file
        fh.truncate(os.path.getsize(path) // 2)
    assert integrity.sweep_chunks(out) == [(0, 4)]
    with pytest.raises(RuntimeError, match="incomplete chunk coverage"):
        orchestrate.load_fit_state(out, 4)


def test_load_fit_state_raises_typed_integrity_error(tmp_path):
    out = str(tmp_path)
    orchestrate.save_chunk_atomic(out, 0, 4, _fake_state())
    path = orchestrate._chunk_path(out, 0, 4)
    z = dict(np.load(path))
    z["loss"] = z["loss"] + 7.0
    np.savez(path, **z)
    with pytest.raises(integrity.ChunkIntegrityError) as ei:
        orchestrate.load_fit_state(out, 4)
    assert ei.value.ranges == [(0, 4)]


def test_load_prep_rejects_corrupt_cache(tmp_path):
    """A corrupt prep payload must fall through to local prep (None) and
    be deleted, never fed to the fit."""
    from collections import namedtuple

    out = str(tmp_path)
    Packed = namedtuple("Packed", ["y"])
    Meta = namedtuple("Meta", ["y_scale"])
    orchestrate.save_prep_atomic(
        out, 0, 8, 8, Packed(y=np.ones((8, 4), np.float32)),
        Meta(y_scale=np.ones(8)),
    )
    path = orchestrate._prep_path(out, 0, 8)
    z = dict(np.load(path))
    z["packed_y"] = z["packed_y"] * 2
    np.savez(path, **z)
    assert orchestrate.load_prep(out, 0, 8) is None
    assert not os.path.exists(path)


def test_completed_ranges_sorts_numerically_past_1e6(tmp_path):
    """ADVICE r5 regression: 7-digit chunk names sort lexicographically
    BEFORE 6-digit ones; completed_ranges must return numeric order or
    load_fit_state concatenates chunks into the wrong series rows."""
    out = str(tmp_path)
    ranges = [(999_936, 1_000_448), (998_912, 999_936),
              (1_000_448, 1_000_960), (0, 512)]
    for lo, hi in ranges:
        open(orchestrate._chunk_path(out, lo, hi), "w").close()
    got = orchestrate.completed_ranges(out)
    assert got == sorted(ranges)
    # and the glob order it replaced really was wrong:
    names = sorted(os.path.basename(p) for p in
                   glob.glob(os.path.join(out, "chunk_*.npz")))
    lex = [tuple(map(int, n[len("chunk_"):-len(".npz")].split("_")))
           for n in names]
    assert lex != got


# -- finite-observed-y pre-validation (ADVICE r5) --------------------------


def test_finite_contract_raises_immediately_without_quarantine(tmp_path):
    ds, y, mask = _batch(series=8)
    y = y.copy()
    mask = mask.copy()
    y[3, 10] = np.nan
    mask[3, 10] = 1.0  # observed-but-NaN: the pack contract violation
    with pytest.raises(ValueError, match="finite y wherever mask == 1"):
        _fit(tmp_path, "s", ds, y, mask, quarantine=False)
    # Raised BEFORE spilling data / spawning workers: no scratch content.
    assert not os.path.exists(str(tmp_path / "s" / "data" / "y.npy"))


# -- crash-recovery resume (fault harness) ---------------------------------


def test_crash_resume_bit_identical(tmp_path, monkeypatch):
    """Kill the fit worker mid-run via the fault harness, let the parent
    respawn/resume, and require the final FitState byte-identical to an
    uninterrupted run."""
    # Pin ONE phase-2 mechanism for both runs: a resumed worker has only
    # partial device-resident coverage and would take the host gather
    # path, which agrees with the resident path only to f32 noise
    # (tests/test_orchestrate.py pins that equivalence separately).
    monkeypatch.setenv("BENCH_NO_RESIDENT", "1")
    ds, y, mask = _batch(series=48)
    ref = _fit(tmp_path, "ref", ds, y, mask)

    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "fit_worker_chunk", after=1, attempts=1, mode="exit", rc=31
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    got = _fit(tmp_path, "faulted", ds, y, mask)
    monkeypatch.delenv(faults.ENV_VAR)

    assert get_report(got).retries >= 1  # the kill really happened
    for field in ("theta", "loss", "grad_norm", "converged", "n_iters",
                  "status"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, field)),
            np.asarray(getattr(ref, field)), err_msg=field,
        )
    for field in ref.meta._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(got.meta, field)),
            np.asarray(getattr(ref.meta, field)), err_msg=field,
        )


# -- acceptance: kills + corruption + poison in one run --------------------


def test_acceptance_faulted_fit_completes_and_matches(tmp_path,
                                                      monkeypatch):
    """The issue's acceptance scenario: worker killed twice, one chunk
    checksum-corrupted, one series NaN-poisoned.  fit_resilient must
    complete on CPU, re-fit the corrupt chunk, quarantine + report the
    poisoned series, and leave every healthy series bit-for-bit equal to
    the fault-free run."""
    monkeypatch.setenv("BENCH_NO_RESIDENT", "1")  # see crash-resume test
    ds, y, mask = _batch(series=48)
    ref = _fit(tmp_path, "ref", ds, y, mask)

    y_bad = y.copy()
    mask_bad = mask.copy()
    poison = 21
    y_bad[poison, 5] = np.nan
    mask_bad[poison, 5] = 1.0
    plan = (
        faults.FaultPlan(state_dir=str(tmp_path / "faults"))
        # two worker deaths, each after landing one more chunk
        .fail("fit_worker_chunk", after=1, attempts=2, mode="exit", rc=29)
        # silently corrupt the saved chunk that covers series 0..15
        .fail("chunk_save", series=0, attempts=1, mode="corrupt")
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    got = _fit(tmp_path, "faulted", ds, y_bad, mask_bad)
    monkeypatch.delenv(faults.ENV_VAR)

    report = get_report(got)
    assert report is not None
    assert report.quarantined_indices == (poison,)
    assert "non-finite observed y" in report.quarantined[0].reason
    assert report.retries >= 2  # both kills hit
    # The corrupted chunk was quarantined on disk and re-fit.
    scratch_out = str(tmp_path / "faulted" / "out")
    assert glob.glob(os.path.join(scratch_out, "chunk_*.npz.corrupt"))
    assert not orchestrate.missing_ranges(
        orchestrate.completed_ranges(scratch_out), 48
    )
    # Quarantined row: NaN params, explicit status, not converged.
    assert np.isnan(np.asarray(got.theta)[poison]).all()
    assert np.asarray(got.status)[poison] == STATUS_QUARANTINED
    assert not np.asarray(got.converged)[poison]
    # Every healthy series matches the fault-free run bit for bit.
    healthy = np.asarray([i for i in range(48) if i != poison])
    for field in ("theta", "loss", "grad_norm", "converged", "n_iters",
                  "status"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, field))[healthy],
            np.asarray(getattr(ref, field))[healthy], err_msg=field,
        )


# -- poison bisection + CPU degradation ------------------------------------


def test_spawn_always_failing_degrades_to_cpu(tmp_path, monkeypatch):
    """When the worker path is environmentally dead (every spawn fails,
    zero progress ever), the fit must NOT raise: it bisects, concludes
    the failures are not data-bound, and degrades to the CPU backend
    with a loud ResilienceWarning."""
    ds, y, mask = _batch(series=12)
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "worker_spawn", attempts=10_000, mode="flag"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.warns(ResilienceWarning, match="DEGRADING"):
        got = _fit(tmp_path, "s", ds, y, mask,
                   retry_policy=RetryPolicy(max_attempts=2,
                                            base_delay_s=0.05),
                   max_quarantine=2)
    monkeypatch.delenv(faults.ENV_VAR)
    report = get_report(got)
    assert report.degraded_to_cpu
    assert np.asarray(got.theta).shape[0] == 12
    assert np.isfinite(np.asarray(got.loss)).all()
    assert np.isfinite(np.asarray(got.theta)).all()
    # scipy may hit the 60-iteration cap on a few series; most converge.
    assert np.asarray(got.converged).sum() >= 8


def test_degrade_disabled_raises(tmp_path, monkeypatch):
    ds, y, mask = _batch(series=8)
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "worker_spawn", attempts=10_000, mode="flag"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.raises(orchestrate.WorkerCrashLoopError):
        _fit(tmp_path, "s", ds, y, mask,
             retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.05),
             max_quarantine=1, degrade_to_cpu=False)
    monkeypatch.delenv(faults.ENV_VAR)


@pytest.mark.slow
def test_poison_series_isolated_by_bisection(tmp_path, monkeypatch):
    """A series whose chunk kills the worker wherever it lands is
    bisected down, quarantined, and reported; survivors complete."""
    ds, y, mask = _batch(series=16)
    poison = 9
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "fit_chunk", series=poison, attempts=10_000, mode="exit", rc=33
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.warns(ResilienceWarning, match="quarantined 1 poison"):
        got = _fit(tmp_path, "s", ds, y, mask, chunk=8,
                   retry_policy=RetryPolicy(max_attempts=2,
                                            base_delay_s=0.05))
    monkeypatch.delenv(faults.ENV_VAR)
    report = get_report(got)
    assert report.quarantined_indices == (poison,)
    assert "bisection" in report.quarantined[0].reason
    assert np.asarray(got.status)[poison] == STATUS_QUARANTINED
    assert np.isnan(np.asarray(got.theta)[poison]).all()
    healthy = np.asarray([i for i in range(16) if i != poison])
    assert np.isfinite(np.asarray(got.loss)[healthy]).all()
    assert np.isfinite(np.asarray(got.theta)[healthy]).all()
    # Stuck exits (FLOOR/STALLED) legitimately stay unconverged under
    # two-phase semantics; most series should converge though.
    assert np.asarray(got.converged)[healthy].sum() >= 0.6 * healthy.size


def test_quarantine_placeholder_rows_assemble(tmp_path):
    """Placeholder chunks written for quarantined series must satisfy
    load_fit_state's coverage/shape contract and carry the quarantine
    markers (fast unit path for what the slow bisection test proves end
    to end)."""
    from tsspark_tpu.resilience.report import ResilienceReport

    out = str(tmp_path)
    st = _fake_state(n=4)
    orchestrate.save_chunk_atomic(out, 0, 4, st)
    orchestrate.save_chunk_atomic(out, 5, 8, _fake_state(n=3))
    report = orchestrate._write_quarantine_placeholders(
        out, [4], "test poison", ResilienceReport()
    )
    assert report.quarantined_indices == (4,)
    assembled = orchestrate.load_fit_state(out, 8)
    assert np.isnan(np.asarray(assembled.theta)[4]).all()
    assert np.asarray(assembled.status)[4] == STATUS_QUARANTINED
    assert not np.asarray(assembled.converged)[4]
    np.testing.assert_array_equal(np.asarray(assembled.theta)[:4],
                                  np.asarray(st.theta))
    # The placeholder is flagged so a phase-2 pass never gathers it.
    z = np.load(orchestrate._chunk_path(out, 4, 5))
    assert z["phase2"] == 1 and z["quarantined"] == 1


def test_annotated_state_pickles_to_base_fitstate():
    """The report-annotated state must survive pickle (Spark transfer,
    multiprocessing queues): the generated subclass is not importable,
    so pickling rebuilds the plain FitState (report dropped, like under
    jax.tree transforms)."""
    import pickle

    from tsspark_tpu.models.prophet.model import FitState
    from tsspark_tpu.resilience.report import (
        ResilienceReport, attach_report, get_report,
    )

    st = _fake_state(n=3)
    ann = attach_report(st, ResilienceReport(warnings=("w",)))
    assert get_report(ann) is not None
    back = pickle.loads(pickle.dumps(ann))
    assert type(back) is FitState
    np.testing.assert_array_equal(np.asarray(back.theta),
                                  np.asarray(st.theta))
    # Re-annotation (add_warning on an annotated state, the resilient
    # gate's path) reuses the same class and still pickles clean.
    from tsspark_tpu.resilience.report import add_warning

    ann2 = add_warning(ann, "again")
    assert type(ann2) is type(ann)
    assert get_report(ann2).warnings == ("w", "again")
    assert type(pickle.loads(pickle.dumps(ann2))) is FitState


# -- resilient-gate warning (ADVICE r5) ------------------------------------


def test_resilient_gate_warns_once_and_annotates(tmp_path, monkeypatch):
    from tsspark_tpu.backends import tpu as tpu_mod
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import SolverConfig

    monkeypatch.setattr(tpu_mod, "_RESILIENT_GATE_WARNED", False)
    ds, y, mask = _batch(series=8)
    bk = TpuBackend(
        _model_config(), SolverConfig(max_iters=60), chunk_size=16,
        resilient=True,
        resilient_opts={"scratch_dir": str(tmp_path / "s"),
                        "phase1_iters": 4, "no_phase1_tune": True,
                        "retry_policy": FAST_RETRY},
    )
    with pytest.warns(ResilienceWarning, match="two-phase worker path"):
        state = bk.fit(ds, y, mask=mask)
    report = get_report(state)
    assert report is not None and any(
        "rescue" in w for w in report.warnings
    )
    # One-time: the second eligible fit stays quiet (fresh scratch, same
    # process) but still annotates.
    bk2 = TpuBackend(
        _model_config(), SolverConfig(max_iters=60), chunk_size=16,
        resilient=True,
        resilient_opts={"scratch_dir": str(tmp_path / "s2"),
                        "phase1_iters": 4, "no_phase1_tune": True,
                        "retry_policy": FAST_RETRY},
    )
    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter("error", ResilienceWarning)
        state2 = bk2.fit(ds, y, mask=mask)
    assert any("rescue" in w for w in get_report(state2).warnings)


# -- streaming poll resilience ---------------------------------------------


def test_streaming_poll_retries_transient_faults(tmp_path, monkeypatch):
    import pandas as pd

    from tsspark_tpu.streaming.source import InMemorySource, ResilientSource

    batches = [
        pd.DataFrame({
            "series_id": ["a"] * 30,
            "ds": np.arange(30, dtype=float) + 60 * i,
            "y": np.random.default_rng(i).normal(10, 1, 30),
        })
        for i in range(2)
    ]
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "stream_poll", attempts=2, mode="raise"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    src = ResilientSource(
        InMemorySource(batches),
        RetryPolicy(max_attempts=5, base_delay_s=0.0),
    )
    got = [src.poll(), src.poll(), src.poll()]
    monkeypatch.delenv(faults.ENV_VAR)
    assert got[0] is batches[0] and got[1] is batches[1] and got[2] is None


def test_streaming_poll_policy_exhaustion_reraises(tmp_path, monkeypatch):
    from tsspark_tpu.streaming.source import InMemorySource, ResilientSource

    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "stream_poll", attempts=100, mode="raise"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    src = ResilientSource(
        InMemorySource([]), RetryPolicy(max_attempts=2, base_delay_s=0.0)
    )
    with pytest.raises(faults.FaultInjected):
        src.poll()
    monkeypatch.delenv(faults.ENV_VAR)


def test_driver_run_with_poll_policy(tmp_path, monkeypatch):
    """StreamingForecaster.run(poll_policy=...) survives transient poll
    faults end to end and still refits every batch."""
    import pandas as pd

    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig
    from tsspark_tpu.streaming.driver import StreamingForecaster
    from tsspark_tpu.streaming.source import InMemorySource

    rng = np.random.default_rng(0)
    batches = [
        pd.DataFrame({
            "series_id": ["s0"] * 40 + ["s1"] * 40,
            "ds": np.tile(np.arange(40, dtype=float) + 40 * i, 2),
            "y": rng.normal(5, 0.5, 80),
        })
        for i in range(2)
    ]
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "stream_poll", attempts=1, mode="raise"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    fc = StreamingForecaster(
        ProphetConfig(seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
                      n_changepoints=3),
        backend="tpu",
    )
    stats = fc.run(
        InMemorySource(batches),
        poll_policy=RetryPolicy(max_attempts=4, base_delay_s=0.0),
    )
    monkeypatch.delenv(faults.ENV_VAR)
    assert stats.micro_batches == 2
    assert stats.rows_ingested == 160


def test_streaming_poll_honors_total_budget(tmp_path, monkeypatch):
    """total_budget_s must bound the poll retry loop in WALL time: with
    unlimited attempts against a permanently-failing source, the policy's
    budget (not an attempt count) is what re-raises."""
    import time as _time

    from tsspark_tpu.streaming.source import InMemorySource, ResilientSource

    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "stream_poll", attempts=10_000, mode="raise"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    src = ResilientSource(
        InMemorySource([]),
        RetryPolicy(max_attempts=None, base_delay_s=0.05,
                    total_budget_s=0.2),
    )
    t0 = _time.time()
    with pytest.raises(faults.FaultInjected):
        src.poll()
    assert _time.time() - t0 < 5.0  # budget fired, not 10k attempts
    monkeypatch.delenv(faults.ENV_VAR)
