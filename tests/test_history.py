"""Cross-run history index + regression sentinel + live watch
(tsspark_tpu/obs/{history,regress,watch}.py, docs/OBSERVABILITY.md
"Trajectory & SLOs").

The issue's acceptance, pinned as tests: backfill ingests every
committed BENCH/EVAL round artifact into a non-empty trajectory; the
reader tolerates a torn final line and a duplicate ingest (idempotent
by trace id); the sentinel is green on an unchanged re-run and red
(nonzero CLI exit) on an injected 3x throughput or p99 regression; the
watcher records SLO breaches back into the watched run's own trace.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tsspark_tpu.obs import context, history, regress, watch  # noqa: E402
from tsspark_tpu.obs.__main__ import main as obs_main  # noqa: E402
from tsspark_tpu.utils.atomic import append_line  # noqa: E402


@pytest.fixture(autouse=True)
def _unbind_obs_run():
    yield
    context.end_run(None)


def _bench_report(trace, series_per_s, first_flush_s=5.0,
                  workload="m5_512x256_fit_wall_clock"):
    return {
        "metric": workload, "value": 8.0, "unit": "s",
        "vs_baseline": 0.1,
        "extra": {
            "trace_id": trace, "numerics_rev": 7,
            "device": "TFRT_CPU_0", "series_per_s": series_per_s,
            "series_done": 512, "complete": True, "datagen_s": 3.0,
            "perf": {"first_flush_s": first_flush_s,
                     "compile_misses": 2},
        },
    }


def _serve_report(trace, p99, n=200):
    return {
        "kind": "serve-loadgen", "unix": 1000.0, "trace_id": trace,
        "numerics_rev": 7, "n_requests": n, "n_series": 48,
        "wall_s": 1.0, "requests_per_s": n / 1.0,
        "engine": {
            "submitted": n, "completed": n, "shed": 2, "failed": 0,
            "rejected": 0,
            "latency_ms": {"p50": 2.0, "p95": 5.0, "p99": p99,
                           "mean": 2.5, "max": p99},
            "batch_occupancy": {"mean_fill": 0.8},
        },
        "cache": {"hit_rate": 0.4},
    }


# ---------------------------------------------------------------------------
# history index
# ---------------------------------------------------------------------------


def test_backfill_ingests_committed_artifacts(tmp_path):
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    summary = history.backfill(REPO, hpath)
    rows = history.read_history(hpath)
    by_kind = {}
    for r in rows:
        by_kind.setdefault(r["kind"], []).append(r)
    # The committed trajectory: BENCH_r01-r05 (driver wrappers, incl.
    # r01's parsed:null crash round) + BENCH_builder_r06, and the five
    # EVAL parity artifacts.
    assert len(by_kind.get("bench", [])) >= 6, summary
    assert len(by_kind.get("eval", [])) >= 5, summary
    # Round order survives the glob: r06 (the only complete run) last.
    bench_sources = [r["source"] for r in by_kind["bench"]]
    assert bench_sources[0] == "BENCH_r01.json"
    assert bench_sources[5] == "BENCH_builder_r06.json"
    r06 = by_kind["bench"][5]
    assert r06["device_class"] == "cpu"
    assert r06["metrics"]["series_per_s"] == 63.44
    assert r06["metrics"]["first_flush_s"] == 20.73
    # Non-empty rendered trajectory (the roadmap's ask).
    lines = history.trajectory(rows)
    assert any("bench trajectory" in ln for ln in lines)
    assert any("series_per_s=63.44" in ln for ln in lines)
    # Idempotent: a second backfill appends nothing.
    again = history.backfill(REPO, hpath)
    assert again["ingested"] == []
    assert len(history.read_history(hpath)) == len(rows)


def test_ingest_idempotent_by_trace_id(tmp_path):
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    row1, app1 = history.ingest(_bench_report("t-abc", 60.0), hpath)
    row2, app2 = history.ingest(_bench_report("t-abc", 60.0), hpath)
    assert app1 and not app2
    assert row1["row_id"] == row2["row_id"] == "bench:t-abc"
    assert len(history.read_history(hpath)) == 1
    # A different trace is a different row.
    _, app3 = history.ingest(_bench_report("t-def", 61.0), hpath)
    assert app3 and len(history.read_history(hpath)) == 2


def test_history_reader_tolerates_torn_tail_and_junk(tmp_path):
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    history.ingest(_bench_report("t-1", 60.0), hpath)
    history.ingest(_serve_report("t-2", 8.0), hpath)
    # A writer killed mid-append tears its own last line; earlier rows
    # must survive, and non-row junk lines are skipped.
    append_line(hpath, json.dumps({"not": "a row"}))
    with open(hpath, "a") as fh:
        fh.write('{"kind": "bench", "row_id": "bench:torn", "metr')
    rows = history.read_history(hpath)
    assert [r["row_id"] for r in rows] == ["bench:t-1", "serve:t-2"]
    # Serve normalization: shed rate derived, latency flattened.
    assert rows[1]["metrics"]["p99_ms"] == 8.0
    assert rows[1]["metrics"]["shed_rate"] == 0.01


# ---------------------------------------------------------------------------
# regression sentinel
# ---------------------------------------------------------------------------


def test_sentinel_green_on_rerun_red_on_3x_drop(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    for i in range(3):
        v = regress.sentinel_report(_bench_report(f"t{i}", 60.0 + i))
        assert v is not None and v["ok"], v
    # Unchanged re-run: green, with a populated baseline.
    v = regress.sentinel_report(_bench_report("t-rerun", 61.0))
    assert v["ok"] and v["baseline"]["n"] == 3
    assert "series_per_s" in [c["metric"] for c in v["checks"]]
    assert os.path.exists(v["path"])
    # 3x throughput collapse: red, named in the verdict.
    v = regress.sentinel_report(_bench_report("t-drop", 20.0))
    assert not v["ok"] and "series_per_s" in v["breaches"]
    with open(v["path"]) as fh:
        on_disk = json.load(fh)
    assert on_disk["kind"] == "regression-verdict"
    assert not on_disk["ok"]
    assert "REGRESSION" in regress.summarize(v)


def test_sentinel_baselines_respect_comparability(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # TPU-class history must not gate a CPU run, and a different
    # workload (smoke vs full) must not share a baseline either.
    tpu = _bench_report("t-tpu", 600.0)
    tpu["extra"]["device"] = "TPU v5 lite"
    regress.sentinel_report(tpu)
    smoke = _bench_report("t-smoke", 10.0,
                          workload="m5_64x64_fit_wall_clock")
    regress.sentinel_report(smoke)
    v = regress.sentinel_report(_bench_report("t-cpu", 60.0))
    assert v["ok"] and v["baseline"]["n"] == 0


def test_sentinel_cli_exit_codes_on_p99_regression(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    for i in range(3):
        path = f"SERVE_{i}.json"
        with open(path, "w") as fh:
            json.dump(_serve_report(f"t{i}", 8.0 + 0.1 * i), fh)
        assert obs_main(["sentinel", path]) == 0
    with open("SERVE_bad.json", "w") as fh:
        json.dump(_serve_report("t-bad", 25.0), fh)
    rc = obs_main(["sentinel", "SERVE_bad.json",
                   "--out", "REGRESSION_bad.json"])
    assert rc == 1
    with open("REGRESSION_bad.json") as fh:
        verdict = json.load(fh)
    assert "p99_ms" in verdict["breaches"]


def test_slo_budgets_load_from_pyproject():
    slo = regress.load_slo(REPO)
    assert slo["window"] == 8
    assert slo["budgets"]["bench"]["series_per_s"]["direction"] == \
        "higher"
    assert "mttr_*" in slo["budgets"]["chaos"]


def test_default_slo_stays_in_sync_with_pyproject():
    # DEFAULT_SLO only covers running outside a checkout; the committed
    # pyproject table is the source of truth.  Pin them equal so a
    # budget edit that touches one side but not the other fails HERE
    # instead of silently judging differently on installed wheels.
    slo = regress.load_slo(REPO)
    assert slo["budgets"] == regress.DEFAULT_SLO["budgets"]
    for key in ("window", "min_history", "mad_k"):
        assert slo[key] == regress.DEFAULT_SLO[key]


def test_failed_runs_emit_no_throughput_metric(tmp_path):
    # A wedged run's series_per_s=0.0 means "never ran", not "ran at
    # zero" — admitting it would drag the rolling median to 0 and make
    # the throughput budget vacuous.  BENCH_r03-r05 are such rows.
    dead = _bench_report("t-dead", 0.0)
    dead["extra"]["series_done"] = 0
    dead["extra"]["complete"] = False
    row = history.row_from_report(dead)
    assert "series_per_s" not in row["metrics"]
    assert row["metrics"]["series_done"] == 0
    # Against the real committed trajectory: a 12x collapse vs r06's
    # 63.44 series/s must breach even though r03-r05 "scored" 0.0.
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    history.backfill(REPO, hpath)
    slow = {
        "metric": "m5_30490x1941_fit_wall_clock", "value": 100.0,
        "unit": "s", "vs_baseline": 0.0,
        "extra": {"trace_id": "t-slow", "device": "TFRT_CPU_0",
                  "series_per_s": 5.0, "series_done": 30490,
                  "complete": True},
    }
    v = regress.evaluate(history.row_from_report(slow),
                         history.read_history(hpath),
                         slo=regress.load_slo(REPO))
    assert "series_per_s" in v["breaches"], v["checks"]


def test_sentinel_amends_a_row_backfilled_before_judging(tmp_path,
                                                         monkeypatch):
    # A regressed artifact that reaches the index unjudged (backfill,
    # or a TSSPARK_SENTINEL=0 run) must still get its breached flag
    # when the sentinel later judges it — else the poisoned baseline
    # normalizes the next identical regression to green.
    monkeypatch.chdir(tmp_path)
    for i in range(3):
        regress.sentinel_report(_serve_report(f"t{i}", 8.0))
    bad = _serve_report("t-bad", 25.0)
    _row, appended = history.ingest(bad)  # indexed unflagged
    assert appended
    v = regress.sentinel_report(bad)
    assert not v["ok"]
    rows = history.read_history()
    stored = next(r for r in rows if r["row_id"] == "serve:t-bad")
    assert stored.get("breached") == v["breaches"]
    # An identical second regression still judges red.
    v2 = regress.sentinel_report(_serve_report("t-bad2", 25.0))
    assert not v2["ok"] and "p99_ms" in v2["breaches"]


def test_breached_rows_do_not_seed_baselines(tmp_path, monkeypatch):
    # A persistent regression must stay red run after run: red rows are
    # ingested (the trajectory is honest) but excluded from baselines,
    # so the collapse cannot normalize the median that catches it.
    monkeypatch.chdir(tmp_path)
    for i in range(3):
        assert regress.sentinel_report(
            _bench_report(f"t{i}", 60.0 + i)
        )["ok"]
    for i in range(5):
        v = regress.sentinel_report(_bench_report(f"t-bad{i}", 20.0))
        assert not v["ok"], f"regressed run {i} judged green: {v}"
        assert "series_per_s" in v["breaches"]
    rows = history.read_history()
    assert sum(1 for r in rows if r.get("breached")) == 5
    # A recovered run is green again against the healthy baseline.
    assert regress.sentinel_report(_bench_report("t-fixed", 59.0))["ok"]


def test_chaos_mttr_regression_flagged(tmp_path):
    hpath = str(tmp_path / "RUNHISTORY.jsonl")

    def storm(trace, mttr):
        return {"kind": "chaos-storm", "unix": 1.0, "trace_id": trace,
                "profile": "smoke", "ok": True, "invariants": {},
                "mttr_s": {"worker-kill": mttr}}

    for i in range(3):
        history.ingest(storm(f"c{i}", 1.0), hpath)
    rows = history.read_history(hpath)
    row = history.row_from_report(storm("c-bad", 9.0))
    v = regress.evaluate(row, rows, slo=regress.load_slo(REPO))
    # budget: 2x + 2 s slack off a 1 s median -> 9 s breaches.
    assert "mttr_worker-kill" in v["breaches"], v["checks"]
    row_ok = history.row_from_report(storm("c-ok", 1.1))
    assert regress.evaluate(row_ok, rows,
                            slo=regress.load_slo(REPO))["ok"]


# ---------------------------------------------------------------------------
# chrome-trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_export(tmp_path):
    scratch = tmp_path / "run"
    prev = context.start_run(str(scratch / "spans.jsonl"))
    tid = context.trace_id()
    with context.span("stage.orchestrate", seed=0):
        with context.span("chunk.fit", lo=0, hi=8):
            pass
        context.event("fault", tag="worker-kill")
    context.end_run(prev)
    # An open span with no later closed span (the wedged-worker shape):
    # must stay visible, never a zero-width sliver.
    prev = context.start_run(str(scratch / "spans.jsonl"), trace_id=tid)
    context.open_span("worker.attempt", attempt=1)
    context.end_run(prev)
    out = str(tmp_path / "trace.json")
    assert obs_main(["report", str(scratch), "--chrome-trace", out]) == 0
    with open(out) as fh:
        payload = json.load(fh)
    evs = payload["traceEvents"]
    complete = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert {e["name"] for e in complete} == {"stage.orchestrate",
                                             "chunk.fit",
                                             "worker.attempt"}
    assert instants[0]["name"] == "fault"
    fit = next(e for e in complete if e["name"] == "chunk.fit")
    assert fit["args"]["lo"] == 0 and fit["dur"] >= 0
    open_ev = next(e for e in complete if e["name"] == "worker.attempt")
    assert open_ev["args"]["status"] == "open"
    assert open_ev["dur"] >= 1e3  # >= 1 ms floor, visible in Perfetto
    assert all(e["ts"] >= 0 for e in evs)


# ---------------------------------------------------------------------------
# live watch
# ---------------------------------------------------------------------------


def _write_span(path, name, t0, dur_s, trace="tw", **attrs):
    append_line(path, json.dumps({
        "kind": "span", "trace_id": trace,
        "span_id": os.urandom(4).hex(), "parent_id": None,
        "name": name, "t0": t0, "dur_s": dur_s, "status": "ok",
        "pid": 1, "attrs": attrs,
    }))


def test_watch_once_records_breach_into_the_trace(tmp_path):
    scratch = tmp_path / "run"
    scratch.mkdir()
    spans = str(scratch / "spans.jsonl")
    # A slow in-flight run: 20 series landed over a 10 s window.
    _write_span(spans, "stage.orchestrate", 1000.0, 20.0)
    _write_span(spans, "chunk.land", 1000.0, 1.0, lo=0, hi=10)
    _write_span(spans, "chunk.land", 1009.0, 1.0, lo=10, hi=20)
    # The run's workers stamp their device into times.jsonl; the live
    # baseline must scope to that device class — the TPU rows below
    # would otherwise distort the median.
    append_line(str(scratch / "times.jsonl"),
                json.dumps({"lo": 0, "hi": 10, "fit_s": 1.0,
                            "device": "TFRT_CPU_0"}))
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    for i in range(3):
        history.ingest(_bench_report(f"t{i}", 60.0), hpath)
    for i in range(3):
        tpu = _bench_report(f"tpu{i}", 600.0)
        tpu["extra"]["device"] = "TPU v5 lite"
        history.ingest(tpu, hpath)

    st = watch.observe_run(str(scratch), history.read_history(hpath),
                           slo=regress.load_slo(REPO))
    assert st["series_done"] == 20
    assert st["series_per_s"] == 2.0
    assert [c["metric"] for c in st["breaches"]] == ["series_per_s"]
    assert st["breaches"][0]["median"] == 60.0  # cpu baseline only

    out_lines = []
    rc = watch.watch(str(scratch), history_path=hpath, once=True,
                     emit=out_lines.append)
    assert rc == 1
    assert any("SLO:BREACH" in ln for ln in out_lines)
    # The breach landed in the run's OWN trace (joinable by the ledger).
    recs = context.read_records(spans)
    breaches = [r for r in recs if r.get("kind") == "event"
                and r.get("name") == "slo.breach"]
    assert len(breaches) == 1
    assert breaches[0]["trace_id"] == "tw"
    assert breaches[0]["attrs"]["metric"] == "series_per_s"
    # A healthy run (no baseline misses): clean pass, no event spam.
    rc2 = watch.watch(str(scratch),
                      history_path=str(tmp_path / "none.jsonl"),
                      once=True, emit=lambda s: None)
    assert rc2 == 0
    assert len([r for r in context.read_records(spans)
                if r.get("name") == "slo.breach"]) == 1


def test_watch_reads_serve_metrics_snapshot(tmp_path):
    scratch = tmp_path / "serve"
    scratch.mkdir()
    _write_span(str(scratch / "spans.jsonl"), "serve.request",
                1000.0, 0.002)
    snap = {
        "kind": "metrics-snapshot", "unix": 1001.0, "trace_id": "tw",
        "pid": 1,
        "metrics": {
            "counters": [
                {"name": "tsspark_serve_requests_total",
                 "labels": {"result": "completed"}, "value": 98},
                {"name": "tsspark_serve_requests_total",
                 "labels": {"result": "shed"}, "value": 2},
            ],
            "gauges": [
                {"name": "tsspark_serve_queue_depth", "value": 4.0},
                {"name": "tsspark_serve_breaker_open", "value": 0.0},
            ],
            "histograms": [],
        },
    }
    with open(scratch / "metrics_daemon.json", "w") as fh:
        json.dump(snap, fh)
    st = watch.observe_run(str(scratch))
    assert st["queue_depth"] == 4.0
    assert st["shed_rate"] == 0.02
    assert st["breaker"] == "closed"
    assert st["p99_ms"] == 2.0
    line = watch.format_line(dict(st, t_offset_s=0.0))
    assert "queue=4" in line and "breaker=closed" in line


# ---------------------------------------------------------------------------
# serve daemon: metrics command + periodic export
# ---------------------------------------------------------------------------


def test_daemon_metrics_cmd_and_periodic_export(tmp_path):
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS
    from tsspark_tpu.serve.__main__ import _serve_lines

    METRICS.counter("tsspark_serve_requests_total",
                    result="completed").inc(5)
    emitted = []
    rc = _serve_lines(
        object(), object(), emitted.append,
        lines=['{"cmd": "metrics", "id": "m1"}'],
        metrics_every=0.0, metrics_dir=str(tmp_path),
    )
    assert rc == 0
    assert emitted and emitted[0]["ok"] and emitted[0]["id"] == "m1"
    assert "tsspark_serve_requests_total" in emitted[0]["prometheus"]
    snap_path = tmp_path / "metrics_daemon.json"
    assert snap_path.exists()
    with open(snap_path) as fh:
        assert json.load(fh)["kind"] == "metrics-snapshot"


def test_serveplane_reports_get_their_own_row_family(tmp_path):
    """bench --serveplane reports (serve-loadgen + a "plane" block) are
    re-kinded into the ``serveplane`` family: their own row_id
    namespace, workload prefix, trajectory block, and SLO section
    ([tool.tsspark.slo.serveplane]) — never baselined against ordinary
    loadgen rows."""
    rep = _serve_report("t-sp", 4.0)
    rep["plane"] = {
        "plane_hit_rate": 0.97,
        "read_latency_ms": {"p50": 0.02, "p99": 0.08},
        "hot_read": {"plane_rps": 5000.0, "dispatch_rps": 250.0},
        "publish_s": 0.4,
        "ttfr": {"cold_s": 9.0, "aot_warm_s": 2.5},
    }
    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    row, appended = history.ingest(rep, hpath)
    assert appended and row["kind"] == "serveplane"
    assert row["row_id"] == "serveplane:t-sp"
    assert row["workload"].startswith("serveplane_")
    m = row["metrics"]
    assert m["plane_hit_rate"] == 0.97
    assert m["plane_read_p99_ms"] == 0.08
    assert m["plane_requests_per_s"] == 5000.0
    assert m["dispatch_requests_per_s"] == 250.0
    assert m["ttfr_cold_s"] == 9.0 and m["ttfr_aot_warm_s"] == 2.5
    # A plane-less loadgen still lands in the ordinary serve family.
    row2, _ = history.ingest(_serve_report("t-plain", 4.0), hpath)
    assert row2["kind"] == "serve"
    lines = history.trajectory(history.read_history(hpath))
    assert any("serveplane trajectory" in ln for ln in lines)
    assert any("serve trajectory" in ln for ln in lines)
