"""Uncertainty tier (tsspark_tpu/uncertainty/, docs/UNCERTAINTY.md):
the lazy package-export sweep, NUTS determinism under a fixed key (the
contract uncertainty/gold.py builds on), the ADVI fit + posterior
artifact roundtrip, and the end-to-end calibration smoke landing in
RUNHISTORY as a ``calibration`` row within its SLO budget."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import tsspark_tpu
from tsspark_tpu.config import (
    AdviConfig,
    McmcConfig,
    ProphetConfig,
    SeasonalityConfig,
)
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.ops import hmc
from tsspark_tpu.uncertainty import advi, calibrate

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)


# ---------------------------------------------------------------------------
# lazy package exports (PEP 562)
# ---------------------------------------------------------------------------


def test_every_lazy_export_resolves():
    """A typo'd _EXPORTS entry must fail here, in tier-1, instead of
    surfacing as a runtime AttributeError inside a serve replica."""
    for name, module in tsspark_tpu._EXPORTS.items():
        value = getattr(tsspark_tpu, name)
        assert value is not None, f"{name} ({module})"
    # __all__ and _EXPORTS agree, and __dir__ advertises every name.
    assert set(tsspark_tpu.__all__) == set(tsspark_tpu._EXPORTS)
    assert set(tsspark_tpu._EXPORTS) <= set(dir(tsspark_tpu))
    with pytest.raises(AttributeError):
        tsspark_tpu.definitely_not_an_export


# ---------------------------------------------------------------------------
# NUTS determinism (the gold tier's foundation)
# ---------------------------------------------------------------------------


def test_hmc_deterministic_under_fixed_key():
    """Two sample() calls with the same key, init, and config return
    bitwise-identical chains — gold.py's audit reports are only
    reproducible if the sampler is."""
    b, p = 2, 3
    mu = jnp.asarray([[0.5, -1.0, 2.0], [1.5, 0.0, -0.5]], jnp.float32)

    def logdensity(th):
        z = th - mu
        return -0.5 * jnp.sum(z * z, axis=-1), -z

    cfg = McmcConfig(num_samples=16, num_warmup=8, num_leapfrog=4)
    key = jax.random.PRNGKey(42)
    theta0 = jnp.zeros((b, p), jnp.float32)
    r1 = hmc.sample(logdensity, theta0, key, cfg)
    r2 = hmc.sample(logdensity, theta0, key, cfg)
    assert r1.samples.shape == (16, b, p)
    np.testing.assert_array_equal(np.asarray(r1.samples),
                                  np.asarray(r2.samples))
    np.testing.assert_array_equal(np.asarray(r1.accept_rate),
                                  np.asarray(r2.accept_rate))
    np.testing.assert_array_equal(np.asarray(r1.step_size),
                                  np.asarray(r2.step_size))
    # A different key must actually move the draws.
    r3 = hmc.sample(logdensity, theta0, jax.random.PRNGKey(43), cfg)
    assert not np.array_equal(np.asarray(r1.samples),
                              np.asarray(r3.samples))


# ---------------------------------------------------------------------------
# ADVI fit + posterior artifact
# ---------------------------------------------------------------------------


def _tiny_fit_data(b=3, n=96, seed=0):
    rng = np.random.default_rng(seed)
    ds = np.arange(float(n))
    y = (8.0 + 0.03 * ds[None] + np.sin(2 * np.pi * ds[None] / 7.0)
         + rng.normal(0, 0.15, (b, n))).astype(np.float32)
    data, _meta = prepare_fit_data(ds, y, CFG)
    return data


def test_advi_fit_shapes_and_posterior_roundtrip(tmp_path):
    data = _tiny_fit_data()
    n_params = int(np.asarray(data.y).shape[0])
    from tsspark_tpu.models.prophet.params import init_theta

    theta0 = np.asarray(
        init_theta(CFG, data.y, data.mask, data.t), np.float32
    )
    post = advi.fit_advi(theta0, data, jax.random.PRNGKey(0), CFG,
                         AdviConfig(num_steps=40))
    mu = np.asarray(post.mu)
    rho = np.asarray(post.rho)
    assert mu.shape == theta0.shape and rho.shape == theta0.shape
    assert np.isfinite(mu).all() and np.isfinite(rho).all()
    assert np.asarray(post.elbo).shape == (n_params,)
    # Deterministic under the key.
    post2 = advi.fit_advi(theta0, data, jax.random.PRNGKey(0), CFG,
                          AdviConfig(num_steps=40))
    np.testing.assert_array_equal(mu, np.asarray(post2.mu))
    # Artifact roundtrip: bitwise payload + identity header.
    advi.save_posterior(str(tmp_path), post, seed=5, num_steps=40)
    loaded = advi.load_posterior(str(tmp_path))
    assert loaded is not None
    got, header = loaded
    np.testing.assert_array_equal(np.asarray(got.mu), mu)
    np.testing.assert_array_equal(np.asarray(got.rho), rho)
    assert header["seed"] == 5 and header["num_steps"] == 40
    assert advi.load_posterior(str(tmp_path / "nowhere")) is None


# ---------------------------------------------------------------------------
# calibration smoke -> RUNHISTORY within budget
# ---------------------------------------------------------------------------


def test_calibration_smoke_lands_in_history_within_budget(
        tmp_path, monkeypatch):
    """The acceptance pin: the uncertainty smoke runs the whole ladder
    (MAP fit -> ADVI advance -> quantile publish -> coverage eval ->
    gold audit), its report joins RUNHISTORY as a ``calibration`` row,
    and the [tool.tsspark.slo.calibration] sentinel is green."""
    from tsspark_tpu.obs import history, regress

    report = calibrate.run_calibration_smoke(
        str(tmp_path / "scratch"), n_series=8, seed=0, read_probes=25,
        data_root=str(tmp_path / "data"),
    )
    cal = report["calibration"]
    assert cal["mode"] == "advi"
    # Coverage within the declared budget's absolute ceiling: nominal
    # 0.8 interval, observed within half of reality at worst.
    assert 0.0 <= cal["coverage_abs_gap"] <= 0.5
    assert cal["qread_p99_ms"] is not None
    assert cal["gold"] is not None and cal["gold"]["rows"]

    hpath = str(tmp_path / "RUNHISTORY.jsonl")
    row, appended = history.ingest(report, hpath)
    assert appended and row["kind"] == "calibration"
    assert row["workload"] == "calibration_8x28"
    m = row["metrics"]
    assert m["coverage_abs_gap"] == cal["coverage_abs_gap"]
    assert m["mode_advi"] == 1
    assert "advi_series_per_s" in m and "qread_p99_ms" in m
    assert "qdiv_max" in m and "rhat_max" in m
    # Rendered trajectory grows a calibration block.
    lines = history.trajectory(history.read_history(hpath))
    assert any("calibration trajectory" in ln for ln in lines)

    monkeypatch.chdir(tmp_path)
    verdict = regress.sentinel_report(report)
    assert verdict is not None and verdict["ok"], verdict
    budget_metrics = set(
        regress.load_slo()["budgets"]["calibration"]
    )
    assert {"coverage_abs_gap", "advi_series_per_s",
            "qread_p99_ms", "qdiv_max"} <= budget_metrics
