"""Batched HMC sampler: correctness on known targets + Prophet integration.

Mirrors how upstream Prophet's ``mcmc_samples`` path is validated: the
sampler must recover the moments of a tractable target, and the Prophet
posterior-predictive must bracket the truth with wider, seasonality-aware
intervals than the MAP path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import McmcConfig, ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.models.prophet.model import ProphetModel
from tsspark_tpu.ops import hmc


def test_hmc_recovers_gaussian_moments():
    """B independent anisotropic Gaussians: each chain must match its target."""
    b, p = 4, 6
    rng = np.random.default_rng(0)
    mu = jnp.asarray(rng.normal(0, 3.0, (b, p)), jnp.float32)
    # Per-chain, per-dim scales spanning two orders of magnitude: exercises
    # the diagonal mass-matrix adaptation.
    sd = jnp.asarray(10.0 ** rng.uniform(-1, 1, (b, p)), jnp.float32)

    def logdensity(th):
        z = (th - mu) / sd
        lp = -0.5 * jnp.sum(z * z, axis=-1)
        grad = -(th - mu) / (sd * sd)
        return lp, grad

    cfg = McmcConfig(num_samples=600, num_warmup=400, num_leapfrog=16)
    res = hmc.sample(
        logdensity, jnp.zeros((b, p), jnp.float32), jax.random.PRNGKey(1), cfg
    )

    assert res.samples.shape == (600, b, p)
    assert float(res.divergences.sum()) == 0
    # Acceptance adapted near the 0.8 target, per chain.
    assert np.all(np.asarray(res.accept_rate) > 0.55)
    mean_err = np.abs(np.asarray(res.samples.mean(0) - mu)) / np.asarray(sd)
    assert mean_err.max() < 0.35  # within ~a third of a posterior sd
    sd_ratio = np.asarray(res.samples.std(0)) / np.asarray(sd)
    assert sd_ratio.min() > 0.6 and sd_ratio.max() < 1.5
    # Adapted metric should track the target variance (up to MC error).
    mass_ratio = np.asarray(res.inv_mass) / np.asarray(sd * sd)
    assert np.median(mass_ratio) == pytest.approx(1.0, rel=0.6)


def _synthetic_batch(b=3, n=160, seed=0):
    rng = np.random.default_rng(seed)
    ds = np.arange(n, dtype=np.float64)
    season = 1.5 * np.sin(2 * np.pi * ds / 7.0)
    y = 10.0 + 0.02 * ds + season + rng.normal(0, 0.4, (b, n))
    return jnp.asarray(ds), jnp.asarray(y)


def test_prophet_mcmc_posterior_predictive():
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=5,
    )
    model = ProphetModel(cfg)
    ds, y = _synthetic_batch()

    state = model.fit_mcmc(
        ds, y, mcmc_config=McmcConfig(num_samples=200, num_warmup=200,
                                      num_leapfrog=12),
    )
    assert state.samples.shape[:2] == (200, y.shape[0])
    assert np.all(np.asarray(state.accept_rate) > 0.4)

    horizon = np.arange(160, 200, dtype=np.float64)
    out = model.predict_mcmc(state, horizon, max_draws=100)
    yhat = np.asarray(out["yhat"])
    lo, hi = np.asarray(out["yhat_lower"]), np.asarray(out["yhat_upper"])

    # Point forecast close to the noiseless truth on the horizon.
    truth = 10.0 + 0.02 * np.asarray(horizon) + 1.5 * np.sin(
        2 * np.pi * np.asarray(horizon) / 7.0
    )
    assert np.abs(yhat - truth[None]).mean() < 0.6
    # Intervals are ordered, nontrivial, and cover most of the truth.
    assert np.all(lo < hi)
    coverage = ((truth[None] >= lo) & (truth[None] <= hi)).mean()
    assert coverage > 0.7

    # MCMC intervals include seasonality uncertainty -> at least as wide on
    # average as the MAP trend-only intervals.
    map_state = model.fit(ds, y)
    map_out = model.predict(map_state, horizon, seed=0)
    map_width = np.asarray(map_out["yhat_upper"] - map_out["yhat_lower"]).mean()
    mcmc_width = (hi - lo).mean()
    assert mcmc_width > 0.5 * map_width


def test_forecaster_mcmc_samples_front_end():
    """The mcmc_samples knob on the DataFrame front-end (Prophet parity)."""
    import pandas as pd
    from tsspark_tpu import Forecaster

    rng = np.random.default_rng(4)
    n = 150
    ds = pd.date_range("2024-03-01", periods=n, freq="D")
    t = np.arange(n)
    df = pd.concat([
        pd.DataFrame({"series_id": f"s{i}", "ds": ds,
                      "y": 7 + 0.03 * t + 1.5 * np.sin(2 * np.pi * t / 7)
                           + rng.normal(0, 0.3, n)})
        for i in range(2)
    ], ignore_index=True)

    fc = Forecaster(
        ProphetConfig(seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
                      n_changepoints=4),
        mcmc_samples=120,
        mcmc_config=McmcConfig(num_samples=120, num_warmup=150,
                               num_leapfrog=10),
    )
    fc.fit(df)
    assert fc.mcmc_state is not None
    assert fc.mcmc_state.samples.shape[:2] == (120, 2)

    out = fc.predict(horizon=14)
    assert {"yhat", "yhat_lower", "yhat_upper"} <= set(out.columns)
    assert (out["yhat_lower"] < out["yhat_upper"]).all()
    truth = (7 + 0.03 * np.arange(n, n + 14)
             + 1.5 * np.sin(2 * np.pi * np.arange(n, n + 14) / 7))
    for sid in ("s0", "s1"):
        sub = out[out.series_id == sid]
        assert np.abs(sub["yhat"].to_numpy() - truth).mean() < 0.8


def test_mcmc_predictive_samples():
    """predictive_samples on an MCMC fit returns one trajectory per
    retained draw, consistent with predict()'s posterior intervals."""
    import pandas as pd

    from tsspark_tpu.frame import Forecaster

    rng = np.random.default_rng(2)
    n = 120
    ds = pd.date_range("2022-01-01", periods=n, freq="D")
    frames = [
        pd.DataFrame({
            "series_id": f"s{i}",
            "ds": ds,
            "y": 5 + i + 0.02 * np.arange(n) + rng.normal(0, 0.2, n),
        })
        for i in range(2)
    ]
    fc = Forecaster(
        ProphetConfig(seasonalities=(), n_changepoints=3),
        SolverConfig(max_iters=40),
        backend="tpu",
        mcmc_samples=24,
        mcmc_config=McmcConfig(num_samples=24, num_warmup=24, num_leapfrog=8),
    ).fit(pd.concat(frames, ignore_index=True))
    out = fc.predictive_samples(horizon=7, num_samples=12)
    assert out["yhat_samples"].shape == (12, 2, 7)
    assert np.isfinite(out["yhat_samples"]).all()
