"""Materialized forecast plane (tsspark_tpu/serve/fplane.py,
docs/SERVING.md "Forecast plane"): full-grid bitwise parity of
plane-served vs engine-computed forecasts, delta copy-forward flips,
torn-publish rejection + compute fallback + bitwise-equal retry, and
the coverage rules (sampled and long-tail requests stay on compute)."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.resilience import FaultPlan, faults
from tsspark_tpu.serve import (
    ForecastCache,
    ParamRegistry,
    PredictionEngine,
    fplane,
)

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)
HOT = fplane.DEFAULT_HOT_HORIZONS


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(7)
    t = np.arange(150.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (6, 150)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    return backend, state, [f"s{i}" for i in range(6)]


def _registry(tmp_path, fitted):
    backend, state, ids = fitted
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    reg.publish(state, ids, step=np.ones(len(ids)))
    return reg


def _forecasts(engine, ids, horizons=HOT):
    return {h: engine.forecast(list(ids), int(h), num_samples=0, seed=0)
            for h in horizons}


def _assert_bitwise(got, want):
    for h in want:
        np.testing.assert_array_equal(got[h].ds, want[h].ds)
        assert set(got[h].values) == set(want[h].values)
        for k in want[h].values:
            np.testing.assert_array_equal(
                got[h].values[k], want[h].values[k], err_msg=f"h={h} {k}"
            )


def test_bucket_ladder():
    assert fplane.bucket_ladder(HOT) == (8, 16, 32)
    assert fplane.bucket_ladder((3,)) == (8,)
    assert fplane.bucket_ladder((9, 16, 17)) == (16, 32)


def test_plane_columns_bitwise_equal_direct_predict(tmp_path, fitted):
    """THE plane pin, full grid: every (series, bucket, key) cell of a
    published plane is bitwise a direct backend.predict over the same
    snapshot rows — the publisher's chunked/padded batch compute is
    invisible in the bytes."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    pub = fplane.maybe_publish(reg, 1, backend)
    assert pub["status"] == "published" and pub["buckets"] == [8, 16, 32]
    view = fplane.attach(reg.version_dir(1))
    snap = reg.load()
    sub, step = snap.take(np.arange(len(ids)))
    for hb in view.buckets:
        grid = fplane.future_grid(sub, step, hb)
        direct = backend.predict(sub, grid, num_samples=0)
        for k in fplane.POINT_KEYS:
            np.testing.assert_array_equal(
                np.asarray(view.columns[hb][k]), np.asarray(direct[k]),
                err_msg=f"hb={hb} {k}",
            )
    # plane_rows serves arbitrary row subsets with the recomputed ds
    # grid, bitwise the gathered direct rows.
    idx = np.asarray([3, 0, 5])
    rows = fplane.plane_rows(view, snap, idx, 8)
    sub2, step2 = snap.take(idx)
    grid2 = fplane.future_grid(sub2, step2, 8)
    direct2 = backend.predict(sub2, grid2, num_samples=0)
    for i in range(len(idx)):
        np.testing.assert_array_equal(rows[i]["ds"], grid2[i])
        for k in fplane.POINT_KEYS:
            np.testing.assert_array_equal(
                rows[i][k], np.asarray(direct2[k])[i]
            )


def test_engine_plane_serves_bitwise_vs_compute_full_grid(tmp_path,
                                                          fitted):
    """Plane-served engine answers equal the forced-compute engine's
    across the full hot grid, and actually come from the plane."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert fplane.maybe_publish(reg, 1, backend)["status"] == "published"

    eng_plane = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp._planes = {1: None}  # force the compute path
    got = _forecasts(eng_plane, ids)
    want = _forecasts(eng_disp, ids)
    _assert_bitwise(got, want)
    assert eng_plane.stats.plane_hits == len(ids) * len(HOT)
    assert eng_plane.stats.dispatches == 0
    assert eng_disp.stats.plane_hits == 0
    assert eng_disp.stats.dispatches > 0


def test_engine_plane_coverage_rules(tmp_path, fitted):
    """Sampled requests and horizons past the plane's ladder stay on
    the compute path — the plane covers deterministic hot reads only."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert fplane.maybe_publish(reg, 1, backend)
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    sampled = eng.forecast(ids[:2], 7, num_samples=20, seed=3)
    assert sampled.values["yhat"].shape == (2, 7)
    long_tail = eng.forecast(ids[:2], 60, num_samples=0, seed=0)
    assert long_tail.values["yhat"].shape == (2, 60)
    assert eng.stats.plane_hits == 0
    assert eng.stats.dispatches > 0
    hot = eng.forecast(ids[:2], 7, num_samples=0, seed=0)
    assert hot.values["yhat"].shape == (2, 7)
    assert eng.stats.plane_hits == 2


def test_delta_copy_forward_plane_flip(tmp_path, fitted):
    """Delta flip: unchanged rows' plane cells are bitwise the BASE
    plane's (copy-forward, no recompute), changed rows are bitwise a
    fresh compute over the new snapshot, and the engine serves the
    delta version's plane bitwise vs its compute path."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert fplane.maybe_publish(reg, 1, backend)["status"] == "published"
    base_view = fplane.attach(reg.version_dir(1))

    snap1 = reg.load()
    changed = np.asarray([1, 3])
    sub, step_sub = snap1.take(changed)
    refit = sub._replace(theta=np.asarray(sub.theta) * 1.02)
    v2 = reg.publish_delta(refit, changed.tolist(), step_sub=step_sub)
    pub = fplane.maybe_publish(reg, v2, backend)
    assert pub["status"] == "published-delta"

    view2 = fplane.attach(reg.version_dir(v2))
    snap2 = reg.load()
    assert snap2.version == v2
    unchanged = np.asarray([0, 2, 4, 5])
    sub_ch, step_ch = snap2.take(changed)
    for hb in view2.buckets:
        grid = fplane.future_grid(sub_ch, step_ch, hb)
        direct = backend.predict(sub_ch, grid, num_samples=0)
        for k in fplane.POINT_KEYS:
            np.testing.assert_array_equal(
                np.asarray(view2.columns[hb][k])[unchanged],
                np.asarray(base_view.columns[hb][k])[unchanged],
                err_msg=f"copy-forward hb={hb} {k}",
            )
            np.testing.assert_array_equal(
                np.asarray(view2.columns[hb][k])[changed],
                np.asarray(direct[k]),
                err_msg=f"changed hb={hb} {k}",
            )
        # The perturbed rows really moved (yhat only: the additive-only
        # config keeps the multiplicative column identically zero).
        assert not np.array_equal(
            np.asarray(view2.columns[hb]["yhat"])[changed],
            np.asarray(base_view.columns[hb]["yhat"])[changed],
        )
    eng_plane = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp._planes = {v2: None}
    _assert_bitwise(_forecasts(eng_plane, ids),
                    _forecasts(eng_disp, ids))
    assert eng_plane.stats.plane_hits > 0


def test_torn_publish_rejected_fallback_then_bitwise_retry(
        tmp_path, fitted, monkeypatch):
    """The torn-forecast-plane contract, in process: a publish killed
    mid-column (armed ``fplane_publish`` fault) leaves a plane the CRC
    sentinel REJECTS; the engine serves through compute — bitwise the
    pre-tear answers, never an outage — and the retried publish lands a
    plane whose served rows are bitwise the compute path's."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    vdir = reg.version_dir(1)
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    ref = _forecasts(eng, ids)  # no plane yet: pure compute reference

    plan = FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("fplane_publish", after=3, mode="raise", tag="torn-fplane")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.raises(faults.FaultInjected):
        fplane.write_plane(vdir, reg.load(), backend)
    monkeypatch.delenv(faults.ENV_VAR)

    assert not fplane.has_plane(vdir)          # sentinel never landed
    assert not fplane.verify_plane(vdir)
    with pytest.raises(fplane.ForecastPlaneError) as e:
        fplane.attach(vdir)
    assert e.value.reason == "corrupt"

    eng_mid = PredictionEngine(reg, cache=ForecastCache(0))
    mid = _forecasts(eng_mid, ids)
    assert eng_mid.stats.plane_hits == 0
    _assert_bitwise(mid, ref)

    retry = fplane.maybe_publish(reg, 1, backend, force=True)
    assert retry["status"] == "published"
    assert fplane.verify_plane(vdir)
    assert eng_mid.attach_plane(1)
    after = _forecasts(eng_mid, ids)
    assert eng_mid.stats.plane_hits > 0
    _assert_bitwise(after, ref)


def test_attach_rejects_corrupt_column(tmp_path, fitted):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert fplane.maybe_publish(reg, 1, backend)
    vdir = reg.version_dir(1)
    path = os.path.join(vdir, "fcol_h8_yhat.npy")
    mm = np.lib.format.open_memmap(path, mode="r+")
    mm[2:3].view(np.uint32)[...] ^= np.uint32(0x5A5A5A5A)
    mm.flush()
    del mm
    assert not fplane.verify_plane(vdir)
    with pytest.raises(fplane.ForecastPlaneError) as e:
        fplane.attach(vdir)
    assert e.value.reason == "corrupt"
    # The engine memoizes the rejection and serves compute — same
    # numbers a plane-less registry would produce.
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    res = eng.forecast(ids[:3], 7, num_samples=0, seed=0)
    assert res.version == 1 and eng.stats.plane_hits == 0
    eng_ref = PredictionEngine(reg, cache=ForecastCache(0))
    eng_ref._planes = {1: None}
    ref = eng_ref.forecast(ids[:3], 7, num_samples=0, seed=0)
    for k in ref.values:
        np.testing.assert_array_equal(res.values[k], ref.values[k])


def test_maybe_publish_idempotent_and_kill_switch(tmp_path, fitted,
                                                  monkeypatch):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert fplane.maybe_publish(reg, 1, backend)["status"] == "published"
    again = fplane.maybe_publish(reg, 1, backend)
    assert again == {"status": "present", "version": 1}
    monkeypatch.setenv("TSSPARK_FPLANE", "0")
    reg2 = ParamRegistry(str(tmp_path / "reg2"), CFG)
    reg2.publish(state, ids, step=np.ones(len(ids)))
    assert fplane.maybe_publish(reg2, 1, backend) is None
    assert not fplane.has_plane(reg2.version_dir(1))
