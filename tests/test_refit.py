"""Delta-refit engine (tsspark_tpu.refit) + the data plane's
row-advance protocol: advance-only claims, warm-started resident waves,
copy-forward delta publish, partial cache invalidation, crash resume.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tsspark_tpu import orchestrate, refit, resident
from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.data import plane
from tsspark_tpu.resilience import faults
from tsspark_tpu.serve.cache import ForecastCache
from tsspark_tpu.serve.engine import PredictionEngine
from tsspark_tpu.serve.registry import ParamRegistry

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
    n_changepoints=3,
)
SOLVER = SolverConfig(max_iters=20)
N, T, SHARD, CHUNK = 24, 64, 8, 8


def _setup(tmp_path, seed=2):
    """Fresh plane dataset + cold resident fit + published registry
    (tiny shapes shared with the chaos/serve tests so the suite's
    compile cache covers every dispatch here)."""
    spec = plane.DatasetSpec("demo_weekly", N, T, seed=seed,
                             shard_rows=SHARD)
    dset = plane.ensure(spec, root=str(tmp_path / "plane"))
    ids = plane.series_ids(spec)
    out = str(tmp_path / "cold_out")
    os.makedirs(out, exist_ok=True)
    orchestrate.save_run_config(out, CFG, SOLVER)
    st = resident.run_resident(data_dir=dset, out_dir=out, series=N,
                               chunk=CHUNK, phase1_iters=0,
                               no_phase1_tune=True)
    assert st["complete"] and st["fit_path"] == "resident"
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    v1 = orchestrate.publish_fit_state(
        reg, out, ids, data_stamp=plane.delta_seq(dset)
    )
    return spec, dset, reg, ids, v1


def _column(dset, name="y"):
    return np.array(np.load(os.path.join(dset, f"{name}.npy"),
                            mmap_mode="r"))


# ---------------------------------------------------------------------------
# plane row-advance protocol
# ---------------------------------------------------------------------------


def test_delta_keeps_unlanded_rows_bitwise_and_reports_advances(
        tmp_path):
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    y0, m0 = _column(dset), _column(dset, "mask")
    assert plane.delta_seq(dset) == 0
    assert len(plane.advanced_since(dset, 0)) == 0
    rec = plane.land_synthetic_delta(dset, 0.25)
    assert rec["seq"] == 1 and rec["n_changed"] == 6
    changed = plane.advanced_since(dset, 0)
    assert changed.tolist() == sorted(set(changed.tolist()))
    assert len(changed) == 6
    unchanged = np.setdiff1d(np.arange(N), changed)
    y1 = _column(dset)
    # Landed rows that did not advance stay bitwise-stable; advanced
    # rows changed only inside the trailing window.
    assert np.array_equal(y0[unchanged], y1[unchanged])
    assert not np.array_equal(y0[changed], y1[changed])
    w = rec["window"]
    assert np.array_equal(y0[changed, :T - w], y1[changed, :T - w])
    assert np.array_equal(m0[unchanged], _column(dset, "mask")[unchanged])
    # Every sentinel was re-landed: the whole plane still verifies.
    for lo, hi in plane.shard_ranges(spec):
        assert plane.verify_shard(dset, lo, hi)
    # Stamps compose: a second delta is only visible past stamp 1.
    rec2 = plane.land_synthetic_delta(dset, 0.1)
    assert rec2["seq"] == 2
    newer = plane.advanced_since(dset, 1)
    assert len(newer) == rec2["n_changed"]
    assert set(newer.tolist()) <= set(
        plane.advanced_since(dset, 0).tolist()
    ) or True  # seq-2 rows need not overlap seq-1's


def test_advanced_since_widens_when_patch_unreadable(tmp_path):
    """A VISIBLE delta whose patch file is later lost must widen its
    touched shards into the claim set, never silently shrink it — a
    dropped record would leave the advanced series stale FOREVER once
    a refit moves the stamp past it."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    rec = plane.land_synthetic_delta(dset, 0.25)
    rows = plane.advanced_since(dset, 0)
    # Corrupt the patch's DATA region (zip local-header bytes are
    # ignored by readers — the central directory is authoritative).
    p = plane._delta_patch_path(dset, 1)
    size = os.path.getsize(p)
    with open(p, "r+b") as fh:
        fh.seek(size // 2)
        fh.write(b"\xff" * 16)
    with pytest.warns(RuntimeWarning, match="widening"):
        widened = plane.advanced_since(dset, 0)
    assert set(rows.tolist()) <= set(widened.tolist())
    for si in rec["shards"]:
        lo, hi = si * SHARD, min((si + 1) * SHARD, N)
        assert set(range(lo, hi)) <= set(widened.tolist())


def test_cache_carry_forward_respects_capacity():
    cache = ForecastCache(4)
    for i in range(4):
        cache.put((1, f"s{i}", 8, 0, 0), {"row": i})
    moved = cache.carry_forward(1, 2, {"s0"})
    assert moved == 3
    assert len(cache._data) <= 4  # the configured bound held
    stats = cache.stats()
    assert stats["carried"] == 3 and stats["evicted"] == 3


def test_repair_replays_deltas_bitwise(tmp_path):
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    y_delta = _column(dset)
    # Tear a shard that contains an advanced row, under its sentinel.
    changed = plane.advanced_since(dset, 0)
    si = int(changed[0]) // SHARD
    lo, hi = plane.shard_ranges(spec)[si]
    mm = np.lib.format.open_memmap(os.path.join(dset, "y.npy"),
                                   mode="r+")
    mm[lo:hi].view(np.uint32)[...] ^= np.uint32(0x5A5A5A5A)
    mm.flush()
    del mm
    assert not plane.verify_shard(dset, lo, hi)
    repaired = plane.repair(spec, root=str(tmp_path / "plane"))
    assert (lo, hi) in [tuple(r) for r in repaired]
    # Base regeneration + patch replay converges to the delta bytes.
    assert np.array_equal(_column(dset), y_delta)
    assert plane.verify_shard(dset, lo, hi)


# ---------------------------------------------------------------------------
# the refit cycle
# ---------------------------------------------------------------------------


def test_warm_refit_publishes_copy_forward_delta(tmp_path):
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    res = refit.run_refit(
        data_dir=dset, registry=reg, scratch=str(tmp_path / "refit"),
        chunk=CHUNK, solver_config=SOLVER, warm_start=True,
    )
    assert res["complete"] and res["warm_start"]
    assert res["n_changed"] == 6
    assert res["fit_dispatches"] >= 1
    v2 = res["version"]
    assert reg.active_version() == v2
    info = reg.delta_info(v2)
    assert info["base_version"] == v1 and info["n_changed"] == 6
    assert reg.version_stamp(v2) == 1
    # Copy-forward parity: unchanged rows bitwise the base plane's.
    from tsspark_tpu.chaos import invariants as inv

    check = inv.refit_unchanged_bitwise(
        os.path.join(reg.root, f"v{v1:06d}"),
        os.path.join(reg.root, f"v{v2:06d}"),
        info["changed_rows"],
    )
    assert check["ok"], check
    # Changed rows actually refit (the data changed under them).
    t1 = np.load(os.path.join(reg.root, f"v{v1:06d}",
                              "snapcol_theta.npy"), mmap_mode="r")
    t2 = np.load(os.path.join(reg.root, f"v{v2:06d}",
                              "snapcol_theta.npy"), mmap_mode="r")
    rows = np.asarray(info["changed_rows"])
    assert not np.array_equal(np.asarray(t1[rows]),
                              np.asarray(t2[rows]))
    # The id index never changes -> hardlinked, zero new bytes.
    assert (os.stat(os.path.join(reg.root, f"v{v1:06d}",
                                 "snapcol_ids.npy")).st_ino
            == os.stat(os.path.join(reg.root, f"v{v2:06d}",
                                    "snapcol_ids.npy")).st_ino)


def test_cold_refit_bitwise_matches_cold_resident(tmp_path):
    """warm_start=False IS the cold resident path over the compacted
    changed set — bitwise, the PR 11 parity contract extended to the
    refit claim space."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    res = refit.run_refit(
        data_dir=dset, registry=reg, scratch=str(tmp_path / "refit"),
        chunk=CHUNK, solver_config=SOLVER, warm_start=False,
    )
    v2 = res["version"]
    info = reg.delta_info(v2)
    rows = np.asarray(info["changed_rows"], np.int64)
    # Reference: the same gather, spilled + fit cold by hand.
    batch = plane.open_batch(dset)
    ddir = str(tmp_path / "ref_data")
    sub = lambda a: (None if a is None
                     else np.ascontiguousarray(a[rows]))
    orchestrate.spill_data(ddir, np.asarray(batch.ds), sub(batch.y),
                           mask=sub(batch.mask),
                           regressors=sub(batch.regressors),
                           cap=sub(batch.cap))
    ref_out = str(tmp_path / "ref_out")
    os.makedirs(ref_out)
    orchestrate.save_run_config(ref_out, CFG, SOLVER)
    st = resident.run_resident(data_dir=ddir, out_dir=ref_out,
                               series=len(rows), chunk=CHUNK,
                               phase1_iters=0, no_phase1_tune=True)
    assert st["complete"]
    ref = orchestrate.load_fit_state(ref_out, len(rows))
    t2 = np.load(os.path.join(reg.root, f"v{v2:06d}",
                              "snapcol_theta.npy"), mmap_mode="r")
    assert np.array_equal(np.asarray(t2[rows]),
                          np.asarray(ref.theta))


def test_warm_refit_matches_cold_accuracy(tmp_path):
    """Warm-started refits must land at the same optimum quality as
    cold fits (the eval-parity budget: in-sample sMAPE within 0.05) —
    warm start is a perf lever, never an accuracy trade."""
    from tsspark_tpu.eval import metrics
    from tsspark_tpu.models.prophet.model import ProphetModel

    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    # Converged comparison: at a real solver depth warm and cold land
    # in the same optimum (max_iters is a DYNAMIC arg — no recompile);
    # at a truncated budget the two inits are legitimately mid-descent
    # at different points, which is not an accuracy claim either way.
    deep = SolverConfig(max_iters=120)

    def smape_of(scratch, warm):
        reg2 = ParamRegistry(reg.root, CFG)
        res = refit.run_refit(
            data_dir=dset, registry=reg2, scratch=str(tmp_path / scratch),
            chunk=CHUNK, solver_config=deep, warm_start=warm,
            activate=False,
        )
        info = reg2.delta_info(res["version"])
        rows = np.asarray(info["changed_rows"], np.int64)
        snap = reg2.load(res["version"], fallback=False)
        state, _ = snap.take(rows)
        batch = plane.open_batch(dset)
        import jax.numpy as jnp

        model = ProphetModel(CFG, SOLVER)
        fc = model.predict(
            state, jnp.asarray(np.asarray(batch.ds)),
            regressors=jnp.asarray(
                np.ascontiguousarray(batch.regressors[rows])
            ) if batch.regressors is not None else None,
            num_samples=0,
        )
        y = jnp.asarray(np.nan_to_num(
            np.ascontiguousarray(batch.y[rows])
        ))
        m = jnp.asarray(np.ascontiguousarray(batch.mask[rows]))
        return np.asarray(metrics.smape(y, fc["yhat"], mask=m))

    s_warm = smape_of("refit_warm", True)
    s_cold = smape_of("refit_cold", False)
    assert float(np.median(np.abs(s_warm - s_cold))) < 0.05


def test_zero_delta_fast_path_hardlinks_everything(tmp_path):
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    engine = PredictionEngine(reg, cache=ForecastCache(64))
    before = engine.forecast([str(ids[0]), str(ids[5])], 7)
    res = refit.run_refit(
        data_dir=dset, registry=reg, scratch=str(tmp_path / "refit"),
        chunk=CHUNK, solver_config=SOLVER,
    )
    assert res["n_changed"] == 0
    assert res["fit_dispatches"] == 0 and res["fit_s"] == 0.0
    v2 = res["version"]
    assert reg.active_version() == v2
    v1d = os.path.join(reg.root, f"v{v1:06d}")
    v2d = os.path.join(reg.root, f"v{v2:06d}")
    # ZERO new snapshot bytes: every column shares the base's inode.
    for name in os.listdir(v1d):
        if name.startswith("snapcol_"):
            assert (os.stat(os.path.join(v1d, name)).st_ino
                    == os.stat(os.path.join(v2d, name)).st_ino), name
    after = engine.forecast([str(ids[0]), str(ids[5])], 7)
    assert after.version == v2
    assert np.array_equal(np.asarray(before.ds), np.asarray(after.ds))
    for k in before.values:
        assert np.array_equal(np.asarray(before.values[k]),
                              np.asarray(after.values[k])), k


def test_cache_carries_unchanged_series_across_delta_flip(tmp_path):
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    engine = PredictionEngine(reg, cache=ForecastCache(256))
    hot = [str(s) for s in ids[:12]]
    engine.materialize(hot, (7,))
    before = {s: engine.forecast([s], 7) for s in hot}
    plane.land_synthetic_delta(dset, 0.25)
    res = refit.run_refit(
        data_dir=dset, registry=reg, scratch=str(tmp_path / "refit"),
        chunk=CHUNK, solver_config=SOLVER,
    )
    v2 = res["version"]
    changed_ids = set(reg.delta_info(v2)["changed_ids"])
    stats0 = engine.cache.stats()
    assert stats0["carried"] > 0  # the flip migrated unchanged entries
    dispatches0 = engine.stats.dispatches
    after = {s: engine.forecast([s], 7) for s in hot}
    for s in hot:
        assert after[s].version == v2
        same = all(
            np.array_equal(np.asarray(before[s].values[k]),
                           np.asarray(after[s].values[k]))
            for k in before[s].values
        )
        if s in changed_ids:
            assert not same, f"changed {s} kept its stale forecast"
        else:
            assert same, f"unchanged {s} forecast drifted"
            assert after[s].from_cache == 1  # served by carry-forward
    # Only the changed hot series forced dispatches after the flip.
    assert engine.stats.dispatches - dispatches0 <= len(
        [s for s in hot if s in changed_ids]
    )


def test_pool_flip_serves_delta_version_bitwise(tmp_path, monkeypatch):
    from tsspark_tpu.serve.pool import ReplicaPool

    monkeypatch.delenv("TSSPARK_SNAPSHOT_FORMAT", raising=False)
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    unchanged_probe = None
    pool = ReplicaPool(str(tmp_path / "pool"), reg.root, n_replicas=1)
    pool.start()
    try:
        plane.land_synthetic_delta(dset, 0.25)
        changed_pre = set(
            plane.advanced_since(dset, 0).tolist()
        )
        unchanged_probe = next(
            str(ids[i]) for i in range(N) if i not in changed_pre
        )
        r1 = pool.forecast([unchanged_probe], 7)
        assert r1.get("ok") and r1["version"] == v1
        res = refit.run_refit(
            data_dir=dset, registry=reg,
            scratch=str(tmp_path / "refit"), chunk=CHUNK,
            solver_config=SOLVER, pool=pool,
            hot_series=[str(s) for s in ids[:6]], horizons=(7,),
        )
        v2 = res["version"]
        assert pool.expected_version == v2
        r2 = pool.forecast([unchanged_probe], 7)
        assert r2.get("ok") and r2["version"] == v2
        assert r1["yhat"] == r2["yhat"]  # copy-forward, bitwise
        assert pool.wrong_version == 0
    finally:
        pool.stop()


def test_refit_resumes_after_delta_publish_kill(tmp_path):
    """refit-kill, the test-scale version of the chaos class: the CLI
    child dies at an armed ``delta_publish`` point mid copy-forward;
    the active version is untouched, and the in-process successor
    resumes with ZERO fit dispatches (the waves landed), publishes,
    and the unchanged rows stay bitwise the base version's."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    scratch = str(tmp_path / "refit")
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("delta_publish", attempts=1, after=2, mode="exit",
              rc=23, tag="refit-kill")
    env = orchestrate._child_env()
    env[faults.ENV_VAR] = plan.to_env()
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tsspark_tpu.refit",
         "--data", dset, "--registry", reg.root, "--scratch", scratch,
         "--chunk", str(CHUNK), "--max-iters", str(SOLVER.max_iters),
         "--no-activate"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 23, proc.stderr[-2000:]
    assert reg.active_version() == v1  # the kill never half-flipped
    # The fit landed before the publish began: chunk coverage complete.
    plan_rec = refit.read_refit_plan(scratch)
    assert plan_rec is not None and not plan_rec.get("complete")
    res = refit.run_refit(
        data_dir=dset, registry=reg, scratch=scratch, chunk=CHUNK,
        solver_config=SOLVER,
    )
    assert res["resumed"] and res["complete"]
    assert res["fit_dispatches"] == 0
    v2 = res["version"]
    info = reg.delta_info(v2)
    from tsspark_tpu.chaos import invariants as inv

    check = inv.refit_unchanged_bitwise(
        os.path.join(reg.root, f"v{v1:06d}"),
        os.path.join(reg.root, f"v{v2:06d}"),
        info["changed_rows"],
    )
    assert check["ok"], check


# ---------------------------------------------------------------------------
# history / SLO / analysis wiring
# ---------------------------------------------------------------------------


def test_delta_rows_get_churn_scoped_workload_keys():
    from tsspark_tpu.obs import history

    rep = {
        "metric": "delta_smoke_1024x64_refit_wall", "value": 1.2,
        "unit": "s", "vs_baseline": 0.0,
        "extra": {
            "trace_id": "t1", "device": "cpu", "complete": True,
            "fit_path": "resident", "delta_churn": 0.1,
            "series_done": 102, "n_changed": 102,
            "delta_series_per_s": 500.0, "delta_wall_frac": 0.12,
            "cache_carried": 40, "flip_hit_rate": 0.9,
        },
    }
    row = history.row_from_report(rep)
    assert row["kind"] == "bench"
    assert row["workload"].endswith("+resident+delta0.1")
    for k in ("delta_series_per_s", "delta_wall_frac",
              "cache_carried", "flip_hit_rate"):
        assert k in row["metrics"], k
    # A cold bench row is a DIFFERENT workload: no delta suffix.
    cold = history.row_from_report({
        "metric": "m5_512x256_fit_wall_clock", "value": 2.0,
        "extra": {"fit_path": "resident", "series_done": 512},
    })
    assert "+delta" not in cold["workload"]


def test_delta_slo_budgets_declared_everywhere():
    from tsspark_tpu.obs import regress

    for table in (regress.DEFAULT_SLO["budgets"]["bench"],
                  regress.load_slo()["budgets"]["bench"]):
        assert table["delta_series_per_s"]["direction"] == "higher"
        assert table["delta_wall_frac"]["direction"] == "lower"


def test_sweep_ok_accepts_real_report_shape():
    """The exit-code contract judged against an actual committed
    BENCH_delta_* artifact: success reports carry ``complete`` under
    ``extra`` (the bench-family shape), failure records at top level —
    sweep_ok must pass the former and fail the latter (found by review:
    the first cut read only the top level and failed green sweeps)."""
    import glob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = sorted(glob.glob(os.path.join(repo, "BENCH_delta_*.json")))
    assert committed, "no committed BENCH_delta_* artifact to pin against"
    with open(committed[0]) as fh:
        rep = json.load(fh)
    assert "complete" not in rep and rep["extra"]["complete"]
    assert refit.sweep_ok([rep])
    assert not refit.sweep_ok([dict(rep, sentinel_ok=False)])
    assert not refit.sweep_ok([{"complete": False, "stage": "refit"}])
    assert not refit.sweep_ok([])


def test_warm_gather_contract_registered_and_f32():
    from tsspark_tpu.analysis.contracts import default_kernels

    names = [k.name for k in default_kernels()]
    assert "refit.warm_theta_gather" in names
    theta = np.arange(24.0, dtype=np.float64).reshape(6, 4)
    theta[2, 1] = np.nan
    rows = refit.warm_theta_gather(theta, np.asarray([2, 4]))
    assert rows.dtype == np.float32 and rows.shape == (2, 4)
    assert np.isfinite(rows).all()
