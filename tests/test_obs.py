"""Observability subsystem (tsspark_tpu/obs, docs/OBSERVABILITY.md):
trace/span context and cross-process propagation, the metrics registry,
the run ledger, and the instrumentation's overhead bound.

The cross-process acceptance reuses the PR-5 lease machinery: a
SIGKILLed fit worker's reclaimed ranges must yield a ledger whose claim
spans link to the stolen claim, with zero orphan spans; and the serve
loadgen's request spans must reconcile with the SERVE_*.json latency
percentiles (they are one measurement, recorded twice).
"""

import argparse
import json
import logging
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tsspark_tpu import orchestrate  # noqa: E402
from tsspark_tpu.obs import context, ledger as ledger_mod, metrics  # noqa: E402


@pytest.fixture(autouse=True)
def _unbind_obs_run():
    """Every test leaves the process-global run binding as it found it
    (a leaked binding would spray spans from unrelated tests into a
    deleted tmp dir)."""
    yield
    context.end_run(None)


# ---------------------------------------------------------------------------
# context: spans, events, parents, crash visibility
# ---------------------------------------------------------------------------


def test_span_nesting_records_and_orphan_check(tmp_path):
    spans_path = str(tmp_path / "spans.jsonl")
    prev = context.start_run(spans_path)
    assert prev is None and context.active()
    with context.span("stage.orchestrate") as root:
        with context.span("chunk.fit", lo=0, hi=8) as child:
            assert context.current_span_id() == child
        context.event("fault", tag="worker-kill", mode="exit")
    context.end_run(prev)

    spans, events = ledger_mod.merge_spans(
        context.read_records(spans_path)
    )
    by_name = {s["name"]: s for s in spans}
    assert by_name["chunk.fit"]["parent_id"] == root
    assert by_name["stage.orchestrate"]["parent_id"] is None
    assert by_name["chunk.fit"]["attrs"] == {"lo": 0, "hi": 8}
    assert all(s["trace_id"] == spans[0]["trace_id"] for s in spans)
    # The event rode the stage span.
    assert events[0]["span_id"] == root
    assert events[0]["attrs"]["tag"] == "worker-kill"
    assert ledger_mod.orphan_spans(spans) == []
    # An inactive context records nothing and costs nothing.
    with context.span("ghost"):
        pass
    assert len(context.read_records(spans_path)) == 3


def test_open_span_survives_a_killed_writer(tmp_path):
    """The crash-safe parent contract: the ``open`` record written at
    span begin keeps a killed process's children out of the orphan
    list; a span never closed reports status ``open``."""
    spans_path = str(tmp_path / "spans.jsonl")
    context.start_run(spans_path)
    wid = context.open_span("fit.worker", make_current=True)
    context.record("chunk.claim", time.time(), 0.0, lo=0, hi=8)
    # ...process dies here: no close_span ever runs.
    context.end_run(None)
    spans, _ = ledger_mod.merge_spans(context.read_records(spans_path))
    worker = next(s for s in spans if s["name"] == "fit.worker")
    claim = next(s for s in spans if s["name"] == "chunk.claim")
    assert worker["status"] == "open" and worker["dur_s"] is None
    assert claim["parent_id"] == wid
    assert ledger_mod.orphan_spans(spans) == []


def test_env_propagation_round_trip(tmp_path, monkeypatch):
    spans_path = str(tmp_path / "spans.jsonl")
    context.start_run(spans_path, trace_id="feedbeefcafe")
    with context.span("stage.orchestrate") as parent:
        env = {}
        context.inject_env(env)
    context.end_run(None)
    monkeypatch.setenv(context.ENV_VAR, env[context.ENV_VAR])
    assert context.adopt_env()
    assert context.trace_id() == "feedbeefcafe"
    # The injected parent became the adopted current span.
    assert context.current_span_id() == parent
    context.end_run(None)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_pow2_buckets_and_prometheus(tmp_path):
    reg = metrics.MetricsRegistry()
    reg.counter("tsspark_serve_requests_total", result="completed").inc(5)
    reg.counter("tsspark_serve_requests_total", result="shed").inc()
    reg.gauge("tsspark_serve_queue_depth").set(17)
    h = reg.histogram("tsspark_serve_request_seconds")
    for v in (0.0003, 0.0009, 0.0017, 0.9, 3.0):
        h.observe(v)
    # Pow-2 buckets: each observation lands at 2**ceil(log2(v)).
    assert h.count == 5
    assert h.buckets[-10] == 1          # 0.0009 <= 2**-10
    assert h.buckets[-9] == 1           # 0.0017 <= 2**-9
    assert h.quantile(0.5) in (2.0 ** -9, 2.0 ** -10)
    text = reg.to_prometheus()
    assert 'tsspark_serve_requests_total{result="completed"} 5' in text
    assert "tsspark_serve_queue_depth 17" in text
    assert 'le="+Inf"} 5' in text
    assert "tsspark_serve_request_seconds_count 5" in text

    # Atomic snapshot export round-trips and is ledger-joinable.
    out = str(tmp_path / "metrics_test.json")
    reg.export(out, trace_id="aaaabbbbcccc")
    with open(out) as fh:
        snap = json.load(fh)
    assert snap["kind"] == "metrics-snapshot"
    assert snap["trace_id"] == "aaaabbbbcccc"
    assert metrics.prometheus_text(snap["metrics"]) == text


# ---------------------------------------------------------------------------
# satellite: monotonic timers + trace-stamped structured logs
# ---------------------------------------------------------------------------


def test_timed_and_timers_survive_wall_clock_steps(monkeypatch):
    """Durations must come off the monotonic clock: a wall-clock step
    backwards mid-block (NTP correction) may not produce a negative
    duration."""
    from tsspark_tpu.utils.logging import StructuredLogger, timed
    from tsspark_tpu.utils.profiling import Timers

    seen = {}

    class _Sink:
        def info(self, event, **fields):
            seen.update(fields)

    # Wall clock jumps 1000 s BACKWARDS between enter and exit.
    walls = iter([2_000_000.0, 1_999_000.0, 1_998_000.0])
    monkeypatch.setattr(time, "time", lambda: next(walls))
    with timed(_Sink(), "step"):
        pass
    assert 0.0 <= seen["seconds"] < 1.0

    t = Timers()
    with t.section("s"):
        pass
    assert 0.0 <= t.summary()["s"]["total_s"] < 1.0


def test_structured_logger_stamps_trace_ids(tmp_path, capsys):
    from tsspark_tpu.utils.logging import get_logger

    log = get_logger("tsspark.test_obs")
    log._logger.setLevel(logging.INFO)
    context.start_run(str(tmp_path / "spans.jsonl"),
                      trace_id="0123456789ab")
    with context.span("stage.test") as sid:
        log.info("inside_span", n=1)
    context.end_run(None)
    log.info("outside_span", n=2)
    lines = [json.loads(l) for l in
             capsys.readouterr().err.strip().splitlines() if l.strip()]
    inside = next(l for l in lines if l["event"] == "inside_span")
    outside = next(l for l in lines if l["event"] == "outside_span")
    assert inside["trace_id"] == "0123456789ab"
    assert inside["span_id"] == sid
    assert "trace_id" not in outside


# ---------------------------------------------------------------------------
# cross-process propagation: SIGKILL mid-run, reclaimed-range lineage
# ---------------------------------------------------------------------------


def _model_config():
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig

    return ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )


def test_sigkill_reclaim_spans_parent_to_stolen_claim(tmp_path,
                                                      monkeypatch):
    """A worker killed mid-run leaves leases behind; the respawned
    worker steals them.  The ledger must show that lineage: the thief's
    ``chunk.claim`` links ``stolen_from`` to the dead worker's claim
    span (readable because claim spans are written AT claim time), the
    reclaimed range's ``chunk.fit`` parents to the thief's claim, and
    no span in the whole multi-process run is an orphan."""
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets
    from tsspark_tpu.resilience import faults
    from tsspark_tpu.resilience.policy import RetryPolicy

    batch = datasets.m5_like(n_series=48, n_days=96)
    scratch = tmp_path / "scratch"
    data_dir = str(scratch / "data")
    out_dir = str(scratch / "out")
    # No regressor spill: the weekly-only test config carries no
    # RegressorConfig, and the packer rejects a mismatched reg array.
    orchestrate.spill_data(
        data_dir, batch.ds, np.nan_to_num(batch.y), mask=batch.mask,
    )
    orchestrate.save_run_config(
        out_dir, _model_config(), SolverConfig(max_iters=40)
    )
    plan = (
        faults.FaultPlan(state_dir=str(tmp_path / "faults"))
        .fail("fit_worker_chunk", after=0, attempts=1, mode="exit",
              rc=31, tag="worker-kill")
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    context.start_run(os.path.join(out_dir, "spans.jsonl"))
    state = orchestrate.run_resilient(
        data_dir=data_dir, out_dir=out_dir, series=48, chunk=16,
        min_chunk=16, segment=0, phase1_iters=0, deadline=None,
        progress_timeout=600.0, probe_accelerator=False,
        retry_policy=RetryPolicy(max_attempts=9, base_delay_s=0.2,
                                 max_delay_s=0.2),
    )
    context.end_run(None)
    monkeypatch.delenv(faults.ENV_VAR)
    assert state["complete"] and state["retries"] >= 1

    led = ledger_mod.build_ledger(str(scratch))
    spans = led["spans"]
    by_id = {s["span_id"]: s for s in spans}
    assert led["orphan_spans"] == []
    assert len(led["processes"]) >= 3  # parent + >= 2 worker attempts

    # The kill is on the trace, and the dead worker's span stayed open.
    kills = [e for e in led["events"]
             if e["name"] == "fault" and e["attrs"]["tag"] == "worker-kill"]
    assert len(kills) == 1
    dead_pid = kills[0]["pid"]
    dead_worker = next(s for s in spans if s["name"] == "fit.worker"
                       and s["pid"] == dead_pid)
    assert dead_worker["status"] == "open"

    stolen = [s for s in spans if s["name"] == "chunk.claim"
              and s["attrs"].get("stolen_from")]
    assert stolen, "no reclaimed-range claim recorded a stolen_from link"
    for claim in stolen:
        orig = by_id[claim["attrs"]["stolen_from"]]
        # The link resolves to the DEAD worker's claim on the same range.
        assert orig["name"] == "chunk.claim"
        assert orig["pid"] == dead_pid != claim["pid"]
        assert (orig["attrs"]["lo"], orig["attrs"]["hi"]) == \
            (claim["attrs"]["lo"], claim["attrs"]["hi"])
        # And the reclaimed range's fit parents to the thief's claim.
        fit = next(s for s in spans if s["name"] == "chunk.fit"
                   and s["parent_id"] == claim["span_id"])
        assert fit["attrs"]["lo"] == claim["attrs"]["lo"]
    # MTTR for the kill is derivable from spans alone.
    assert led["mttr_s"]["worker-kill"] is not None


# ---------------------------------------------------------------------------
# serve loadgen spans reconcile with the SERVE_*.json report
# ---------------------------------------------------------------------------


def test_loadgen_spans_reconcile_with_serve_report(tmp_path):
    """Engine request spans and the SERVE report's latency percentiles
    are ONE measurement recorded twice — same clock, same values — so
    the span-side p50/p99 must reproduce the report's within float
    noise, and the report's trace id must match the span log's."""
    from tsspark_tpu.serve import __main__ as serve_main

    report_path = str(tmp_path / "SERVE_test.json")
    rc = serve_main.main([
        "--loadgen", "300", "--dir", str(tmp_path), "--series", "8",
        "--report", report_path, "--seed", "3",
    ])
    context.end_run(None)
    assert rc == 0
    with open(report_path) as fh:
        report = json.load(fh)
    assert report["trace_id"]

    led = ledger_mod.build_ledger(str(tmp_path / "serve_scratch"))
    assert led["trace_id"] == report["trace_id"]
    durs = np.asarray([
        s["dur_s"] for s in led["spans"]
        if s["name"] == "serve.request" and s["status"] == "ok"
    ])
    assert len(durs) == report["engine"]["completed"]
    for q in (50, 99):
        got = float(np.percentile(durs, q)) * 1e3
        want = report["engine"]["latency_ms"][f"p{q}"]
        assert got == pytest.approx(want, rel=0.01, abs=0.05), \
            f"p{q}: spans {got} vs report {want}"
    # The loadgen's metrics snapshot joined the same trace.
    assert any(m["trace_id"] == report["trace_id"]
               for m in led["metrics"])
    assert led["red"]["serve.dispatch"]["n"] >= 1


# ---------------------------------------------------------------------------
# overhead smoke: tracing must stay out of the fit's way
# ---------------------------------------------------------------------------


def test_instrumentation_overhead_under_2pct(tmp_path):
    """The instrumentation volume a traced fit of this size emits must
    cost < 2% of the fit's wall time.  Measured directly — N span/metric
    records timed against the same compacted fit the records would
    describe — rather than as a wall-clock A/B of two subprocess runs,
    whose spawn/compile noise exceeds the 2% band being asserted."""
    import jax.numpy as jnp

    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import SolverConfig

    rng = np.random.default_rng(0)
    n, t_len = 128, 128
    ds = np.arange(t_len, dtype=np.float64)
    y = (10.0 + 0.02 * ds[None, :]
         + rng.normal(0, 0.3, (n, t_len))).astype(np.float32)
    backend = TpuBackend(_model_config(), SolverConfig(max_iters=40),
                         chunk_size=64, compact=True)
    backend.fit(ds, jnp.asarray(y))  # warm the compile cache
    t0 = time.perf_counter()
    backend.fit(ds, jnp.asarray(y))
    fit_wall = time.perf_counter() - t0

    # A traced orchestrate run of this shape (2 chunks) emits ~a dozen
    # records; measure 100x that volume and scale down.
    context.start_run(str(tmp_path / "spans.jsonl"))
    reg = metrics.MetricsRegistry()
    counter = reg.counter("tsspark_fit_chunks_total")
    hist = reg.histogram("tsspark_fit_chunk_seconds")
    n_records = 1200
    t0 = time.perf_counter()
    for i in range(n_records):
        context.record("chunk.fit", time.time(), 0.01, lo=i, hi=i + 64,
                       width=64, compile_miss=False)
        counter.inc()
        hist.observe(0.01)
    obs_wall = (time.perf_counter() - t0) / 100.0
    context.end_run(None)
    assert obs_wall < 0.02 * fit_wall, (
        f"instrumentation {obs_wall * 1e3:.2f}ms vs 2% of fit "
        f"{fit_wall * 1e3:.1f}ms"
    )


# ---------------------------------------------------------------------------
# CLI: obs report / ledger / prom, and perf's ledger input
# ---------------------------------------------------------------------------


def _tiny_run(tmp_path):
    context.start_run(str(tmp_path / "spans.jsonl"))
    with context.span("stage.orchestrate"):
        with context.span("chunk.claim", lo=0, hi=8):
            pass
        context.record("chunk.fit", time.time(), 0.25, lo=0, hi=8,
                       width=8)
        context.record("chunk.land", time.time(), 0.001, lo=0, hi=8)
    context.record("registry.publish", time.time(), 0.01, version=1)
    context.record("registry.activate", time.time(), 0.001, version=1)
    context.record("serve.request", time.time(), 0.002, cached=1,
                   n_series=1, horizon=7, version=1)
    reg = metrics.MetricsRegistry()
    reg.counter("tsspark_fit_chunks_total").inc()
    reg.export(str(tmp_path / "metrics_t.json"),
               trace_id=context.trace_id())
    with open(tmp_path / "times.jsonl", "w") as fh:
        fh.write(json.dumps({"lo": 0, "hi": 8, "fit_s": 0.25, "t": 0.3,
                             "width": 8, "series_per_s": 32.0}) + "\n")
    context.end_run(None)


def test_obs_cli_ledger_report_and_prom(tmp_path, capsys):
    from tsspark_tpu.obs import __main__ as obs_main

    _tiny_run(tmp_path)
    out = str(tmp_path / "RUNLEDGER_t.json")
    assert obs_main.main(["ledger", str(tmp_path), "-o", out]) == 0
    assert obs_main.main(["report", out]) == 0
    text = capsys.readouterr().out
    assert "orphan spans: 0" in text
    assert "chunk.claim" in text and "serve.request" in text
    assert "serve.first_cache_hit" in text
    assert "registry.publish" in text
    # The timeline reads in pipeline order from one joined trace.
    assert text.index("chunk.claim") < text.index("registry.publish")

    assert obs_main.main(["prom", out]) == 0
    assert "tsspark_fit_chunks_total 1" in capsys.readouterr().out


def test_perf_cli_accepts_run_ledger(tmp_path, capsys):
    from tsspark_tpu.obs import __main__ as obs_main
    from tsspark_tpu.perf import __main__ as perf_main

    _tiny_run(tmp_path)
    out = str(tmp_path / "RUNLEDGER_t.json")
    obs_main.main(["ledger", str(tmp_path), "-o", out])
    capsys.readouterr()
    assert perf_main.main([out]) == 0
    text = capsys.readouterr().out
    assert "chunks fitted:     1" in text
    assert "series/s by chunk size:" in text
