"""Always-on refit scheduler (tsspark_tpu.sched): pipelined loop,
speculative warm prep, data-to-forecast freshness, crash resume, and
the freshness SLO/history wiring."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tsspark_tpu import orchestrate, refit, resident, sched
from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.data import plane
from tsspark_tpu.resilience import faults
from tsspark_tpu.serve.cache import ForecastCache
from tsspark_tpu.serve.engine import PredictionEngine
from tsspark_tpu.serve.registry import ParamRegistry

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
    n_changepoints=3,
)
SOLVER = SolverConfig(max_iters=20)
N, T, SHARD, CHUNK = 24, 64, 8, 8


def _setup(tmp_path, seed=2):
    """Fresh plane dataset + cold resident fit + published registry —
    the same tiny shapes as tests/test_refit.py so the suite's compile
    cache covers every dispatch here."""
    spec = plane.DatasetSpec("demo_weekly", N, T, seed=seed,
                             shard_rows=SHARD)
    dset = plane.ensure(spec, root=str(tmp_path / "plane"))
    ids = plane.series_ids(spec)
    out = str(tmp_path / "cold_out")
    os.makedirs(out, exist_ok=True)
    orchestrate.save_run_config(out, CFG, SOLVER)
    st = resident.run_resident(data_dir=dset, out_dir=out, series=N,
                               chunk=CHUNK, phase1_iters=0,
                               no_phase1_tune=True)
    assert st["complete"]
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    v1 = orchestrate.publish_fit_state(
        reg, out, ids, data_stamp=plane.delta_seq(dset)
    )
    return spec, dset, reg, ids, v1


def _engine_loop(tmp_path, reg, ids, **kw):
    """A scheduler wired to an in-process engine: flips go through the
    prefetch/materialize/activate path, freshness probes are REAL
    served requests (the metric's definition)."""
    engine = PredictionEngine(reg, cache=ForecastCache(256))
    hot = [str(s) for s in ids[:8]]
    engine.materialize(hot, (7,))

    def flip_fn(v):
        engine.prefetch(v)
        engine.materialize(hot, (7,), version=v)
        reg.activate(v)

    def probe(v):
        return engine.forecast([hot[0]], 7).version

    dset = kw.pop("dset")
    loop = sched.RefitScheduler(
        dset, reg, str(tmp_path / "sched"), chunk=CHUNK,
        solver_config=SOLVER, flip_fn=flip_fn, freshness_probe=probe,
        poll_s=0.02, debounce_s=0.02, spec_refresh_s=0.05, **kw,
    )
    return loop, engine


# ---------------------------------------------------------------------------
# idle discipline
# ---------------------------------------------------------------------------


def test_idle_ticks_never_publish(tmp_path, monkeypatch):
    """Zero-delta idle ticks must not publish versions, accrue
    RUNHISTORY rows, or grow the snapshot dir — the scheduler never
    even enters the publish path without an advanced series."""
    monkeypatch.chdir(tmp_path)
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    versions_before = reg.versions()
    snap_dirs = sorted(os.listdir(reg.root))
    loop = sched.RefitScheduler(
        dset, reg, str(tmp_path / "sched"), chunk=CHUNK,
        solver_config=SOLVER, poll_s=0.01, debounce_s=0.0,
        spec_refresh_s=0.02,
    )
    summary = loop.run(duration_s=0.4)
    assert summary["cycles"] == 0
    assert reg.versions() == versions_before
    assert sorted(os.listdir(reg.root)) == snap_dirs
    assert not os.path.exists(str(tmp_path / "RUNHISTORY.jsonl"))
    # The advisory state file exists and says so.
    state = sched.read_sched_state(str(tmp_path / "sched"))
    assert state is not None and state["cycles"] == 0


# ---------------------------------------------------------------------------
# the loop end to end
# ---------------------------------------------------------------------------


def test_pipelined_stream_serves_fresh_versions(tmp_path):
    from tsspark_tpu.chaos import invariants as inv

    spec, dset, reg, ids, v1 = _setup(tmp_path)
    loop, engine = _engine_loop(tmp_path, reg, ids, dset=dset,
                                pipeline=True)
    seq0 = plane.delta_seq(dset)

    def lander():
        for _ in range(3):
            plane.land_synthetic_delta(dset, 0.2)
            time.sleep(0.6)

    t = threading.Thread(target=lander, daemon=True)
    t.start()
    summary = loop.run(until_stamp=seq0 + 3, duration_s=300)
    t.join()
    assert summary["ok"], summary
    assert summary["cycles"] >= 1
    assert summary["freshness"]["n"] == 3
    assert summary["freshness"]["p95_s"] > 0
    assert summary["wrong_version"] == 0
    assert summary["pending_deltas"] == 0
    v_final = summary["head_version"]
    assert reg.active_version() == v_final
    assert reg.version_stamp(v_final) == seq0 + 3
    # Copy-forward parity holds on the final hop.
    info = reg.delta_info(v_final)
    check = inv.refit_unchanged_bitwise(
        reg.version_dir(info["base_version"]),
        reg.version_dir(v_final), info["changed_rows"],
    )
    assert check["ok"], check
    # The engine really served the fresh version (probe path).
    assert engine.forecast([str(ids[0])], 7).version == v_final


def test_pipelined_and_serialized_converge_bitwise(tmp_path):
    """The pipeline (and its carry/speculation theta cache) is a
    latency lever, never a numerics input: the same delta stream
    processed pipelined and serialized lands bitwise-identical
    parameters.  Deterministic by construction — both roots share the
    dataset seed, so land_synthetic_delta lands identical bytes."""
    results = {}
    for mode, sub in (("pipelined", "a"), ("serialized", "b")):
        root = tmp_path / sub
        root.mkdir()
        spec, dset, reg, ids, v1 = _setup(root)
        loop = sched.RefitScheduler(
            dset, reg, str(root / "sched"), chunk=CHUNK,
            solver_config=SOLVER, pipeline=(mode == "pipelined"),
            poll_s=0.01, debounce_s=0.0, spec_refresh_s=0.02,
        )
        seq = plane.delta_seq(dset)
        for i in range(2):
            plane.land_synthetic_delta(dset, 0.2)
            seq += 1
            s = loop.run(until_stamp=seq, duration_s=300)
            assert s["ok"], s
        v = reg.active_version()
        theta = np.array(np.load(
            os.path.join(reg.version_dir(v), "snapcol_theta.npy"),
            mmap_mode="r",
        ))
        results[mode] = theta
    assert np.array_equal(results["pipelined"],
                          results["serialized"])


def test_scheduler_cli_resumes_after_flip_kill(tmp_path):
    """The loop-storm semantic at test scale: the CLI daemon dies at an
    armed ``sched_flip`` exit fault (version published, flip pending,
    plan incomplete); a successor scheduler resumes the pinned plan
    with ZERO new fit dispatches and completes the flip."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    scratch = str(tmp_path / "sched")
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("sched_flip", attempts=1, after=0, mode="exit", rc=29,
              tag="loop-storm")
    env = orchestrate._child_env()
    env[faults.ENV_VAR] = plan.to_env()
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tsspark_tpu.sched",
         "--data", dset, "--registry", reg.root, "--scratch", scratch,
         "--chunk", str(CHUNK), "--max-iters", str(SOLVER.max_iters),
         "--until-stamp", "1", "--duration", "120",
         "--poll", "0.02", "--debounce", "0.02"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 29, proc.stderr[-2000:]
    assert reg.active_version() == v1  # the kill never half-flipped
    plan_rec = refit.read_refit_plan(scratch)
    assert plan_rec is not None and not plan_rec.get("complete")
    loop = sched.RefitScheduler(
        dset, reg, scratch, chunk=CHUNK, solver_config=SOLVER,
        poll_s=0.02, debounce_s=0.0,
    )
    summary = loop.run(until_stamp=1, duration_s=300)
    assert summary["ok"], summary
    assert summary["resumed_cycles"] == 1
    # ONE cycle: the resumed publish advances the frontier, so the
    # loop must not re-detect (and re-fit) the set it just covered.
    assert summary["cycles"] == 1
    assert summary["freshness"]["n"] == 1
    v2 = summary["head_version"]
    assert reg.active_version() == v2 and v2 != v1
    assert reg.version_stamp(v2) == 1


def test_resume_of_plan_based_on_unflipped_version(tmp_path):
    """A front elsewhere owns the flip (activate=False): published
    versions never become active, so a successor must resume a pinned
    plan against the plan's OWN base — re-detecting from the stale
    active pointer would re-fit already-published rows and race deltas
    landed after the crash."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    scratch = str(tmp_path / "sched")
    plane.land_synthetic_delta(dset, 0.2)
    loop = sched.RefitScheduler(
        dset, reg, scratch, chunk=CHUNK, solver_config=SOLVER,
        activate=False, poll_s=0.01, debounce_s=0.0,
    )
    s1 = loop.run(until_stamp=1, duration_s=300)
    assert s1["ok"], s1
    v2 = s1["head_version"]
    assert reg.active_version() == v1 and v2 != v1  # never flipped
    # The next cycle pins against the unflipped head... then "dies".
    plane.land_synthetic_delta(dset, 0.2)
    plan = refit.draft_plan(dset, 1)
    plan = refit.pin_drafted(scratch, plan, v2)
    d2_rows = set(plan["changed_rows"])
    successor = sched.RefitScheduler(
        dset, reg, scratch, chunk=CHUNK, solver_config=SOLVER,
        activate=False, poll_s=0.01, debounce_s=0.0,
    )
    s2 = successor.run(until_stamp=2, duration_s=300)
    assert s2["ok"], s2
    assert s2["resumed_cycles"] == 1  # the pinned plan, not a re-detect
    v3 = s2["head_version"]
    info = reg.delta_info(v3)
    assert info["base_version"] == v2
    assert set(info["changed_rows"]) == d2_rows  # delta-2 rows ONLY


def test_publish_failure_is_retried_in_process(tmp_path):
    """A transient publish/flip failure must be re-driven by the loop
    itself (under backoff) — not parked until the next delta happens
    to land or the process restarts."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    calls = {"n": 0}

    def flaky_flip(v):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient flip outage")
        reg.activate(v)

    plane.land_synthetic_delta(dset, 0.2)
    loop = sched.RefitScheduler(
        dset, reg, str(tmp_path / "sched"), chunk=CHUNK,
        solver_config=SOLVER, flip_fn=flaky_flip,
        poll_s=0.01, debounce_s=0.0, backoff_base_s=0.05,
    )
    summary = loop.run(until_stamp=1, duration_s=300)
    assert summary["ok"], summary  # the retry succeeded: streak reset
    assert summary["failures"] == 1 and calls["n"] == 2
    assert summary["cycles"] == 1
    v = summary["head_version"]
    assert reg.active_version() == v
    assert reg.version_stamp(v) == 1
    assert summary["pending_deltas"] == 0  # freshness resolved


# ---------------------------------------------------------------------------
# speculation
# ---------------------------------------------------------------------------


def test_arrival_model_predicts_recurring_rows():
    model = sched.ArrivalModel(alpha=0.5)
    hot = [3, 7, 11]
    t0 = 1000.0
    for seq in range(1, 6):
        rows = hot + [17 + seq]  # hot set recurs; cold rows churn
        model.note_delta(seq, t0 + 5.0 * seq, np.asarray(rows))
    pred = model.predicted_rows(cap=3)
    assert set(pred.tolist()) == set(hot)
    # Idempotent by seq: replaying a record changes nothing.
    tracked = model.tracked()
    model.note_delta(5, t0 + 25.0, np.asarray(hot))
    assert model.tracked() == tracked
    # Bounded: the tracked set caps at max_tracked.
    small = sched.ArrivalModel(max_tracked=4)
    small.note_delta(1, t0, np.arange(10))
    small.note_delta(2, t0 + 1, np.arange(10, 20))
    assert small.tracked() <= 4


def test_speculative_cache_hits_are_counted(tmp_path):
    """A hot-biased stream gives the arrival model signal: the
    speculative pre-gather must score hits against the next landed
    delta, and a speculative init is bitwise the plane gather it
    replaces (pinned via the theta cache path in fit_changed)."""
    spec, dset, reg, ids, v1 = _setup(tmp_path)
    hot_rows = np.asarray([1, 5, 9, 13], np.int64)
    # Seed the model's history: the same hot rows advance repeatedly.
    loop, engine = _engine_loop(tmp_path, reg, ids, dset=dset)
    seq = plane.delta_seq(dset)
    for i in range(3):
        plane.land_synthetic_delta(dset, 0.2, rows=hot_rows)
        seq += 1
        s = loop.run(until_stamp=seq, duration_s=300)
        assert s["ok"], s
        # Let an idle tick refresh the speculation between deltas.
        loop.run(duration_s=0.15)
    spec_stats = loop.spec_summary()
    assert spec_stats["predicted"] > 0
    assert spec_stats["hits"] > 0  # the recurring rows were predicted
    assert spec_stats["hit_rate"] > 0


def test_warm_theta_cache_is_bitwise_the_plane_gather(tmp_path):
    """fit_changed with a theta cache must consume EXACTLY the bytes
    the per-wave plane gather would produce — speculation can never
    change an init."""
    from tsspark_tpu.serve import snapplane

    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    changed = plane.advanced_since(dset, 0)
    view = snapplane.attach(reg.version_dir(v1), verify=False)
    want = refit.warm_theta_gather(view.state.theta, changed)
    # Cache half the rows; the consume path must merge cache + plane
    # into the same array the pure plane gather yields.
    half = changed[: len(changed) // 2]
    cache = {"base_stamp": 0, "rows": half,
             "theta": refit.warm_theta_gather(view.state.theta, half)}
    plan = refit.draft_plan(dset, 0, base_version=v1)
    refit.ensure_spill(dset, plan, str(tmp_path / "scr"))
    # Exercise exactly the theta0_fn merge fit_changed builds: run the
    # fit twice, cache on/off, and require bitwise-equal solutions.
    r_cache = refit.fit_changed(
        dset, reg, plan, str(tmp_path / "scr"), chunk=CHUNK,
        solver_config=SOLVER, warm_start=True, theta_cache=cache,
    )
    assert r_cache["complete"] and r_cache["warm_cache_hits"] > 0
    r_plain = refit.fit_changed(
        dset, reg, plan, str(tmp_path / "scr2"), chunk=CHUNK,
        solver_config=SOLVER, warm_start=True,
    )
    assert np.array_equal(np.asarray(r_cache["state_sub"].theta),
                          np.asarray(r_plain["state_sub"].theta))
    assert want.dtype == np.float32  # the gather contract held


# ---------------------------------------------------------------------------
# reuse-cold amortization
# ---------------------------------------------------------------------------


def test_reuse_cold_amortizes_the_reference(tmp_path):
    from tsspark_tpu.bench_scale import ScaleRung

    rung = ScaleRung("smoke", N, T, SOLVER.max_iters, CHUNK, 0, 8, 4,
                     8, False)
    base_dir = str(tmp_path / "coldbase")
    os.makedirs(base_dir)
    spec = plane.DatasetSpec("demo_weekly", N, T, seed=2,
                             shard_rows=SHARD)
    dset = plane.ensure(spec, root=os.path.join(base_dir, "plane"))
    ids = plane.series_ids(spec)
    reg1, cold1, catchup1 = refit.prepare_cold_registry(
        rung, CFG, SOLVER, str(tmp_path / "run1"), dset, ids,
        reuse_cold=base_dir,
    )
    assert reg1 is not None and not cold1["reused"]
    assert catchup1 is None
    meta = refit.load_cold_meta(base_dir, rung)
    assert meta is not None and meta["fit_s"] == round(cold1["fit_s"], 3)
    # Deltas land between sweeps; the reused base must CATCH UP
    # (untimed) so measured cycles see only their own churn.
    plane.land_synthetic_delta(dset, 0.25)
    reg2, cold2, catchup2 = refit.prepare_cold_registry(
        rung, CFG, SOLVER, str(tmp_path / "run2"), dset, ids,
        reuse_cold=base_dir,
    )
    assert cold2["reused"] and cold2["fit_s"] == meta["fit_s"]
    assert catchup2 is not None and catchup2["complete"]
    active = reg2.active_version()
    assert reg2.version_stamp(active) == plane.delta_seq(dset)
    # A shape mismatch refuses reuse instead of serving a stale base.
    other = ScaleRung("smoke", N + 8, T, SOLVER.max_iters, CHUNK, 0,
                      8, 4, 8, False)
    assert refit.load_cold_meta(base_dir, other) is None


# ---------------------------------------------------------------------------
# freshness metric / history / SLO wiring
# ---------------------------------------------------------------------------


def test_freshness_rows_get_mode_scoped_workload_keys():
    from tsspark_tpu.obs import history

    rep = {
        "kind": "freshness-bench", "unix": 1.0, "trace_id": "t9",
        "device": "cpu", "rung": "smoke", "mode": "pipelined",
        "churn": 0.05, "complete": True,
        "freshness_p50_s": 0.4, "freshness_p95_s": 0.9,
        "freshness_vs_cold_frac": 0.2, "cycle_overhead_frac": 0.5,
        "spec_hit_rate": 0.3, "cycles": 6, "wrong_version": 0,
        "cold_wall_s": 4.0, "wall_s": 12.0,
    }
    row = history.row_from_report(rep)
    assert row["kind"] == "freshness"
    assert row["workload"] == "freshness_smoke_c0050+pipelined"
    for k in ("freshness_p95_s", "cycle_overhead_frac",
              "spec_hit_rate", "wrong_version"):
        assert k in row["metrics"], k
    # The serialized arm is a DIFFERENT workload — the p95 gap between
    # the two is the bench's whole point, never baseline noise.
    ser = history.row_from_report(dict(rep, mode="serialized"))
    assert ser["workload"] != row["workload"]


def test_freshness_slo_budgets_declared_everywhere():
    from tsspark_tpu.obs import regress

    for table in (regress.DEFAULT_SLO["budgets"]["freshness"],
                  regress.load_slo()["budgets"]["freshness"]):
        assert table["freshness_p95_s"]["direction"] == "lower"
        assert table["cycle_overhead_frac"]["direction"] == "lower"
        assert table["spec_hit_rate"]["direction"] == "higher"


def test_freshness_spans_reach_obs_watch(tmp_path):
    """The scheduler's refit.freshness spans are what `obs watch`
    reads: live trailing-window p95 appears in the observation."""
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs import watch

    scratch = tmp_path / "scr"
    scratch.mkdir()
    prev = obs.start_run(str(scratch / "spans.jsonl"))
    try:
        now = time.time()
        for i, fr in enumerate((0.2, 0.5, 0.9)):
            obs.record("refit.freshness", now - fr, fr, seq=i + 1,
                       version=2, probe="serve")
    finally:
        obs.end_run(prev)
    st = watch.observe_run(str(scratch), [])
    assert st["freshness_p95_s"] == pytest.approx(0.86, abs=0.02)


def test_cache_carried_metric_exported():
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    cache = ForecastCache(16)
    for i in range(4):
        cache.put((1, f"s{i}", 8, 0, 0), {"row": i})
    before = METRICS.counter("tsspark_serve_cache_carried").value
    moved = cache.carry_forward(1, 2, {"s0"})
    assert moved == 3
    assert METRICS.counter("tsspark_serve_cache_carried").value \
        == before + 3


# ---------------------------------------------------------------------------
# disk-pressure degradation ladder (docs/RESILIENCE.md § Storage fault
# domain): idle ticks shed speculation and reap eagerly under pressure,
# and resume once the budget clears.
# ---------------------------------------------------------------------------


def test_idle_tick_sheds_speculation_and_reaps_under_pressure(
        tmp_path, monkeypatch):
    from tsspark_tpu.io import atomic_write_text, current_state
    from tsspark_tpu.io import budget as iobudget

    spec, dset, reg, ids, v1 = _setup(tmp_path)
    scratch = str(tmp_path / "sched")
    loop = sched.RefitScheduler(
        dset, reg, scratch, chunk=CHUNK, solver_config=SOLVER,
        poll_s=0.0, debounce_s=0.0, spec_refresh_s=0.0,
    )
    calls = []
    monkeypatch.setattr(loop, "_refresh_speculation",
                        lambda: calls.append(1))
    # A stale completed cycle, with real bytes so an exhausted budget
    # reads as zero headroom.
    stale = os.path.join(scratch, "cycle_b000001_s000002")
    os.makedirs(stale, exist_ok=True)
    atomic_write_text(os.path.join(stale, "spill.bin"), "x" * 4096)
    # Unarmed: the tick speculates and leaves retained history alone.
    loop._idle_tick()
    assert calls == [1]
    assert os.path.isdir(stale)
    # Exhausted budget over scratch: rung 1 sheds the warm prep, rung 2
    # reaps the stale cycle — on the SAME idle tick, no publish needed.
    used = iobudget.DiskBudget(scratch).used_bytes()
    monkeypatch.setenv(iobudget.ENV_BUDGET_ROOT, scratch)
    monkeypatch.setenv(iobudget.ENV_BUDGET_BYTES, str(max(1, used)))
    loop._idle_tick()
    assert calls == [1]  # speculation shed
    assert not os.path.exists(stale)  # history reaped
    # The advisory state file reports the rung for operators.  The
    # reap itself freed budgeted bytes, so the rung may already have
    # climbed — but with a budget armed it cannot read "normal".
    loop._write_sched_state()
    state = sched.read_sched_state(scratch)
    assert state["disk_ladder"] in ("shed_spec", "reap",
                                    "pause_ingest", "stale_serve")
    # Budget cleared: the next tick resumes speculative warm prep.
    monkeypatch.delenv(iobudget.ENV_BUDGET_ROOT)
    monkeypatch.delenv(iobudget.ENV_BUDGET_BYTES)
    loop._idle_tick()
    assert calls == [1, 1]
    assert current_state(scratch) == "normal"


def test_tick_pauses_refit_intake_at_pause_ingest(tmp_path, monkeypatch):
    """Rung 3 (pause_ingest): with pending deltas but no headroom the
    tick must not draft a cycle (the spill would grow scratch at the
    worst moment) — deltas stay pending until relief."""
    from tsspark_tpu.io import atomic_write_text
    from tsspark_tpu.io import budget as iobudget

    spec, dset, reg, ids, v1 = _setup(tmp_path)
    plane.land_synthetic_delta(dset, 0.25)
    scratch = str(tmp_path / "sched")
    loop = sched.RefitScheduler(
        dset, reg, scratch, chunk=CHUNK, solver_config=SOLVER,
        poll_s=0.0, debounce_s=0.0, spec_refresh_s=1e9,
    )
    loop._startup_resume()
    assert loop._pending  # the delta is seen and owed a cycle
    real_draft = refit.draft_plan
    os.makedirs(scratch, exist_ok=True)
    atomic_write_text(os.path.join(scratch, "ballast"), "b" * 4096)
    used = iobudget.DiskBudget(scratch).used_bytes()
    monkeypatch.setenv(iobudget.ENV_BUDGET_ROOT, scratch)
    monkeypatch.setenv(iobudget.ENV_BUDGET_BYTES, str(max(1, used)))
    monkeypatch.setattr(
        refit, "draft_plan",
        lambda *a, **k: pytest.fail("drafted a cycle under pause_ingest"))
    loop._tick()
    assert loop._pending  # still owed — intake paused, not dropped
    assert loop.failures == 0  # a pause is not a failure
    # Relief: the same tick drafts (and the recorder proves it got
    # past the gate).
    monkeypatch.delenv(iobudget.ENV_BUDGET_ROOT)
    monkeypatch.delenv(iobudget.ENV_BUDGET_BYTES)
    drafted = []

    def record_draft(*a, **k):
        drafted.append(1)
        return real_draft(*a, **k)

    monkeypatch.setattr(refit, "draft_plan", record_draft)
    loop._tick()
    loop._join_publisher(block=True)
    assert drafted
