"""Seasonality features, fit-data prep, and the batched forward model."""

import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import (
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    WEEKLY,
    YEARLY,
)
from tsspark_tpu.models.prophet import seasonality
from tsspark_tpu.models.prophet.design import model_yhat, prepare_fit_data
from tsspark_tpu.models.prophet.params import ProphetParams, pack, unpack, init_theta


def test_fourier_features_values():
    t = jnp.asarray([0.0, 1.75, 14.0])
    x = np.asarray(seasonality.fourier_features(t, period=7.0, order=2))
    assert x.shape == (3, 4)
    for i, tt in enumerate([0.0, 1.75, 14.0]):
        want = [
            np.sin(2 * np.pi * 1 * tt / 7),
            np.cos(2 * np.pi * 1 * tt / 7),
            np.sin(2 * np.pi * 2 * tt / 7),
            np.cos(2 * np.pi * 2 * tt / 7),
        ]
        np.testing.assert_allclose(x[i], want, atol=1e-6)


def test_fourier_large_t_phase_stable():
    # Large absolute day counts must not lose phase (mod-period fold).
    t = jnp.asarray([100000.0 + 1.75], dtype=jnp.float32)
    x = np.asarray(seasonality.fourier_features(t, period=7.0, order=1))
    tt = (100000.0 + 1.75) % 7.0
    np.testing.assert_allclose(
        x[0], [np.sin(2 * np.pi * tt / 7), np.cos(2 * np.pi * tt / 7)], atol=1e-4
    )


def test_param_pack_roundtrip():
    cfg = ProphetConfig(n_changepoints=5, seasonalities=(WEEKLY,))
    rng = np.random.default_rng(0)
    theta = jnp.asarray(rng.normal(size=(3, cfg.num_params)))
    p = unpack(theta, cfg)
    np.testing.assert_allclose(np.asarray(pack(p)), np.asarray(theta))
    assert p.delta.shape == (3, 5)
    assert p.beta.shape == (3, WEEKLY.num_features)


def test_prepare_fit_data_scaling_and_mask():
    cfg = ProphetConfig(seasonalities=(WEEKLY,), n_changepoints=3)
    ds = jnp.arange(10.0)
    y = np.ones((2, 10))
    y[0] *= 4.0
    y[1] *= -2.0
    y[1, 7:] = np.nan  # missing tail
    data, meta = prepare_fit_data(ds, jnp.asarray(y), cfg)

    np.testing.assert_allclose(np.asarray(meta.y_scale), [4.0, 2.0])
    np.testing.assert_allclose(np.asarray(data.mask[1]), [1] * 7 + [0] * 3)
    # Scaled y in [-1, 1]; masked entries zeroed.
    assert np.abs(np.asarray(data.y)).max() <= 1.0 + 1e-6
    assert (np.asarray(data.y[1, 7:]) == 0).all()
    # Scaled time: series 1 spans only 6 observed days.
    np.testing.assert_allclose(float(data.t[0, -1]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(data.t[1, 6]), 1.0, atol=1e-6)
    # Shared grid -> shared (T, F) seasonal matrix.
    assert data.X_season.shape == (10, WEEKLY.num_features)


def test_prepare_logistic_requires_cap():
    cfg = ProphetConfig(growth="logistic", seasonalities=())
    with pytest.raises(ValueError):
        prepare_fit_data(jnp.arange(5.0), jnp.ones((1, 5)), cfg)


def test_regressor_standardization():
    cfg = ProphetConfig(
        seasonalities=(),
        n_changepoints=0,
        regressors=(
            RegressorConfig("temp"),
            RegressorConfig("promo"),  # binary -> left unscaled
        ),
    )
    rng = np.random.default_rng(1)
    temp = rng.normal(20.0, 5.0, (2, 40, 1))
    promo = (rng.uniform(size=(2, 40, 1)) < 0.3).astype(float)
    reg = np.concatenate([temp, promo], axis=-1)
    data, meta = prepare_fit_data(
        jnp.arange(40.0), jnp.asarray(rng.normal(size=(2, 40))), cfg,
        regressors=jnp.asarray(reg),
    )
    x = np.asarray(data.X_reg)
    np.testing.assert_allclose(x[:, :, 0].mean(axis=1), 0.0, atol=1e-5)
    np.testing.assert_allclose(x[:, :, 0].std(axis=1), 1.0, atol=1e-3)
    np.testing.assert_allclose(x[:, :, 1], reg[:, :, 1], atol=1e-6)  # untouched


def test_model_yhat_additive_vs_multiplicative():
    weekly_add = SeasonalityConfig("weekly", 7.0, 2, mode="additive")
    weekly_mult = SeasonalityConfig("weekly", 7.0, 2, mode="multiplicative")
    rng = np.random.default_rng(2)
    ds = jnp.arange(60.0)
    y = jnp.asarray(rng.normal(10, 1, (1, 60)))
    beta = rng.normal(size=4)

    for mode_cfg, mult in ((weekly_add, False), (weekly_mult, True)):
        cfg = ProphetConfig(seasonalities=(mode_cfg,), n_changepoints=0)
        data, _ = prepare_fit_data(ds, y, cfg)
        p = ProphetParams(
            k=jnp.asarray([0.5]),
            m=jnp.asarray([1.0]),
            log_sigma=jnp.asarray([0.0]),
            delta=jnp.zeros((1, 0)),
            beta=jnp.asarray(beta[None, :]),
        )
        yhat, g = model_yhat(pack(p), data, cfg)
        x = np.asarray(data.X_season)
        season = x @ beta
        want = np.asarray(g[0]) * (1 + season) if mult else np.asarray(g[0]) + season
        np.testing.assert_allclose(np.asarray(yhat[0]), want, rtol=1e-4, atol=1e-5)


def test_init_theta_reasonable():
    cfg = ProphetConfig(seasonalities=(YEARLY,), n_changepoints=4)
    ds = jnp.arange(100.0)
    y_raw = 2.0 + 3.0 * np.arange(100) / 99.0  # line from 2 to 5
    data, meta = prepare_fit_data(ds, jnp.asarray(y_raw[None, :]), cfg)
    theta0 = init_theta(cfg, data.y, data.mask, data.t)
    p = unpack(theta0, cfg)
    # Scaled: y/5 spans 0.4 -> 1.0 over t 0 -> 1: slope 0.6, intercept 0.4.
    np.testing.assert_allclose(float(p.k[0]), 0.6, atol=1e-3)
    np.testing.assert_allclose(float(p.m[0]), 0.4, atol=1e-3)
    assert np.asarray(p.delta).shape == (1, 4)


def _mixed_batch(b=6, t_len=120):
    """Shared-grid batch with binary + continuous regressors and a masked-out
    tail on one series (exercises every packed-transfer special case)."""
    rng = np.random.default_rng(3)
    ds = np.arange(t_len, dtype=np.float64) + 19000.0
    promo = (rng.random((b, t_len, 1)) < 0.2).astype(np.float64)
    price = rng.normal(3.0, 1.0, (b, t_len, 1))
    reg = np.concatenate([promo, price], axis=-1)
    y = 10 + 0.05 * np.arange(t_len) + 2 * promo[..., 0] + rng.normal(
        0, 0.2, (b, t_len)
    )
    mask = np.ones((b, t_len))
    mask[0, t_len // 2:] = 0.0
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        regressors=(
            RegressorConfig("promo", standardize=False),
            RegressorConfig("price"),
        ),
        n_changepoints=5,
    )
    return cfg, ds, y, mask, reg


@pytest.mark.parametrize("t_len", [120, 125])
def test_packed_fit_data_roundtrip(t_len):
    """pack_fit_data -> unpack_fit_data reproduces the prepared FitData:
    bit-for-bit except t (reconstructed on device from per-series scalars,
    allowed a few f32 ulps).  t_len=125 exercises the bit-packed
    indicator tail (T % 8 != 0: the last byte carries padding bits that
    must be sliced off on device)."""
    import jax

    from tsspark_tpu.models.prophet.design import (
        pack_fit_data,
        unpack_fit_data,
    )

    cfg, ds, y, mask, reg = _mixed_batch(t_len=t_len)
    data, meta = prepare_fit_data(
        ds, y, cfg, mask=mask, regressors=reg, as_numpy=True
    )
    packed, u8_cols = pack_fit_data(data, meta, ds, collapse_cap=True)
    # Binary promo column (index 0) travels bit-packed, continuous price
    # as f32; the mask travels folded into y as NaN.
    assert u8_cols == (0,)
    assert packed.X_reg_bits.shape[-1] == 1
    assert packed.X_reg_bits.shape[1] == -(-y.shape[1] // 8)
    assert packed.X_reg_bits.dtype == np.uint8
    assert packed.X_reg.shape[-1] == 1
    assert bool(np.any(~np.isfinite(packed.y))) == bool(np.any(mask == 0))
    assert packed.cap.shape[-1] == 1  # linear growth: cap not shipped

    un = jax.jit(
        unpack_fit_data, static_argnames=("reg_u8_cols",)
    )(jax.tree.map(jnp.asarray, packed), reg_u8_cols=u8_cols)
    for name in data._fields:
        a = np.asarray(getattr(data, name))
        b_ = np.asarray(getattr(un, name))
        assert a.shape == b_.shape, name
        tol = 5e-7 if name == "t" else 0.0
        np.testing.assert_allclose(a, b_, atol=tol, err_msg=name)


def test_pack_fit_data_rejects_nonfinite_observed_y():
    """A NaN/inf cell with mask == 1 must fail loudly at pack time: the
    NaN-fold transit recovers the mask as isfinite(y), so it would
    silently reclassify the cell as missing while the plain FitData path
    propagates the non-finite value into the loss (ADVICE r4)."""
    from tsspark_tpu.models.prophet.design import pack_fit_data

    cfg, ds, y, mask, reg = _mixed_batch()
    data, meta = prepare_fit_data(
        ds, y, cfg, mask=mask, regressors=reg, as_numpy=True
    )
    # Poke the pathological combination straight into the prepared batch:
    # an OBSERVED cell whose value is non-finite.
    y_bad = np.asarray(data.y).copy()
    y_bad[1, 10] = np.nan
    data = data._replace(y=y_bad)
    with pytest.raises(ValueError, match="finite y"):
        pack_fit_data(data, meta, ds, collapse_cap=True)


def test_fit_core_packed_matches_plain():
    """The packed fit program lands on the same optima as the plain one
    (identical inputs up to 1 ulp of t -> same in-sample accuracy; exact
    per-iterate equality is not required of a chaotic 12-step solver)."""
    import jax

    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.models.prophet.design import pack_fit_data
    from tsspark_tpu.models.prophet.model import (
        fit_core,
        fit_core_packed,
    )

    cfg, ds, y, mask, reg = _mixed_batch()
    solver = SolverConfig(max_iters=60)
    data, meta = prepare_fit_data(
        ds, y, cfg, mask=mask, regressors=reg, as_numpy=True
    )
    packed, u8_cols = pack_fit_data(data, meta, ds)
    theta_p, stats = fit_core_packed(
        packed, None, cfg, solver, reg_u8_cols=u8_cols
    )
    res = fit_core(jax.tree.map(jnp.asarray, data), None, cfg, solver)
    # Same objective value per series within float32 solver noise.
    scale = np.maximum(np.abs(np.asarray(res.f)), 1.0)
    np.testing.assert_allclose(
        np.asarray(stats[0]) / scale, np.asarray(res.f) / scale, atol=2e-3
    )
    # Packed stats rows carry exactly what LbfgsResult carries.
    assert stats.shape == (5, y.shape[0])
    assert set(np.asarray(stats[4]).astype(int).tolist()) <= {0, 1, 2, 3, 4}
