"""Seasonality features, fit-data prep, and the batched forward model."""

import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import (
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    WEEKLY,
    YEARLY,
)
from tsspark_tpu.models.prophet import seasonality
from tsspark_tpu.models.prophet.design import model_yhat, prepare_fit_data
from tsspark_tpu.models.prophet.params import ProphetParams, pack, unpack, init_theta


def test_fourier_features_values():
    t = jnp.asarray([0.0, 1.75, 14.0])
    x = np.asarray(seasonality.fourier_features(t, period=7.0, order=2))
    assert x.shape == (3, 4)
    for i, tt in enumerate([0.0, 1.75, 14.0]):
        want = [
            np.sin(2 * np.pi * 1 * tt / 7),
            np.cos(2 * np.pi * 1 * tt / 7),
            np.sin(2 * np.pi * 2 * tt / 7),
            np.cos(2 * np.pi * 2 * tt / 7),
        ]
        np.testing.assert_allclose(x[i], want, atol=1e-6)


def test_fourier_large_t_phase_stable():
    # Large absolute day counts must not lose phase (mod-period fold).
    t = jnp.asarray([100000.0 + 1.75], dtype=jnp.float32)
    x = np.asarray(seasonality.fourier_features(t, period=7.0, order=1))
    tt = (100000.0 + 1.75) % 7.0
    np.testing.assert_allclose(
        x[0], [np.sin(2 * np.pi * tt / 7), np.cos(2 * np.pi * tt / 7)], atol=1e-4
    )


def test_param_pack_roundtrip():
    cfg = ProphetConfig(n_changepoints=5, seasonalities=(WEEKLY,))
    rng = np.random.default_rng(0)
    theta = jnp.asarray(rng.normal(size=(3, cfg.num_params)))
    p = unpack(theta, cfg)
    np.testing.assert_allclose(np.asarray(pack(p)), np.asarray(theta))
    assert p.delta.shape == (3, 5)
    assert p.beta.shape == (3, WEEKLY.num_features)


def test_prepare_fit_data_scaling_and_mask():
    cfg = ProphetConfig(seasonalities=(WEEKLY,), n_changepoints=3)
    ds = jnp.arange(10.0)
    y = np.ones((2, 10))
    y[0] *= 4.0
    y[1] *= -2.0
    y[1, 7:] = np.nan  # missing tail
    data, meta = prepare_fit_data(ds, jnp.asarray(y), cfg)

    np.testing.assert_allclose(np.asarray(meta.y_scale), [4.0, 2.0])
    np.testing.assert_allclose(np.asarray(data.mask[1]), [1] * 7 + [0] * 3)
    # Scaled y in [-1, 1]; masked entries zeroed.
    assert np.abs(np.asarray(data.y)).max() <= 1.0 + 1e-6
    assert (np.asarray(data.y[1, 7:]) == 0).all()
    # Scaled time: series 1 spans only 6 observed days.
    np.testing.assert_allclose(float(data.t[0, -1]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(data.t[1, 6]), 1.0, atol=1e-6)
    # Shared grid -> shared (T, F) seasonal matrix.
    assert data.X_season.shape == (10, WEEKLY.num_features)


def test_prepare_logistic_requires_cap():
    cfg = ProphetConfig(growth="logistic", seasonalities=())
    with pytest.raises(ValueError):
        prepare_fit_data(jnp.arange(5.0), jnp.ones((1, 5)), cfg)


def test_regressor_standardization():
    cfg = ProphetConfig(
        seasonalities=(),
        n_changepoints=0,
        regressors=(
            RegressorConfig("temp"),
            RegressorConfig("promo"),  # binary -> left unscaled
        ),
    )
    rng = np.random.default_rng(1)
    temp = rng.normal(20.0, 5.0, (2, 40, 1))
    promo = (rng.uniform(size=(2, 40, 1)) < 0.3).astype(float)
    reg = np.concatenate([temp, promo], axis=-1)
    data, meta = prepare_fit_data(
        jnp.arange(40.0), jnp.asarray(rng.normal(size=(2, 40))), cfg,
        regressors=jnp.asarray(reg),
    )
    x = np.asarray(data.X_reg)
    np.testing.assert_allclose(x[:, :, 0].mean(axis=1), 0.0, atol=1e-5)
    np.testing.assert_allclose(x[:, :, 0].std(axis=1), 1.0, atol=1e-3)
    np.testing.assert_allclose(x[:, :, 1], reg[:, :, 1], atol=1e-6)  # untouched


def test_model_yhat_additive_vs_multiplicative():
    weekly_add = SeasonalityConfig("weekly", 7.0, 2, mode="additive")
    weekly_mult = SeasonalityConfig("weekly", 7.0, 2, mode="multiplicative")
    rng = np.random.default_rng(2)
    ds = jnp.arange(60.0)
    y = jnp.asarray(rng.normal(10, 1, (1, 60)))
    beta = rng.normal(size=4)

    for mode_cfg, mult in ((weekly_add, False), (weekly_mult, True)):
        cfg = ProphetConfig(seasonalities=(mode_cfg,), n_changepoints=0)
        data, _ = prepare_fit_data(ds, y, cfg)
        p = ProphetParams(
            k=jnp.asarray([0.5]),
            m=jnp.asarray([1.0]),
            log_sigma=jnp.asarray([0.0]),
            delta=jnp.zeros((1, 0)),
            beta=jnp.asarray(beta[None, :]),
        )
        yhat, g = model_yhat(pack(p), data, cfg)
        x = np.asarray(data.X_season)
        season = x @ beta
        want = np.asarray(g[0]) * (1 + season) if mult else np.asarray(g[0]) + season
        np.testing.assert_allclose(np.asarray(yhat[0]), want, rtol=1e-4, atol=1e-5)


def test_init_theta_reasonable():
    cfg = ProphetConfig(seasonalities=(YEARLY,), n_changepoints=4)
    ds = jnp.arange(100.0)
    y_raw = 2.0 + 3.0 * np.arange(100) / 99.0  # line from 2 to 5
    data, meta = prepare_fit_data(ds, jnp.asarray(y_raw[None, :]), cfg)
    theta0 = init_theta(cfg, data.y, data.mask, data.t)
    p = unpack(theta0, cfg)
    # Scaled: y/5 spans 0.4 -> 1.0 over t 0 -> 1: slope 0.6, intercept 0.4.
    np.testing.assert_allclose(float(p.k[0]), 0.6, atol=1e-3)
    np.testing.assert_allclose(float(p.m[0]), 0.4, atol=1e-3)
    assert np.asarray(p.delta).shape == (1, 4)
