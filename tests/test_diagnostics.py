"""Pandas-level cross_validation / performance_metrics diagnostics."""

import numpy as np
import pandas as pd
import pytest

from tsspark_tpu import Forecaster, ProphetConfig, SeasonalityConfig
from tsspark_tpu.eval import diagnostics


@pytest.fixture(scope="module")
def cv_df():
    rng = np.random.default_rng(3)
    n = 240
    ds = pd.date_range("2023-01-01", periods=n, freq="D")
    t = np.arange(n)
    frames = []
    for i in range(3):
        y = 10 + 0.03 * t + 2 * np.sin(2 * np.pi * t / 7) + rng.normal(0, 0.3, n)
        frames.append(pd.DataFrame({"series_id": f"s{i}", "ds": ds, "y": y}))
    df = pd.concat(frames, ignore_index=True)

    fc = Forecaster(
        ProphetConfig(seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
                      n_changepoints=5),
        backend="tpu",
    )
    return diagnostics.cross_validation(
        fc, df, horizon="14D", period="30D", initial="90D"
    )


def test_cross_validation_frame_shape(cv_df):
    assert set(cv_df.columns) == {
        "series_id", "ds", "cutoff", "y", "yhat", "yhat_lower", "yhat_upper"
    }
    # Every row is within (cutoff, cutoff + horizon].
    gap = (cv_df["ds"] - cv_df["cutoff"]) / pd.Timedelta(days=1)
    assert (gap > 0).all() and (gap <= 14).all()
    # All series, several cutoffs.
    assert set(cv_df["series_id"]) == {"s0", "s1", "s2"}
    assert cv_df["cutoff"].nunique() >= 3
    # Forecast quality: this synthetic signal is easy.
    mae = (cv_df["y"] - cv_df["yhat"]).abs().mean()
    assert mae < 1.0
    assert (cv_df["yhat_lower"] <= cv_df["yhat_upper"]).all()


def test_performance_metrics_table(cv_df):
    pm = diagnostics.performance_metrics(cv_df, rolling_window=0.1)
    assert {"horizon", "mse", "rmse", "mae", "mape", "mdape", "smape",
            "coverage"} <= set(pm.columns)
    assert pm["horizon"].is_monotonic_increasing
    assert (pm["rmse"] >= pm["mae"] * 0.99).all()  # rmse >= mae always
    assert pm["smape"].between(0, 2).all()
    assert pm["coverage"].between(0, 1).all()
    # Horizon column stays a timedelta for datetime inputs.
    assert pd.api.types.is_timedelta64_dtype(pm["horizon"])


def test_performance_metrics_no_smoothing(cv_df):
    pm = diagnostics.performance_metrics(cv_df, rolling_window=0)
    # One row per distinct horizon step.
    assert pm["horizon"].is_unique
    assert len(pm) == 14
    # Exact per-horizon average, not a single sample: recompute by hand.
    h1 = cv_df[(cv_df["ds"] - cv_df["cutoff"]) == pd.Timedelta(days=1)]
    expect_mae = (h1["y"] - h1["yhat"]).abs().mean()
    got = pm.loc[pm["horizon"] == pd.Timedelta(days=1), "mae"].iloc[0]
    assert got == pytest.approx(expect_mae, rel=1e-9)


def test_cross_validation_rejects_nonpositive_horizon(cv_df):
    fc = Forecaster(ProphetConfig(seasonalities=(), n_changepoints=2))
    df = pd.DataFrame({"series_id": "a", "ds": np.arange(50.0),
                       "y": np.arange(50.0)})
    for bad in (0, -14, "-14D"):
        with pytest.raises(ValueError, match="positive"):
            diagnostics.cross_validation(fc, df, horizon=bad)


def test_performance_metrics_rejects_unknown_metric(cv_df):
    with pytest.raises(ValueError, match="unknown metrics"):
        diagnostics.performance_metrics(cv_df, metrics=("mae", "nope"))


def test_cross_validation_numeric_ds():
    rng = np.random.default_rng(5)
    n = 200
    t = np.arange(n, dtype=float)
    df = pd.DataFrame({
        "series_id": "a",
        "ds": t,
        "y": 5 + 0.1 * t + rng.normal(0, 0.2, n),
    })
    fc = Forecaster(ProphetConfig(seasonalities=(), n_changepoints=3))
    cv = diagnostics.cross_validation(fc, df, horizon=10, period=40,
                                      initial=100)
    assert np.issubdtype(cv["ds"].dtype, np.floating)
    assert ((cv["ds"] - cv["cutoff"]) <= 10).all()
