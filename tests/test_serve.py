"""Serving subsystem (tsspark_tpu/serve, docs/SERVING.md): registry
publish/activate/rollback + corrupt-manifest rejection, engine deadline
shedding and batch-coalescing bitwise determinism, cache invalidation on
version flips, the loadgen report, and the streaming driver's engine
routing."""

import json
import os
import time

import numpy as np
import jax.numpy as jnp
import pandas as pd
import pytest

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.resilience import FaultPlan, RetryPolicy, faults
from tsspark_tpu.serve import (
    EngineOverloaded,
    ForecastCache,
    ForecastRequest,
    ParamRegistry,
    PredictionEngine,
    RegistryError,
    RequestShed,
    UnknownSeries,
)
from tsspark_tpu.streaming.driver import StreamingForecaster, median_steps
from tsspark_tpu.streaming.state import ParamStore

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)


@pytest.fixture(scope="module")
def fitted():
    """One fitted 6-series batch shared across the module (fits are the
    slow part; every test only reads)."""
    rng = np.random.default_rng(0)
    t = np.arange(150.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (6, 150)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    return backend, state, [f"s{i}" for i in range(6)]


def _registry(tmp_path, fitted, **kwargs):
    backend, state, ids = fitted
    reg = ParamRegistry(str(tmp_path / "registry"), CFG, **kwargs)
    reg.publish(state, ids, step=np.ones(len(ids)))
    return reg


# ---------------------------------------------------------------------------
# registry lifecycle
# ---------------------------------------------------------------------------


def test_registry_publish_activate_rollback(tmp_path, fitted):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert reg.active_version() == 1 and reg.versions() == (1,)
    v2 = reg.publish(state._replace(theta=state.theta * 1.01), ids)
    assert (v2, reg.active_version()) == (2, 2)
    snap2 = reg.load()
    assert snap2.version == 2

    assert reg.rollback() == 1
    snap1 = reg.load()
    assert snap1.version == 1
    np.testing.assert_array_equal(
        np.asarray(snap1.state.theta) * 1.01, np.asarray(snap2.state.theta)
    )
    # Publish without activation leaves the active pointer alone.
    v3 = reg.publish(state, ids, activate=False)
    assert v3 == 3 and reg.active_version() == 1
    reg.activate(v3)
    assert reg.active_version() == 3
    with pytest.raises(RegistryError) as e:
        reg.activate(99)
    assert e.value.reason == "unknown-version"


def test_registry_snapshot_lookup_and_gather(tmp_path, fitted):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    snap = reg.load()
    idx, missing = snap.rows(["s3", "s0", "ghost"])
    assert missing == ["ghost"] and idx.tolist() == [3, 0]
    sub, step = snap.take(idx)
    np.testing.assert_array_equal(
        np.asarray(sub.theta), np.asarray(state.theta)[[3, 0]]
    )
    # Meta leaves stay host float64 through the gather (ds precision).
    assert sub.meta.ds_start.dtype == np.float64
    assert step.tolist() == [1.0, 1.0]


def test_registry_rejects_corrupt_manifest(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    with open(os.path.join(reg.root, "manifest.json"), "w") as fh:
        fh.write('{"format": 1, "versi')  # torn write simulation
    with pytest.raises(RegistryError) as e:
        ParamRegistry(reg.root, CFG)
    assert e.value.reason == "corrupt-manifest"


def test_registry_rejects_incompatible_snapshots(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    other = ProphetConfig(seasonalities=(), n_changepoints=3)
    with pytest.raises(RegistryError) as e:
        ParamRegistry(reg.root, other)
    assert e.value.reason == "fingerprint-mismatch"
    with pytest.raises(RegistryError) as e:
        ParamRegistry(reg.root, CFG, numerics_rev=999)
    assert e.value.reason == "numerics-rev-mismatch"
    # strict=False force-attaches (the operator override).
    assert ParamRegistry(reg.root, CFG, numerics_rev=999,
                         strict=False).active_version() == 1


def test_registry_open_rebuilds_config(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    reopened = ParamRegistry.open(reg.root)
    assert reopened.config == CFG
    assert reopened.load().version == 1


# ---------------------------------------------------------------------------
# engine: coalescing determinism, shedding, admission, retries
# ---------------------------------------------------------------------------


def test_engine_batched_bitwise_equals_direct_predict(tmp_path, fitted):
    """THE serving parity pin: two coalesced requests, padded to the
    pow-2 width/horizon buckets, must reproduce a direct
    backend.predict for the same series bit for bit."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    eng = PredictionEngine(reg)
    p1 = eng.submit(ForecastRequest.make(["s1", "s3", "s4"], 7))
    p2 = eng.submit(ForecastRequest.make(["s5", "s1"], 5))
    assert eng.pump() == 2  # one batch, one dispatch group (same bucket)
    r1, r2 = p1.result(5), p2.result(5)

    snap = reg.load()
    for res, sids, h in ((r1, ["s1", "s3", "s4"], 7),
                         (r2, ["s5", "s1"], 5)):
        idx, _ = snap.rows(sids)
        sub, step = snap.take(idx)
        last = np.asarray(sub.meta.ds_start + sub.meta.ds_span, np.float64)
        grid = last[:, None] + step[:, None] * np.arange(1, h + 1)
        direct = backend.predict(sub, grid, num_samples=0)
        np.testing.assert_array_equal(res.ds, grid)
        for k, v in direct.items():
            np.testing.assert_array_equal(
                res.values[k], np.asarray(v), err_msg=k
            )
    # Both requests rode one dispatch: s1 was gathered once.
    assert eng.stats.dispatches == 1
    occ = eng.stats.occupancy[0]
    assert occ[0] == 4 and occ[2] == 2  # 4 unique series, 2 requests


def test_engine_deadline_shedding_structured(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    eng = PredictionEngine(reg)
    dead = eng.submit(ForecastRequest.make(["s0"], 7, deadline_in_s=0.0))
    alive = eng.submit(ForecastRequest.make(["s2"], 7, deadline_in_s=30.0))
    time.sleep(0.005)
    assert eng.pump() == 2
    with pytest.raises(RequestShed) as e:
        dead.result(5)
    d = e.value.to_dict()
    assert d["reason"] == "deadline-exceeded" and d["late_s"] >= 0
    assert alive.result(5).values["yhat"].shape == (1, 7)
    assert eng.stats.shed == 1 and eng.stats.completed == 1


def test_engine_admission_and_unknown_series(tmp_path, fitted):
    reg = _registry(tmp_path, fitted)
    eng = PredictionEngine(reg, max_queue=1)
    eng.submit(ForecastRequest.make(["s0"], 7))
    with pytest.raises(EngineOverloaded):
        eng.submit(ForecastRequest.make(["s1"], 7))
    assert eng.stats.rejected == 1
    eng.pump()
    with pytest.raises(UnknownSeries) as e:
        eng.forecast(["s0", "ghost"], 7)
    assert e.value.missing == ("ghost",) and e.value.version == 1
    # Malformed requests fail alone, with structured errors — never the
    # batch they were coalesced into.
    with pytest.raises(ValueError):
        ForecastRequest.make([], 7)
    bad = eng.submit(ForecastRequest(series_ids=(), horizon=7))
    eng.pump()
    with pytest.raises(ValueError):
        bad.result(5)
    ok = eng.submit(ForecastRequest.make(["s0"], 7))
    eng.pump()
    assert ok.result(5).values["yhat"].shape == (1, 7)


def test_registry_concurrent_publishers_get_distinct_versions(tmp_path,
                                                              fitted):
    import threading

    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    got = []
    publish = lambda: got.append(reg.publish(state, ids))
    threads = [threading.Thread(target=publish) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(got) == [2, 3, 4, 5]  # no duplicate version numbers
    assert reg.versions() == (1, 2, 3, 4, 5)  # no catalog entry lost
    for v in got:
        assert reg.load(v).version == v  # every snapshot loads whole


def test_engine_retry_policy_covers_transient_faults(tmp_path, fitted,
                                                     monkeypatch):
    reg = _registry(tmp_path, fitted)
    plan = FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "serve_predict", attempts=1, mode="raise"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    eng = PredictionEngine(
        reg, retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                      max_delay_s=0.0),
    )
    res = eng.forecast(["s0"], 7)  # first dispatch faults, retry lands
    assert res.values["yhat"].shape == (1, 7)


def test_registry_corrupt_active_snapshot_falls_back(tmp_path, fitted):
    """A corrupt ACTIVE snapshot must not take down the read path: the
    CRC check rejects it and the registry serves the last good version
    (with a warning and ``fallback_from`` set); an explicitly requested
    version still raises."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)  # v1 active
    v2 = reg.publish(state._replace(theta=state.theta * 1.01), ids)
    assert reg.active_version() == v2
    # Silent corruption of BOTH snapshot representations (the mmap
    # column plane is the preferred format and the npz its per-version
    # archival fallback — only when both are torn does the registry
    # degrade to an older version): flip bytes at several offsets (same
    # spread as faults.corrupt_file — a single flip can land entirely
    # inside npz alignment padding no loader parses).
    for name in ("state.npz", "snapcol_theta.npy"):
        path = os.path.join(reg.root, f"v{v2:06d}", name)
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            for k in range(1, 8):
                fh.seek(size * k // 8)
                chunk = fh.read(16)
                fh.seek(size * k // 8)
                fh.write(bytes(b ^ 0xFF for b in chunk))

    with pytest.warns(RuntimeWarning, match="last good"):
        snap = reg.load()
    assert snap.version == 1 and snap.fallback_from == v2
    with pytest.raises(RegistryError) as e:
        reg.load(v2)  # explicit request: no silent substitution
    assert e.value.reason == "corrupt-snapshot"

    # The engine keeps serving through the fallback — and does NOT
    # thrash reloads (the served version differs from the active
    # pointer by design while the corruption stands).
    eng = PredictionEngine(reg)
    with pytest.warns(RuntimeWarning):
        res = eng.forecast(["s0"], 7)
    assert res.version == 1
    assert eng.forecast(["s1"], 7).version == 1  # steady state, no warn
    # Republishing a good version clears the degradation.
    v3 = reg.publish(state, ids)
    assert eng.forecast(["s0"], 7).version == v3


def test_engine_retries_registry_after_breaker_window(tmp_path, fitted):
    """While the registry breaker is open the engine serves its held
    snapshot WITHOUT marking the missed flip as seen — once the window
    elapses, the next pump retries the (recovered) registry instead of
    staying pinned to the stale version forever."""
    import time as time_mod

    from tsspark_tpu.resilience.policy import CircuitBreaker

    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)  # v1 active
    eng = PredictionEngine(
        reg,
        registry_breaker=CircuitBreaker(failure_threshold=1,
                                        reset_timeout_s=0.05,
                                        name="registry"),
    )
    assert eng.forecast(["s0"], 7).version == 1
    # Cross-process flip: a SECOND registry handle publishes v2, so the
    # engine only sees the manifest key change (no in-process listener).
    reg2 = ParamRegistry(reg.root, CFG)
    v2 = reg2.publish(state._replace(theta=state.theta * 1.01), ids)

    # The reload attempt fails transiently -> breaker opens.
    real_load = reg.load
    reg.load = lambda *a, **k: (_ for _ in ()).throw(OSError("hiccup"))
    try:
        with pytest.raises(OSError):
            eng.forecast(["s0"], 7)
        # Breaker open: the engine degrades to the held v1 snapshot.
        assert eng.forecast(["s0"], 7).version == 1
    finally:
        reg.load = real_load
    # Window elapses; registry recovered: the engine must pick up v2.
    time_mod.sleep(0.06)
    assert eng.forecast(["s0"], 7).version == v2


def test_cache_not_pinned_by_activation_race(tmp_path, fitted):
    """ISSUE 5 satellite: an activation landing between the snapshot
    read and the cache insert used to pin a stale version-keyed entry
    (inserted AFTER the activation's invalidation swept the cache).
    The engine now re-checks the snapshot slot before inserting."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    eng = PredictionEngine(reg)
    orig = eng._dispatch

    def racing_dispatch(snap, sids, hb, num_samples, seed, n_requests):
        out = orig(snap, sids, hb, num_samples, seed, n_requests)
        # The race: v2 activates (listener invalidates the cache) while
        # this batch's dispatch is still in flight.
        reg.publish(state._replace(theta=state.theta * 1.03), ids)
        return out

    eng._dispatch = racing_dispatch
    try:
        res = eng.forecast(["s0"], 7)
    finally:
        eng._dispatch = orig
    assert res.version == 1  # the in-flight batch still serves v1...
    assert len(eng.cache) == 0  # ...but pins NOTHING under v1
    assert eng.cache.key_versions() == []
    nxt = eng.forecast(["s0"], 7)
    assert nxt.version == 2 and eng.cache.key_versions() == [2]


def test_engine_cache_invalidated_on_version_flip(tmp_path, fitted):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    cache = ForecastCache(capacity=64)
    eng = PredictionEngine(reg, cache=cache)
    r1 = eng.forecast(["s0", "s1"], 7)
    assert eng.forecast(["s0", "s1"], 7).from_cache == 2
    assert cache.hits == 2 and len(cache) == 2

    reg.publish(state._replace(theta=state.theta * 1.02), ids)
    assert len(cache) == 0  # activation listener dropped v1 entries
    r2 = eng.forecast(["s0", "s1"], 7)
    assert r2.version == 2 and r2.from_cache == 0
    assert not np.array_equal(r2.values["yhat"], r1.values["yhat"])
    # Rollback flips back; old values return (recomputed, version-keyed).
    reg.rollback()
    r3 = eng.forecast(["s0", "s1"], 7)
    assert r3.version == 1
    np.testing.assert_array_equal(r3.values["yhat"], r1.values["yhat"])


# ---------------------------------------------------------------------------
# streaming integration: cadence column + shared read path
# ---------------------------------------------------------------------------


def _series_df(n, sid="s0", seed=0, step=1.0):
    rng = np.random.default_rng(seed)
    t = np.arange(n, dtype=float) * step
    y = (10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7)
         + rng.normal(0, 0.1, n))
    return pd.DataFrame({"series_id": sid, "ds": t, "y": y})


def test_median_steps_vectorized():
    grid = np.array([0.0, 1.0, 2.0, 4.0, 8.0, 9.0])
    y = np.array([
        [1.0, 1.0, 1.0, np.nan, np.nan, np.nan],   # daily prefix
        [1.0, np.nan, np.nan, 1.0, 1.0, np.nan],   # gaps of 4
        [np.nan, 1.0, np.nan, np.nan, np.nan, np.nan],  # 1 obs -> default
    ])
    assert median_steps(grid, y).tolist() == [1.0, 4.0, 1.0]


def test_store_records_cadence_and_forecast_continues_it(tmp_path):
    weekly = _series_df(40, "w", seed=1, step=7.0)
    daily = _series_df(120, "d", seed=2, step=1.0)
    sf = StreamingForecaster(CFG, SOLVER, backend="tpu")
    sf.process(pd.concat([weekly, daily]))
    np.testing.assert_allclose(sf.store.lookup_step(["w", "d"]), [7.0, 1.0])
    fc = sf.forecast(["w", "d"], horizon=3, num_samples=0)
    ds = fc.ds.to_numpy().reshape(2, 3)
    np.testing.assert_allclose(np.diff(ds[0]), 7.0)  # weekly continues
    np.testing.assert_allclose(np.diff(ds[1]), 1.0)
    # The cadence column survives the checkpoint round trip.
    path = str(tmp_path / "store")
    sf.store.save(path)
    loaded = ParamStore.load(path, CFG)
    np.testing.assert_allclose(loaded.lookup_step(["w", "d"]), [7.0, 1.0])


def test_driver_routes_forecast_through_engine(tmp_path):
    sf = StreamingForecaster(CFG, SOLVER, backend="tpu")
    sf.process(pd.concat([_series_df(120, "a", 1), _series_df(120, "b", 2)]))
    direct = sf.forecast(["a", "b"], horizon=9, num_samples=0)

    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    assert sf.publish(reg) == 1
    eng = PredictionEngine(reg)
    sf.attach_engine(eng)
    routed = sf.forecast(["a", "b"], horizon=9, num_samples=0)
    # One read path: the engine-routed frame is the direct frame, bit
    # for bit (same grid, same values, same layout).
    pd.testing.assert_frame_equal(routed, direct)
    assert eng.stats.completed == 1
    # Unknown series keep the driver's KeyError contract on both paths.
    with pytest.raises(KeyError):
        sf.forecast(["nope"], horizon=3)
    # The engine's source of truth is the PUBLISHED snapshot: a series
    # refit after publish() is served from the registry version, and a
    # fresh read-only driver over the same registry can serve series
    # its own (empty) store has never seen.
    ro = StreamingForecaster(CFG, SOLVER, backend="tpu", engine=eng)
    pd.testing.assert_frame_equal(
        ro.forecast(["a", "b"], horizon=9, num_samples=0), direct
    )
    sf.attach_engine(None)
    pd.testing.assert_frame_equal(
        sf.forecast(["a", "b"], horizon=9, num_samples=0), direct
    )


def test_orchestrate_publish_fit_state(tmp_path, fitted):
    from tsspark_tpu import orchestrate

    import jax

    backend, state, ids = fitted
    out = str(tmp_path / "chunks")
    os.makedirs(out)
    s = lambda lo, hi: jax.tree.map(lambda a: np.asarray(a)[lo:hi], state)
    orchestrate.save_chunk_atomic(out, 0, 4, s(0, 4))
    orchestrate.save_chunk_atomic(out, 4, 6, s(4, 6))
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    assert orchestrate.publish_fit_state(reg, out, ids) == 1
    snap = reg.load()
    # The mmap snapshot exposes ids as an array view, not a tuple.
    assert list(snap.series_ids) == list(ids)
    np.testing.assert_allclose(
        np.asarray(snap.state.theta), np.asarray(state.theta), rtol=1e-6
    )


# ---------------------------------------------------------------------------
# loadgen
# ---------------------------------------------------------------------------


def test_loadgen_emits_report(tmp_path, capsys):
    from tsspark_tpu.serve.__main__ import main

    report = str(tmp_path / "SERVE_test.json")
    rc = main([
        "--loadgen", "200", "--series", "12", "--seed", "1",
        "--dir", str(tmp_path), "--report", report,
    ])
    assert rc == 0
    with open(report) as fh:
        r = json.load(fh)
    assert r["n_requests"] == 200
    lat = r["engine"]["latency_ms"]
    assert all(lat[q] is not None for q in ("p50", "p95", "p99"))
    assert lat["p50"] <= lat["p95"] <= lat["p99"]
    occ = r["engine"]["batch_occupancy"]
    assert occ["mean_fill"] is not None and 0 < occ["mean_fill"] <= 1
    assert 0 <= r["cache"]["hit_rate"] <= 1
    assert r["engine"]["completed"] + r["engine"]["shed"] \
        + r["engine"]["failed"] + r["engine"]["rejected"] == 200
    assert r["dispatch"]["n_dispatches"] == r["engine"]["dispatches"]
    assert "loadgen" in capsys.readouterr().out
