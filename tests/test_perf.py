"""tsspark_tpu.perf: recorder telemetry, the online chunk autotuner, the
FitState annotation, bench-extras summarization, and the __main__
printer — plus the orchestrate wiring (autotune.json persisted, times
rows carrying telemetry)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tsspark_tpu.perf import (  # noqa: E402
    ChunkAutotuner,
    CompileWatch,
    PerfRecorder,
    PerfReport,
    SegmentRecord,
    attach_perf,
    get_perf,
    load_learned_chunk,
    summarize_times,
)


# -- recorder ---------------------------------------------------------------

class _FakeWatch:
    def __init__(self):
        self.n = 0

    def size(self):
        return self.n


def test_recorder_segments_and_compile_miss():
    w = _FakeWatch()
    rec = PerfRecorder(watch=w)
    with rec.dispatch(128, live=100, kind="chunk"):
        w.n += 1  # a compile happened inside this dispatch
    with rec.dispatch(64):
        pass
    rep = rec.report()
    assert rep.widths == (128, 64)
    assert [s.compile_miss for s in rep.segments] == [True, False]
    assert rep.compile_misses == 1
    assert rep.segments[0].live == 100 and rep.segments[1].live == 64
    assert rep.total_s == rep.compile_s + rep.execute_s
    d = rep.to_dict(n_series=100)
    assert d["n_dispatches"] == 2 and "series_per_s" in d


def test_compile_watch_detects_jit_cache_growth():
    import jax

    @jax.jit
    def f(x):
        return x + 1

    watch = CompileWatch((f,))
    before = watch.size()
    f(np.float32(1.0))
    assert watch.size() >= before  # grew (or cache API absent -> 0)


def test_attach_perf_composes_with_resilience_report():
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState
    from tsspark_tpu.resilience.report import (
        ResilienceReport, attach_report, get_report,
    )

    z = np.zeros(2)
    meta = ScalingMeta(*([z] * 7))
    state = FitState(theta=np.zeros((2, 3)), meta=meta, loss=z,
                     grad_norm=z, converged=z.astype(bool),
                     n_iters=z.astype(np.int32))
    rep = PerfReport(segments=(
        SegmentRecord(0, "fit", 2, 2, 0.5, False),
    ))
    both = attach_perf(attach_report(state, ResilienceReport()), rep)
    # Both annotations ride the same derived instance; neither drops.
    assert get_perf(both) is rep
    assert get_report(both) is not None
    assert isinstance(both, FitState)
    assert get_perf(state) is None


def test_backend_attaches_cumulative_report():
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    rng = np.random.default_rng(0)
    ds = np.arange(96, dtype=np.float64)
    y = (0.1 * ds + rng.normal(0, 0.1, (40, 96))).astype(np.float32)
    rec = PerfRecorder()
    bk = TpuBackend(cfg, SolverConfig(max_iters=20), perf=rec, rescue=False)
    state = bk.fit(ds, y)
    rep = get_perf(state)
    assert rep is not None and len(rep.segments) >= 1
    assert rep.total_s > 0
    assert all(s.width >= 40 for s in rep.segments)


# -- autotuner --------------------------------------------------------------

def test_autotuner_starts_small_and_explores_up():
    tu = ChunkAutotuner(cap=1024, floor=128)
    assert tu.next_size() == 128
    # Compile-tainted sample: no decision, no best.
    tu.record(128, 128, 10.0, compile_miss=True)
    assert tu.next_size() == 128
    # Warm sample -> explore upward.
    tu.record(128, 128, 1.0)
    assert tu.next_size() == 256
    tu.record(256, 256, 10.0, compile_miss=True)
    tu.record(256, 256, 1.0)   # 256 series/s > 128 -> keep climbing
    assert tu.next_size() == 512


def test_autotuner_backs_off_when_bigger_is_slower():
    tu = ChunkAutotuner(cap=512, floor=128)
    tu.record(128, 128, 1.0)      # 128/s
    assert tu.next_size() == 256  # explore
    tu.record(256, 256, 4.0)      # 64/s — worse
    assert tu.next_size() == 128  # back to the measured optimum
    assert tu.best_size == 128
    # Stays put: both neighbors known, neither better.
    tu.record(128, 128, 1.0)
    assert tu.next_size() == 128


def test_autotuner_respects_cap_and_floor():
    tu = ChunkAutotuner(cap=256, floor=64)
    for _ in range(6):
        tu.record(tu.next_size(), tu.next_size(), 0.01)
    assert tu.next_size() <= 256
    tu2 = ChunkAutotuner(cap=32, floor=128)  # floor clamped to cap
    assert tu2.next_size() == 32


def test_autotuner_persists_and_warm_starts(tmp_path):
    path = str(tmp_path / "autotune.json")
    tu = ChunkAutotuner(cap=1024, floor=128, state_path=path)
    tu.record(128, 128, 1.0)
    tu.record(256, 256, 0.5)
    assert os.path.exists(path)
    # External consumers read the MEASURED-BEST width; the resumed
    # tuner continues from the exploration cursor (which may be an
    # unexplored rung — here 512, mid-climb).
    assert load_learned_chunk(path) == tu.best_size == 256
    warm = ChunkAutotuner.load(path, cap=1024, floor=128)
    assert warm.next_size() == tu.next_size()
    assert warm.throughput(128) == pytest.approx(128.0)
    # Corrupt state is pure cache: ignored, fresh tuner.
    with open(path, "w") as fh:
        fh.write("{not json")
    assert load_learned_chunk(path) is None
    fresh = ChunkAutotuner.load(path, cap=1024, floor=128)
    assert fresh.next_size() == 128


# -- summarization + __main__ ----------------------------------------------

_TIMES = [
    {"lo": 0, "hi": 128, "fit_s": 2.0, "width": 128, "live": 128,
     "series_per_s": 64.0, "compile_miss": True, "t": 2.1},
    {"lo": 128, "hi": 256, "fit_s": 0.5, "width": 128, "live": 128,
     "series_per_s": 256.0, "compile_miss": False, "t": 2.7},
    {"phase2_s": 1.0, "stragglers": 10},
]


def test_summarize_times():
    out = summarize_times(_TIMES, autotune={"chunk": 256})
    assert out["n_chunks"] == 2
    assert out["first_flush_s"] == 2.1
    assert out["compile_misses"] == 1
    assert out["chunk_sizes"] == [128]
    assert out["series_per_s_by_size"]["128"] == pytest.approx(160.0)
    assert out["autotune"]["chunk"] == 256
    assert len(out["segments"]) == 2


def test_perf_main_over_bench_json_and_dir(tmp_path, capsys):
    from tsspark_tpu.perf.__main__ import main as perf_main

    bench = {"metric": "m", "value": 1.0,
             "extra": {"perf": summarize_times(_TIMES)}}
    p = tmp_path / "BENCH_x.json"
    p.write_text(json.dumps(bench))
    assert perf_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "chunks fitted:     2" in out
    assert "first chunk flush: 2.1 s" in out

    d = tmp_path / "out"
    d.mkdir()
    with open(d / "times.jsonl", "w") as fh:
        for row in _TIMES:
            fh.write(json.dumps(row) + "\n")
    (d / "autotune.json").write_text(json.dumps({"chunk": 128}))
    assert perf_main([str(d)]) == 0
    assert "autotuned chunk:   128" in capsys.readouterr().out


# -- orchestrate wiring -----------------------------------------------------

@pytest.mark.slow
def test_fit_resilient_autotune_end_to_end(tmp_path):
    from tsspark_tpu import orchestrate
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.data import datasets

    batch = datasets.m5_like(n_series=300, n_days=128)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=5,
    )
    scratch = str(tmp_path / "scratch")
    state = orchestrate.fit_resilient(
        cfg, SolverConfig(max_iters=60),
        batch.ds, np.nan_to_num(batch.y).astype(np.float32),
        mask=batch.mask.astype(np.float32),
        chunk=256, phase1_iters=8, autotune=True,
        scratch_dir=scratch, keep_scratch=True, budget_s=600,
    )
    assert np.asarray(state.theta).shape[0] == 300
    out = os.path.join(scratch, "out")
    # The learned state persisted next to the chunk files.
    at = json.load(open(os.path.join(out, "autotune.json")))
    assert 128 <= at["chunk"] <= 256
    # times.jsonl rows carry the telemetry schema bench.py summarizes.
    rows = [json.loads(line) for line in open(os.path.join(out,
                                                           "times.jsonl"))]
    chunk_rows = [r for r in rows if "fit_s" in r]
    assert chunk_rows, rows
    for r in chunk_rows:
        assert {"width", "live", "series_per_s", "compile_miss",
                "t"} <= set(r)
    # The first chunk is tuner-floor-sized: small first flush.
    assert chunk_rows[0]["width"] == 128
    summary = summarize_times(rows, at)
    assert summary["n_chunks"] == len(chunk_rows)
    # The streaming driver warm-starts its backend at the learned width.
    from tsspark_tpu.streaming.driver import StreamingForecaster

    fc = StreamingForecaster(
        cfg, SolverConfig(max_iters=20),
        autotune_state=os.path.join(out, "autotune.json"),
    )
    assert fc.backend.chunk_size == at["chunk"]


# -- probe budget / CPU degradation -----------------------------------------

def test_probe_budget_degrades_to_cpu_and_survives_resume(tmp_path,
                                                          monkeypatch):
    """A wedged accelerator (injected probe failures) must stop burning
    budget after ``probe_budget_s`` and complete on CPU-pinned workers —
    including on a RESUMED scratch dir that already holds chunks from a
    previous run (the budget clock keys on progress THIS run, not on the
    directory ever having held a chunk)."""
    from tsspark_tpu import orchestrate
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.data import datasets
    from tsspark_tpu.resilience import faults

    batch = datasets.m5_like(n_series=96, n_days=96)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    data_dir, out_dir = str(tmp_path / "data"), str(tmp_path / "out")
    os.makedirs(out_dir)
    orchestrate.spill_data(data_dir, batch.ds,
                           np.nan_to_num(batch.y).astype(np.float32),
                           mask=batch.mask.astype(np.float32))
    orchestrate.save_run_config(out_dir, cfg, SolverConfig(max_iters=40))
    # Every probe this process makes fails (flag mode = tunnel wedged).
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults")).fail(
        "device_probe", attempts=1000, mode="flag"
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())

    import time

    def run(state):
        return orchestrate.run_resilient(
            data_dir=data_dir, out_dir=out_dir, series=96, chunk=64,
            min_chunk=32, phase1_iters=6,
            probe_accelerator=True,      # force the probe loop on
            probe_budget_s=0.0,          # degrade on the first failure
            deadline=time.time() + 300, state=state,
        )

    state = run({})
    assert state.get("degraded_cpu") is True
    assert state["complete"] is True
    n1 = len(orchestrate.completed_ranges(out_dir))
    assert n1 > 0
    # Resume with banked chunks: remove the phase-2 marker so work
    # remains, and the second run must degrade again (not probe forever)
    # even though the scratch already holds chunks.
    os.remove(os.path.join(out_dir, "phase2_done"))
    ranges = orchestrate.completed_ranges(out_dir)
    os.remove(orchestrate._chunk_path(out_dir, *ranges[-1]))
    state2 = run({})
    assert state2.get("degraded_cpu") is True
    assert state2["complete"] is True
