"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable locally, so sharding tests run against
``--xla_force_host_platform_device_count=8`` on CPU, exactly as the driver's
multi-chip dry-run does.  This must happen before the first ``import jax``
resolves a backend, hence it lives at conftest import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The machine image's sitecustomize registers the "axon" TPU plugin and sets
# jax_platforms="axon,cpu" at interpreter start — BEFORE this conftest runs —
# so the env var alone is not enough: the first array op would try to create
# the axon TPU client, which blocks whenever another process holds the single
# TPU tunnel.  Overriding at the config level keeps the whole test run on the
# virtual 8-device CPU mesh and off the TPU.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite's ~140 tests re-jit the same fit and
# predict programs every run; caching them across runs cuts several minutes
# of pure XLA:CPU compile time per invocation.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache_tests"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
