"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable locally, so sharding tests run against
``--xla_force_host_platform_device_count=8`` on CPU, exactly as the driver's
multi-chip dry-run does.  This must happen before the first ``import jax``
resolves a backend, hence it lives at conftest import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# The regression sentinel's entrypoint post-steps (bench.py, serve
# --loadgen, chaos CLI) would otherwise append RUNHISTORY.jsonl rows in
# the pytest cwd and gate test runs on machine-local baselines; the
# sentinel itself is tested explicitly in tests/test_history.py.
os.environ["TSSPARK_SENTINEL"] = "0"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The machine image's sitecustomize registers the "axon" TPU plugin and sets
# jax_platforms="axon,cpu" at interpreter start — BEFORE this conftest runs —
# so the env var alone is not enough: the first array op would try to create
# the axon TPU client, which blocks whenever another process holds the single
# TPU tunnel.  Overriding at the config level keeps the whole test run on the
# virtual 8-device CPU mesh and off the TPU.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the suite's ~140 tests re-jit the same fit and
# predict programs every run; caching them across runs cuts several minutes
# of pure XLA:CPU compile time per invocation.  The directory is keyed by a
# HOST-CPU fingerprint: XLA:CPU AOT artifacts bake in the compile machine's
# feature set, and loading one on a different VM generation segfaults the
# process mid-suite (observed: entries from a prior session's host killed
# test_prophet_features on this one with "machine features ... could lead
# to execution errors such as SIGILL" warnings followed by a real SIGSEGV).


def _host_cpu_tag() -> str:
    from tsspark_tpu.utils.platform import host_cpu_tag

    return host_cpu_tag()


jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 f".jax_cache_tests_{_host_cpu_tag()}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# ---------------------------------------------------------------------------
# mmap exhaustion guard.  Measured on this VM: one full-suite process
# accumulates >64k memory mappings (every live XLA:CPU executable holds
# dozens) and SEGFAULTS mid-suite when it crosses vm.max_map_count
# (default 65530) — the crash surfaces as a random compile failing, at a
# position that drifts with every code change.  Two layers of defense:
# raise the sysctl when the image allows it, and drop compiled-program
# references between test modules so dead executables actually unmap (the
# persistent compile cache above makes any cross-module recompiles cheap).

try:
    with open("/proc/sys/vm/max_map_count") as _fh:
        _cur = int(_fh.read())
    if _cur < 1 << 20:
        with open("/proc/sys/vm/max_map_count", "w") as _fh:
            _fh.write(str(1 << 20))
except OSError:
    pass  # unprivileged: the per-module cache clear below still bounds maps

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    jax.clear_caches()
