"""Real-format dataset loaders: tiny files in the actual M5/M4 CSV layouts."""

import numpy as np
import pandas as pd
import pytest

from tsspark_tpu.data import loaders


@pytest.fixture
def m5_files(tmp_path):
    # 3 series x 5 days in the Kaggle M5 layout.
    sales = pd.DataFrame({
        "id": ["A_1_CA_1_validation", "A_2_CA_1_validation",
               "B_1_TX_1_validation"],
        "item_id": ["A_1", "A_2", "B_1"],
        "dept_id": ["A", "A", "B"],
        "cat_id": ["A", "A", "B"],
        "store_id": ["CA_1", "CA_1", "TX_1"],
        "state_id": ["CA", "CA", "TX"],
        **{f"d_{k}": v for k, v in zip(
            range(1, 6),
            [[3, 0, 2], [1, 1, 0], [0, 2, 5], [4, 0, 1], [2, 3, 0]],
        )},
    })
    cal = pd.DataFrame({
        "date": pd.date_range("2016-01-01", periods=6, freq="D").astype(str),
        "wm_yr_wk": [11601, 11601, 11601, 11601, 11602, 11602],
        "d": [f"d_{k}" for k in range(1, 7)],
        "event_name_1": [None, "NewYear", None, None, None, None],
        "event_name_2": [None] * 6,
        "snap_CA": [1, 0, 1, 0, 0, 0],
        "snap_TX": [0, 0, 0, 1, 1, 0],
    })
    prices = pd.DataFrame({
        "store_id": ["CA_1", "CA_1", "CA_1", "TX_1"],
        "item_id": ["A_1", "A_1", "A_2", "B_1"],
        "wm_yr_wk": [11601, 11602, 11601, 11601],
        "sell_price": [2.5, 2.75, 1.0, 9.99],
    })
    paths = {}
    for name, frame in (("sales", sales), ("cal", cal), ("prices", prices)):
        p = tmp_path / f"{name}.csv"
        frame.to_csv(p, index=False)
        paths[name] = str(p)
    return paths


def test_load_m5(m5_files):
    batch = loaders.load_m5(
        m5_files["sales"], m5_files["cal"], m5_files["prices"]
    )
    assert batch.y.shape == (3, 5)  # calendar tail row d_6 dropped
    np.testing.assert_allclose(batch.y[0], [3, 1, 0, 4, 2])
    # 2016-01-01 is epoch day 16801.
    assert batch.ds[0] == 16801.0
    assert batch.regressor_names == ("holiday", "price", "promo")
    holiday, price, promo = (batch.regressors[..., i] for i in range(3))
    np.testing.assert_allclose(holiday[0], [0, 1, 0, 0, 0])
    # Price switches at the wm_yr_wk boundary (day 5 -> week 11602).
    np.testing.assert_allclose(price[0], [2.5, 2.5, 2.5, 2.5, 2.75])
    np.testing.assert_allclose(price[1], [1.0] * 5)  # single listed week
    # Promo = the series' own state's SNAP flags.
    np.testing.assert_allclose(promo[0], [1, 0, 1, 0, 0])
    np.testing.assert_allclose(promo[2], [0, 0, 0, 1, 1])


def test_load_m5_without_prices(m5_files):
    batch = loaders.load_m5(m5_files["sales"], m5_files["cal"])
    np.testing.assert_allclose(batch.regressors[..., 1], 0.0)


def test_load_m4(tmp_path):
    df = pd.DataFrame({
        "V1": ["H1", "H2"],
        "V2": [10.0, 5.0],
        "V3": [11.0, 6.0],
        "V4": [12.0, np.nan],  # H2 is shorter
    })
    p = tmp_path / "Hourly-train.csv"
    df.to_csv(p, index=False)
    batch = loaders.load_m4(str(p), freq_hours=1.0)
    assert batch.y.shape == (2, 3)
    # Right-aligned: H2's two points end at the common forecast origin.
    np.testing.assert_allclose(batch.y[0], [10, 11, 12])
    np.testing.assert_allclose(batch.y[1][1:], [5, 6])
    assert np.isnan(batch.y[1][0]) and batch.mask[1][0] == 0.0
    np.testing.assert_allclose(np.diff(batch.ds), 1 / 24.0)


def test_loaded_batch_imports_into_plane(m5_files, tmp_path):
    """Real CSV data rides the same manifest as the generators:
    load -> import_batch -> open_batch round-trips bitwise (after the
    plane's float32/nan_to_num disk conversion) and content-hash keys
    the cache (a changed file set never aliases a stale import)."""
    from tsspark_tpu.data import plane

    batch = loaders.load_m5(
        m5_files["sales"], m5_files["cal"], m5_files["prices"]
    )
    root = str(tmp_path / "plane")
    d = plane.import_batch(batch, "m5_csv", root=root, shard_rows=2)
    assert plane.is_complete(d)
    got = plane.open_batch(d)
    ref = plane.batch_columns(batch)
    np.testing.assert_array_equal(np.asarray(got.y), ref["y"])
    np.testing.assert_array_equal(np.asarray(got.mask), ref["mask"])
    np.testing.assert_array_equal(np.asarray(got.regressors), ref["reg"])
    np.testing.assert_array_equal(got.series_ids, batch.series_ids)
    assert got.regressor_names == batch.regressor_names
    # Idempotent re-import hits the same dataset dir...
    assert plane.import_batch(batch, "m5_csv", root=root,
                              shard_rows=2) == d
    # ...while changed content keys a different one.
    changed = batch._replace(y=batch.y + 1.0)
    assert plane.import_batch(changed, "m5_csv", root=root,
                              shard_rows=2) != d


def test_load_m4_feeds_fit(tmp_path):
    """The loaded layout must flow straight into the batched fit."""
    import jax.numpy as jnp

    from tsspark_tpu import ProphetConfig, SolverConfig, get_backend
    from tsspark_tpu.config import SeasonalityConfig

    rng = np.random.default_rng(0)
    n = 72
    rows = {"V1": ["H1", "H2"]}
    for k in range(n):
        y = 10 + 2 * np.sin(2 * np.pi * k / 24)
        rows[f"V{k + 2}"] = [y + rng.normal(0, 0.1),
                             y * 0.5 + rng.normal(0, 0.1)]
    p = tmp_path / "Hourly-train.csv"
    pd.DataFrame(rows).to_csv(p, index=False)
    batch = loaders.load_m4(str(p))
    bk = get_backend(
        "tpu",
        ProphetConfig(seasonalities=(SeasonalityConfig("daily", 1.0, 3),),
                      n_changepoints=3),
        SolverConfig(max_iters=80),
    )
    state = bk.fit(jnp.asarray(batch.ds),
                   jnp.asarray(np.nan_to_num(batch.y)),
                   mask=jnp.asarray(batch.mask))
    assert bool(np.isfinite(np.asarray(state.loss)).all())
