"""Trend math: the cumsum+gather formulation must match the naive A-matrix
Prophet formulation exactly."""

import jax.numpy as jnp
import numpy as np

from tsspark_tpu.models.prophet import trend


def _naive_piecewise_linear(t, k, m, delta, s):
    """Textbook Prophet: g(t) = (k + A@delta) t + (m + A@(-s*delta))."""
    a = (t[:, None] >= s[None, :]).astype(np.float64)  # (T, n_cp)
    slope = k + a @ delta
    offset = m + a @ (-s * delta)
    return slope * t + offset


def test_piecewise_linear_matches_naive():
    rng = np.random.default_rng(0)
    b, t_len, n_cp = 4, 50, 7
    t = np.sort(rng.uniform(0, 1, (b, t_len)), axis=-1)
    s = np.sort(rng.uniform(0.05, 0.8, (b, n_cp)), axis=-1)
    k = rng.normal(size=b)
    m = rng.normal(size=b)
    delta = rng.normal(size=(b, n_cp))

    got = np.asarray(
        trend.piecewise_linear(
            jnp.asarray(t), jnp.asarray(k), jnp.asarray(m), jnp.asarray(delta),
            jnp.asarray(s),
        )
    )
    for i in range(b):
        want = _naive_piecewise_linear(t[i], k[i], m[i], delta[i], s[i])
        np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-5)


def test_piecewise_linear_continuous_at_changepoints():
    # Evaluate just before/after each changepoint: jump must vanish.
    s = jnp.asarray([[0.25, 0.5, 0.75]])
    k = jnp.asarray([1.3])
    m = jnp.asarray([0.2])
    delta = jnp.asarray([[2.0, -3.0, 1.0]])
    eps = 1e-5
    t = jnp.asarray([[0.25 - eps, 0.25 + eps, 0.5 - eps, 0.5 + eps]])
    g = trend.piecewise_linear(t, k, m, delta, s)
    assert abs(float(g[0, 1] - g[0, 0])) < 1e-3
    assert abs(float(g[0, 3] - g[0, 2])) < 1e-3


def test_piecewise_linear_no_changepoints():
    t = jnp.linspace(0, 1, 10)[None, :]
    g = trend.piecewise_linear(
        t, jnp.asarray([2.0]), jnp.asarray([1.0]),
        jnp.zeros((1, 0)), jnp.zeros((1, 0)),
    )
    np.testing.assert_allclose(np.asarray(g[0]), 2.0 * np.asarray(t[0]) + 1.0, rtol=1e-6)


def test_logistic_continuity_and_cap():
    rng = np.random.default_rng(1)
    b, n_cp = 3, 5
    s = np.sort(rng.uniform(0.1, 0.8, (b, n_cp)), axis=-1)
    k = rng.uniform(1.0, 3.0, b)
    m = rng.uniform(0.2, 0.5, b)
    delta = rng.normal(scale=0.5, size=(b, n_cp))
    t = np.linspace(0, 1, 400)[None, :].repeat(b, axis=0)
    cap = np.full_like(t, 2.5)

    g = np.asarray(
        trend.logistic(
            jnp.asarray(t), jnp.asarray(cap), jnp.asarray(k), jnp.asarray(m),
            jnp.asarray(delta), jnp.asarray(s),
        )
    )
    # Bounded by (0, cap).
    assert (g > 0).all() and (g < 2.5).all()
    # Continuity: max step between adjacent dense samples stays small.
    assert np.abs(np.diff(g, axis=-1)).max() < 0.05


def test_logistic_no_changepoints_closed_form():
    t = jnp.linspace(0, 1, 20)[None, :]
    cap = jnp.full((1, 20), 3.0)
    k, m = jnp.asarray([2.0]), jnp.asarray([0.4])
    g = trend.logistic(t, cap, k, m, jnp.zeros((1, 0)), jnp.zeros((1, 0)))
    want = 3.0 / (1.0 + np.exp(-2.0 * (np.asarray(t[0]) - 0.4)))
    np.testing.assert_allclose(np.asarray(g[0]), want, rtol=1e-5)


def test_flat():
    t = jnp.linspace(0, 1, 11)[None, :]
    g = trend.flat(t, jnp.asarray([0.7]))
    np.testing.assert_allclose(np.asarray(g), 0.7, rtol=1e-6)


def test_uniform_changepoints():
    s = trend.uniform_changepoints(
        jnp.zeros((2,)), jnp.ones((2,)), n_changepoints=4, changepoint_range=0.8
    )
    np.testing.assert_allclose(np.asarray(s[0]), [0.2, 0.4, 0.6, 0.8], rtol=1e-6)
    assert s.shape == (2, 4)
