"""Columnar data plane (tsspark_tpu.data.plane + data.ingest): block
parity, manifest lifecycle, torn-shard rejection, scenario packs, and
the ingestion/fit overlap (docs/DATA.md)."""

import argparse
import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from tsspark_tpu.data import datasets, ingest, plane


def _spec(**kw):
    base = dict(generator="m5", n_series=300, n_timesteps=48, seed=3,
                shard_rows=128)
    base.update(kw)
    return plane.DatasetSpec(**base)


# ---------------------------------------------------------------------------
# block-seeded generation
# ---------------------------------------------------------------------------


def test_row_slices_are_bitwise_stable():
    """m5_rows(lo, hi) == m5_rows(0, N)[lo:hi] — the property parallel
    shard ingestion rests on (rows independent of who generates them)."""
    full = datasets.m5_rows(0, 2100, n_days=40, seed=2)
    part = datasets.m5_rows(1000, 1500, n_days=40, seed=2)
    np.testing.assert_array_equal(full.y[1000:1500], part.y)
    np.testing.assert_array_equal(full.mask[1000:1500], part.mask)
    np.testing.assert_array_equal(
        full.regressors[1000:1500], part.regressors
    )
    # ...and independent of the total series count (datasets extend).
    longer = datasets.m5_rows(1000, 1500, n_days=40, seed=2)
    np.testing.assert_array_equal(part.y, longer.y)


def test_scenario_packs():
    t_len = 120
    base = datasets.m5_rows(0, 256, n_days=t_len, seed=1)
    irr = datasets.m5_rows(0, 256, n_days=t_len, seed=1,
                           scenario="irregular")
    cold = datasets.m5_rows(0, 256, n_days=t_len, seed=1,
                            scenario="cold_start")
    wins = datasets.m5_rows(0, 256, n_days=t_len, seed=1,
                            scenario="missing_windows")
    hier = datasets.m5_rows(0, 256, n_days=t_len, seed=1,
                            scenario="hier")
    for b in (base, irr, cold, wins, hier):
        assert b.y.shape == (256, t_len)
        assert ((b.mask > 0) == np.isfinite(b.y)).all()
    # Irregular cadence drops interior observations.
    assert irr.mask.sum() < 0.95 * base.mask.sum()
    # Cold start: a real late-launch population exists.
    obs_len = cold.mask.sum(axis=1)
    assert (obs_len < 0.35 * t_len).mean() > 0.3
    # Missing windows: some series have an interior gap (0 run inside
    # the observed region).
    inner_gap = 0
    for row in wins.mask[:64]:
        on = np.flatnonzero(row > 0)
        if on.size and (row[on[0]:on[-1] + 1] == 0).any():
            inner_gap += 1
    assert inner_gap > 0
    # Hierarchy ids follow store->dept->item; the series distribution
    # actually differs from the flat pack.
    assert hier.series_ids[0] == "S0_D0_I00000"
    assert hier.series_ids[10] == "S0_D1_I00000"
    assert not np.array_equal(hier.y, base.y)


# ---------------------------------------------------------------------------
# cache lifecycle
# ---------------------------------------------------------------------------


def test_cache_bitwise_parity_and_warm_hit(tmp_path):
    spec = _spec()
    root = str(tmp_path)
    d = plane.ensure(spec, root=root, processes=2)
    assert plane.is_complete(d)

    batch = plane.open_batch(d)
    ref = plane.batch_columns(plane.generate_rows(spec, 0, spec.n_series))
    # The closed-form calendar create_columns writes must equal the grid
    # the row generators emit (create probes a tiny grid for fields).
    np.testing.assert_array_equal(
        np.asarray(batch.ds), plane.generate_rows(spec, 0, 1).ds
    )
    np.testing.assert_array_equal(np.asarray(batch.y), ref["y"])
    np.testing.assert_array_equal(np.asarray(batch.mask), ref["mask"])
    np.testing.assert_array_equal(np.asarray(batch.regressors),
                                  ref["reg"])
    np.testing.assert_array_equal(
        batch.series_ids, datasets.dataset_ids("m5", 0, spec.n_series)
    )

    # Warm hit: ensure() returns without touching the columns.
    mtime = os.path.getmtime(os.path.join(d, "y.npy"))
    assert plane.ensure(spec, root=root) == d
    assert os.path.getmtime(os.path.join(d, "y.npy")) == mtime

    # A complete plane dir IS a valid orchestrate --data dir.
    from tsspark_tpu.orchestrate import _load_data

    ds, cols = _load_data(d)
    np.testing.assert_array_equal(ds, np.asarray(batch.ds))
    np.testing.assert_array_equal(np.asarray(cols["y"]), ref["y"])


def test_manifest_key_rotates_with_identity(tmp_path):
    root = str(tmp_path)
    a = plane.dataset_dir(_spec(seed=3), root)
    assert a != plane.dataset_dir(_spec(seed=4), root)
    assert a != plane.dataset_dir(_spec(n_series=301), root)
    # The datagen fingerprint (whole data package) is baked into the key.
    assert plane.dataset_fingerprint() in os.path.basename(a)


def test_torn_shard_rejected_and_repaired(tmp_path):
    spec = _spec()
    root = str(tmp_path)
    d = plane.ensure(spec, root=root)
    ref = np.array(np.asarray(plane.open_batch(d).y))

    mm = np.lib.format.open_memmap(os.path.join(d, "y.npy"), mode="r+")
    mm[5, :7] = 1e9  # silent corruption inside shard 0
    mm.flush()
    del mm
    assert not plane.verify_shard(d, 0, spec.shard_rows)
    assert plane.verify_shard(d, spec.shard_rows, 2 * spec.shard_rows)

    rewritten = plane.repair(spec, root=root)
    assert rewritten == [(0, spec.shard_rows)]
    assert plane.verify_shard(d, 0, spec.shard_rows)
    assert plane.is_complete(d)
    np.testing.assert_array_equal(np.asarray(plane.open_batch(d).y), ref)


def test_ready_coverage_and_self_heal(tmp_path):
    spec = _spec()
    root = str(tmp_path)
    d = plane.create_columns(spec, root)
    # Nothing landed: a plane dir gates everything; plain dirs gate
    # nothing.
    assert plane.ready_coverage(d, spec.n_series) == []
    assert plane.ready_coverage(str(tmp_path)) is None
    assert plane.ingest_pending(d, spec.n_series)

    plane.write_shard(spec, 0, root=root)
    assert plane.ready_coverage(d, spec.n_series) == [(0, 128)]
    assert plane.covers(plane.ready_coverage(d), 0, 128)
    assert not plane.covers(plane.ready_coverage(d), 64, 192)

    # A consumer can self-heal a dead ingest driver: deterministic
    # generation means it lands the identical bytes.
    assert plane.produce_next_missing(d)
    assert plane.ready_coverage(d, spec.n_series) == [(0, 256)]
    assert plane.produce_next_missing(d)
    assert not plane.ingest_pending(d, spec.n_series)
    # Coverage complete but not finalized: a resumed ingest closes out.
    assert not plane.is_complete(d)
    ingest.run_ingest(spec, root=root)
    assert plane.is_complete(d)


def test_ingest_resumes_missing_shards_only(tmp_path):
    spec = _spec()
    root = str(tmp_path)
    plane.create_columns(spec, root)
    plane.write_shard(spec, 1, root=root)
    d = plane.dataset_dir(spec, root)
    mtime = os.path.getmtime(plane._sentinel_path(d, 128, 256))
    ingest.run_ingest(spec, root=root)
    assert plane.is_complete(d)
    # The already-landed shard was not rewritten.
    assert os.path.getmtime(plane._sentinel_path(d, 128, 256)) == mtime
    rep = ingest.read_ingest_report(d)
    assert rep["shards_produced"] == 2 and rep["shards_total"] == 3


# ---------------------------------------------------------------------------
# overlap: fitting starts before ingestion finishes
# ---------------------------------------------------------------------------


def test_fit_overlaps_ingestion(tmp_path):
    """Cold-run overlap (ISSUE 9 acceptance): the fit worker's first
    chunk lands BEFORE the last shard does — claims are gated on landed
    coverage, and the producer here deliberately holds the tail shards
    until fitting has visibly started."""
    from tsspark_tpu import orchestrate
    from tsspark_tpu.config import (
        ProphetConfig, RegressorConfig, SeasonalityConfig, SolverConfig,
    )
    from tsspark_tpu.obs import context as obs

    spec = plane.DatasetSpec(
        generator="m5", n_series=512, n_timesteps=40, seed=5,
        shard_rows=128,
    )
    root = str(tmp_path / "plane")
    data_dir = plane.create_columns(spec, root)
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    orchestrate.save_run_config(
        out_dir,
        ProphetConfig(
            seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
            regressors=(
                RegressorConfig("holiday", standardize=False),
                RegressorConfig("price"),
                RegressorConfig("promo", standardize=False),
            ),
            n_changepoints=3,
        ),
        SolverConfig(max_iters=30),
    )

    def produce():
        plane.write_shard(spec, 0, root=root)
        deadline = time.time() + 120
        while not glob.glob(os.path.join(out_dir, "chunk_*.npz")):
            if time.time() > deadline:  # pragma: no cover - timing guard
                return
            time.sleep(0.1)
        for i in range(1, 4):
            plane.write_shard(spec, i, root=root)
        plane.finalize(spec, root)

    prev = obs.start_run(os.path.join(out_dir, "spans.jsonl"))
    producer = threading.Thread(target=produce)
    producer.start()
    try:
        args = argparse.Namespace(
            data=data_dir, out=out_dir, lo=0, hi=spec.n_series,
            chunk=128, segment=0, series=spec.n_series, phase1_iters=0,
            no_phase1_tune=True, autotune=False, max_ahead=6,
        )
        assert orchestrate.fit_worker(args) == 0
    finally:
        producer.join(timeout=120)
        obs.end_run(prev)

    done = orchestrate.completed_ranges(out_dir)
    assert not orchestrate.missing_ranges(done, spec.n_series)
    first_chunk = min(
        os.path.getmtime(f)
        for f in glob.glob(os.path.join(out_dir, "chunk_*.npz"))
    )
    last_shard = max(
        os.path.getmtime(f)
        for f in glob.glob(os.path.join(data_dir, "shardok_*.json"))
    )
    assert first_chunk < last_shard, \
        "fit should start before ingestion finishes"
    # The spans tell the same story on one trace: datagen.shard and
    # chunk.fit interleave.
    recs = obs.read_records(os.path.join(out_dir, "spans.jsonl"))
    names = {r.get("name") for r in recs}
    assert "datagen.shard" in names and "chunk.fit" in names
    fit_starts = [r["t0"] for r in recs if r.get("name") == "chunk.fit"]
    shard_ends = [r["t0"] + r["dur_s"] for r in recs
                  if r.get("name") == "datagen.shard"
                  and r.get("dur_s") is not None]
    assert min(fit_starts) < max(shard_ends)


# ---------------------------------------------------------------------------
# shared consumers
# ---------------------------------------------------------------------------


def test_replay_source_reads_the_plane(tmp_path):
    from tsspark_tpu.streaming.source import PlaneReplaySource

    spec = plane.DatasetSpec("demo_weekly", 8, 40, seed=7, shard_rows=8)
    src = PlaneReplaySource(spec=spec, root=str(tmp_path), window=16,
                            max_series=5)
    frames = []
    while True:
        f = src.poll()
        if f is None:
            break
        frames.append(f)
        src.commit()
    assert len(frames) == 3  # 40 timesteps / window 16
    assert list(frames[0].columns) == ["series_id", "ds", "y"]
    assert len(frames[0]) == 5 * 16  # demo series are fully observed
    batch = plane.open_batch(plane.dataset_dir(spec, str(tmp_path)))
    np.testing.assert_allclose(
        frames[0]["y"].to_numpy()[:16], np.asarray(batch.y[0, :16]),
    )


def test_datagen_metrics_and_spans(tmp_path):
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.metrics import DEFAULT as METRICS

    spans = str(tmp_path / "spans.jsonl")
    prev = obs.start_run(spans)
    try:
        METRICS.reset()
        spec = _spec(n_series=200, seed=9)
        plane.ensure(spec, root=str(tmp_path))  # miss -> ingest
        plane.ensure(spec, root=str(tmp_path))  # hit
        snap = METRICS.snapshot()
        counters = {m["name"]: m["value"] for m in snap["counters"]}
        assert counters["tsspark_datagen_cache_misses_total"] == 1
        assert counters["tsspark_datagen_cache_hits_total"] == 1
        assert counters["tsspark_datagen_shards_total"] == 2
        assert counters["tsspark_datagen_rows_total"] == 200
    finally:
        obs.end_run(prev)
    recs = obs.read_records(spans)
    assert sum(1 for r in recs if r.get("name") == "datagen.shard") == 2
    assert any(r.get("name") == "datagen.ingest" for r in recs)


def test_calendar_matches_every_generator():
    for gen in plane.GENERATORS:
        got = plane.generate_rows(
            plane.DatasetSpec(gen, 4, 24, seed=1), 0, 1
        ).ds
        np.testing.assert_array_equal(
            datasets.dataset_calendar(gen, 24), got
        )


def test_concurrent_create_never_clobbers_landed_rows(tmp_path):
    """The review race: producer B preallocating columns after producer
    A already landed shard 0 must not zero A's rows (os.link publish is
    create-if-absent, not rename-clobber)."""
    spec = _spec()
    root = str(tmp_path)
    d = plane.create_columns(spec, root)
    plane.write_shard(spec, 0, root=root)
    ref = np.array(np.load(os.path.join(d, "y.npy"), mmap_mode="r")[:128])
    # Producer B re-runs creation (spec.json removed to simulate its
    # pre-check happening before A's publish).
    os.remove(os.path.join(d, "spec.json"))
    plane.create_columns(spec, root)
    np.testing.assert_array_equal(
        np.load(os.path.join(d, "y.npy"), mmap_mode="r")[:128], ref
    )
    assert plane.verify_shard(d, 0, 128)


def test_unknown_generator_rejected():
    with pytest.raises(ValueError, match="unknown generator"):
        plane.DatasetSpec("nope", 8, 8)


def test_package_export_surface():
    """Satellite: the data package exports the public API so call sites
    stop deep-importing modules."""
    from tsspark_tpu import data

    for name in ("SeriesBatch", "m5_like", "m5_rows", "demo_weekly_rows",
                 "DatasetSpec", "ensure", "open_batch", "import_batch",
                 "load_m5", "load_m4", "dataset_fingerprint"):
        assert callable(getattr(data, name)) or name == "SeriesBatch"
