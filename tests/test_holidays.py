"""Holiday calendars, window expansion, and end-to-end effect recovery."""

import datetime as dt

import numpy as np
import pandas as pd
import pytest

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.frame import Forecaster
from tsspark_tpu.models import holidays as hol


def _days(*dates):
    return hol.to_days(dates)


def test_computus_easter_known_years():
    assert hol._easter(2024) == dt.date(2024, 3, 31)
    assert hol._easter(2025) == dt.date(2025, 4, 20)
    assert hol._easter(2016) == dt.date(2016, 3, 27)


def test_us_calendar_known_dates():
    hs = {h.name: h for h in hol.country_holidays("US", [2023])}
    assert _days("2023-11-23")[0] in hs["Thanksgiving"].dates  # 4th Thu Nov
    assert _days("2023-05-29")[0] in hs["Memorial Day"].dates  # last Mon May
    assert _days("2023-01-16")[0] in hs["Martin Luther King Jr. Day"].dates
    assert "Juneteenth" in hs  # post-2021 only
    assert "Juneteenth" not in {
        h.name for h in hol.country_holidays("US", [2019])
    }


def test_ca_victoria_day():
    hs = {h.name: h for h in hol.country_holidays("CA", [2023, 2021])}
    assert _days("2023-05-22")[0] in hs["Victoria Day"].dates
    assert _days("2021-05-24")[0] in hs["Victoria Day"].dates  # May 24 is a Monday


def test_unknown_country_raises():
    with pytest.raises(ValueError, match="unknown country"):
        hol.country_holidays("ZZ", [2023])


def test_window_expansion_columns_and_features():
    h = hol.Holiday.from_dates(
        "xmas", ["2023-12-25"], lower_window=-1, upper_window=1
    )
    cols = hol.holiday_column_configs([h])
    assert [c.name for c in cols] == ["xmas_-1", "xmas", "xmas_+1"]
    assert all(not c.standardize for c in cols)

    grid = _days("2023-12-23", "2023-12-24", "2023-12-25", "2023-12-26")
    x = hol.holiday_features(grid, [h])
    assert x.shape == (4, 3)
    np.testing.assert_array_equal(x[:, 0], [0, 1, 0, 0])  # eve column
    np.testing.assert_array_equal(x[:, 1], [0, 0, 1, 0])  # day column
    np.testing.assert_array_equal(x[:, 2], [0, 0, 0, 1])  # day-after column


def test_holidays_from_df_groups_and_windows():
    df = pd.DataFrame(
        {
            "holiday": ["a", "a", "b"],
            "ds": ["2023-01-01", "2024-01-01", "2023-06-01"],
            "lower_window": [0, 0, -1],
            "upper_window": [1, 1, 0],
        }
    )
    specs = hol.holidays_from_df(df)
    assert [h.name for h in specs] == ["a", "b"]
    assert len(specs[0].dates) == 2
    assert specs[1].offsets == (-1, 0)


def test_add_holidays_extends_config():
    cfg = ProphetConfig(seasonalities=())
    h = hol.Holiday.from_dates("d", ["2023-07-04"], prior_scale=3.0)
    cfg2 = hol.add_holidays(cfg, [h])
    assert cfg2.num_regressors == 1
    assert cfg2.regressors[0].prior_scale == 3.0
    assert cfg.num_regressors == 0  # original untouched


def test_forecaster_recovers_holiday_effect():
    """A known additive spike on one recurring date is attributed to the
    holiday coefficient and reproduced in future forecasts of that date."""
    rng = np.random.default_rng(0)
    dates = pd.date_range("2021-01-01", periods=3 * 365, freq="D")
    effect = 5.0
    july4 = (dates.month == 7) & (dates.day == 4)
    frames = []
    for i in range(3):
        y = 10.0 + i + rng.normal(0, 0.15, len(dates)) + effect * july4
        frames.append(pd.DataFrame({"series_id": f"s{i}", "ds": dates, "y": y}))
    df = pd.concat(frames, ignore_index=True)

    h = hol.Holiday.from_dates(
        "july4", ["2021-07-04", "2022-07-04", "2023-07-04", "2024-07-04"]
    )
    fc = Forecaster(
        ProphetConfig(
            seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
            n_changepoints=5,
        ),
        SolverConfig(max_iters=120),
        backend="tpu",
        holidays=[h],
    )
    fc.fit(df)
    # Horizon crossing 2024-07-04 (predict path computes the indicator
    # itself — no future_df needed for holiday-only models).
    out = fc.predict(horizon=250)
    s0 = out[out.series_id == "s0"].set_index("ds")
    on = s0.loc[pd.Timestamp("2024-07-04"), "yhat"]
    off = s0.loc[pd.Timestamp("2024-07-10"), "yhat"]
    assert on - off == pytest.approx(effect, abs=0.75)


def test_new_country_calendars_known_dates():
    """Spot-check one movable and one fixed holiday per added country
    against published 2023 dates."""
    import datetime as dt

    def dates(country, name, year=2023):
        return [
            dt.date(1970, 1, 1) + dt.timedelta(days=int(d))
            for h in hol.country_holidays(country, [year]) if h.name == name
            for d in h.dates
        ]

    # 2023: Easter Sunday = April 9.
    assert dates("FR", "Ascension") == [dt.date(2023, 5, 18)]
    assert dates("FR", "Fete nationale") == [dt.date(2023, 7, 14)]
    assert dates("IT", "Lunedi dell'Angelo") == [dt.date(2023, 4, 10)]
    assert dates("ES", "Viernes Santo") == [dt.date(2023, 4, 7)]
    assert dates("BR", "Carnaval") == [dt.date(2023, 2, 21)]
    assert dates("BR", "Corpus Christi") == [dt.date(2023, 6, 8)]
    assert dates("JP", "Coming of Age Day") == [dt.date(2023, 1, 9)]
    assert dates("JP", "Respect for the Aged Day") == [dt.date(2023, 9, 18)]
    assert dates("IN", "Republic Day") == [dt.date(2023, 1, 26)]
    # Every registered country yields a parsable calendar for a decade.
    for c in ("US", "CA", "GB", "DE", "FR", "IT", "ES", "BR", "JP", "IN"):
        hs = hol.country_holidays(c, range(2015, 2025))
        assert len(hs) >= 4, c
