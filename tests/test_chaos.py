"""Chaos harness (tsspark_tpu/chaos, docs/RESILIENCE.md): deterministic
storm composition and the tier-1 smoke storm — a small seeded fault
storm driven through orchestrate -> registry -> streaming -> serve on
CPU with every invariant required green."""

import json
import os

from tsspark_tpu.chaos import compose, run_storm, write_scorecard


def test_storm_schedule_is_deterministic():
    """The acceptance property that makes a storm a regression gate:
    the same (seed, profile) composes the same injection schedule —
    points, windows, targets, request indices — every time."""
    a = compose(0, "smoke")
    b = compose(0, "smoke")
    assert a.schedule() == b.schedule()
    assert compose(0, "full").schedule() == compose(0, "full").schedule()
    # The env-plan rules (rule ids included) are stable too: MTTR is
    # read off claim files named by those ids.
    plan_a, cls_a = a.build_fault_plan("/tmp/unused_a")
    plan_b, cls_b = b.build_fault_plan("/tmp/unused_b")
    assert [r["id"] for r in plan_a.rules] == [r["id"] for r in
                                               plan_b.rules]
    assert cls_a == cls_b
    # Different seeds do differ somewhere (sanity that the seed is
    # actually consumed).
    assert compose(0, "full").schedule() != compose(7, "full").schedule()


def test_storm_covers_required_fault_classes():
    classes = set(compose(0, "smoke").by_class())
    assert len(classes) >= 5
    assert {"worker-kill", "torn-artifact", "serve-fault",
            "queue-overload", "registry-corrupt"} <= classes
    # The full profile adds the accelerator-probe wedge.
    assert "wedged-client" in compose(0, "full").by_class()


def test_smoke_storm_all_invariants_green(tmp_path):
    """The tier-1 chaos smoke: a small seeded storm on CPU through the
    whole pipeline.  Every invariant must hold — zero lost/duplicated
    series (bitwise vs the fault-free reference), zero torn reads,
    registry fallback served, engine/direct bitwise parity, the breaker
    cycled closed, recovery inside the budget, and the observability
    trace joined (zero orphan spans; span-derived MTTR matching the
    claim-file-mtime measurement within 1 s)."""
    ledger_path = str(tmp_path / "RUNLEDGER_smoke.json")
    report = run_storm(seed=0, profile="smoke",
                       scratch=str(tmp_path / "storm"),
                       ledger_path=ledger_path)
    assert report["ok"], report["invariants"]
    assert len(report["fault_classes"]) >= 5
    inv = report["invariants"]
    assert inv["series_exactly_once"]["ok"]
    assert inv["series_exactly_once"]["bitwise_vs_reference"]["ok"]
    assert inv["no_torn_reads"]["ok"]
    assert inv["registry_fallback"]["ok"]
    assert inv["engine_direct_parity"]["requests_checked"] > 0
    assert inv["breaker_cycled"]["breaker"]["opens"] >= 1
    assert inv["recovery_within_budget"]["ok"]
    # Faults really fired: the storm is not vacuous.
    fired = {c: f["fired"] for c, f in report["faults"].items()}
    assert fired["worker-kill"] >= 1
    assert fired["torn-artifact"] >= 1
    assert fired["serve-fault"] >= 1

    # Scorecard round trip: atomic write, parseable, schedule recorded
    # verbatim for reproduction.
    out = write_scorecard(report, str(tmp_path / "CHAOS_smoke.json"))
    with open(out) as fh:
        loaded = json.load(fh)
    assert loaded["schedule"] == [
        i for i in compose(0, "smoke").schedule()
    ]
    assert loaded["ok"] is True
    assert os.path.basename(out).startswith("CHAOS_")

    # Observability acceptance (ISSUE 7): one joined timeline under a
    # single trace id covering every subsystem, zero orphan spans, and
    # per-class MTTR readable off the spans alone — agreeing with the
    # harness's claim-file-mtime measurement within 1 s.
    tj = inv["trace_joined"]
    assert tj["ok"], tj
    assert tj["orphan_spans"] == []
    assert tj["subsystems_missing"] == []
    assert report["trace_id"] == tj["trace_id"]
    for cls, delta in tj["mttr_delta_s"].items():
        assert delta <= 1.0, f"{cls}: span/mtime MTTR differ by {delta}s"
    with open(ledger_path) as fh:
        led = json.load(fh)
    assert led["kind"] == "run-ledger"
    assert led["trace_id"] == report["trace_id"]
    assert led["orphan_spans"] == []
    assert len(led["processes"]) >= 3  # harness + fit worker attempts
    # The ledger renders end to end (the `obs report` entry point).
    from tsspark_tpu.obs.__main__ import main as obs_main

    assert obs_main(["report", ledger_path]) == 0


def test_alerts_storm_all_invariants_green(tmp_path):
    """The alert-stream fault-domain smoke (docs/ALERTS.md,
    docs/RESILIENCE.md § Alert-stream fault domain): the scorer child
    SIGKILLed mid-publish (record landed, CRC sentinel not) and again
    mid-delivery, a sink brownout opening the breaker with the
    watermark held, and a torn certified record — judged by
    alerts_exactly_once: every certified alert key in the sink exactly
    once, watermark at the scored head."""
    classes = set(compose(1, "alerts").by_class())
    assert {"alert-scorer-kill", "alert-sink-brownout",
            "torn-alert-record"} <= classes
    # Both kill points are scheduled for the scorer-kill class.
    points = {i.point for i in compose(1, "alerts").injections
              if i.cls == "alert-scorer-kill"}
    assert points == {"alert_publish", "alert_deliver"}
    report = run_storm(seed=1, profile="alerts",
                       scratch=str(tmp_path / "storm"))
    assert report["ok"], report["invariants"]
    inv = report["invariants"]
    for key in ("alerts_scorer_kill", "alerts_sink_brownout",
                "alerts_torn_record", "alerts_exactly_once"):
        assert inv[key]["ok"], (key, inv[key])
    eo = inv["alerts_exactly_once"]
    assert eo["duplicates"] == 0 and eo["missing"] == 0
    assert eo["watermark"] == eo["scored"] > 0
    assert inv["alerts_scorer_kill"]["deliver"]["deduped"] >= 1
    assert inv["alerts_sink_brownout"]["breaker_opened"]
    assert inv["alerts_sink_brownout"]["watermark_held"]
    assert inv["alerts_torn_record"]["crc_rejected_tear"]
    assert inv["alerts_torn_record"]["rescore_bitwise"]
    assert inv["recovery_within_budget"]["ok"]
    assert inv["trace_joined"]["ok"], inv["trace_joined"]
    assert report["workload"]["alerts_storm"] is True


def test_storage_storm_all_invariants_green(tmp_path):
    """The storage-fault-domain smoke (docs/RESILIENCE.md § Storage
    fault domain): the five storage chaos classes — ENOSPC mid-publish,
    EIO on the manifest flip, a short-write-torn column, a lost fsync
    followed by a kill, and a disk-pressure brownout — each with its
    invariant (no torn read served, bitwise equality with the
    fault-free run, the degradation ladder recovers)."""
    classes = set(compose(3, "storage").by_class())
    assert {"enospc-mid-publish", "eio-on-flip",
            "short-write-torn-column", "lost-fsync-then-kill",
            "disk-pressure-brownout"} <= classes
    report = run_storm(seed=3, profile="storage",
                       scratch=str(tmp_path / "storm"))
    assert report["ok"], report["invariants"]
    inv = report["invariants"]
    for key in ("storage_enospc_publish", "storage_eio_flip",
                "storage_short_write", "storage_lost_fsync",
                "storage_brownout"):
        assert inv[key]["ok"], (key, inv[key])
    assert inv["recovery_within_budget"]["ok"]
    assert inv["trace_joined"]["ok"], inv["trace_joined"]
    # The io.* counters prove the faults went through the durable-I/O
    # layer, not around it.
    io = report["io"]
    assert io["tsspark_io_fault_enospc_total"] >= 1
    assert io["tsspark_io_fault_eio_total"] >= 1
    assert io["tsspark_io_fault_shortwrite_total"] >= 1
    assert io["tsspark_io_disk_errors_total"] >= 2
    assert io["tsspark_io_writes_total"] > 0
    st = report["stages"]["storage"]
    assert st["brownout"]["ladder"][0] == "stale_serve"
    assert report["workload"]["storage_storm"] is True
