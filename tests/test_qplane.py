"""Quantile forecast plane (tsspark_tpu/uncertainty/qplane.py,
docs/UNCERTAINTY.md): bitwise parity of plane-served vs computed
interval quantiles (MAP and ADVI modes), the full kill-point sweep on
the spec-first/CRC-sentinel publish protocol, delta copy-forward, and
the engine's coverage rules + compute fallback."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.resilience import FaultPlan, faults
from tsspark_tpu.serve import (
    ForecastCache,
    ParamRegistry,
    PredictionEngine,
)
from tsspark_tpu.uncertainty import advi, qplane

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)
HOT = qplane.DEFAULT_HOT_HORIZONS
QS = qplane.DEFAULT_QUANTILES
#: Columns a default publish lands: 3 buckets x 3 quantiles.
N_COLS = 9


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(7)
    t = np.arange(150.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (6, 150)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    return backend, state, [f"s{i}" for i in range(6)]


def _registry(tmp_path, fitted):
    backend, state, ids = fitted
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    reg.publish(state, ids, step=np.ones(len(ids)))
    return reg


def _quantile_reads(engine, ids, horizons=HOT):
    return {h: engine.quantiles(list(ids), int(h)) for h in horizons}


def _assert_bitwise(got, want):
    for h in want:
        np.testing.assert_array_equal(got[h].ds, want[h].ds)
        assert set(got[h].values) == set(want[h].values)
        for k in want[h].values:
            np.testing.assert_array_equal(
                got[h].values[k], want[h].values[k], err_msg=f"h={h} {k}"
            )


def test_qplane_columns_bitwise_equal_compute_rows(tmp_path, fitted):
    """THE interval pin, full grid: every (series, bucket, quantile)
    cell of a published quantile plane is bitwise ``compute_rows`` over
    the same snapshot rows — the publisher's batching is invisible in
    the bytes because every cell is keyed on ``(seed, global_row)``
    alone."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    pub = qplane.maybe_publish(reg, 1, backend)
    assert pub["status"] == "published" and pub["mode"] == "map"
    assert pub["buckets"] == [8, 16, 32]
    view = qplane.attach(reg.version_dir(1))
    snap = reg.load()
    for hb in view.buckets:
        ref = qplane.compute_rows(snap, CFG, backend,
                                  np.arange(len(ids)), hb)
        for pm in ref:
            np.testing.assert_array_equal(
                np.asarray(view.columns[hb][pm]), ref[pm],
                err_msg=f"hb={hb} q{pm:03d}",
            )
    # quantile_rows serves arbitrary row subsets with the recomputed ds
    # grid, bitwise the gathered full-plane rows.
    idx = np.asarray([3, 0, 5])
    rows = qplane.quantile_rows(view, snap, idx, 8)
    for i, row in enumerate(idx):
        for pm in view.columns[8]:
            np.testing.assert_array_equal(
                rows[i][f"q{pm:03d}"],
                np.asarray(view.columns[8][pm])[row],
            )


def test_engine_quantiles_plane_vs_compute_bitwise(tmp_path, fitted):
    """Plane-served engine intervals equal the forced-compute engine's
    across the full hot grid, and actually come from the plane."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert qplane.maybe_publish(reg, 1, backend)["status"] == "published"

    eng_plane = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp = PredictionEngine(reg, cache=ForecastCache(0))
    eng_disp._qplanes = {1: None}  # force the compute fallback
    got = _quantile_reads(eng_plane, ids)
    want = _quantile_reads(eng_disp, ids)
    _assert_bitwise(got, want)
    assert eng_plane.stats.qplane_hits == len(ids) * len(HOT)
    assert eng_plane.stats.dispatches == 0
    assert eng_disp.stats.qplane_hits == 0
    assert eng_disp.stats.qplane_misses == len(ids) * len(HOT)


def test_engine_quantile_coverage_rules(tmp_path, fitted):
    """A quantile the plane does not carry routes the whole request to
    compute; the plane covers published (bucket, quantile) pairs only."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert qplane.maybe_publish(reg, 1, backend)
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    long_tail = eng.quantiles(ids[:2], 7, quantiles=(0.25, 0.75))
    assert set(long_tail.values) == {"q250", "q750"}
    assert long_tail.values["q250"].shape == (2, 7)
    assert eng.stats.qplane_hits == 0
    assert eng.stats.qplane_misses == 2
    hot = eng.quantiles(ids[:2], 7)
    assert set(hot.values) == {"q100", "q500", "q900"}
    assert eng.stats.qplane_hits == 2
    # Bands must be ordered at every cell.
    assert np.all(hot.values["q100"] <= hot.values["q500"])
    assert np.all(hot.values["q500"] <= hot.values["q900"])


def test_full_kill_point_sweep_every_tear_rejected(tmp_path, fitted,
                                                   monkeypatch):
    """The acceptance sweep: a publish killed between ANY two of the 9
    column writes (spec always landed, sentinel never) leaves a plane
    the reader refuses — no kill point is survivable-but-corrupt."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    snap = reg.load()
    for k in range(N_COLS):
        vdir = str(tmp_path / f"tear{k}")
        os.makedirs(vdir)
        plan = FaultPlan(state_dir=str(tmp_path / "faults" / str(k)))
        plan.fail("qplane_publish", after=k, mode="raise",
                  tag=f"tear-{k}")
        monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
        with pytest.raises(faults.FaultInjected):
            qplane.write_qplane(vdir, snap, CFG, backend)
        monkeypatch.delenv(faults.ENV_VAR)
        assert not qplane.has_qplane(vdir), f"kill point {k}"
        assert not qplane.verify_qplane(vdir), f"kill point {k}"
        with pytest.raises(qplane.QuantilePlaneError) as e:
            qplane.attach(vdir)
        assert e.value.reason == "corrupt", f"kill point {k}"


def test_torn_publish_fallback_then_bitwise_retry(tmp_path, fitted,
                                                  monkeypatch):
    """The torn-quantile-plane contract in process: mid-tear the engine
    serves intervals through compute — bitwise the pre-tear answers —
    and the retried publish lands a plane whose served rows are bitwise
    the compute path's."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    vdir = reg.version_dir(1)
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    ref = _quantile_reads(eng, ids)  # no plane yet: compute reference

    plan = FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("qplane_publish", after=3, mode="raise", tag="torn-qplane")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.raises(faults.FaultInjected):
        qplane.write_qplane(vdir, reg.load(), CFG, backend)
    monkeypatch.delenv(faults.ENV_VAR)

    assert not qplane.has_qplane(vdir)
    assert not qplane.verify_qplane(vdir)

    eng_mid = PredictionEngine(reg, cache=ForecastCache(0))
    mid = _quantile_reads(eng_mid, ids)
    assert eng_mid.stats.qplane_hits == 0
    _assert_bitwise(mid, ref)

    retry = qplane.maybe_publish(reg, 1, backend, force=True)
    assert retry["status"] == "published"
    assert qplane.verify_qplane(vdir)
    assert eng_mid.attach_qplane(1)
    after = _quantile_reads(eng_mid, ids)
    assert eng_mid.stats.qplane_hits > 0
    _assert_bitwise(after, ref)


def test_delta_copy_forward_quantile_columns(tmp_path, fitted):
    """Delta flip: unchanged rows' quantile cells are bitwise the BASE
    plane's (copy-forward, no re-sample), changed rows are bitwise a
    fresh ``compute_rows`` over the new snapshot."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert qplane.maybe_publish(reg, 1, backend)["status"] == "published"
    base_view = qplane.attach(reg.version_dir(1))

    snap1 = reg.load()
    changed = np.asarray([1, 3])
    sub, step_sub = snap1.take(changed)
    refit = sub._replace(theta=np.asarray(sub.theta) * 1.02)
    v2 = reg.publish_delta(refit, changed.tolist(), step_sub=step_sub)
    pub = qplane.maybe_publish(reg, v2, backend)
    assert pub["status"] == "published-delta"

    view2 = qplane.attach(reg.version_dir(v2))
    snap2 = reg.load()
    assert snap2.version == v2
    unchanged = np.asarray([0, 2, 4, 5])
    for hb in view2.buckets:
        ref = qplane.compute_rows(snap2, CFG, backend, changed, hb)
        for pm in ref:
            np.testing.assert_array_equal(
                np.asarray(view2.columns[hb][pm])[unchanged],
                np.asarray(base_view.columns[hb][pm])[unchanged],
                err_msg=f"copy-forward hb={hb} q{pm:03d}",
            )
            np.testing.assert_array_equal(
                np.asarray(view2.columns[hb][pm])[changed], ref[pm],
                err_msg=f"changed hb={hb} q{pm:03d}",
            )
        # The perturbed rows really moved.
        assert not np.array_equal(
            np.asarray(view2.columns[hb][500])[changed],
            np.asarray(base_view.columns[hb][500])[changed],
        )


def test_advi_mode_selected_and_bitwise(tmp_path, fitted):
    """With a posterior artifact in the version dir the publish flips
    to ADVI-mode sampling, and plane cells stay bitwise the ADVI
    compute path's."""
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    mu = np.nan_to_num(np.asarray(state.theta, np.float32))
    post = advi.AdviPosterior(
        mu=mu, rho=np.full_like(mu, -2.0),
        elbo=np.zeros(mu.shape[0], np.float32),
    )
    advi.save_posterior(reg.version_dir(1), post, seed=0, num_steps=0)
    pub = qplane.maybe_publish(reg, 1, backend)
    assert pub["status"] == "published" and pub["mode"] == "advi"
    view = qplane.attach(reg.version_dir(1))
    assert view.mode == "advi"
    snap = reg.load()
    for hb in view.buckets:
        ref = qplane.compute_rows(snap, CFG, backend,
                                  np.arange(len(ids)), hb,
                                  posterior=post)
        for pm in ref:
            np.testing.assert_array_equal(
                np.asarray(view.columns[hb][pm]), ref[pm],
                err_msg=f"advi hb={hb} q{pm:03d}",
            )
    # Engine plane reads come from the mmap, bitwise the view's cells.
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    res = eng.quantiles(ids[:3], 8)
    assert eng.stats.qplane_hits == 3
    np.testing.assert_array_equal(
        res.values["q500"], np.asarray(view.columns[8][500])[:3]
    )


def test_attach_rejects_corrupt_column(tmp_path, fitted):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert qplane.maybe_publish(reg, 1, backend)
    vdir = reg.version_dir(1)
    path = os.path.join(vdir, "qcol_h8_q500.npy")
    mm = np.lib.format.open_memmap(path, mode="r+")
    mm[2:3].view(np.uint32)[...] ^= np.uint32(0x5A5A5A5A)
    mm.flush()
    del mm
    assert not qplane.verify_qplane(vdir)
    with pytest.raises(qplane.QuantilePlaneError) as e:
        qplane.attach(vdir)
    assert e.value.reason == "corrupt"
    # The engine memoizes the rejection and serves compute — same
    # numbers a plane-less registry would produce.
    eng = PredictionEngine(reg, cache=ForecastCache(0))
    res = eng.quantiles(ids[:3], 7)
    assert res.version == 1 and eng.stats.qplane_hits == 0
    eng_ref = PredictionEngine(reg, cache=ForecastCache(0))
    eng_ref._qplanes = {1: None}
    ref = eng_ref.quantiles(ids[:3], 7)
    for k in ref.values:
        np.testing.assert_array_equal(res.values[k], ref.values[k])


def test_maybe_publish_idempotent_and_kill_switch(tmp_path, fitted,
                                                  monkeypatch):
    backend, state, ids = fitted
    reg = _registry(tmp_path, fitted)
    assert qplane.maybe_publish(reg, 1, backend)["status"] == "published"
    again = qplane.maybe_publish(reg, 1, backend)
    assert again == {"status": "present", "version": 1}
    monkeypatch.setenv("TSSPARK_QPLANE", "0")
    reg2 = ParamRegistry(str(tmp_path / "reg2"), CFG)
    reg2.publish(state, ids, step=np.ones(len(ids)))
    assert qplane.maybe_publish(reg2, 1, backend) is None
    assert not qplane.has_qplane(reg2.version_dir(1))
