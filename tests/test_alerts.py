"""Exactly-once anomaly alert stream (tsspark_tpu/alerts,
docs/ALERTS.md): deterministic scoring (interval vs z-score breach
parity), the record/CRC-sentinel publish protocol under a full
kill-point sweep, replay idempotence across randomized kill points and
sink brownouts, the data-liveness kind's durable queue, and the alert
key dedup that turns at-least-once delivery into exactly-once."""

import collections
import json
import os
import random

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu.alerts.score import (
    alert_key,
    canonical_bytes,
    record_crc,
    score_delta,
    score_rows,
)
from tsspark_tpu.alerts.sink import (
    FlakySink,
    JsonlSink,
    SinkError,
    build_sink,
)
from tsspark_tpu.alerts.stream import AlertStream
from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.data import plane
from tsspark_tpu.resilience import FaultPlan, faults
from tsspark_tpu.serve import ForecastCache, ParamRegistry, PredictionEngine

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)
N = 6
#: Fires on any visible residual / silences data-liveness — the tests
#: control WHICH alerts exist, not the model's accuracy.
Z_FIRE, K_QUIET = 0.05, 1e9


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(7)
    t = np.arange(120.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (N, 120)))
    backend = get_backend("tpu", CFG, SOLVER)
    return backend.fit(t, jnp.asarray(y))


@pytest.fixture()
def world(tmp_path, fitted):
    """(dset_dir, registry, engine): a plane dataset whose series ids
    are what the registry serves — the scorer's whole universe."""
    spec = plane.DatasetSpec(generator="demo_weekly", n_series=N,
                             n_timesteps=64, seed=2)
    dset = plane.ensure(spec, root=str(tmp_path / "plane"))
    pids = plane.series_ids(spec)
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    reg.publish(fitted, pids, step=np.ones(N))
    engine = PredictionEngine(reg, cache=ForecastCache(0))
    return dset, reg, engine


def _stream(world, log_dir, sink=None, **kw):
    dset, _reg, engine = world
    kw.setdefault("z", Z_FIRE)
    kw.setdefault("overdue_k", K_QUIET)
    return AlertStream(str(log_dir), dset, engine,
                       sink if sink is not None
                       else JsonlSink(str(log_dir) + "_sink.jsonl"),
                       horizon=1, **kw)


def _land(dset, rows=(0, 2, 4)):
    plane.land_synthetic_delta(
        dset, 0.5, rows=np.asarray(rows, np.int64))


def _rec_path(log_dir, seq):
    return os.path.join(str(log_dir), f"alertrec_{seq:06d}.json")


def _ok_path(log_dir, seq):
    return os.path.join(str(log_dir), f"alertok_{seq:06d}.json")


def test_score_rows_interval_vs_zscore_breach_parity():
    """The mode-parity pin: where both representations describe the
    SAME band (interval [lo, hi] == yhat +/- z*sigma), they make the
    same breach decisions — mode degradation changes evidence fields,
    never which alerts exist."""
    y = np.array([0.0, 10.0, 5.0, 6.5, 3.5])
    yhat = np.full(5, 5.0)
    sigma = np.full(5, 0.5)
    z = 3.0  # band [3.5, 6.5]
    fired_i, sev_i, mode_i = score_rows(y, lo=yhat - z * sigma,
                                        hi=yhat + z * sigma)
    fired_z, sev_z, mode_z = score_rows(y, yhat=yhat, sigma=sigma, z=z)
    assert mode_i == "interval" and mode_z == "zscore"
    np.testing.assert_array_equal(fired_i, fired_z)
    np.testing.assert_array_equal(fired_i,
                                  [True, True, False, False, False])
    # Severity is positive exactly on fired rows in both modes.
    assert ((sev_i > 0) == fired_i).all()
    assert ((sev_z > 0) == fired_z).all()


def test_score_delta_is_deterministic_bitwise(world):
    """Re-scoring the same delta yields byte-identical canonical
    records — the property that makes a successor's re-score converge
    on the dead scorer's bytes."""
    dset, _reg, engine = world
    _land(dset)
    a = score_delta(engine, dset, 1, z=Z_FIRE)
    b = score_delta(engine, dset, 1, z=Z_FIRE)
    assert canonical_bytes(a) == canonical_bytes(b)
    assert record_crc(a) == record_crc(b)
    assert a["n_fired"] >= 1
    assert a["mode"] in ("interval", "zscore")
    for al in a["alerts"]:
        assert al["key"] == alert_key(al["kind"], al["series"],
                                      a["seq"])


def test_publish_kill_point_sweep_rescore_bitwise(world, tmp_path,
                                                  monkeypatch):
    """The protocol sweep: a scorer killed at ANY of the three
    alert_publish injection sites (before the record, between record
    and sentinel, after the sentinel) leaves a log a successor heals
    to the SAME certified bytes a fault-free scorer writes."""
    dset, _reg, engine = world
    _land(dset)
    ref = _stream(world, tmp_path / "ref")
    ref.poll_once()
    with open(_rec_path(tmp_path / "ref", 1), "rb") as fh:
        want = fh.read()

    for k in range(3):
        log_dir = tmp_path / f"kill{k}"
        s = _stream(world, log_dir)
        plan = FaultPlan(state_dir=str(tmp_path / "faults" / str(k)))
        plan.fail("alert_publish", after=k, mode="raise",
                  tag=f"kill-{k}")
        monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
        with pytest.raises(faults.FaultInjected):
            s.poll_once()
        monkeypatch.delenv(faults.ENV_VAR)
        # k=2 dies after certification; earlier sites leave the seq
        # uncertified.  Either way the successor converges bitwise.
        heal = _stream(world, log_dir)
        res = heal.poll_once()
        assert heal.record_ok(1) is not None, f"kill point {k}"
        with open(_rec_path(log_dir, 1), "rb") as fh:
            assert fh.read() == want, f"kill point {k}"
        assert not res["stalled"]
        assert heal.delivered_seq() == heal.scored_seq() == 1


def test_torn_record_and_torn_sentinel_rejected_then_healed(
        world, tmp_path):
    """CRC discipline: a flipped byte in a certified record (or a torn
    sentinel) makes record_ok refuse it; the re-score restores the
    original bytes and redelivery dedups to zero duplicates."""
    dset, _reg, engine = world
    _land(dset)
    log_dir = tmp_path / "log"
    s = _stream(world, log_dir)
    s.poll_once()
    with open(_rec_path(log_dir, 1), "rb") as fh:
        orig = fh.read()

    with open(_rec_path(log_dir, 1), "r+b") as fh:
        fh.seek(7)
        fh.write(bytes([orig[7] ^ 0xFF]))
    s2 = _stream(world, log_dir)
    assert s2.record_ok(1) is None
    res = s2.poll_once()
    with open(_rec_path(log_dir, 1), "rb") as fh:
        assert fh.read() == orig
    assert res["deduped"] == 0 and res["delivered"] == 0

    os.truncate(_ok_path(log_dir, 1), 5)
    s3 = _stream(world, log_dir)
    assert s3.record_ok(1) is None
    s3.poll_once()
    assert s3.record_ok(1) is not None
    with open(_rec_path(log_dir, 1), "rb") as fh:
        assert fh.read() == orig
    # The sink holds each key exactly once through all of it.
    keys = [a["key"] for a in JsonlSink(
        str(log_dir) + "_sink.jsonl").alerts()]
    assert len(keys) == len(set(keys))


def test_replay_idempotent_across_randomized_kill_points(world,
                                                         tmp_path,
                                                         monkeypatch):
    """The property behind the chaos storm, in process: a randomized
    schedule of publish kills, delivery kills, sink brownouts, torn
    sentinels, and torn records — whatever the interleaving, once the
    faults clear the sink holds every certified alert key EXACTLY once
    and the watermark sits at the scored head."""
    dset, _reg, engine = world
    log_dir = tmp_path / "log"
    sink_path = str(log_dir) + "_sink.jsonl"

    for seed in range(4):
        rng = random.Random(f"alert-replay:{seed}")
        _land(dset, rows=rng.sample(range(N), 3))
        disruption = rng.choice(
            ["pub_kill", "del_kill", "brownout", "tear_ok",
             "tear_rec"])
        flaky = FlakySink(JsonlSink(sink_path), fail_n=0)
        s = _stream(world, log_dir, sink=flaky)
        if disruption == "pub_kill":
            plan = FaultPlan(
                state_dir=str(tmp_path / "f" / f"p{seed}"))
            plan.fail("alert_publish", after=rng.randrange(3),
                      mode="raise", tag="p")
            monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
            with pytest.raises(faults.FaultInjected):
                s.poll_once()
            monkeypatch.delenv(faults.ENV_VAR)
        elif disruption == "del_kill":
            plan = FaultPlan(
                state_dir=str(tmp_path / "f" / f"d{seed}"))
            plan.fail("alert_deliver", after=rng.randrange(2),
                      mode="raise", tag="d")
            monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
            res = s.poll_once()  # delivery stalls, never raises out
            assert res["stalled"]
            monkeypatch.delenv(faults.ENV_VAR)
        elif disruption == "brownout":
            flaky.fail_n = flaky.attempts + rng.randrange(3, 9)
            res = s.poll_once()
            assert res["stalled"]
            flaky.fail_n = 0
        elif disruption == "tear_ok":
            s.poll_once()
            seq = s.scored_seq()
            os.truncate(_ok_path(log_dir, seq), rng.randrange(5))
        elif disruption == "tear_rec":
            s.poll_once()
            seq = s.scored_seq()
            with open(_rec_path(log_dir, seq), "r+b") as fh:
                fh.seek(3)
                fh.write(b"\x00")

        # Recovery: a fresh stream over the same log, healthy sink.
        import time as _time

        heal = _stream(world, log_dir,
                       sink=JsonlSink(sink_path),
                       breaker=None)
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline:
            res = heal.poll_once()
            if (not res["stalled"]
                    and heal.delivered_seq() == heal.scored_seq()):
                break
            _time.sleep(0.2)  # breaker reset window

        assert heal.scored_seq() == plane.delta_seq(dset)
        assert heal.delivered_seq() == heal.scored_seq()
        expected = []
        for q in range(1, heal.scored_seq() + 1):
            rec = heal.record_ok(q)
            assert rec is not None, (seed, disruption, q)
            assert record_crc(rec) is not None
            expected += [a["key"] for a in rec["alerts"]]
        counts = collections.Counter(
            a["key"] for a in JsonlSink(sink_path).alerts())
        dupes = {k: n for k, n in counts.items() if n > 1}
        assert not dupes, (seed, disruption, dupes)
        assert set(expected) <= set(counts), (seed, disruption)


def test_liveness_alerts_queue_survives_brownout(world, tmp_path):
    """The data-liveness kind rides the durable loose queue: overdue
    series alert once per silence episode, a browned-out sink queues
    them durably, and the drain delivers each exactly once."""
    dset, _reg, engine = world
    _land(dset, rows=(0, 1, 2, 3, 4, 5))
    _land(dset, rows=(0, 1, 2, 3, 4, 5))
    _land(dset, rows=(0, 3))
    log_dir = tmp_path / "log"
    sink_path = str(log_dir) + "_sink.jsonl"
    flaky = FlakySink(JsonlSink(sink_path), fail_n=0)
    s = _stream(world, log_dir, sink=flaky)  # liveness quiet for now
    s.poll_once()
    # Rows 1/2/4/5 saw two arrivals then silence: with a tiny overdue
    # multiple they are overdue "now"; the browned-out sink queues.
    s.overdue_k = 0.1
    flaky.fail_n = 500
    now = __import__("time").time() + 3600.0
    live = s.liveness_alerts(now)
    assert {a["kind"] for a in live} == {"data-liveness"}
    assert {a["series"] for a in live} >= {"1", "2"} or len(live) >= 2
    res = s.deliver_loose(live)
    assert res["stalled"] and res["queued"] >= len(live)
    q_path = os.path.join(str(log_dir), "alerts_queue.jsonl")
    assert os.path.exists(q_path)

    flaky.fail_n = 0
    import time as _time

    _time.sleep(1.1)  # default breaker reset window
    drained = s.deliver_loose([])
    assert not drained["stalled"] and drained["queued"] == 0
    counts = collections.Counter(
        a["key"] for a in JsonlSink(sink_path).alerts()
        if a["kind"] == "data-liveness")
    assert counts and all(n == 1 for n in counts.values())


def test_sink_specs_and_recover():
    with pytest.raises(ValueError):
        build_sink("kafka://nope")
    assert build_sink("jsonl:/tmp/x.jsonl").name == "jsonl"


def test_alert_record_protocol_registered():
    """The analysis tier models the alert log's write protocol (spec
    FIRST, record, CRC sentinel LAST as the gate) — the gate that keeps
    refactors from reordering the crash-safety dance."""
    from tsspark_tpu.analysis import protomodel

    spec = next(p for p in protomodel.PROTOCOLS
                if p.name == "alert-record")
    assert [s.name for s in spec.steps] == ["spec", "record",
                                            "sentinel"]
    gate = spec.steps[-1]
    assert gate.role == "gate"
    assert set(gate.certifies) == {"spec", "record"}


def test_arrival_model_overdue_rows():
    """The scheduler-side satellite: overdue_rows surfaces rows whose
    silence exceeds k EWMAs — the gauge feed and the liveness kind's
    trigger."""
    from tsspark_tpu.sched import ArrivalModel

    m = ArrivalModel()
    for seq, t in ((1, 100.0), (2, 110.0), (3, 120.0)):
        m.note_delta(seq, t, [0, 1])
    m.note_delta(4, 130.0, [1])
    # Row 0's EWMA gap is 10s, last seen t=120.  At t=200 it is 80s
    # silent: overdue for any k below 8.
    over = m.overdue_rows(200.0, k=3.0)
    assert 0 in over and over[0] == pytest.approx(80.0 - 30.0)
    assert m.overdue_rows(121.0, k=3.0) == {}
