"""End-to-end slice (eval config 1 analog): fit a Peyton-Manning-like daily
series, check in-sample accuracy, held-out forecast accuracy, and interval
behavior.  This is the minimum end-to-end proof of model math + solver."""

import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig, WEEKLY, YEARLY
from tsspark_tpu.data import datasets
from tsspark_tpu.eval import metrics
from tsspark_tpu.models.prophet.model import ProphetModel


@pytest.fixture(scope="module")
def peyton_fit():
    batch = datasets.peyton_manning_like(n_days=1200)
    holdout = 60
    y_train = batch.y[:, :-holdout].copy()
    model = ProphetModel(
        ProphetConfig(seasonalities=(YEARLY, WEEKLY), n_changepoints=15),
        SolverConfig(max_iters=300),
    )
    state = model.fit(batch.ds[:-holdout], jnp.asarray(y_train))
    return batch, holdout, model, state


def test_in_sample_accuracy(peyton_fit):
    batch, holdout, model, state = peyton_fit
    assert bool(state.converged.all())
    fc = model.predict(state, batch.ds[:-holdout], num_samples=0)
    y = np.asarray(batch.y[0, :-holdout])
    m = np.isfinite(y)
    s = float(metrics.smape(y[m], np.asarray(fc["yhat"][0])[m]))
    # Noise floor: sigma=0.25 on level ~8 gives sMAPE ~2.5%; the fit should
    # land close to it.
    assert s < 4.0, f"in-sample sMAPE {s}"


def test_holdout_forecast(peyton_fit):
    batch, holdout, model, state = peyton_fit
    fc = model.predict(state, batch.ds[-holdout:], seed=1)
    y = np.asarray(batch.y[0, -holdout:])
    m = np.isfinite(y)
    s = float(metrics.smape(y[m], np.asarray(fc["yhat"][0])[m]))
    assert s < 8.0, f"holdout sMAPE {s}"
    # Intervals must bracket the point forecast and cover most of the truth.
    lo, hi = np.asarray(fc["yhat_lower"][0]), np.asarray(fc["yhat_upper"][0])
    assert (lo[m] <= hi[m]).all()
    cov = float(metrics.coverage(y[m], lo[m], hi[m]))
    assert cov > 0.6, f"coverage {cov}"


def test_components_decompose(peyton_fit):
    batch, holdout, model, state = peyton_fit
    comps = model.components(state, batch.ds[:-holdout])
    assert set(comps) == {"trend", "yearly", "weekly"}
    # Weekly component must actually oscillate with period 7.
    wk = np.asarray(comps["weekly"][0])
    assert wk.std() > 0.05
    np.testing.assert_allclose(wk[:-7], wk[7:], atol=1e-3)


def test_multiplicative_logistic_fit():
    batch = datasets.wiki_logistic_like(n_series=4, n_days=600)
    cfg = ProphetConfig(
        growth="logistic",
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3, mode="multiplicative"),),
        n_changepoints=8,
    )
    model = ProphetModel(cfg, SolverConfig(max_iters=300))
    state = model.fit(
        batch.ds, jnp.asarray(batch.y), cap=jnp.asarray(batch.cap)
    )
    fc = model.predict(state, batch.ds, cap=jnp.asarray(batch.cap), num_samples=0)
    s = np.asarray(metrics.smape(jnp.asarray(batch.y), fc["yhat"]))
    assert s.max() < 8.0, f"logistic sMAPE {s}"
    # Trend must respect the cap.
    assert (np.asarray(fc["trend"]) <= np.asarray(batch.cap) + 1e-3).all()


def test_warm_start_beats_cold_under_budget():
    """The streaming property: warm-starting from previous parameters reaches
    a better loss than a cold start when the iteration budget is small."""
    batch = datasets.peyton_manning_like(n_days=700, seed=5)
    cfg = ProphetConfig(seasonalities=(YEARLY, WEEKLY), n_changepoints=10)
    y = jnp.asarray(batch.y)
    full = ProphetModel(cfg, SolverConfig(max_iters=300)).fit(batch.ds, y)

    budget = ProphetModel(cfg, SolverConfig(max_iters=20))
    warm = budget.fit(batch.ds, y, init=full.theta)
    cold = budget.fit(batch.ds, y)
    # Armijo acceptance means warm can only improve on the converged loss —
    # up to a few float32 ulps of the objective: the closed-form ladder
    # (loss.fan_value_closed_form) reports accepted losses that can differ from
    # direct evaluation by ~1-2 ulps, and at |loss| ~ 2000 one ulp is
    # ~1.2e-4, so a fixed 1e-4 margin is BELOW representational noise.
    tol = 8 * np.finfo(np.float32).eps * abs(float(full.loss[0])) + 1e-4
    assert float(warm.loss[0]) <= float(full.loss[0]) + tol
    assert float(warm.loss[0]) <= float(cold.loss[0]) + tol


def test_logistic_fit_with_floor_saturates_in_band():
    """Logistic growth with a nonzero floor: the fitted curve and forecasts
    must live in [floor, cap] and track a saturating series."""
    rng = np.random.default_rng(11)
    n = 300
    t = np.arange(float(n))
    floor, cap = 200.0, 1000.0
    true = floor + (cap - floor) / (1.0 + np.exp(-0.03 * (t - 120)))
    y = (true + rng.normal(0, 10.0, n)).astype(np.float32)

    cfg = ProphetConfig(growth="logistic", seasonalities=(), n_changepoints=5)
    model = ProphetModel(cfg, SolverConfig(max_iters=200))
    state = model.fit(
        jnp.asarray(t), jnp.asarray(y[None, :]),
        cap=jnp.full((1, n), cap), floor=jnp.asarray([floor]),
    )
    fut = np.arange(float(n), float(n) + 60)
    fc = model.predict(state, jnp.asarray(fut), cap=jnp.full((1, 60), cap))
    yhat = np.asarray(fc["yhat"])[0]
    assert np.all(yhat >= floor - 25.0) and np.all(yhat <= cap + 25.0)
    # Far future approaches the cap (the series saturated during training).
    assert yhat[-1] > 0.9 * cap
    # In-sample accuracy near the noise level.
    ins = np.asarray(model.predict(
        state, jnp.asarray(t), cap=jnp.full((1, n), cap)
    )["yhat"])[0]
    assert np.abs(ins - true).mean() < 25.0
