"""tsspark_tpu.orchestrate: two-phase chunk workers, straggler patching,
crash-resume idempotency, parent retry loop, and numerical equality with
the in-memory TpuBackend.fit_twophase (driven on the CPU backend).

Replaces tests/test_bench_worker.py — the machinery these tests cover
moved from bench.py into the package (round-4 verdict item 3); bench.py
is now a thin caller.
"""

import argparse
import glob
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tsspark_tpu import orchestrate  # noqa: E402


def _model_config():
    from tsspark_tpu.config import (
        ProphetConfig, RegressorConfig, SeasonalityConfig,
    )

    return ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )


def _args(tmp_path, series=96, days=128, chunk=32, phase1=6, segment=12):
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets

    data_dir = tmp_path / "data"
    out_dir = tmp_path / "out"
    data_dir.mkdir()
    out_dir.mkdir()
    batch = datasets.m5_like(n_series=series, n_days=days)
    np.save(data_dir / "ds.npy", batch.ds.astype(np.float32))
    np.save(data_dir / "y.npy", np.nan_to_num(batch.y).astype(np.float32))
    np.save(data_dir / "mask.npy", batch.mask.astype(np.float32))
    np.save(data_dir / "reg.npy", batch.regressors.astype(np.float32))
    orchestrate.save_run_config(
        str(out_dir), _model_config(), SolverConfig(max_iters=120)
    )
    return argparse.Namespace(
        data=str(data_dir), out=str(out_dir), lo=0, hi=series, chunk=chunk,
        segment=segment, series=series, phase1_iters=phase1,
        no_phase1_tune=False, max_ahead=6,
    )


def test_fit_worker_two_phase_and_resume(tmp_path):
    args = _args(tmp_path)
    assert orchestrate.fit_worker(args) == 0

    files = sorted(glob.glob(os.path.join(args.out, "chunk_*.npz")))
    assert len(files) == 3
    for f in files:
        z = np.load(f)
        # Phase 2 ran: every chunk is flagged patched and fully converged.
        assert z["phase2"] == 1
        assert z["converged"].all()
        assert z["theta"].shape[0] == 32
    assert os.path.exists(os.path.join(args.out, "phase2_done"))
    with open(os.path.join(args.out, "times.jsonl")) as fh:
        times = [json.loads(l) for l in fh if l.strip()]
    assert sum(1 for t in times if "fit_s" in t) == 3
    phase2 = [t for t in times if "phase2_s" in t]
    assert len(phase2) == 1 and phase2[0]["stragglers"] >= 0
    # Heartbeats fired (the stall watchdog's liveness signal).
    assert os.path.exists(os.path.join(args.out, "heartbeat"))

    # Fully-complete rerun: nothing refits, marker short-circuits.
    n_times = len(times)
    assert orchestrate.fit_worker(args) == 0
    with open(os.path.join(args.out, "times.jsonl")) as fh:
        assert len([l for l in fh if l.strip()]) == n_times

    # Crash-resume: lose one chunk and the phase-2 marker mid-"crash".
    victim = files[1]
    os.remove(victim)
    os.remove(os.path.join(args.out, "phase2_done"))
    assert orchestrate.fit_worker(args) == 0
    z = np.load(victim)
    # The missing chunk was refit AND re-patched; untouched chunks kept
    # their already-patched results (idempotent phase 2).
    assert z["phase2"] == 1 and z["converged"].all()
    for f in files:
        assert np.load(f)["phase2"] == 1
    assert os.path.exists(os.path.join(args.out, "phase2_done"))


def test_prep_worker_cache_matches_inline_prep(tmp_path):
    """The overlapped CPU --_prep worker and the fit worker's inline prep
    run the same prepare/pack code path; the cached payload must be
    BIT-identical so a chunk fit from cache reproduces the inline fit."""
    args = _args(tmp_path, series=64, days=128, chunk=32, phase1=0)
    args.max_ahead = 1
    assert orchestrate.prep_worker(args) == 0
    cached = orchestrate.load_prep(args.out, 0, 32)
    assert cached is not None
    b_real, packed, meta = cached
    assert b_real == 32

    # Inline reference: same construction as fit_worker.prep.
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.models.prophet.design import (
        _indicator_reg_cols, pack_fit_data,
    )
    from tsspark_tpu.models.prophet.model import ProphetModel

    ds = np.load(os.path.join(args.data, "ds.npy"))
    y = np.load(os.path.join(args.data, "y.npy"))
    mask = np.load(os.path.join(args.data, "mask.npy"))
    reg = np.load(os.path.join(args.data, "reg.npy"))
    model = ProphetModel(_model_config(), SolverConfig(max_iters=120))
    u8 = _indicator_reg_cols(reg)
    y_c = np.zeros((32, y.shape[1]), np.float32); y_c[:] = y[0:32]
    m_c = np.zeros((32, y.shape[1]), np.float32); m_c[:] = mask[0:32]
    r_c = np.zeros((32,) + reg.shape[1:], np.float32); r_c[:] = reg[0:32]
    data, meta_ref = model.prepare(
        ds, y_c, mask=m_c, regressors=r_c, as_numpy=True
    )
    packed_ref, _ = pack_fit_data(data, meta_ref, ds, reg_u8_cols=u8,
                                  collapse_cap=True)
    for k in packed._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(packed, k)),
            np.asarray(getattr(packed_ref, k)), err_msg=k,
        )
    for k in meta._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(meta, k)),
            np.asarray(getattr(meta_ref, k)), err_msg=k,
        )

    # A second prep run is a no-op (file exists), and a chunk file
    # supersedes the prep cache.
    assert orchestrate.prep_worker(args) == 0


def test_phase2_resident_matches_host_path(tmp_path, monkeypatch):
    """The device-resident phase-2 gather and the host re-prep path must
    produce equivalent straggler refits: same convergence/status and
    thetas equal to f32 solver tolerance (the gathered payload is
    bit-identical to a re-packed one; only dispatch mechanics differ)."""
    (tmp_path / "resident").mkdir()
    (tmp_path / "host").mkdir()
    args_r = _args(tmp_path / "resident", series=96, days=128, chunk=32,
                   phase1=6, segment=0)
    args_h = _args(tmp_path / "host", series=96, days=128, chunk=32,
                   phase1=6, segment=0)
    monkeypatch.delenv("BENCH_NO_RESIDENT", raising=False)
    assert orchestrate.fit_worker(args_r) == 0
    monkeypatch.setenv("BENCH_NO_RESIDENT", "1")
    assert orchestrate.fit_worker(args_h) == 0

    def mode(out):
        with open(os.path.join(out, "times.jsonl")) as fh:
            rows = [json.loads(l) for l in fh if l.strip()]
        return next(t["phase2_mode"] for t in rows if "phase2_s" in t)

    assert mode(args_r.out) == "resident"
    assert mode(args_h.out) == "host"
    fr = sorted(glob.glob(os.path.join(args_r.out, "chunk_*.npz")))
    fh_ = sorted(glob.glob(os.path.join(args_h.out, "chunk_*.npz")))
    assert len(fr) == len(fh_) == 3
    for a, b in zip(fr, fh_):
        za, zb = np.load(a), np.load(b)
        assert za["phase2"] == 1 and zb["phase2"] == 1
        np.testing.assert_array_equal(za["status"], zb["status"])
        np.testing.assert_array_equal(za["converged"], zb["converged"])
        # Same data, same warm start, same program semantics: thetas agree
        # to f32 noise.
        np.testing.assert_allclose(
            za["theta"], zb["theta"], rtol=2e-4, atol=2e-4
        )
        for k in ("y_scale", "ds_start", "ds_span"):
            np.testing.assert_array_equal(za[k], zb[k])


def test_worker_phase2_equals_fit_twophase(tmp_path, monkeypatch):
    """THE unification gate (round-4 verdict item 4): the orchestrator's
    chunk-worker two-phase flow and TpuBackend.fit_twophase read their
    phase dispatches from the same phase{1,2}_dynamic_args policy, run
    the same prepare/pack per sub-chunk, and must land on IDENTICAL
    results for the same inputs (per-series trajectories are independent
    of batch padding width, so even the differing pad widths cannot
    diverge them)."""
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import SolverConfig

    monkeypatch.delenv("BENCH_NO_RESIDENT", raising=False)
    args = _args(tmp_path, series=96, days=128, chunk=32, phase1=6,
                 segment=0)
    args.no_phase1_tune = True
    assert orchestrate.fit_worker(args) == 0
    worker_state = orchestrate.load_fit_state(args.out, args.series)

    y = np.load(os.path.join(args.data, "y.npy"))
    ds = np.load(os.path.join(args.data, "ds.npy"))
    mask = np.load(os.path.join(args.data, "mask.npy"))
    reg = np.load(os.path.join(args.data, "reg.npy"))
    bk = TpuBackend(
        _model_config(), SolverConfig(max_iters=120), chunk_size=32,
    )
    mem_state = bk.fit_twophase(
        ds, y, mask=mask, regressors=reg, phase1_iters=6
    )
    np.testing.assert_array_equal(
        np.asarray(worker_state.converged), np.asarray(mem_state.converged)
    )
    np.testing.assert_array_equal(
        np.asarray(worker_state.status), np.asarray(mem_state.status)
    )
    np.testing.assert_array_equal(
        np.asarray(worker_state.theta), np.asarray(mem_state.theta)
    )
    np.testing.assert_array_equal(
        np.asarray(worker_state.loss), np.asarray(mem_state.loss)
    )
    np.testing.assert_array_equal(
        np.asarray(worker_state.n_iters), np.asarray(mem_state.n_iters)
    )


def test_single_phase_worker_writes_phase2_marker(tmp_path):
    """phase1_iters >= solver max_iters degenerates to single-phase — the
    worker must STILL write phase2_done at full coverage, or the parent
    (which only knows phase1_iters > 0) would respawn workers forever."""
    from tsspark_tpu.config import SolverConfig

    args = _args(tmp_path, series=64, days=128, chunk=32, phase1=12,
                 segment=0)
    orchestrate.save_run_config(
        args.out, _model_config(), SolverConfig(max_iters=10)
    )
    assert orchestrate.fit_worker(args) == 0
    assert os.path.exists(os.path.join(args.out, "phase2_done"))


def test_resilient_backend_falls_back_on_fractional_mask(tmp_path):
    """TpuBackend(resilient=True) with fractional observation weights is
    NOT packable — it must fall back to the in-process fit instead of
    spawning workers that die on pack_fit_data's 0/1-mask contract."""
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    rng = np.random.default_rng(5)
    n, t_len = 6, 120
    ds = np.arange(t_len, dtype=np.float64)
    y = 4.0 + 0.01 * np.arange(t_len) + rng.normal(0, 0.1, (n, t_len))
    weights = np.full((n, t_len), 0.5, np.float32)  # fractional mask
    called = {"n": 0}
    from tsspark_tpu import orchestrate as orch_mod

    orig = orch_mod.fit_resilient

    def counting(*a, **k):
        called["n"] += 1
        return orig(*a, **k)

    orch_mod.fit_resilient = counting
    try:
        state = TpuBackend(
            cfg, SolverConfig(max_iters=40), resilient=True,
            resilient_opts={"scratch_dir": str(tmp_path / "s")},
        ).fit(ds, y, mask=weights)
    finally:
        orch_mod.fit_resilient = orig
    assert called["n"] == 0, "fractional mask must not route to workers"
    assert np.isfinite(np.asarray(state.loss)).all()


def test_run_resilient_survives_worker_crash(tmp_path, monkeypatch):
    """A library user's fit survives a worker death mid-run: the parent
    retries, completed chunks persist, and the final state is complete.
    TSSPARK_TEST_CRASH_AFTER makes each child exit(17) after saving N
    chunks — attempt 1 lands 2 of 3 chunks and dies; the retry fits the
    last chunk and runs phase 2."""
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets

    batch = datasets.m5_like(n_series=96, n_days=128)
    scratch = tmp_path / "scratch"
    data_dir = str(scratch / "data")
    out_dir = str(scratch / "out")
    orchestrate.spill_data(
        data_dir, batch.ds, np.nan_to_num(batch.y), mask=batch.mask,
        regressors=batch.regressors,
    )
    orchestrate.save_run_config(
        out_dir, _model_config(), SolverConfig(max_iters=120)
    )
    monkeypatch.setenv("TSSPARK_TEST_CRASH_AFTER", "2")
    state = orchestrate.run_resilient(
        data_dir=data_dir, out_dir=out_dir, series=96, chunk=32,
        min_chunk=32, segment=0, phase1_iters=6, no_phase1_tune=True,
        deadline=None, progress_timeout=600.0, probe_accelerator=False,
    )
    assert state["complete"]
    assert state["retries"] >= 1
    fit_state = orchestrate.load_fit_state(out_dir, 96)
    assert np.asarray(fit_state.converged).all()
    assert np.asarray(fit_state.theta).shape[0] == 96


def test_fit_resilient_public_api(tmp_path, monkeypatch):
    """fit_resilient end-to-end (subprocess workers on CPU): returns a
    complete FitState equal to the in-memory two-phase fit."""
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets

    monkeypatch.delenv("TSSPARK_TEST_CRASH_AFTER", raising=False)
    batch = datasets.m5_like(n_series=64, n_days=128)
    y = np.nan_to_num(batch.y).astype(np.float32)
    cfg, solver = _model_config(), SolverConfig(max_iters=120)
    state = orchestrate.fit_resilient(
        cfg, solver, batch.ds, y, mask=batch.mask,
        regressors=batch.regressors, chunk=32, phase1_iters=6,
        no_phase1_tune=True, scratch_dir=str(tmp_path / "s"),
    )
    assert np.asarray(state.theta).shape[0] == 64
    assert np.asarray(state.converged).all()
    mem = TpuBackend(cfg, solver, chunk_size=32).fit_twophase(
        batch.ds.astype(np.float32), y, mask=batch.mask.astype(np.float32),
        regressors=batch.regressors.astype(np.float32), phase1_iters=6,
    )
    np.testing.assert_allclose(
        np.asarray(state.theta), np.asarray(mem.theta), rtol=2e-4,
        atol=2e-4,
    )
    # Scratch-resume guard: the same scratch_dir with DIFFERENT data must
    # refuse to resume instead of silently mixing chunk results.
    with pytest.raises(ValueError, match="DIFFERENT resilient run"):
        orchestrate.fit_resilient(
            cfg, solver, batch.ds, y + 1.0, mask=batch.mask,
            regressors=batch.regressors, chunk=32, phase1_iters=6,
            no_phase1_tune=True, scratch_dir=str(tmp_path / "s"),
        )


def test_forecaster_resilient_end_to_end(tmp_path, monkeypatch):
    """The user-facing spelling: Forecaster(cfg, backend="tpu",
    resilient=True) routes the DataFrame fit through the orchestrator's
    subprocess workers and still produces a normal forecast."""
    import pandas as pd

    import tsspark_tpu as tt
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig

    monkeypatch.delenv("TSSPARK_TEST_CRASH_AFTER", raising=False)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    rng = np.random.default_rng(3)
    n = 200
    ds = pd.date_range("2023-01-01", periods=n, freq="D")
    rows = []
    for sid in range(6):
        yv = 5 + sid + 0.01 * np.arange(n) + rng.normal(0, 0.1, n)
        rows.append(pd.DataFrame(
            {"series_id": f"s{sid}", "ds": ds, "y": yv}
        ))
    df = pd.concat(rows, ignore_index=True)
    called = {"n": 0}
    orig = orchestrate.fit_resilient

    def counting(*a, **k):
        called["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(orchestrate, "fit_resilient", counting)
    f = tt.Forecaster(
        cfg, backend="tpu", resilient=True,
        resilient_opts={"scratch_dir": str(tmp_path / "s"),
                        "phase1_iters": 6, "no_phase1_tune": True},
    ).fit(df)
    assert called["n"] == 1, "Forecaster fit did not route to fit_resilient"
    fc = f.predict(horizon=7)
    assert np.isfinite(fc["yhat"].to_numpy()).all()
    assert len(fc) == 6 * 7


def test_chunk_lease_claim_steal_and_fence(tmp_path):
    """Lease-fenced range claims: a live lease blocks rivals, a stale
    one (expired, or owner pid dead) is stolen, and the loser of a
    steal is fenced — ``holds_lease`` refuses its token, so its save is
    discarded instead of double-landing the range."""
    import json as json_mod
    import subprocess

    out = str(tmp_path)
    assert orchestrate.claim_lease(out, 0, 32, "w1")
    # Live lease (our own pid, future expiry): a rival cannot claim...
    assert not orchestrate.claim_lease(out, 0, 32, "w2")
    # ...but the holder re-claims (renews) its own lease freely.
    assert orchestrate.claim_lease(out, 0, 32, "w1")
    assert orchestrate.holds_lease(out, 0, 32, "w1")
    assert not orchestrate.holds_lease(out, 0, 32, "w2")

    # Expired lease: stealable even when the owner pid is alive (the
    # owner is fenced at save time, which keeps the steal safe).
    with open(orchestrate._lease_path(out, 0, 32), "w") as fh:
        json_mod.dump({"token": "w1", "pid": os.getpid(),
                       "expires_unix": 0.0}, fh)
    assert orchestrate.claim_lease(out, 0, 32, "w2")
    assert not orchestrate.holds_lease(out, 0, 32, "w1")  # fenced
    assert orchestrate.holds_lease(out, 0, 32, "w2")

    # Dead-owner lease: reclaimed immediately, before expiry (the
    # watchdog's SIGKILL leaves exactly this state behind).
    dead = subprocess.Popen(["true"])
    dead.wait()  # reaped: its pid no longer exists
    with open(orchestrate._lease_path(out, 64, 96), "w") as fh:
        json_mod.dump({"token": "gone", "pid": dead.pid,
                       "expires_unix": 4e12}, fh)
    assert orchestrate.claim_lease(out, 64, 96, "w3")

    # Torn lease record (writer died mid-create): reads as stale.
    with open(orchestrate._lease_path(out, 96, 128), "w") as fh:
        fh.write('{"token": "to')
    assert orchestrate.claim_lease(out, 96, 128, "w4")

    # A live lease blocks OVERLAPPING claims at any width, not just the
    # exact range — claim grids differ across workers (tuner sizing,
    # chunk halving), and two non-identical overlapping leases would
    # double-land series.
    assert orchestrate.claim_lease(out, 128, 160, "wa")
    assert not orchestrate.claim_lease(out, 136, 144, "wb")  # inside
    assert not orchestrate.claim_lease(out, 152, 176, "wb")  # straddles
    assert orchestrate.claim_lease(out, 160, 192, "wb")      # adjacent
    # The holder itself may re-claim a sub-range of its own coverage
    # grid without self-conflict (same token).
    assert orchestrate.claim_lease(out, 136, 144, "wa")

    # Release only honors the holder's token.
    orchestrate.release_lease(out, 0, 32, "w1")  # loser: no-op
    assert orchestrate.holds_lease(out, 0, 32, "w2")
    orchestrate.release_lease(out, 0, 32, "w2")
    assert orchestrate.read_lease(out, 0, 32) is None
    assert orchestrate.claim_lease(out, 0, 32, "w5")


def test_sigkill_mid_chunk_restart_lands_exactly_once(tmp_path,
                                                      monkeypatch):
    """The crash-resume acceptance (ISSUE 5 satellite): SIGKILL a fit
    worker mid-chunk (exit-mode fault after its first save, plus a
    silent chunk corruption), restart through the parent loop, and
    assert every series lands exactly once — coverage tiles [0, n) with
    no gap or overlap — with no ``*.corrupt`` quarantine file leaking
    into the assembled results."""
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets
    from tsspark_tpu.resilience import faults
    from tsspark_tpu.resilience.policy import RetryPolicy

    batch = datasets.m5_like(n_series=48, n_days=96)
    scratch = tmp_path / "scratch"
    data_dir = str(scratch / "data")
    out_dir = str(scratch / "out")
    orchestrate.spill_data(
        data_dir, batch.ds, np.nan_to_num(batch.y), mask=batch.mask,
        regressors=batch.regressors,
    )
    orchestrate.save_run_config(
        out_dir, _model_config(), SolverConfig(max_iters=60)
    )
    plan = (
        faults.FaultPlan(state_dir=str(tmp_path / "faults"))
        # the worker dies right after landing its first chunk...
        .fail("fit_worker_chunk", after=0, attempts=1, mode="exit",
              rc=31)
        # ...and one later save is silently corrupted on disk.
        .fail("chunk_save", series=40, attempts=1, mode="corrupt")
    )
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    state = orchestrate.run_resilient(
        data_dir=data_dir, out_dir=out_dir, series=48, chunk=16,
        min_chunk=16, segment=0, phase1_iters=0, deadline=None,
        progress_timeout=600.0, probe_accelerator=False,
        retry_policy=RetryPolicy(max_attempts=9, base_delay_s=0.2,
                                 max_delay_s=0.2),
    )
    monkeypatch.delenv(faults.ENV_VAR)
    assert state["complete"] and state["retries"] >= 1

    # Exactly once: completed ranges tile [0, 48) disjointly.
    done = sorted(orchestrate.completed_ranges(out_dir))
    cur = 0
    for lo, hi in done:
        assert lo == cur, f"gap or overlap at {lo} (covered to {cur})"
        cur = hi
    assert cur == 48
    # The injected corruption was quarantined, re-fit, and never
    # assembled: the corrupt file sits outside the resume glob and the
    # full state loads clean with every row finite.
    assert glob.glob(os.path.join(out_dir, "*.corrupt"))
    fit_state = orchestrate.load_fit_state(out_dir, 48)
    assert np.asarray(fit_state.theta).shape[0] == 48
    assert np.isfinite(np.asarray(fit_state.theta)).all()
    # Any lease a dead worker left behind is immediately reclaimable —
    # a resumed run never deadlocks on its predecessor's leases.
    for lo, hi in done:
        assert orchestrate.claim_lease(out_dir, lo, hi, "post-check")


def test_run_resilient_gives_up_on_deterministic_failure(tmp_path,
                                                         monkeypatch):
    """A child that dies with ZERO progress every attempt (here: the data
    dir does not exist) is a deterministic failure, not a wedge — with no
    deadline the parent must raise after max_fruitless_retries instead of
    respawning forever."""
    from tsspark_tpu.config import SolverConfig

    out_dir = str(tmp_path / "out")
    orchestrate.save_run_config(
        out_dir, _model_config(), SolverConfig(max_iters=10)
    )
    monkeypatch.setenv("TSSPARK_TEST_CRASH_AFTER", "0")  # short retry sleep
    with pytest.raises(RuntimeError, match="consecutive"):
        orchestrate.run_resilient(
            data_dir=str(tmp_path / "no_such_data"), out_dir=out_dir,
            series=64, chunk=32, min_chunk=32, segment=0, phase1_iters=0,
            deadline=None, progress_timeout=120.0,
            probe_accelerator=False, max_fruitless_retries=1,
        )
