"""Backend registry + CPU/TPU parity: same loss, independent optimizers
(scipy L-BFGS-B per series vs the batched JAX solver) must land on forecasts
with near-identical accuracy — the driver's sMAPE-parity criterion
(BASELINE.json:2)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu import (
    ProphetConfig,
    SeasonalityConfig,
    SolverConfig,
    get_backend,
    list_backends,
    register_backend,
)
from tsspark_tpu.backends.registry import ForecastBackend
from tsspark_tpu.data import datasets
from tsspark_tpu.eval import metrics


def test_registry_lists_builtins():
    assert {"cpu", "tpu"} <= set(list_backends())


def test_registry_unknown_backend():
    with pytest.raises(KeyError):
        get_backend("cuda")


def test_register_custom_backend():
    @register_backend
    class EchoBackend(ForecastBackend):
        name = "echo-test"

        def fit(self, ds, y, **kw):
            return "fitted"

        def predict(self, state, ds, **kw):
            return {}

    assert get_backend("echo-test").fit(None, None) == "fitted"


@pytest.fixture(scope="module")
def small_batch():
    batch = datasets.peyton_manning_like(n_days=500, seed=7)
    # Three series with different scales/offsets derived from one generator.
    y0 = batch.y[0]
    y = np.stack([y0, 3.0 * y0 + 5.0, 0.5 * y0 - 2.0])
    return batch.ds, y


def test_cpu_tpu_smape_parity(small_batch):
    ds, y = small_batch
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),),
        n_changepoints=8,
    )
    solver = SolverConfig(max_iters=300)
    y_j = jnp.asarray(y)

    st_cpu = get_backend("cpu", cfg, solver).fit(ds, y_j)
    st_tpu = get_backend("tpu", cfg, solver).fit(ds, y_j)
    fc_cpu = get_backend("cpu", cfg, solver).predict(st_cpu, ds, num_samples=0)
    fc_tpu = get_backend("tpu", cfg, solver).predict(st_tpu, ds, num_samples=0)

    mask = jnp.asarray(np.isfinite(y).astype(np.float32))
    y_clean = jnp.asarray(np.nan_to_num(y))
    s_cpu = np.asarray(metrics.smape(y_clean, fc_cpu["yhat"], mask))
    s_tpu = np.asarray(metrics.smape(y_clean, fc_tpu["yhat"], mask))
    # Parity: batched solver must be as accurate as the scipy oracle.
    # Thresholds track the committed audit (EVAL_r02.json): per-series
    # worst |delta| there is ~0.1 on train configs; 0.1 here keeps margin
    # without letting a real regression through.
    np.testing.assert_allclose(s_tpu, s_cpu, atol=0.1)
    assert abs(s_tpu.mean() - s_cpu.mean()) < 0.05
    # And both must actually fit well.
    assert s_cpu.max() < 6.0 and s_tpu.max() < 6.0


def test_tpu_chunked_fit_matches_unchunked(small_batch):
    ds, y = small_batch
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
    )
    solver = SolverConfig(max_iters=150)
    y_j = jnp.asarray(y)
    whole = get_backend("tpu", cfg, solver).fit(ds, y_j)
    chunked = get_backend("tpu", cfg, solver, chunk_size=2).fit(ds, y_j)
    assert chunked.theta.shape == whole.theta.shape
    # Chunk padding must not perturb real series' results.
    np.testing.assert_allclose(
        np.asarray(chunked.loss), np.asarray(whole.loss), rtol=1e-3, atol=1e-3
    )


def test_tpu_backend_iter_segment_matches_full_solve():
    """Segmented dispatches (iter_segment) reach the same optimum quality."""
    import numpy as np
    import jax.numpy as jnp
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
    )
    rng = np.random.default_rng(7)
    n = 200
    ds = jnp.arange(n, dtype=jnp.float32)
    t = np.arange(n)
    y = jnp.asarray(
        (4 + 0.02 * t + np.sin(2 * np.pi * t / 7)
         + rng.normal(0, 0.2, (3, n))).astype(np.float32)
    )
    solver = SolverConfig(max_iters=120)
    full = get_backend("tpu", cfg, solver).fit(ds, y)
    seg = get_backend("tpu", cfg, solver, iter_segment=16).fit(ds, y)
    # Same posterior optimum to within solver noise.
    assert np.allclose(np.asarray(seg.loss), np.asarray(full.loss),
                       rtol=1e-3, atol=1e-2)
    assert bool(seg.converged.all())
    # Accumulated iteration counts are reported across segments.
    assert int(np.asarray(seg.n_iters).max()) >= 16


def test_parity_delta_distribution_gate():
    """The parity artifact's gate statistic (per-series holdout |delta
    sMAPE| p95) must stay under threshold on the M5-style config — the
    small-scale version of EVAL_r03's bench-scale distribution check."""
    from tsspark_tpu.eval import parity

    out = parity.run_config3_at_scale(n_series=24, oracle_n=24)
    # Train-window parity is the optimizer-quality statement: both solvers
    # must land on the same optimum (p95 observed ~0.09 at this scale).
    assert out["delta_train_dist"]["p95"] < 0.25
    # Holdout deltas add extrapolation sensitivity: tiny parameter
    # differences near the series end tip the projected slope, so the
    # per-series tail is wider (observed ~0.9, symmetric) — gate the tail
    # and the mean, which must stay near zero.
    assert out["delta_holdout_dist"]["p95"] < 1.5
    assert abs(
        out["smape_holdout_tpu_sub"] - out["smape_holdout_cpu_sub"]
    ) < 0.15


def test_tpu_twophase_matches_full_depth():
    """Straggler compaction (short phase 1 + compacted deep phase 2) must
    reach the same optimum quality as one full-depth solve."""
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
    )
    rng = np.random.default_rng(13)
    n, b = 240, 6
    ds = jnp.arange(n, dtype=jnp.float32)
    t = np.arange(n)
    # Mixed difficulty: smooth series converge in a handful of iterations;
    # high-noise heavy-seasonality ones need many more.
    y = np.stack([
        4 + 0.02 * t + np.sin(2 * np.pi * t / 7) + rng.normal(0, s, n)
        for s in (0.05, 0.05, 0.05, 0.05, 2.0, 3.0)
    ]).astype(np.float32)
    solver = SolverConfig(max_iters=120)
    bk = get_backend("tpu", cfg, solver)
    full = bk.fit(ds, jnp.asarray(y))
    two = bk.fit_twophase(ds, jnp.asarray(y), phase1_iters=2)
    assert bool(two.converged.all())
    # Same posterior optimum to within solver noise.
    np.testing.assert_allclose(
        np.asarray(two.loss), np.asarray(full.loss), rtol=1e-3, atol=1e-2
    )
    # Phase-2 series report accumulated (phase1 + phase2) iteration counts.
    assert int(np.asarray(two.n_iters).max()) > 2
    assert two.status is not None


def test_difficulty_order_nan_hardest():
    """NaN grad norms (diverged series) must sort FIRST (hardest), not
    last: argsort on raw values seats NaN rows in the easiest sub-chunk
    and defeats similar-difficulty grouping (ADVICE r4)."""
    from tsspark_tpu.backends.tpu import difficulty_order

    g = np.array([1.0, np.nan, 50.0, 0.1, np.nan])
    order = difficulty_order(g)
    assert set(order[:2].tolist()) == {1, 4}  # NaN rows first (stable)
    assert order[2:].tolist() == [2, 0, 3]  # then descending grad norm


def test_cpu_backend_components():
    """components is part of the backend interface (base-class default)."""
    import numpy as np
    import jax.numpy as jnp
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
    )
    rng = np.random.default_rng(11)
    n = 120
    ds = jnp.arange(n, dtype=jnp.float32)
    y = jnp.asarray(
        (3 + np.sin(2 * np.pi * np.arange(n) / 7)
         + rng.normal(0, 0.2, (2, n))).astype(np.float32)
    )
    bk = get_backend("cpu", cfg)
    state = bk.fit(ds, y)
    comps = bk.components(state, ds)
    assert set(comps) == {"trend", "weekly"}
    assert np.asarray(comps["weekly"]).shape == (2, n)


def test_on_segment_liveness_hook_fires():
    """The per-dispatch liveness hook (bench's stall-watchdog feed) must
    fire once per completed segment."""
    from tsspark_tpu.backends.registry import get_backend
    from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig

    calls = []
    bk = get_backend(
        "tpu",
        ProphetConfig(seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
                      n_changepoints=2),
        SolverConfig(max_iters=40),
        iter_segment=8, on_segment=lambda: calls.append(1),
    )
    rng = np.random.default_rng(0)
    n = 120
    y = (3 + np.sin(2 * np.pi * np.arange(n) / 7)
         + rng.normal(0, 0.5, (2, n))).astype(np.float32)
    bk.fit(jnp.arange(n, dtype=jnp.float32), jnp.asarray(y))
    assert 1 <= len(calls) <= 5  # one per dispatched segment


def test_predict_chunked_matches_unchunked():
    """Series-axis predict chunking (the (S, B, T) sample tensor must not
    scale with the full batch) reproduces the unchunked deterministic
    outputs exactly and keeps interval ordering."""
    import numpy as np

    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )

    rng = np.random.default_rng(7)
    b, t_len = 37, 90  # deliberately not a multiple of the chunk
    ds = np.arange(t_len, dtype=np.float64)
    y = (
        5.0
        + 0.02 * ds[None, :]
        + np.sin(2 * np.pi * ds[None, :] / 7.0)
        + rng.normal(0, 0.1, (b, t_len))
    )
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    backend = TpuBackend(cfg, SolverConfig(max_iters=30), chunk_size=16)
    state = backend.fit(ds, y)
    fut = np.arange(t_len, t_len + 14, dtype=np.float64)

    chunked = backend.predict(state, fut, seed=0)
    whole = backend._model.predict(state, fut, seed=0)
    for k in ("yhat", "trend", "additive", "multiplicative"):
        np.testing.assert_allclose(
            np.asarray(chunked[k]), np.asarray(whole[k]), atol=1e-5,
            err_msg=k,
        )
    assert np.all(
        np.asarray(chunked["yhat_lower"]) <= np.asarray(chunked["yhat_upper"])
    )
    assert np.asarray(chunked["yhat"]).shape == (b, 14)


def test_components_chunked_matches_unchunked():
    import numpy as np

    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import (
        ProphetConfig, SeasonalityConfig, SolverConfig,
    )

    rng = np.random.default_rng(17)
    b, t_len = 37, 90
    ds = np.arange(t_len, dtype=np.float64)
    y = 5 + np.sin(2 * np.pi * ds[None, :] / 7.0) \
        + rng.normal(0, 0.1, (b, t_len))
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=3,
    )
    backend = TpuBackend(cfg, SolverConfig(max_iters=25), chunk_size=16)
    state = backend.fit(ds, y)
    grid = np.arange(t_len + 14, dtype=np.float64)
    chunked = backend.components(state, grid)
    whole = backend._model.components(state, grid)
    assert set(chunked) == set(whole)
    for k in whole:
        np.testing.assert_allclose(
            np.asarray(chunked[k]), np.asarray(whole[k]), atol=1e-5,
            err_msg=k,
        )


def test_twophase_multistart_never_worse():
    """The straggler multi-start keeps per-series argmin loss: the
    two-phase result is never worse than either candidate alone would
    allow, and select_better_state prefers finite losses."""
    import numpy as np

    from tsspark_tpu.models.prophet.model import (
        FitState, select_better_state,
    )

    a = FitState(
        theta=np.zeros((3, 2)), meta=None,
        loss=np.asarray([1.0, np.nan, 5.0]),
        grad_norm=np.asarray([0.1, 0.2, 0.3]),
        converged=np.asarray([True, False, True]),
        n_iters=np.asarray([3, 4, 5]), status=np.asarray([1, 0, 2]),
    )
    b = FitState(
        theta=np.ones((3, 2)), meta=None,
        loss=np.asarray([2.0, 7.0, 4.0]),
        grad_norm=np.asarray([0.4, 0.5, 0.6]),
        converged=np.asarray([True, True, True]),
        n_iters=np.asarray([6, 7, 8]), status=np.asarray([1, 1, 1]),
    )
    out = select_better_state(a, b)
    np.testing.assert_allclose(out.loss, [1.0, 7.0, 4.0])
    np.testing.assert_allclose(out.theta[:, 0], [0.0, 1.0, 1.0])
    assert list(out.n_iters) == [3, 7, 8]


def test_rescue_pass_never_degrades_and_triggers():
    """fit()'s stuck-exit rescue (GN-diag multi-start over FLOOR/STALLED
    exits) must keep each series' best loss — original included — so it
    can only improve, and it must actually fire on an M5-like batch
    (where most series exit via the f32 floor)."""
    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.config import RegressorConfig

    batch = datasets.m5_like(n_series=48, n_days=256)
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )
    y = np.nan_to_num(batch.y)
    kw = dict(mask=batch.mask, regressors=batch.regressors)
    solver = SolverConfig(max_iters=120)
    st_plain = TpuBackend(cfg, solver, rescue=False).fit(batch.ds, y, **kw)
    st_resc = TpuBackend(cfg, solver).fit(batch.ds, y, **kw)
    # The suspect set is non-empty on this data (else the test is vacuous).
    assert np.isin(np.asarray(st_plain.status), (3, 4)).any()
    l0 = np.asarray(st_plain.loss)
    l1 = np.asarray(st_resc.loss)
    # Keep-best contract: never worse (tiny f32 slack).  Whether any series
    # improves is data-dependent (a restart must beat the incumbent by
    # KEEP_BEST_MARGIN to win — see select_better_state); the margin
    # semantics themselves are unit-tested in test_select_better_margin.
    assert (l1 <= l0 + 1e-4).all()


def test_select_better_margin():
    """A challenger must beat the incumbent by MORE than the margin: ties
    and epsilon wins keep the incumbent's theta (basin stability for
    warm-start continuity)."""
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import (
        FitState, select_better_state,
    )

    def st(loss, tag):
        b = len(loss)
        meta = ScalingMeta(
            y_scale=np.ones(b), floor=np.zeros(b), ds_start=np.zeros(b),
            ds_span=np.ones(b), reg_mean=np.zeros((b, 0)),
            reg_std=np.ones((b, 0)), changepoints=np.zeros((b, 0)),
        )
        return FitState(
            theta=np.full((b, 2), tag, np.float32),
            meta=meta, loss=np.asarray(loss, np.float32),
            grad_norm=np.zeros(b, np.float32),
            converged=np.ones(b, bool), n_iters=np.ones(b, np.int32),
            status=np.zeros(b, np.int32),
        )

    #           tie,  eps win, real win, worse
    a = st([10.0, 10.0, 10.0, 10.0], tag=1.0)
    b_ = st([10.0, 9.99, 9.80, 11.0], tag=2.0)
    out = select_better_state(a, b_, margin=0.05)
    np.testing.assert_array_equal(
        np.asarray(out.theta)[:, 0], [1.0, 1.0, 2.0, 1.0]
    )
    np.testing.assert_allclose(
        np.asarray(out.loss), [10.0, 10.0, 9.80, 10.0]
    )


def test_small_batches_share_one_compiled_shape():
    """Every b <= 32 pads to one 32-row program (round-3 Weak #5: tiny
    batches paid a compile per size; streaming refits a different touched
    count every micro-batch)."""
    from unittest import mock

    from tsspark_tpu.backends.tpu import TpuBackend
    from tsspark_tpu.models.prophet.model import ProphetModel

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    bk = TpuBackend(cfg, SolverConfig(max_iters=8), rescue=False)
    ds = np.arange(64, dtype=np.float64)
    rng = np.random.default_rng(0)
    seen = []
    real_fit = ProphetModel.fit

    def spy(self, ds_, y_, **kw):
        seen.append(np.asarray(y_).shape[0])
        return real_fit(self, ds_, y_, **kw)

    with mock.patch.object(ProphetModel, "fit", spy):
        for b in (1, 5, 17, 32):
            y = 5 + rng.normal(0, 0.1, (b, 64))
            st = bk.fit(ds, y)
            assert np.asarray(st.theta).shape[0] == b
    assert seen == [32, 32, 32, 32]


def test_partial_dynamic_flags_keep_static_semantics():
    """Passing ONLY max_iters_dynamic must behave exactly like the static
    config at that depth: missing flags are normalized (metric from
    resolved_precond — NOT silently 'none' — and a caller init honored),
    on both the packed path and the non-packable fallback (review r4)."""
    from tsspark_tpu.models.prophet.model import ProphetModel

    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=4,
    )
    rng = np.random.default_rng(3)
    ds = np.arange(96, dtype=np.float64)
    y = 5 + 0.4 * ds[None] / 96 + np.sin(2 * np.pi * ds[None] / 7.0) \
        + rng.normal(0, 0.1, (6, 96))

    m_dyn = ProphetModel(cfg, SolverConfig(max_iters=120))
    m_static = ProphetModel(cfg, SolverConfig(max_iters=7))
    for label, mask in (
        ("packed", None),                       # exact 0/1 mask -> packed
        ("fallback", np.full_like(y, 0.5)),     # fractional -> FitData path
    ):
        st_d = m_dyn.fit(ds, y, mask=mask,
                         max_iters_dynamic=np.int32(7))
        st_s = m_static.fit(ds, y, mask=mask)
        np.testing.assert_allclose(
            np.asarray(st_d.theta), np.asarray(st_s.theta),
            rtol=0, atol=1e-5, err_msg=label,
        )
        np.testing.assert_array_equal(
            np.asarray(st_d.n_iters), np.asarray(st_s.n_iters),
            err_msg=label,
        )


def test_resilient_fallback_warns_once_with_reason(monkeypatch):
    """resilient=True on an ineligible batch must say WHICH eligibility
    check failed — once — instead of silently dropping process
    isolation (the gate at backends/tpu.py's resilient route)."""
    import warnings

    from tsspark_tpu.backends import tpu as tpu_mod
    from tsspark_tpu.resilience.report import ResilienceWarning

    monkeypatch.setattr(tpu_mod, "_RESILIENT_FALLBACK_WARNED", False)
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
        n_changepoints=2,
    )
    backend = tpu_mod.TpuBackend(
        cfg, SolverConfig(max_iters=4), resilient=True, rescue=False
    )
    ds = np.arange(60, dtype=np.float64)
    y = np.sin(ds / 7.0)[None, :].repeat(3, axis=0).astype(np.float32)
    init = np.zeros((3, cfg.num_params), np.float32)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        backend.fit(ds, y, init=init)  # init => ineligible
    msgs = [w for w in rec if issubclass(w.category, ResilienceWarning)]
    assert len(msgs) == 1
    text = str(msgs[0].message)
    assert "INELIGIBLE" in text
    assert "init=" in text
    # Second ineligible fit: the announcement stays one-time.
    with warnings.catch_warnings(record=True) as rec2:
        warnings.simplefilter("always")
        backend.fit(ds, y, init=init)
    assert not [w for w in rec2
                if issubclass(w.category, ResilienceWarning)]
