"""Streaming incremental refit (eval config 5 analog): param store,
checkpoint round-trip, warm-start space transfer, and the micro-batch loop."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.data import datasets
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.loss import neg_log_posterior
from tsspark_tpu.models.prophet.model import ProphetModel
from tsspark_tpu.models.prophet.params import init_theta
from tsspark_tpu.streaming.driver import StreamingForecaster
from tsspark_tpu.streaming.source import InMemorySource, KafkaSource
from tsspark_tpu.streaming.state import ParamStore
from tsspark_tpu.streaming.warmstart import transfer_theta
from tsspark_tpu.utils import checkpoint as ckpt

# The streaming path must be NaN-clean by construction: the warm-start
# transfer once relied on downstream masking to hide 0/0 on new-series rows
# (round-2 VERDICT weakness #5).  Escalating RuntimeWarnings keeps it fixed.
pytestmark = pytest.mark.filterwarnings("error::RuntimeWarning")

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=5
)


def _series_df(n_days, sid="s0", seed=0, start_day=0):
    rng = np.random.default_rng(seed)
    t = np.arange(start_day, start_day + n_days, dtype=float)
    y = 10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7) + rng.normal(0, 0.1, n_days)
    return pd.DataFrame({"series_id": sid, "ds": t, "y": y})


def test_checkpoint_roundtrip(tmp_path):
    model = ProphetModel(CFG, SolverConfig(max_iters=60))
    df = _series_df(200)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    path = str(tmp_path / "ck")
    ckpt.save_state(path, state, CFG, series_ids=np.asarray(["s0"]))
    loaded, ids = ckpt.load_state(path, CFG)
    np.testing.assert_allclose(
        np.asarray(loaded.theta), np.asarray(state.theta), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(loaded.meta.y_scale), np.asarray(state.meta.y_scale)
    )
    assert list(ids) == ["s0"]


def test_checkpoint_fingerprint_mismatch(tmp_path):
    model = ProphetModel(CFG, SolverConfig(max_iters=30))
    df = _series_df(100)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    path = str(tmp_path / "ck")
    ckpt.save_state(path, state, CFG)
    other = ProphetConfig(seasonalities=(), n_changepoints=5)
    with pytest.raises(ValueError):
        ckpt.load_state(path, other)


def test_param_store_lookup_mask():
    store = ParamStore(CFG)
    model = ProphetModel(CFG, SolverConfig(max_iters=30))
    df = _series_df(100)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    store.update(["s0"], state)
    theta, meta, found = store.lookup(["s0", "unknown"])
    assert found.tolist() == [True, False]
    np.testing.assert_allclose(np.asarray(theta[0]), np.asarray(state.theta[0]))
    assert "s0" in store and "unknown" not in store


def test_warmstart_transfer_preserves_fit():
    """Transfer old params onto extended data: the transferred theta must
    score a loss close to a fresh converged fit on the new data — i.e. the
    space mapping is right, not just 'some init'."""
    df_old = _series_df(400)
    df_new = _series_df(460)  # 60 more days: scalings + changepoints move
    model = ProphetModel(CFG, SolverConfig(max_iters=300))

    old = model.fit(df_old.ds.to_numpy(), jnp.asarray(df_old.y.to_numpy()[None, :]))
    data_new, meta_new = prepare_fit_data(
        jnp.asarray(df_new.ds.to_numpy()),
        jnp.asarray(df_new.y.to_numpy()[None, :]), CFG,
    )
    warm = transfer_theta(old.theta, old.meta, meta_new, CFG)
    fresh = model.fit(df_new.ds.to_numpy(), jnp.asarray(df_new.y.to_numpy()[None, :]))

    f_warm = float(neg_log_posterior(warm, data_new, CFG)[0])
    f_fresh = float(fresh.loss[0])
    f_cold = float(
        neg_log_posterior(
            init_theta(CFG, data_new.y, data_new.mask, data_new.t),
            data_new, CFG,
        )[0]
    )
    # Warm init must be far closer to the optimum than the cold init.
    assert f_warm < f_cold - 0.5 * (f_cold - f_fresh), (f_warm, f_cold, f_fresh)


def test_streaming_loop_warm_starts_and_improves():
    df_full = _series_df(360, seed=3)
    batches = [
        df_full.iloc[:300],
        df_full.iloc[300:330],
        df_full.iloc[330:360],
    ]
    sf = StreamingForecaster(
        CFG, SolverConfig(max_iters=60), backend="tpu", chunk_size=1
    )
    stats = sf.run(InMemorySource(batches))
    assert stats.micro_batches == 3
    assert stats.cold_starts == 1      # first sight of s0
    assert stats.warm_starts == 2      # subsequent refits warm-start
    fc = sf.forecast(["s0"], horizon=14, num_samples=0)
    assert len(fc) == 14
    t = fc.ds.to_numpy()
    want = 10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7)
    assert np.abs(fc.yhat.to_numpy() - want).mean() < 0.5


def test_streaming_batch_latencies_and_cold_mode():
    """RefitStats records one latency per micro-batch, and
    warm_start=False forces the ridge-init path on every refit (the
    warm-vs-cold instrument eval config 5 uses) while still converging
    to a good forecast."""
    df_full = _series_df(360, seed=3)
    batches = [
        df_full.iloc[:300],
        df_full.iloc[300:330],
        df_full.iloc[330:360],
    ]
    sf = StreamingForecaster(
        CFG, SolverConfig(max_iters=60), backend="tpu", warm_start=False,
    )
    stats = sf.run(InMemorySource(batches))
    assert len(stats.batch_seconds) == 3
    assert all(s > 0 for s in stats.batch_seconds)
    assert abs(sum(stats.batch_seconds) - stats.fit_seconds) < 1e-6
    # Every refit is a forced cold start; none consult the store.
    assert stats.cold_starts == 3
    assert stats.warm_starts == 0
    fc = sf.forecast(["s0"], horizon=14, num_samples=0)
    t = fc.ds.to_numpy()
    want = 10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7)
    assert np.abs(fc.yhat.to_numpy() - want).mean() < 0.5


def test_streaming_multi_series_and_new_series_midstream():
    b1 = pd.concat([_series_df(120, "a", 1), _series_df(120, "b", 2)])
    b2 = pd.concat([
        _series_df(30, "a", 1, start_day=120),
        _series_df(150, "c", 4),  # new series appears mid-stream
    ])
    sf = StreamingForecaster(CFG, SolverConfig(max_iters=40), backend="tpu")
    sf.run(InMemorySource([b1, b2]))
    assert len(sf.store) == 3
    fc = sf.forecast(["a", "b", "c"], horizon=7, num_samples=0)
    assert set(fc.series_id.unique()) == {"a", "b", "c"}
    with pytest.raises(KeyError):
        sf.forecast(["nope"], horizon=3)


def test_kafka_source_gated():
    with pytest.raises(ImportError):
        KafkaSource("topic")


class _FakeMsg:
    def __init__(self, value):
        self.value = value


class _FakeConsumer:
    """Duck-typed KafkaConsumer: poll() drains pre-loaded record batches."""

    def __init__(self, batches):
        self._batches = list(batches)
        self.poll_kwargs = []
        self.events = []  # interleaved "poll"/"commit" order

    def poll(self, timeout_ms=None, max_records=None):
        self.poll_kwargs.append((timeout_ms, max_records))
        self.events.append("poll")
        if not self._batches:
            return {}
        rows = self._batches.pop(0)
        return {("topic", 0): [_FakeMsg(r) for r in rows]}

    def commit(self):
        self.events.append("commit")


def test_kafka_source_fake_consumer_drives_streaming():
    df = _series_df(240, "k0", seed=5)
    rows = df.to_dict("records")
    consumer = _FakeConsumer([rows[:200], rows[200:240], []])
    src = KafkaSource(consumer=consumer, max_records=500)

    b1 = src.poll()
    assert isinstance(b1, pd.DataFrame) and len(b1) == 200
    assert set(b1.columns) == {"series_id", "ds", "y"}
    assert consumer.poll_kwargs[0] == (1000, 500)

    # Remaining batches feed the refit loop; empty poll ends iteration.
    sf = StreamingForecaster(CFG, SolverConfig(max_iters=40), backend="tpu")
    sf.process(b1)
    stats = sf.run(src)
    assert stats.micro_batches == 2          # head batch + the 40-row tail
    assert src.poll() is None                # drained
    fc = sf.forecast(["k0"], horizon=7, num_samples=0)
    assert np.isfinite(fc.yhat.to_numpy()).all()
    # At-least-once contract: the driver commits offsets AFTER each applied
    # refit — one commit for the one batch sf.run processed, and none for
    # the empty terminating poll.
    assert consumer.events == ["poll", "poll", "commit", "poll", "poll"]


def test_param_store_meta_float64_hourly_precision():
    """ds_start rides in absolute epoch days (~2e4); at hourly cadence a
    float32 store quantizes it by ~5 minutes and biases the warm-start time
    map.  The store must round-trip float64 meta exactly."""
    ds_start = 20650.0 + 1.0 / 24.0          # not representable in float32
    ds_span = 30.0 + 1.0 / 24.0
    model = ProphetModel(CFG, SolverConfig(max_iters=5))
    t = ds_start + np.arange(24 * 30, dtype=np.float64) / 24.0
    y = 5 + np.sin(2 * np.pi * t)
    state = model.fit(t, jnp.asarray(y[None, :], jnp.float32))
    # Overwrite meta with exact float64 values (prepare_fit_data's f32
    # pipeline already rounded them; the STORE must not add more).
    state = state._replace(
        meta=state.meta._replace(
            ds_start=np.asarray([ds_start]), ds_span=np.asarray([ds_span])
        )
    )
    store = ParamStore(CFG)
    store.update(["h0"], state)
    _, meta, found = store.lookup(["h0"])
    assert found.all()
    assert meta.ds_start.dtype == np.float64
    assert float(meta.ds_start[0]) == ds_start          # exact
    assert float(np.float32(ds_start)) != ds_start      # f32 would not be
    # ...and through the DISK round trip (save -> load -> lookup): the
    # checkpoint layer must not reintroduce a float32 hop.
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as d:
        store.save(_os.path.join(d, "ps"))
        restored = ParamStore.load(_os.path.join(d, "ps"), CFG)
        _, meta2, found2 = restored.lookup(["h0"])
        assert found2.all()
        assert meta2.ds_start.dtype == np.float64
        assert float(meta2.ds_start[0]) == ds_start
    # The warm-start time offset between two windows 1h apart must come out
    # to 1h with sub-second accuracy (float32 meta is ~5 min off here).
    start_new = ds_start + 1.0 / 24.0
    b = (start_new - float(meta.ds_start[0])) / float(meta.ds_span[0])
    assert abs(b * ds_span - 1.0 / 24.0) < 1e-9


def test_param_store_persistence(tmp_path):
    sf = StreamingForecaster(CFG, SolverConfig(max_iters=40), backend="tpu")
    sf.run(InMemorySource([_series_df(150, "x", 9)]))
    path = str(tmp_path / "store")
    sf.store.save(path)
    restored = ParamStore.load(path, CFG)
    assert "x" in restored
    theta, _, found = restored.lookup(["x"])
    np.testing.assert_allclose(
        np.asarray(theta[0]), np.asarray(sf.store.lookup(["x"])[0][0])
    )


def test_warmstart_transfer_window_slide():
    """When the history window slides (old changepoints fall before the new
    window start), the transferred params must reproduce the same data-unit
    trend on the overlapping days."""
    from tsspark_tpu.models.prophet import predict as predict_mod

    df = _series_df(500, seed=7)
    model = ProphetModel(CFG, SolverConfig(max_iters=300))
    old = model.fit(
        df.ds.to_numpy()[:400], jnp.asarray(df.y.to_numpy()[None, :400])
    )
    # New window: days 150..499 (start slides forward 150, end extends 100).
    ds_new = df.ds.to_numpy()[150:]
    _, meta_new = prepare_fit_data(
        jnp.asarray(ds_new), jnp.asarray(df.y.to_numpy()[None, 150:]), CFG
    )
    warm = transfer_theta(old.theta, old.meta, meta_new, CFG)

    overlap = df.ds.to_numpy()[150:400]
    fc_old = predict_mod.forecast(
        old.theta,
        predict_mod.prepare_predict_data(jnp.asarray(overlap), old.meta, CFG),
        old.meta, CFG,
    )
    fc_new = predict_mod.forecast(
        warm,
        predict_mod.prepare_predict_data(jnp.asarray(overlap), meta_new, CFG),
        meta_new, CFG,
    )
    # Trend (data units) must carry over; tolerance covers changepoint-grid
    # quantization between the two windows.
    err = np.abs(np.asarray(fc_old["trend"] - fc_new["trend"]))
    scale = float(np.abs(np.asarray(fc_old["trend"])).mean())
    assert err.max() / scale < 0.05, err.max() / scale


def test_crash_replay_between_refit_and_commit_is_idempotent():
    """Driver death in the at-least-once window (BASELINE.json:11).

    The driver commits offsets only AFTER a refit lands in the param store
    (driver.run / source.commit), so a crash between the two makes the
    broker re-deliver the uncommitted batch on restart.  The replayed
    application must be idempotent: history appends dedup by (series, ds)
    so rows are counted once, and the refit — warm-started from the params
    the crashed refit already stored — lands on the same parameters."""
    df = _series_df(240, "r0", seed=7)
    rows = df.to_dict("records")

    consumer = _FakeConsumer([rows[:200], rows[200:240]])
    src = KafkaSource(consumer=consumer, max_records=500)
    store = ParamStore(CFG)
    sf = StreamingForecaster(
        CFG, SolverConfig(max_iters=40), backend="tpu", store=store
    )
    b0 = src.poll()
    sf.process(b0)
    src.commit()                       # batch 0 durably applied
    b1 = src.poll()
    sf.process(b1)                     # refit landed in the store...
    # ... and the driver dies HERE: no src.commit() for batch 1.
    assert consumer.events.count("commit") == 1
    theta_crash, _, found = store.lookup(["r0"])
    assert bool(found.all())
    fc_crash = sf.forecast(["r0"], horizon=14, num_samples=0)
    code = sf._codes(["r0"])
    n_hist = len(sf._hist.union_grid(code))
    assert n_hist == 240

    # Restarted poll loop: the broker re-delivers everything after the
    # last committed offset — batch 1 again, then end-of-stream.
    replay = _FakeConsumer([rows[200:240], []])
    stats = sf.run(KafkaSource(consumer=replay, max_records=500))

    # Second application committed, and idempotent:
    assert replay.events.count("commit") == 1
    # (a) rows counted once — the dedup absorbed all 40 replayed rows;
    assert len(sf._hist.union_grid(code)) == 240
    # (b) the refit reproduces the same parameters it already stored.
    theta_replay, _, _ = store.lookup(["r0"])
    # Warm-started at its own stored optimum, the replayed refit may wander
    # the posterior's near-flat valley (loss moves ~1e-4 nats while theta
    # shifts ~1e-2), so raw-theta bit-stability is the wrong contract; the
    # MODEL must not drift: replayed-state forecasts match the crashed
    # state's, and theta stays in the same neighborhood.  Anything beyond
    # that would mean replays compound (dedup failed / rows double-counted).
    np.testing.assert_allclose(
        np.asarray(theta_replay), np.asarray(theta_crash),
        rtol=0, atol=0.05,
    )
    fc_replay = sf.forecast(["r0"], horizon=14, num_samples=0)
    np.testing.assert_allclose(
        fc_replay.yhat.to_numpy(), fc_crash.yhat.to_numpy(),
        rtol=0, atol=0.05,  # y-scale ~15; forecast drift < 0.4%
    )
    # (c) a never-crashed driver over the same stream agrees too.
    clean_consumer = _FakeConsumer([rows[:200], rows[200:240], []])
    sf_clean = StreamingForecaster(
        CFG, SolverConfig(max_iters=40), backend="tpu"
    )
    sf_clean.run(KafkaSource(consumer=clean_consumer, max_records=500))
    theta_clean, _, _ = sf_clean.store.lookup(["r0"])
    fc_clean = sf_clean.forecast(["r0"], horizon=14, num_samples=0)
    np.testing.assert_allclose(
        fc_replay.yhat.to_numpy(), fc_clean.yhat.to_numpy(),
        rtol=0, atol=0.05,
    )
