"""Streaming incremental refit (eval config 5 analog): param store,
checkpoint round-trip, warm-start space transfer, and the micro-batch loop."""

import numpy as np
import pandas as pd
import jax.numpy as jnp
import pytest

from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.data import datasets
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.loss import neg_log_posterior
from tsspark_tpu.models.prophet.model import ProphetModel
from tsspark_tpu.models.prophet.params import init_theta
from tsspark_tpu.streaming.driver import StreamingForecaster
from tsspark_tpu.streaming.source import InMemorySource, KafkaSource
from tsspark_tpu.streaming.state import ParamStore
from tsspark_tpu.streaming.warmstart import transfer_theta
from tsspark_tpu.utils import checkpoint as ckpt

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=5
)


def _series_df(n_days, sid="s0", seed=0, start_day=0):
    rng = np.random.default_rng(seed)
    t = np.arange(start_day, start_day + n_days, dtype=float)
    y = 10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7) + rng.normal(0, 0.1, n_days)
    return pd.DataFrame({"series_id": sid, "ds": t, "y": y})


def test_checkpoint_roundtrip(tmp_path):
    model = ProphetModel(CFG, SolverConfig(max_iters=60))
    df = _series_df(200)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    path = str(tmp_path / "ck")
    ckpt.save_state(path, state, CFG, series_ids=np.asarray(["s0"]))
    loaded, ids = ckpt.load_state(path, CFG)
    np.testing.assert_allclose(
        np.asarray(loaded.theta), np.asarray(state.theta), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(loaded.meta.y_scale), np.asarray(state.meta.y_scale)
    )
    assert list(ids) == ["s0"]


def test_checkpoint_fingerprint_mismatch(tmp_path):
    model = ProphetModel(CFG, SolverConfig(max_iters=30))
    df = _series_df(100)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    path = str(tmp_path / "ck")
    ckpt.save_state(path, state, CFG)
    other = ProphetConfig(seasonalities=(), n_changepoints=5)
    with pytest.raises(ValueError):
        ckpt.load_state(path, other)


def test_param_store_lookup_mask():
    store = ParamStore(CFG)
    model = ProphetModel(CFG, SolverConfig(max_iters=30))
    df = _series_df(100)
    state = model.fit(df.ds.to_numpy(), jnp.asarray(df.y.to_numpy()[None, :]))
    store.update(["s0"], state)
    theta, meta, found = store.lookup(["s0", "unknown"])
    assert found.tolist() == [True, False]
    np.testing.assert_allclose(np.asarray(theta[0]), np.asarray(state.theta[0]))
    assert "s0" in store and "unknown" not in store


def test_warmstart_transfer_preserves_fit():
    """Transfer old params onto extended data: the transferred theta must
    score a loss close to a fresh converged fit on the new data — i.e. the
    space mapping is right, not just 'some init'."""
    df_old = _series_df(400)
    df_new = _series_df(460)  # 60 more days: scalings + changepoints move
    model = ProphetModel(CFG, SolverConfig(max_iters=300))

    old = model.fit(df_old.ds.to_numpy(), jnp.asarray(df_old.y.to_numpy()[None, :]))
    data_new, meta_new = prepare_fit_data(
        jnp.asarray(df_new.ds.to_numpy()),
        jnp.asarray(df_new.y.to_numpy()[None, :]), CFG,
    )
    warm = transfer_theta(old.theta, old.meta, meta_new, CFG)
    fresh = model.fit(df_new.ds.to_numpy(), jnp.asarray(df_new.y.to_numpy()[None, :]))

    f_warm = float(neg_log_posterior(warm, data_new, CFG)[0])
    f_fresh = float(fresh.loss[0])
    f_cold = float(
        neg_log_posterior(
            init_theta(CFG, data_new.y, data_new.mask, data_new.t),
            data_new, CFG,
        )[0]
    )
    # Warm init must be far closer to the optimum than the cold init.
    assert f_warm < f_cold - 0.5 * (f_cold - f_fresh), (f_warm, f_cold, f_fresh)


def test_streaming_loop_warm_starts_and_improves():
    df_full = _series_df(360, seed=3)
    batches = [
        df_full.iloc[:300],
        df_full.iloc[300:330],
        df_full.iloc[330:360],
    ]
    sf = StreamingForecaster(
        CFG, SolverConfig(max_iters=60), backend="tpu", chunk_size=1
    )
    stats = sf.run(InMemorySource(batches))
    assert stats.micro_batches == 3
    assert stats.cold_starts == 1      # first sight of s0
    assert stats.warm_starts == 2      # subsequent refits warm-start
    fc = sf.forecast(["s0"], horizon=14, num_samples=0)
    assert len(fc) == 14
    t = fc.ds.to_numpy()
    want = 10 + 0.02 * t + 1.5 * np.sin(2 * np.pi * t / 7)
    assert np.abs(fc.yhat.to_numpy() - want).mean() < 0.5


def test_streaming_multi_series_and_new_series_midstream():
    b1 = pd.concat([_series_df(120, "a", 1), _series_df(120, "b", 2)])
    b2 = pd.concat([
        _series_df(30, "a", 1, start_day=120),
        _series_df(150, "c", 4),  # new series appears mid-stream
    ])
    sf = StreamingForecaster(CFG, SolverConfig(max_iters=40), backend="tpu")
    sf.run(InMemorySource([b1, b2]))
    assert len(sf.store) == 3
    fc = sf.forecast(["a", "b", "c"], horizon=7, num_samples=0)
    assert set(fc.series_id.unique()) == {"a", "b", "c"}
    with pytest.raises(KeyError):
        sf.forecast(["nope"], horizon=3)


def test_kafka_source_gated():
    with pytest.raises(ImportError):
        KafkaSource("topic")


def test_param_store_persistence(tmp_path):
    sf = StreamingForecaster(CFG, SolverConfig(max_iters=40), backend="tpu")
    sf.run(InMemorySource([_series_df(150, "x", 9)]))
    path = str(tmp_path / "store")
    sf.store.save(path)
    restored = ParamStore.load(path, CFG)
    assert "x" in restored
    theta, _, found = restored.lookup(["x"])
    np.testing.assert_allclose(
        np.asarray(theta[0]), np.asarray(sf.store.lookup(["x"])[0][0])
    )


def test_warmstart_transfer_window_slide():
    """When the history window slides (old changepoints fall before the new
    window start), the transferred params must reproduce the same data-unit
    trend on the overlapping days."""
    from tsspark_tpu.models.prophet import predict as predict_mod

    df = _series_df(500, seed=7)
    model = ProphetModel(CFG, SolverConfig(max_iters=300))
    old = model.fit(
        df.ds.to_numpy()[:400], jnp.asarray(df.y.to_numpy()[None, :400])
    )
    # New window: days 150..499 (start slides forward 150, end extends 100).
    ds_new = df.ds.to_numpy()[150:]
    _, meta_new = prepare_fit_data(
        jnp.asarray(ds_new), jnp.asarray(df.y.to_numpy()[None, 150:]), CFG
    )
    warm = transfer_theta(old.theta, old.meta, meta_new, CFG)

    overlap = df.ds.to_numpy()[150:400]
    fc_old = predict_mod.forecast(
        old.theta,
        predict_mod.prepare_predict_data(jnp.asarray(overlap), old.meta, CFG),
        old.meta, CFG,
    )
    fc_new = predict_mod.forecast(
        warm,
        predict_mod.prepare_predict_data(jnp.asarray(overlap), meta_new, CFG),
        meta_new, CFG,
    )
    # Trend (data units) must carry over; tolerance covers changepoint-grid
    # quantization between the two windows.
    err = np.abs(np.asarray(fc_old["trend"] - fc_new["trend"]))
    scale = float(np.abs(np.asarray(fc_old["trend"])).mean())
    assert err.max() / scale < 0.05, err.max() / scale
