"""The storage fault domain (tsspark_tpu.io, docs/RESILIENCE.md
"Storage fault domain"): the durable-I/O choke point, typed storage
errors, the injectable io_* fault points, the DiskBudget accountant,
and the disk-pressure degradation ladder."""

import errno
import json
import os

import numpy as np
import pytest

from tsspark_tpu.io import (
    BackpressureError,
    DiskFullError,
    DiskIOError,
    ReadOnlyError,
    ShortWriteError,
    StorageError,
    append_line,
    atomic_write,
    atomic_write_text,
    attach_array,
    classify_os_error,
    current_state,
    gate_ingest,
    hardlink,
    is_missing,
    link_or_copy,
    open_memmap,
    reraise_classified,
    stale_serving,
)
from tsspark_tpu.io import budget as iobudget
from tsspark_tpu.io.ladder import (
    LADDER_STATES,
    DegradationLadder,
)
from tsspark_tpu.plane import protocol as planeproto
from tsspark_tpu.resilience import faults


# ---------------------------------------------------------------------------
# typed storage errors
# ---------------------------------------------------------------------------


def test_classify_os_error_maps_errnos_to_typed_subclasses():
    """A failing disk must never read as a missing file: each storage
    errno maps to a typed subclass that is STILL an OSError (existing
    except-OSError sites keep working), and unknown errnos pass
    through unwrapped."""
    cases = [
        (errno.ENOSPC, DiskFullError),
        (errno.EDQUOT, DiskFullError),
        (errno.EIO, DiskIOError),
        (errno.EROFS, ReadOnlyError),
    ]
    for num, cls in cases:
        e = OSError(num, "x")
        ce = classify_os_error(e)
        assert type(ce) is cls
        assert isinstance(ce, StorageError) and isinstance(ce, OSError)
        assert ce.errno == num
    plain = OSError(errno.EACCES, "x")
    assert classify_os_error(plain) is plain


def test_is_missing_is_narrow():
    assert is_missing(OSError(errno.ENOENT, "x"))
    assert is_missing(OSError(errno.ENOTDIR, "x"))
    assert not is_missing(OSError(errno.EIO, "x"))
    assert not is_missing(OSError(errno.ENOSPC, "x"))


def test_reraise_classified_chains_cause():
    with pytest.raises(DiskIOError) as ei:
        try:
            raise OSError(errno.EIO, "the disk is lying")
        except OSError as e:
            reraise_classified(e)
    assert isinstance(ei.value.__cause__, OSError)
    with pytest.raises(OSError) as ei2:
        try:
            raise OSError(errno.EACCES, "not a storage errno")
        except OSError as e:
            reraise_classified(e)
    assert type(ei2.value) is PermissionError  # unwrapped, not StorageError


def test_backpressure_error_is_not_a_storage_error():
    """Backpressure is flow control, not disk failure: an upstream
    catching OSError to classify disk trouble must NOT swallow the
    pause signal."""
    e = BackpressureError("pause_ingest", 0.07)
    assert not isinstance(e, OSError)
    assert e.state == "pause_ingest" and e.headroom == 0.07


# ---------------------------------------------------------------------------
# durable atomic writes + injected storage faults
# ---------------------------------------------------------------------------


def test_atomic_write_roundtrip_and_no_temp_residue(tmp_path):
    p = str(tmp_path / "a.json")
    atomic_write(p, lambda fh: json.dump({"v": 1}, fh), mode="w")
    with open(p) as fh:
        assert json.load(fh) == {"v": 1}
    atomic_write_text(p, "plain")
    with open(p) as fh:
        assert fh.read() == "plain"
    assert os.listdir(tmp_path) == ["a.json"]  # no stray temps


def test_injected_enospc_raises_typed_and_cleans_temp(tmp_path,
                                                      monkeypatch):
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("io_write", mode="enospc", path="victim")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    p = str(tmp_path / "victim.json")
    with pytest.raises(DiskFullError) as ei:
        atomic_write_text(p, "never lands")
    assert ei.value.errno == errno.ENOSPC
    assert not os.path.exists(p)
    assert not [n for n in os.listdir(tmp_path) if "victim" in n]
    # Path scoping: an unscoped sibling write is untouched.
    atomic_write_text(str(tmp_path / "other.json"), "lands")


def test_injected_eio_on_rename_fails_before_publish(tmp_path,
                                                     monkeypatch):
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("io_rename", mode="eio")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    p = str(tmp_path / "b.json")
    with pytest.raises(DiskIOError):
        atomic_write_text(p, "x")
    assert not os.path.exists(p)  # the rename never happened


def test_short_write_lands_torn_and_only_crc_catches_it(tmp_path,
                                                        monkeypatch):
    """The nastiest storage fault: the truncated payload PUBLISHES as
    success (an unchecked write(2) return), so only the CRC-sentinel
    read path stands between it and a served forecast."""
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("io_write", mode="shortwrite", path="col_x",
              fraction=0.4)
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    arr = np.arange(256, dtype=np.float32).reshape(16, 16)
    d = str(tmp_path / "plane")
    os.makedirs(d)
    sent = {"shards": [[0, 16, planeproto.shard_crcs({"x": arr})]]}
    planeproto.publish_plane(
        d, "spec.json", {"n": 16}, {"x": arr},
        lambda vd, name: os.path.join(vd, f"col_{name}.npy"),
        "ok.json", sent,
    )  # reports success — the tear is silent
    monkeypatch.delenv(faults.ENV_VAR)
    assert os.path.getsize(os.path.join(d, "col_x.npy")) < arr.nbytes
    caught = False
    try:
        col = planeproto.attach_column(os.path.join(d, "col_x.npy"))
        caught = planeproto.verify_crcs(
            {"x": np.asarray(col)}, sent["shards"]) is not None
    except (ValueError, OSError):
        caught = True  # the attach itself refused the torn payload
    assert caught


def test_lost_fsync_records_and_replays_pre_write_state(tmp_path,
                                                        monkeypatch):
    """A rename that lived only in the page cache: the caller saw
    success, the crash (exit-mode firing) rolls the file back to its
    pre-write bytes before dying."""
    p = str(tmp_path / "m.json")
    atomic_write_text(p, "old")  # lands before any fault is armed
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("io_fsync", mode="lost_fsync", path="m.json")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    atomic_write_text(p, "new")  # caller sees success
    with open(p) as fh:
        assert fh.read() == "new"
    replayed = faults._replay_lost_fsyncs(plan.state_dir)
    assert replayed == 1
    with open(p) as fh:
        assert fh.read() == "old"  # the crash lost the rename


def test_link_or_copy_degrades_only_for_capability_errnos(tmp_path,
                                                          monkeypatch):
    src = str(tmp_path / "src")
    atomic_write_text(src, "payload")
    dst = str(tmp_path / "dst")
    link_or_copy(src, dst)
    assert os.path.samefile(src, dst)
    # An injected EIO at io_link must PROPAGATE (typed), never be
    # silently healed by the copy fallback.
    plan = faults.FaultPlan(state_dir=str(tmp_path / "faults"))
    plan.fail("io_link", mode="eio")
    monkeypatch.setenv(faults.ENV_VAR, plan.to_env())
    with pytest.raises(DiskIOError):
        link_or_copy(src, str(tmp_path / "dst2"))
    assert not os.path.exists(str(tmp_path / "dst2"))


def test_append_line_and_memmap_helpers(tmp_path):
    log = str(tmp_path / "log.jsonl")
    append_line(log, json.dumps({"i": 1}))
    append_line(log, json.dumps({"i": 2}))
    with open(log) as fh:
        assert [json.loads(x)["i"] for x in fh] == [1, 2]
    p = str(tmp_path / "c.npy")
    mm = open_memmap(p, mode="w+", dtype=np.float32, shape=(4, 3))
    mm[...] = 7.0
    mm.flush()
    del mm
    back = attach_array(p)
    assert back.shape == (4, 3) and float(back[0, 0]) == 7.0
    hardlink(p, str(tmp_path / "c2.npy"))
    assert os.path.samefile(p, str(tmp_path / "c2.npy"))


# ---------------------------------------------------------------------------
# DiskBudget
# ---------------------------------------------------------------------------


def test_disk_budget_check_refuses_overrun_with_enospc(tmp_path):
    root = str(tmp_path / "root")
    os.makedirs(root)
    atomic_write_text(os.path.join(root, "f"), "x" * 4096)
    b = iobudget.DiskBudget(root, budget_bytes=5000)
    assert b.governs(os.path.join(root, "sub", "g"))
    assert not b.governs(str(tmp_path / "elsewhere"))
    b.check(0)  # under budget: fine
    with pytest.raises(DiskFullError) as ei:
        b.check(10_000, what="next-version")
    assert ei.value.errno == errno.ENOSPC
    assert "next-version" in str(ei.value)
    assert 0.0 <= b.headroom() <= 1.0


def test_env_armed_budget_gates_atomic_write(tmp_path, monkeypatch):
    root = str(tmp_path / "gov")
    os.makedirs(root)
    atomic_write_text(os.path.join(root, "seed"), "x" * 2048)
    monkeypatch.setenv(iobudget.ENV_BUDGET_ROOT, root)
    monkeypatch.setenv(iobudget.ENV_BUDGET_BYTES, "1024")
    with pytest.raises(DiskFullError):
        atomic_write_text(os.path.join(root, "more"), "y")
    # Outside the governed root the gate does not apply.
    atomic_write_text(str(tmp_path / "outside"), "y")


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------


class _FakeBudget:
    """Duck-typed budget with a settable headroom dial."""

    root = "/fake"
    budget_bytes = 1

    def __init__(self, h=1.0):
        self.h = h

    def headroom(self):
        return self.h


def test_ladder_descends_in_order_and_improves_with_hysteresis():
    b = _FakeBudget(1.0)
    lad = DegradationLadder(b, hysteresis=0.02)
    assert lad.state() == "normal"
    assert lad.allows("speculate") and lad.allows("ingest")
    for h, want in ((0.39, "shed_spec"), (0.24, "reap"),
                    (0.09, "pause_ingest"), (0.04, "stale_serve")):
        b.h = h
        assert lad.state() == want
    assert not lad.allows("speculate") and not lad.allows("ingest")
    assert lad.should_reap() and lad.stale_serve()
    # Improving: clearing the ENTRY threshold is not enough...
    b.h = 0.051
    assert lad.state() == "stale_serve"  # within hysteresis: hold
    # ...until the margin clears; then the state re-ranks from headroom.
    b.h = 0.20
    assert lad.state() == "reap"
    b.h = 0.45
    assert lad.state() == "normal"
    with pytest.raises(ValueError):
        lad.allows("dance")


def test_ladder_constructor_validates_thresholds():
    with pytest.raises(ValueError):
        DegradationLadder(_FakeBudget(), thresholds=(0.4, 0.25))
    with pytest.raises(ValueError):
        DegradationLadder(_FakeBudget(),
                          thresholds=(0.05, 0.10, 0.25, 0.40))


def test_module_helpers_unarmed_are_normal_and_free(monkeypatch):
    monkeypatch.delenv(iobudget.ENV_BUDGET_BYTES, raising=False)
    monkeypatch.delenv(iobudget.ENV_BUDGET_ROOT, raising=False)
    assert current_state("/anywhere") == "normal"
    gate_ingest("/anywhere")  # no-op, no raise
    assert stale_serving("/anywhere") is False


def test_gate_ingest_raises_backpressure_under_pressure(tmp_path,
                                                        monkeypatch):
    root = str(tmp_path / "press")
    os.makedirs(root)
    atomic_write_text(os.path.join(root, "bulk"), "z" * 8192)
    monkeypatch.setenv(iobudget.ENV_BUDGET_ROOT, root)
    monkeypatch.setenv(iobudget.ENV_BUDGET_BYTES, "8300")
    assert current_state(root) == "stale_serve"
    with pytest.raises(BackpressureError) as ei:
        gate_ingest(root)
    assert ei.value.state in LADDER_STATES
    assert ei.value.headroom < 0.10
    assert stale_serving(root) is True
    # An UNGOVERNED root is untouched: pressure on one storage root
    # must not pause an unrelated one.
    assert current_state(str(tmp_path / "other")) == "normal"
    gate_ingest(str(tmp_path / "other"))


# ---------------------------------------------------------------------------
# plane protocol library
# ---------------------------------------------------------------------------


def test_publish_plane_roundtrip_spec_columns_sentinel(tmp_path):
    d = str(tmp_path / "v1")
    os.makedirs(d)
    cols = {"theta": np.arange(12, dtype=np.float32).reshape(4, 3),
            "step": np.ones(4, np.float64)}
    shards = [[lo, hi, planeproto.shard_crcs(cols, lo, hi)]
              for lo, hi in planeproto.shard_ranges(4, 2)]
    planeproto.publish_plane(
        d, "spec.json", {"n_series": 4}, cols,
        lambda vd, name: os.path.join(vd, f"col_{name}.npy"),
        "ok.json", {"shards": shards},
    )
    spec = planeproto.read_json(os.path.join(d, "spec.json"))
    sent = planeproto.read_json(os.path.join(d, "ok.json"))
    assert spec["n_series"] == 4 and sent["shards"]
    back = {k: np.asarray(planeproto.attach_column(
        os.path.join(d, f"col_{k}.npy"))) for k in cols}
    assert planeproto.verify_crcs(back, sent["shards"]) is None
    back["theta"] = back["theta"].copy()
    back["theta"][1, 1] += 1.0
    bad = planeproto.verify_crcs(back, sent["shards"])
    assert bad is not None and bad[0] == "theta" and bad[1:] == (0, 2)


def test_read_json_absent_and_torn_read_as_none(tmp_path):
    assert planeproto.read_json(str(tmp_path / "nope.json")) is None
    torn = str(tmp_path / "torn.json")
    with open(torn, "w") as fh:
        fh.write('{"half":')
    assert planeproto.read_json(torn) is None
