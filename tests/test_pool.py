"""Serve replica pool (tsspark_tpu/serve/pool.py, docs/SERVING.md
"Replica pool & failure domains"): shard routing + bitwise parity
through replica processes, failover + respawn after SIGKILL, lease
fencing of a stalled-and-replaced zombie, concurrent activations
against a live pool, the ahead-of-time materializer, and the tier-1
pool smoke storm (replica-kill / split-brain-activation / front-crash
plus the data-plane classes)."""

import json
import os
import signal
import socket
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu import orchestrate
from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.serve import (
    ForecastCache,
    ParamRegistry,
    PredictionEngine,
    ReplicaPool,
    shard_of,
)
from tsspark_tpu.serve.pool import _send_line

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=3
)
SOLVER = SolverConfig(max_iters=25)


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(0)
    t = np.arange(150.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (6, 150)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    return backend, state, [f"s{i}" for i in range(6)]


@pytest.fixture(scope="module")
def pool_env(fitted, tmp_path_factory):
    """One live 2-replica pool shared by the module's tests (replica
    spawns are the slow part; tests restore any replica they kill)."""
    backend, state, ids = fitted
    root = tmp_path_factory.mktemp("pool_env")
    registry = ParamRegistry(str(root / "registry"), CFG)
    registry.publish(state, ids, step=np.ones(len(ids)))
    pool = ReplicaPool(str(root / "pool"), registry.root, n_replicas=2,
                       heartbeat_s=0.2, breaker_reset_s=0.3)
    pool.start()
    yield backend, state, ids, registry, pool
    pool.stop()


def _respawn(pool, slot, timeout_s=60.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if slot in pool.ensure_alive():
            return True
        time.sleep(0.2)
    return False


def test_pool_routes_by_shard_and_matches_direct_predict(pool_env):
    """Forecasts served through a replica process are bitwise the
    direct backend.predict (the engine parity pin survives the wire)."""
    backend, state, ids, registry, pool = pool_env
    resp = pool.forecast(["s0", "s3"], 7)
    assert resp["ok"] and resp["replica"] == shard_of("s0", 2)
    snap = registry.load(resp["version"])
    idx, _ = snap.rows(["s0", "s3"])
    sub, step = snap.take(idx)
    last = np.asarray(sub.meta.ds_start + sub.meta.ds_span, np.float64)
    grid = last[:, None] + step[:, None] * np.arange(1, 8)
    direct = backend.predict(sub, grid, num_samples=0)
    np.testing.assert_array_equal(np.asarray(resp["ds"]), grid)
    for k, v in direct.items():
        np.testing.assert_array_equal(
            np.asarray(resp[k]), np.asarray(v), err_msg=k
        )
    # Structured errors cross the wire too.
    bad = pool.forecast(["ghost"], 7)
    assert not bad["ok"] and bad["error"]["reason"] == "unknown-series"


def test_failover_then_respawn_resumes_same_shard(pool_env):
    """ISSUE 10 satellite: SIGKILL a replica — requests for its shard
    keys are served by the sibling with zero failures, and the
    respawned process resumes the same shard keys at the active
    version."""
    backend, state, ids, registry, pool = pool_env
    victim = shard_of(ids[0], 2)
    sid = next(s for s in ids if shard_of(s, 2) == victim)
    pid0 = pool.replicas[victim].pid
    failovers0 = pool.failovers
    os.kill(pid0, signal.SIGKILL)
    resp = pool.forecast([sid], 7)  # in-flight failover, not an error
    assert resp["ok"] and resp["replica"] != victim
    assert pool.failovers > failovers0
    assert _respawn(pool, victim)
    resp2 = pool.forecast([sid], 7)
    assert resp2["ok"] and resp2["replica"] == victim
    assert resp2["version"] == registry.active_version()
    assert pool.replicas[victim].pid != pid0
    assert pool.wrong_version == 0


def test_concurrent_activates_from_two_publishers(pool_env):
    """ISSUE 10 satellite: two publishers activate different versions
    against the live pool concurrently (registry flock + drain
    interaction) — the pool converges on the registry's final active
    pointer with zero wrong-version responses."""
    backend, state, ids, registry, pool = pool_env
    base = registry.active_version()
    va = registry.publish(state._replace(theta=state.theta * 1.003),
                          ids, step=np.ones(len(ids)), activate=False)
    vb = registry.publish(state._replace(theta=state.theta * 1.007),
                          ids, step=np.ones(len(ids)), activate=False)
    errs = []

    def flip(v):
        try:
            pool.activate(v, hot_series=ids, horizons=(7,))
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    threads = [threading.Thread(target=flip, args=(v,))
               for v in (va, vb)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    final = registry.active_version()
    assert final in (va, vb) and final != base
    pool.expected_version = final  # a real front re-reads on mismatch
    for sid in ids:
        resp = pool.forecast([sid], 7)
        assert resp["ok"] and resp["version"] == final
    assert pool.wrong_version == 0


def test_activate_flip_serves_from_materialized_cache(pool_env):
    """The flip lands on a warm cache: the first post-flip request for
    a materialized series is a cache hit on every replica."""
    backend, state, ids, registry, pool = pool_env
    v = registry.publish(state._replace(theta=state.theta * 1.011),
                         ids, step=np.ones(len(ids)), activate=False)
    pool.activate(v, hot_series=ids, horizons=(7,))
    for sid in ids[:4]:
        resp = pool.forecast([sid], 7)
        assert resp["ok"] and resp["version"] == v
        assert resp["from_cache"] == 1, (sid, resp.get("from_cache"))


def test_pool_stats_and_metrics_expose_per_replica_shed(pool_env):
    """ISSUE 10 satellite: per-replica shed counts ride the stats and
    the Prometheus aggregation (tsspark_pool_replica_shed{replica=k}),
    and the engine's retry-after gauge is exported."""
    backend, state, ids, registry, pool = pool_env
    st = pool.stats()
    assert set(st["replicas"]) == {"0", "1"}
    for rep in st["replicas"].values():
        assert "shed" in rep and "latency_ms" in rep
    prom = pool.prometheus()
    assert "tsspark_pool_replica_shed" in prom
    assert 'replica="0"' in prom and 'replica="1"' in prom
    assert "tsspark_serve_retry_after_seconds" in prom
    assert "tsspark_pool_replicas_alive" in prom
    # Storage fault domain: with no disk budget armed the ladder reads
    # normal and nothing is flagged stale.
    assert st["disk_ladder"] == "normal"
    assert st["stale_serve"] is False


def test_zombie_replica_is_fenced_after_lease_steal(pool_env):
    """Split-brain unit: a replica stalls (SIGSTOP), its slot lease
    expires and is stolen; revived, it must answer the structured
    ``fenced`` refusal — never data at any version."""
    backend, state, ids, registry, pool = pool_env
    slot = 1
    info = pool.replicas[slot]
    zpid = info.pid
    zsock = info.socket_path
    os.kill(zpid, signal.SIGSTOP)
    try:
        # Wait out the lease TTL, then steal the slot like a
        # replacement replica would (claim succeeds only once stale).
        deadline = time.time() + 4.0 * pool.lease_ttl_s
        stolen = False
        while time.time() < deadline:
            if orchestrate.claim_lease(pool.pool_dir, slot, slot + 1,
                                       "test-thief",
                                       ttl_s=pool.lease_ttl_s):
                stolen = True
                break
            time.sleep(0.1)
        assert stolen
    finally:
        os.kill(zpid, signal.SIGCONT)
    time.sleep(0.5)  # one heartbeat cycle: the zombie notices
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(15.0)
    s.connect(zsock)
    _send_line(s, {"id": "z", "series_ids": [ids[0]], "horizon": 5,
                   "expect_version": registry.active_version()})
    buf = b""
    while b"\n" not in buf:
        chunk = s.recv(65536)
        assert chunk, "zombie closed without the structured refusal"
        buf += chunk
    s.close()
    resp = json.loads(buf.split(b"\n", 1)[0])
    assert not resp["ok"]
    assert resp["error"]["reason"] == "fenced"
    # Restore the slot for any later test: drop the thief's lease and
    # respawn a healthy replica (the zombie exits on its grace timer).
    orchestrate.release_lease(pool.pool_dir, slot, slot + 1,
                              "test-thief")
    try:
        os.kill(zpid, signal.SIGKILL)
    except OSError:
        pass
    assert _respawn(pool, slot)
    assert pool.forecast([next(s2 for s2 in ids
                               if shard_of(s2, 2) == slot)], 5)["ok"]


def test_engine_prefetch_and_materialize_warm_flip(tmp_path, fitted):
    """Engine-level materializer: forecasts computed for a NOT-yet-
    active version survive its activation (warm-window cache gate) and
    the activation itself reuses the prefetched snapshot — the first
    post-flip request dispatches nothing."""
    backend, state, ids = fitted
    reg = ParamRegistry(str(tmp_path / "registry"), CFG)
    reg.publish(state, ids, step=np.ones(len(ids)))
    eng = PredictionEngine(reg, cache=ForecastCache(capacity=64))
    assert eng.forecast(["s0"], 7).version == 1
    v2 = reg.publish(state._replace(theta=state.theta * 1.01), ids,
                     step=np.ones(len(ids)), activate=False)
    warmed = eng.materialize(ids, [7], version=v2)
    assert warmed == len(ids)
    assert eng.materialize(ids, [7], version=v2) == 0  # idempotent
    # Not yet active: requests still serve v1.
    assert eng.forecast(["s1"], 7).version == 1
    dispatches = eng.stats.dispatches
    reg.activate(v2)
    res = eng.forecast(["s0", "s1"], 7)
    assert res.version == v2 and res.from_cache == 2
    assert eng.stats.dispatches == dispatches  # flip cost zero compute
    # ensure_version soft-fails when the registry is elsewhere.
    assert eng.ensure_version(v2) is True
    assert eng.ensure_version(999) is False


def test_pool_smoke_storm(tmp_path):
    """Tier-1 pool storm (ISSUE 10): replica-kill, front-crash,
    split-brain-activation, plane-torn-shard, and ingest-driver-kill
    ALL GREEN — zero wrong-version responses, zero non-shed failures,
    exactly one lease owner per slot, bitwise-repaired data plane."""
    from tsspark_tpu.chaos import compose, run_storm

    classes = set(compose(0, "pool").by_class())
    assert {"replica-kill", "split-brain-activation", "front-crash",
            "plane-torn-shard", "ingest-driver-kill"} <= classes
    # The full acceptance storm schedules the same classes.
    assert classes <= set(compose(0, "full").by_class())

    report = run_storm(seed=0, profile="pool",
                       scratch=str(tmp_path / "storm"))
    assert report["ok"], report["invariants"]
    inv = report["invariants"]
    assert inv["pool_failover"]["ok"], inv["pool_failover"]
    assert inv["pool_failover"]["counters"]["wrong_version"] == 0
    assert inv["pool_failover"]["counters"]["failed"] == 0
    assert inv["pool_single_owner"]["ok"], inv["pool_single_owner"]
    assert inv["pool_front_reattach"]["ok"]
    assert inv["plane_consistent"]["ok"], inv["plane_consistent"]
    assert inv["plane_consistent"]["torn_detected"]
    assert inv["plane_consistent"]["bitwise_vs_generation"]
    assert inv["recovery_within_budget"]["ok"]
    assert inv["trace_joined"]["ok"], inv["trace_joined"]
    for cls in ("replica-kill", "split-brain-activation",
                "front-crash", "plane-torn-shard",
                "ingest-driver-kill"):
        assert report["mttr_s"].get(cls) is not None, cls
