"""Native ingest/pivot engine: correctness vs numpy semantics, dedup rules,
bounded history, and the threaded large-input path."""

import numpy as np
import pytest

from tsspark_tpu import native


def test_native_compiles_here():
    # The image ships g++; the native path must actually be active (the
    # numpy fallback exists for other machines, not this one).
    assert native.available()


def test_bulk_pivot_matches_numpy_scatter():
    rng = np.random.default_rng(0)
    n, b, t = 200_000, 300, 400  # > threaded threshold
    rows = rng.integers(0, b, n)
    cols = rng.integers(0, t, n)
    vals = rng.normal(size=n)
    got = native.bulk_pivot(rows, cols, vals, b, t)
    want = np.full((b, t), np.nan)
    want[rows, cols] = vals  # numpy fancy assignment is also last-wins
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    np.testing.assert_allclose(np.nan_to_num(got), np.nan_to_num(want))


def test_bulk_pivot_m5_scale_throughput_and_parity():
    """Scale regression guard (round-4 verdict item 8): the native pivot
    must stay bitwise-identical to the numpy fallback AND keep a
    conservative throughput floor at a few-million-row scale.  Measured
    on this 1-core image at the full 30,490 x 1,941 M5 shape (53.3M
    rows): native 15.8M rows/s vs numpy scatter 5.8M rows/s (2.7x) vs
    pandas pivot_table 0.24M rows/s (~67x); peak RSS 3.7 GB.  The floor
    here is 7x under the measured rate so scheduler noise cannot flake
    it, while a real regression (e.g. the threaded path silently
    degrading to per-row python) still trips."""
    import time

    rng = np.random.default_rng(1)
    n, b, t = 4_000_000, 4096, 1024
    rows = rng.integers(0, b, n).astype(np.int64)
    cols = rng.integers(0, t, n).astype(np.int64)
    vals = rng.normal(5, 2, n)
    t0 = time.time()
    got = native.bulk_pivot(rows, cols, vals, b, t)
    dt = time.time() - t0
    want = np.full((b, t), np.nan)
    want[rows, cols] = vals
    fin = np.isfinite(got)
    np.testing.assert_array_equal(fin, np.isfinite(want))
    assert np.array_equal(got[fin], want[fin])
    assert n / dt > 2e6, f"native pivot regressed to {n/dt/1e6:.1f}M rows/s"


def test_bulk_pivot_duplicate_last_wins():
    rows = np.zeros(3, np.int64)
    cols = np.zeros(3, np.int64)
    vals = np.asarray([1.0, 2.0, 3.0])
    out = native.bulk_pivot(rows, cols, vals, 1, 1)
    assert out[0, 0] == 3.0


def test_history_store_sorted_dedup_bounded():
    hs = native.HistoryStore(max_history=4)
    hs.append(
        np.asarray([1, 1, 1, 1, 1, 1], np.int64),
        np.asarray([5.0, 1.0, 3.0, 3.0, 2.0, 4.0]),
        np.asarray([50.0, 10.0, 30.0, 31.0, 20.0, 40.0]),
    )
    # Sorted unique days {1..5} with 3 -> 31 (last wins), trimmed to newest 4.
    assert hs.series_length(1) == 4
    grid = hs.union_grid(np.asarray([1], np.int64))
    np.testing.assert_allclose(grid, [2.0, 3.0, 4.0, 5.0])
    out = hs.materialize(np.asarray([1], np.int64), grid)
    np.testing.assert_allclose(out[0], [20.0, 31.0, 40.0, 50.0])


def test_history_store_incremental_appends():
    hs = native.HistoryStore(max_history=100)
    hs.append(np.asarray([1, 2], np.int64), np.asarray([1.0, 1.0]),
              np.asarray([10.0, 100.0]))
    hs.append(np.asarray([1], np.int64), np.asarray([2.0]), np.asarray([11.0]))
    grid = hs.union_grid(np.asarray([1, 2], np.int64))
    out = hs.materialize(np.asarray([1, 2], np.int64), grid)
    np.testing.assert_allclose(out[0], [10.0, 11.0])
    np.testing.assert_allclose(out[1][0], 100.0)
    assert np.isnan(out[1][1])
    assert len(hs) == 2


def test_history_store_unknown_series_all_nan():
    hs = native.HistoryStore()
    hs.append(np.asarray([1], np.int64), np.asarray([1.0]), np.asarray([1.0]))
    out = hs.materialize(np.asarray([99], np.int64),
                         np.asarray([1.0, 2.0]))
    assert np.isnan(out).all()


def test_python_fallback_parity(monkeypatch):
    """The numpy fallback must agree with the native path row for row."""
    rng = np.random.default_rng(1)
    sids = rng.integers(0, 20, 500)
    days = rng.integers(0, 50, 500).astype(np.float64)
    vals = rng.normal(size=500)

    hs_native = native.HistoryStore(max_history=30)
    hs_native.append(sids, days, vals)

    hs_py = native.HistoryStore.__new__(native.HistoryStore)
    hs_py.max_history = 30
    hs_py._lib = None
    hs_py._py = {}
    hs_py.append(sids, days, vals)

    ids = np.unique(sids)
    grid_n = hs_native.union_grid(ids)
    grid_p = hs_py.union_grid(ids)
    np.testing.assert_allclose(grid_n, grid_p)
    out_n = hs_native.materialize(ids, grid_n)
    out_p = hs_py.materialize(ids, grid_p)
    np.testing.assert_array_equal(np.isnan(out_n), np.isnan(out_p))
    np.testing.assert_allclose(np.nan_to_num(out_n), np.nan_to_num(out_p))


def test_param_table_bulk_roundtrip():
    from tsspark_tpu import native

    t = native.ParamTable(row_dim=6)
    rng = np.random.default_rng(0)
    ids = np.arange(5000, dtype=np.int64)
    rows = rng.normal(0, 1, (5000, 6)).astype(np.float32)
    t.update(ids, rows)
    assert len(t) == 5000

    # overwrite a subset (upsert semantics)
    t.update(ids[:10], np.zeros((10, 6), np.float32))

    probe = np.asarray([3, 7, 9999, 4999, -1], np.int64)
    got, found = t.lookup(probe)
    assert found.tolist() == [True, True, False, True, False]
    np.testing.assert_allclose(got[0], np.zeros(6))
    np.testing.assert_allclose(got[1], np.zeros(6))
    np.testing.assert_allclose(got[3], rows[4999])
    np.testing.assert_allclose(got[2], np.zeros(6))  # miss -> zero-filled

    ids_out, rows_out = t.export()
    assert len(ids_out) == 5000
    # export preserves the updated values
    back = {int(i): r for i, r in zip(ids_out, rows_out)}
    np.testing.assert_allclose(back[4999], rows[4999])
    np.testing.assert_allclose(back[0], np.zeros(6))


def test_param_table_large_threaded_lookup():
    from tsspark_tpu import native

    t = native.ParamTable(row_dim=8)
    n = 20000  # crosses the threaded-gather threshold in the native path
    ids = np.arange(n, dtype=np.int64)
    rows = np.tile(np.arange(8, dtype=np.float32), (n, 1)) + ids[:, None]
    t.update(ids, rows)
    got, found = t.lookup(ids[::-1].copy())
    assert found.all()
    np.testing.assert_allclose(got, rows[::-1])
