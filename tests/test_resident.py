"""Mesh-resident single-program fit path (tsspark_tpu.resident).

The contract under test (ISSUE 11): on the virtual 8-device mesh the
resident path must be BITWISE equal to the chunk-file protocol — full
run and crash-resume-midway — because its waves dispatch the exact
fit_core_packed program with inputs sharded on the series axis only
(per-series math stays shard-local).  A meshless box must degrade to
the file protocol with a single warning.  Satellites: the shard-width
autotuner hook, the path-scoped history workload key, and the
O(shards)-not-O(series) micro-bench for the publish/snapshot hot loops.
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tsspark_tpu import orchestrate, resident  # noqa: E402

STATE_FIELDS = ("theta", "loss", "grad_norm", "converged", "n_iters",
                "status")


def _model_config():
    from tsspark_tpu.config import (
        ProphetConfig, RegressorConfig, SeasonalityConfig,
    )

    return ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", prior_scale=10.0, standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )


def _setup(tmp_path, name, series=96, days=128, max_iters=120):
    from tsspark_tpu.config import SolverConfig
    from tsspark_tpu.data import datasets

    batch = datasets.m5_like(n_series=series, n_days=days)
    dd = tmp_path / name / "data"
    od = tmp_path / name / "out"
    dd.mkdir(parents=True)
    od.mkdir(parents=True)
    np.save(dd / "ds.npy", batch.ds.astype(np.float32))
    np.save(dd / "y.npy", np.nan_to_num(batch.y).astype(np.float32))
    np.save(dd / "mask.npy", batch.mask.astype(np.float32))
    np.save(dd / "reg.npy", batch.regressors.astype(np.float32))
    orchestrate.save_run_config(
        str(od), _model_config(), SolverConfig(max_iters=max_iters)
    )
    return str(dd), str(od)


def _assert_states_bitwise(a, b):
    for f in STATE_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
            err_msg=f,
        )
    for f in a.meta._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a.meta, f)), np.asarray(getattr(b.meta, f)),
            err_msg=f"meta.{f}",
        )


def _fileproto_state(tmp_path, monkeypatch, series=96):
    """The file-protocol reference: one chunk worker run with the HOST
    phase-2 mechanism pinned (the resident path's phase 2 is a host
    gather dispatched sharded, so host is the comparable mechanism —
    the device-resident gather matches only to f32 noise, see
    test_orchestrate.test_phase2_resident_matches_host_path)."""
    dd, od = _setup(tmp_path, "fileproto", series=series)
    monkeypatch.setenv("BENCH_NO_RESIDENT", "1")
    args = argparse.Namespace(
        data=dd, out=od, lo=0, hi=series, chunk=32, segment=0,
        series=series, phase1_iters=6, no_phase1_tune=True, max_ahead=6,
        autotune=False,
    )
    assert orchestrate.fit_worker(args) == 0
    monkeypatch.delenv("BENCH_NO_RESIDENT")
    return orchestrate.load_fit_state(od, series)


def test_resident_bitwise_parity_full_run(tmp_path, monkeypatch):
    """THE parity gate: a full resident run (8 virtual devices, series
    axis only) assembles a FitState bitwise equal to the chunk-file
    protocol's — solver outputs AND scaling meta — through the same
    chunk_*.npz artifacts, with the flush-state artifact proving the
    mesh path ran."""
    import jax

    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    monkeypatch.delenv("TSSPARK_TEST_CRASH_AFTER", raising=False)
    ref = _fileproto_state(tmp_path, monkeypatch)

    dd, od = _setup(tmp_path, "resident")
    out = resident.run_resident(
        data_dir=dd, out_dir=od, series=96, chunk=32, phase1_iters=6,
        no_phase1_tune=True,
    )
    assert out["complete"] and out["fit_path"] == "resident"
    got = orchestrate.load_fit_state(od, 96)
    _assert_states_bitwise(got, ref)
    # Same artifact grid as the file protocol (interchangeable scratch).
    assert sorted(
        os.path.basename(p) for p in glob.glob(od + "/chunk_*.npz")
    ) == ["chunk_000000_000032.npz", "chunk_000032_000064.npz",
          "chunk_000064_000096.npz"]
    assert os.path.exists(os.path.join(od, "phase2_done"))
    with open(os.path.join(od, resident.RESIDENT_STATE_FILE)) as fh:
        st = json.load(fh)
    assert st["path"] == "resident" and st["mesh"] == [8, 1]
    assert st["landed"] == 96
    # times.jsonl rows are stamped with the fit path + shard count.
    with open(os.path.join(od, "times.jsonl")) as fh:
        rows = [json.loads(l) for l in fh if l.strip()]
    waves = [r for r in rows if r.get("path") == "resident"]
    assert len(waves) == 3 and all(r["shards"] == 8 for r in waves)
    assert any(r.get("phase2_mode") == "resident-sharded" for r in rows)


def test_resident_crash_resume_midway_bitwise(tmp_path, monkeypatch):
    """Kill the resident program mid flush-stream (a subprocess child,
    TSSPARK_TEST_CRASH_AFTER=2), resume, and the final assembly is
    STILL bitwise the file protocol's: landed flushes persist through
    the same chunk/lease protocol, the successor claims only the
    missing coverage, and phase 2 patches everything exactly once."""
    ref = _fileproto_state(tmp_path, monkeypatch)

    dd, od = _setup(tmp_path, "resident_crash")
    env = orchestrate._child_env()
    env["TSSPARK_TEST_CRASH_AFTER"] = "2"
    env.pop(  # a parent trace would try to parent spans nowhere
        "TSSPARK_TRACE", None,
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tsspark_tpu.orchestrate", "--_resident",
         "--data", dd, "--out", od, "--series", "96", "--chunk", "32",
         "--phase1-iters", "6", "--no-phase1-tune"],
        env=env, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 17, proc.stderr[-2000:]
    landed = orchestrate.completed_ranges(od)
    assert landed and orchestrate.missing_ranges(landed, 96), \
        "the crash must land mid-run: some coverage, not all"

    monkeypatch.delenv("TSSPARK_TEST_CRASH_AFTER", raising=False)
    out = resident.run_resident(
        data_dir=dd, out_dir=od, series=96, chunk=32, phase1_iters=6,
        no_phase1_tune=True,
    )
    assert out["complete"] and out["fit_path"] == "resident"
    # Exactly once: the resumed coverage tiles [0, 96) disjointly.
    cur = 0
    for lo, hi in sorted(orchestrate.completed_ranges(od)):
        assert lo == cur, f"gap or overlap at {lo} (covered to {cur})"
        cur = hi
    assert cur == 96
    _assert_states_bitwise(orchestrate.load_fit_state(od, 96), ref)


def test_resident_meshless_degrades_with_single_warning(tmp_path,
                                                        monkeypatch):
    """--resident on a meshless box: ONE RuntimeWarning, then the
    chunk-file protocol serves the run (automatic fault-domain
    fallback), with the caller's sizing forwarded."""
    calls = []

    def stub_run_resilient(**kwargs):
        calls.append(kwargs)
        return dict(kwargs.get("state") or {}, complete=True)

    monkeypatch.setattr(resident, "usable_mesh", lambda *a, **k: None)
    monkeypatch.setattr(orchestrate, "run_resilient", stub_run_resilient)
    monkeypatch.setattr(resident, "_MESHLESS_WARNED", False)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        out = resident.run_resident(
            data_dir=str(tmp_path / "d"), out_dir=str(tmp_path / "o"),
            series=64, chunk=16, phase1_iters=6,
        )
        out2 = resident.run_resident(
            data_dir=str(tmp_path / "d"), out_dir=str(tmp_path / "o"),
            series=64, chunk=16, phase1_iters=6,
        )
    meshless = [w for w in rec if "no usable device mesh" in str(w.message)]
    assert len(meshless) == 1, "the degradation warning must fire ONCE"
    assert out["fit_path"] == "fileproto" and out["complete"]
    assert out2["fit_path"] == "fileproto"
    assert len(calls) == 2
    assert calls[0]["series"] == 64 and calls[0]["chunk"] == 16
    assert calls[0]["phase1_iters"] == 6


def test_autotuner_shard_width_multiple():
    """The shard-width hook: every size the tuner emits respects the
    mesh's series-shard multiple (floor included), and the pow-2 ladder
    stays divisible for a pow-2 multiple."""
    from tsspark_tpu.perf import ChunkAutotuner

    t = ChunkAutotuner(cap=1024, floor=16, multiple=64)
    assert t.floor == 64 and t.next_size() % 64 == 0
    # Walk the ladder: every emitted size stays on the multiple.
    for _ in range(8):
        size = t.next_size()
        assert size % 64 == 0 and size <= 1024
        t.record(size, size, 0.5)
    t2 = ChunkAutotuner(cap=256, floor=128, multiple=8)
    assert t2.next_size() % 8 == 0
    # load() honors the multiple the same way (floor clamped up).
    t3 = ChunkAutotuner.load("/nonexistent/autotune.json", cap=512,
                             floor=4, multiple=8)
    assert t3.floor == 8 and t3.next_size() % 8 == 0


def test_bench_history_row_scopes_workload_by_fit_path():
    """RUNHISTORY: the fit path rides the bench workload key (resident
    and fileproto runs must never share a sentinel baseline) and the
    path-scoped resident_series_per_s metric is admitted only when
    stamped.  Rows from before the resident path (no fit_path) keep
    their key unchanged."""
    from tsspark_tpu.obs import history

    def rep(fit_path=None, resident_sps=None):
        extra = {
            "trace_id": f"t-{fit_path}", "series_done": 512,
            "series_per_s": 100.0, "device": "cpu",
            "numerics_rev": 7, "git_rev": "abc", "complete": True,
        }
        if fit_path:
            extra["fit_path"] = fit_path
        if resident_sps is not None:
            extra["resident_series_per_s"] = resident_sps
        return {"metric": "m5_512x256_fit_wall_clock", "value": 5.0,
                "unit": "s", "vs_baseline": 1.0, "extra": extra}

    r_res = history.row_from_report(rep("resident", 100.0))
    r_file = history.row_from_report(rep("fileproto"))
    r_old = history.row_from_report(rep())
    assert r_res["workload"] == "m5_512x256_fit_wall_clock+resident"
    # The DEFAULT path keeps the historical key — renaming it would
    # orphan every committed fileproto baseline row at once.
    assert r_file["workload"] == "m5_512x256_fit_wall_clock"
    assert r_old["workload"] == "m5_512x256_fit_wall_clock"
    assert r_res["metrics"]["resident_series_per_s"] == 100.0
    assert "resident_series_per_s" not in r_file["metrics"]
    # The path-scoped SLO budget exists in both the pyproject table and
    # the pinned defaults (obs.regress keeps them equal).
    from tsspark_tpu.obs.regress import load_slo

    budgets = load_slo()["budgets"]["bench"]
    assert budgets["resident_series_per_s"]["direction"] == "higher"


def test_publish_and_snapshot_hot_loops_are_o_shards(tmp_path):
    """ROADMAP item 2 micro-bench: the publish/snapshot hot paths do
    their per-series work in C, not the Python interpreter — id
    normalization + row-map build handle 300k series in well under the
    budget a Python per-series pass would set on this box, and the
    per-request snapshot lookup does not scale with snapshot size."""
    from tsspark_tpu.serve.registry import Snapshot
    from tsspark_tpu.models.prophet.design import ScalingMeta
    from tsspark_tpu.models.prophet.model import FitState

    n = 300_000
    raw_ids = [f"FOODS_{i % 3}_{i:06d}" for i in range(n)]

    t0 = time.perf_counter()
    ids = orchestrate.normalize_series_ids(raw_ids)
    t_norm = time.perf_counter() - t0
    assert ids.dtype.kind == "U" and len(ids) == n
    # Generous absolute budget (measured ~0.05 s; a per-series Python
    # pass with str() + list building measures ~2-3x and grows with
    # every per-element op added).
    assert t_norm < 1.5, f"id normalization took {t_norm:.2f}s at 300k"

    def state_of(k):
        z1 = np.zeros((k, 1), np.float32)
        zm = np.zeros(k)
        return FitState(
            theta=z1, loss=zm.astype(np.float32),
            grad_norm=zm.astype(np.float32),
            converged=np.ones(k, bool), n_iters=np.ones(k, np.int32),
            status=np.zeros(k, np.int32),
            meta=ScalingMeta(
                y_scale=zm + 1, floor=zm, ds_start=zm, ds_span=zm + 1,
                reg_mean=z1.astype(np.float64),
                reg_std=z1.astype(np.float64) + 1,
                changepoints=z1.astype(np.float64),
            ),
        )

    t0 = time.perf_counter()
    snap_big = Snapshot.build(1, state_of(n), ids, None)
    t_build = time.perf_counter() - t0
    assert t_build < 3.0, f"Snapshot.build took {t_build:.2f}s at 300k"

    # Lookup is O(request), not O(series): the same 16-id lookup on a
    # 1k-series snapshot and a 300k-series snapshot.
    snap_small = Snapshot.build(1, state_of(1000), ids[:1000], None)
    probe = [str(s) for s in ids[:16]]

    def timed_rows(snap):
        t0 = time.perf_counter()
        for _ in range(50):
            idx, missing = snap.rows(probe)
        assert not missing and len(idx) == 16
        return time.perf_counter() - t0

    t_small = timed_rows(snap_small)
    t_big = timed_rows(snap_big)
    assert t_big < max(20 * t_small, 0.05), (
        f"snapshot lookup scales with snapshot size: {t_big:.4f}s vs "
        f"{t_small:.4f}s — the row map stopped being a dict lookup"
    )
