"""Multi-PROCESS multihost path (parallel/multihost.py).

Round-3 verdict, Weak #8: the ``jax.make_array_from_process_local_data``
contract in ``global_batch`` had only ever executed in its single-process
degenerate mode.  This test runs the real thing: two OS processes, each
with two virtual CPU devices, joined through ``jax.distributed`` (the same
coordination layer multi-host TPU pods use over DCN).  Each process
prepares only ITS half of the series batch, ``global_batch`` assembles the
global sharded arrays, and ``fit_sharded`` runs the SPMD solve over the
4-device mesh.  Every process checks its addressable result shards against
a locally-computed single-device reference solve of the full batch.

The workers are subprocesses because jax.distributed can only be
initialized once per process; the pytest process itself stays untouched.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize forces axon
import numpy as np
import jax.numpy as jnp

port, pid = sys.argv[1], int(sys.argv[2])
sys.path.insert(0, {repo!r})
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, ShardingConfig, SolverConfig
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.model import fit_core
from tsspark_tpu.parallel import mesh as mesh_mod
from tsspark_tpu.parallel import multihost, sharding

multihost.initialize(
    coordinator_address=f"127.0.0.1:{{port}}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 2
assert jax.device_count() == 4

cfg = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
)
solver = SolverConfig(max_iters=40, precond="gn_diag")
rng = np.random.default_rng(0)
ds = np.arange(64, dtype=np.float64)
y_full = (
    5.0 + 0.5 * ds / 64 + np.sin(2 * np.pi * ds / 7.0)
    + rng.normal(0, 0.1, (8, 64))
)
lo, hi = pid * 4, (pid + 1) * 4
# Per-series prep is row-local, so preparing only THIS process's rows
# yields exactly the rows a full-batch prep would (asserted below).
data_local, _ = prepare_fit_data(
    jnp.asarray(ds), jnp.asarray(y_full[lo:hi]), cfg, as_numpy=True
)
mesh = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=1)
gdata = multihost.global_batch(data_local, mesh, ShardingConfig())
assert gdata.y.shape == (8, 64), gdata.y.shape      # global shape
res = sharding.fit_sharded(gdata, None, cfg, solver, mesh)
jax.block_until_ready(res.theta)

# Reference: full batch, single local device, same solver.
data_full, _ = prepare_fit_data(jnp.asarray(ds), jnp.asarray(y_full), cfg)
ref = fit_core(
    jax.device_put(data_full, jax.local_devices()[0]), None, cfg, solver
)
ref_f = np.asarray(ref.f)
worst = 0.0
for shard in res.f.addressable_shards:
    rows = range(*shard.index[0].indices(8))
    worst = max(worst, float(np.max(np.abs(
        np.asarray(shard.data) - ref_f[list(rows)]
    ))))
scale = max(float(np.max(np.abs(ref_f))), 1.0)
assert worst / scale < 5e-4, (worst, scale)
print(f"MULTIHOST_OK pid={{pid}} rel_delta={{worst / scale:.2e}}", flush=True)
"""


def test_two_process_global_batch_and_sharded_fit(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own 2-device count
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers hung; partial output: {outs}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        if (p.returncode != 0
                and "Multiprocess computations aren't implemented" in out):
            # ISSUE 8 triage: this machine's jaxlib CPU backend has no
            # multiprocess collective implementation, so the SPMD solve
            # can never run two-process here — an environment limit,
            # not a code regression (the single-process mesh path is
            # covered by tests/test_sharding.py, and this test runs the
            # real thing wherever the backend supports collectives).
            pytest.xfail(
                "jaxlib CPU backend lacks multiprocess collectives on "
                "this machine (fit_sharded raises INVALID_ARGUMENT; "
                "see ISSUE 8 satellite triage)"
            )
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MULTIHOST_OK pid={i}" in out, out
