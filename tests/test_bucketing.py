"""Ragged-length bucketing (SURVEY.md §7 hard part c).

Variable-length batches are padded to the full calendar grid, so device
work scales with the LONGEST series; TpuBackend buckets series by observed
window and slices each bucket's time axis (backends/tpu.py
_plan_length_buckets).  Masked cells contribute exact zeros to every
reduction, so bucketing is a pure partitioning change — results can differ
from the unbucketed fit only at f32 reduction-order level, which these
tests pin down the same way the multichip dryrun does: exact-trajectory
parity at a fixed lockstep depth (where reduction noise cannot
chaos-amplify through convergence-exit flips) plus a full-depth quality
gate (the bucketed solve must not land materially worse).
"""

import numpy as np
import jax
import pytest

from tsspark_tpu.backends.tpu import TpuBackend
from tsspark_tpu.config import ProphetConfig, SeasonalityConfig, SolverConfig
from tsspark_tpu.data import datasets


CFG = ProphetConfig(
    seasonalities=(
        SeasonalityConfig("daily", 1.0, 4),
        SeasonalityConfig("weekly", 7.0, 3),
    ),
    n_changepoints=10,
)


def _ragged_batch():
    # min_len=200 against max_len=960 gives a genuinely ragged batch
    # (M4-Hourly's native 700-960 spread only offers ~14% savings, below
    # the planner's 20% bar — see test_plan_noop_when_waste_small).
    b = datasets.m4_hourly_like(n_series=48, min_len=200)
    return b.ds, np.nan_to_num(b.y), b.mask


def test_plan_covers_every_row_once_and_saves_cells():
    ds, y, mask = _ragged_batch()
    bk = TpuBackend(CFG, SolverConfig(max_iters=30))
    plan = bk._plan_length_buckets(y, mask)
    assert plan is not None
    idx_all = np.sort(np.concatenate([idx for idx, _, _ in plan]))
    np.testing.assert_array_equal(idx_all, np.arange(y.shape[0]))
    # Every bucket's window must cover all its members' observations.
    m = mask > 0
    for idx, lo, hi in plan:
        assert not m[idx][:, :lo].any()
        assert not m[idx][:, hi:].any()
    cost = sum(len(idx) * (hi - lo) for idx, lo, hi in plan)
    waste_saved = 1.0 - cost / (y.shape[0] * y.shape[1])
    assert waste_saved >= 0.20  # the planner's own worthwhileness bar


def test_plan_noop_when_waste_small():
    # M4-Hourly's native length spread (700-960 of 960) is not ragged
    # enough to pay for extra compile shapes: the planner must decline.
    b = datasets.m4_hourly_like(n_series=48)
    bk = TpuBackend(CFG, SolverConfig(max_iters=30))
    assert bk._plan_length_buckets(np.nan_to_num(b.y), b.mask) is None


def test_bucketed_lockstep_trajectory_matches_unbucketed():
    # One iteration, every convergence exit disabled: both fits advance all
    # series in exact lockstep, so any deviation is raw reduction-order
    # noise (~1e-4 on these hourly series).  A real slicing bug would show
    # O(0.1+) errors here.  Deeper lockstep comparison is not stable on
    # this batch: its ill-conditioned rows stall-flip (whole-ladder
    # rejection in one program but not the other) as early as iteration 2,
    # freezing different rows — the same chaos-amplification reasoning as
    # the multichip dryrun's TRAJ_ITERS choice (__graft_entry__.py).
    ds, y, mask = _ragged_batch()
    solver = SolverConfig(
        max_iters=1, tol=0.0, gtol=0.0,
        floor_patience=1 << 30, ftol_patience=1 << 30,
    )
    st0 = TpuBackend(CFG, solver, length_buckets=1, rescue=False).fit(
        ds, y, mask=mask
    )
    st3 = TpuBackend(CFG, solver, rescue=False).fit(ds, y, mask=mask)
    th0, th3 = np.asarray(st0.theta), np.asarray(st3.theta)
    scale = max(np.abs(th0).max(), 1.0)
    assert np.abs(th3 - th0).max() / scale < 1e-3
    # Scaling meta must be bit-identical: slicing fully-masked columns
    # cannot touch what the series actually observed.
    np.testing.assert_array_equal(st0.meta.y_scale, st3.meta.y_scale)
    np.testing.assert_array_equal(st0.meta.ds_start, st3.meta.ds_start)
    np.testing.assert_array_equal(st0.meta.ds_span, st3.meta.ds_span)


def test_bucketed_full_fit_quality_and_order():
    ds, y, mask = _ragged_batch()
    solver = SolverConfig(max_iters=60)
    st0 = TpuBackend(CFG, solver, length_buckets=1, rescue=False).fit(
        ds, y, mask=mask
    )
    st3 = TpuBackend(CFG, solver, rescue=False).fit(ds, y, mask=mask)
    l0, l3 = np.asarray(st0.loss), np.asarray(st3.loss)
    scale = max(np.abs(l0).max(), 1.0)
    # Quality gate: the bucketed solve may differ per series (trajectory
    # chaos on ill-conditioned rows) but must not be materially worse.
    assert (l3 - l0).mean() / scale < 2e-4
    assert (l3 - l0).max() / scale < 2e-3
    # Row order must be restored exactly (theta rows correspond 1:1).
    assert np.asarray(st3.theta).shape == np.asarray(st0.theta).shape
    jax.block_until_ready(st3.theta)
