"""The static-analysis gate (tsspark_tpu.analysis, docs/ANALYSIS.md).

Two layers: each checker must CATCH its seeded-violation fixture (a
checker that silently passes everything is worse than no checker), and
the full pass over this repo must be clean — the tier-1 gate every
subsequent PR runs under.
"""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu import analysis
from tsspark_tpu.analysis import contracts, fileproto, tracelint
from tsspark_tpu.analysis.config import (
    AnalysisSettings, KernelMatrix, load_settings, repo_root,
)
from tsspark_tpu.analysis.findings import Finding, apply_suppressions
from tsspark_tpu.utils.atomic import atomic_write, atomic_write_text


# ---------------------------------------------------------------------------
# trace-safety lint: seeded violations
# ---------------------------------------------------------------------------

_BAD_MODULE = textwrap.dedent(
    '''
    import functools
    import jax
    import jax.numpy as jnp
    import numpy as np


    @functools.partial(jax.jit, static_argnames=("depth",))
    def kernel(x, y, depth):
        if x > 0:                       # trace-branch
            y = y + 1.0
        z = float(y)                    # host-sync (builtin)
        w = np.asarray(x)               # host-sync (numpy pull)
        v = x.item()                    # host-sync (method)
        u = jnp.zeros((3,), np.float64) # f64-dtype
        return y + z + w + v + u.sum()


    def helper(x, y=[]):                # static-hash (mutable default)
        return x


    @functools.partial(jax.jit, static_argnames=("ghost",))
    def misnamed(x):                    # static-hash (ghost static)
        return x


    def rejitter(x):
        f = jax.jit(lambda t: t + 1)    # static-hash (jit of lambda)
        return f(x)


    def flip():
        jax.config.update("jax_enable_x64", True)  # f64-dtype (x64 flip)
    '''
)


@pytest.fixture()
def bad_module(tmp_path):
    p = tmp_path / "badmod.py"
    p.write_text(_BAD_MODULE)
    return str(tmp_path), str(p)


def _rules(findings):
    return {f.rule for f in findings}


def test_tracelint_catches_seeded_violations(bad_module):
    root, path = bad_module
    found = tracelint.lint_paths([path], root)
    rules = _rules(found)
    assert "trace-branch" in rules
    assert "host-sync" in rules
    assert "f64-dtype" in rules
    assert "static-hash" in rules
    # Each seeded hazard is caught individually, not via one noisy rule.
    msgs = "\n".join(f.message for f in found)
    assert "float()" in msgs
    assert "np.asarray" in msgs
    assert ".item()" in msgs
    assert "mutable default" in msgs
    assert "ghost" in msgs
    assert "lambda" in msgs
    assert "jax_enable_x64" in msgs


def test_tracelint_inline_suppression(tmp_path):
    src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            if x > 0:  # lint-ok[trace-branch]: fixture justification
                return x
            return -x
        """
    )
    p = tmp_path / "ok.py"
    p.write_text(src)
    found = tracelint.lint_paths([str(p)], str(tmp_path))
    assert not found
    # The same code WITHOUT the justification comment is flagged.
    p.write_text(src.replace(
        "  # lint-ok[trace-branch]: fixture justification", ""
    ))
    assert _rules(tracelint.lint_paths([str(p)], str(tmp_path))) == {
        "trace-branch"
    }


def test_tracelint_static_params_not_flagged(tmp_path):
    # Branching on a static argument (or shape/None-ness of a traced
    # one) is trace-safe and must NOT be flagged: the gate stays
    # credible only while it is quiet on correct idioms.
    src = textwrap.dedent(
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("config",))
        def kernel(x, theta0, config):
            if config.growth == "logistic":
                x = x + 1.0
            if theta0 is None:
                theta0 = x
            if x.shape[0] > 4:
                x = x[:4]
            return x + theta0
        """
    )
    p = tmp_path / "good.py"
    p.write_text(src)
    assert not tracelint.lint_paths([str(p)], str(tmp_path))


def test_baseline_suppression_applies():
    f = Finding("host-sync", "tsspark_tpu/x.py", 12, "fn", "msg")
    settings = AnalysisSettings(
        suppressions=("host-sync @ tsspark_tpu/x.py::fn",)
    )
    kept, suppressed = apply_suppressions((f,), settings)
    assert not kept and suppressed == (f,)
    with pytest.raises(ValueError):
        AnalysisSettings(suppressions=("garbage",)).suppression_keys()


# ---------------------------------------------------------------------------
# contract checker: seeded violations
# ---------------------------------------------------------------------------

_ONE_CASE = KernelMatrix(
    batch_sizes=(4,), lengths=(16,), n_changepoints=(0,),
    num_regressors=(0,), mesh_shapes=(),
)


def test_contracts_catch_f64_leak():
    bad = contracts.KernelContract(
        "bad.f64",
        lambda case: jax.eval_shape(
            lambda x: x.astype(jnp.float64), contracts._sds((case.b,))
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"f64-leak"}


def test_contracts_catch_shape_violation():
    bad = contracts.KernelContract(
        "bad.shape",
        lambda case: jax.eval_shape(
            lambda x: x[None], contracts._sds((case.b,))
        ),
        lambda case, out: contracts._expect(
            out, (case.b,), "float32", "out"
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"contract-shape"}


def test_contracts_catch_trace_failure():
    bad = contracts.KernelContract(
        "bad.trace",
        lambda case: jax.eval_shape(
            lambda x: x.reshape((3, 5, 7)), contracts._sds((case.b,))
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"contract-trace"}


def test_contracts_x64_mode_is_what_catches_drift():
    # The seeded f64 cast is INVISIBLE with x64 off (jax truncates it
    # to f32) — the checker must trace in x64 mode or the gate is
    # vacuous.  This pins that mode choice.
    def run(case):
        return jax.eval_shape(
            lambda x: x.astype(jnp.float64), contracts._sds((case.b,))
        )

    out = run(contracts.ShapeCase(4, 16, 0, 0))
    assert str(out.dtype) == "float32"  # x64 off: silently truncated
    found = contracts.check_kernels(
        _ONE_CASE, kernels=[contracts.KernelContract("bad", run)]
    )
    assert _rules(found) == {"f64-leak"}


# ---------------------------------------------------------------------------
# file-protocol race checker: seeded violations
# ---------------------------------------------------------------------------

def test_fileproto_catches_non_atomic_write(tmp_path):
    src = textwrap.dedent(
        """
        import numpy as np

        def bad_writer(out_dir, state):
            np.savez(out_dir + "/chunk_000000_000256.npz", **state)

        def bad_sentinel(out_dir):
            with open(out_dir + "/phase2_done", "w") as fh:
                fh.write("ok")
        """
    )
    rel = "tsspark_tpu/badproto.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    found = fileproto.check_write_sites(str(tmp_path), modules=[rel])
    assert _rules(found) == {"non-atomic-write"}
    assert len(found) == 2
    assert any("chunk-result" in f.message for f in found)
    assert any("phase2-sentinel" in f.message for f in found)


def test_fileproto_accepts_atomic_idioms(tmp_path):
    src = textwrap.dedent(
        """
        import os
        import numpy as np
        from tsspark_tpu.utils.atomic import atomic_write

        def save_chunk_atomic(out_dir, arrays):
            atomic_write(out_dir + "/chunk_000000_000256.npz",
                         lambda fh: np.savez(fh, **arrays))

        def manual_idiom(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        """
    )
    rel = "tsspark_tpu/okproto.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    assert not fileproto.check_write_sites(str(tmp_path), modules=[rel])


def test_fileproto_flags_unregistered_artifact(tmp_path):
    src = textwrap.dedent(
        """
        def mystery(out_dir):
            with open(out_dir + "/mystery_state.bin", "w") as fh:
                fh.write("?")
        """
    )
    rel = "tsspark_tpu/mystery.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    found = fileproto.check_write_sites(str(tmp_path), modules=[rel])
    assert len(found) == 1
    assert found[0].rule == "non-atomic-write"


def test_claim_model_catches_overlapping_planner():
    def broken_plan(done, lo, hi, chunk):
        # Ignores completed coverage: refits everything in the window.
        return [(c_lo, min(c_lo + chunk, hi))
                for c_lo in range(lo, hi, chunk)]

    found = fileproto.check_claim_invariants(plan_fn=broken_plan)
    assert "claim-overlap" in _rules(found)
    assert any("overlaps completed coverage" in f.message for f in found)


def test_claim_model_catches_hole_leaving_planner():
    def lazy_plan(done, lo, hi, chunk):
        from tsspark_tpu.orchestrate import plan_chunks

        return plan_chunks(done, lo, hi, chunk)[:-1]  # drops a claim

    found = fileproto.check_claim_invariants(plan_fn=lazy_plan)
    assert any("do not tile" in f.message for f in found)


def test_real_claim_protocol_is_clean():
    assert not fileproto.check_claim_invariants()
    assert not fileproto.check_completed_ranges_order()


# ---------------------------------------------------------------------------
# the shared atomic helper
# ---------------------------------------------------------------------------

def test_atomic_write_roundtrip_and_cleanup(tmp_path):
    target = str(tmp_path / "artifact.npz")
    arrays = {"a": np.arange(5), "b": np.ones((2, 2))}
    atomic_write(target, lambda fh: np.savez(fh, **arrays))
    z = np.load(target)
    np.testing.assert_array_equal(z["a"], arrays["a"])

    atomic_write_text(str(tmp_path / "sentinel"), "ok\n")
    assert (tmp_path / "sentinel").read_text() == "ok\n"

    # A writer crash leaves NEITHER a torn target nor a stray temp.
    with pytest.raises(RuntimeError):
        atomic_write(str(tmp_path / "never.npz"),
                     lambda fh: (_ for _ in ()).throw(RuntimeError("x")))
    leftovers = sorted(os.listdir(tmp_path))
    assert "never.npz" not in leftovers
    assert not [f for f in leftovers if ".tmp" in f]


# ---------------------------------------------------------------------------
# the gate itself: this repo must be clean
# ---------------------------------------------------------------------------

def test_settings_load_from_pyproject():
    settings = load_settings()
    assert isinstance(settings.kernel_matrix.batch_sizes, tuple)
    settings.suppression_keys()  # every committed entry parses


def test_repo_passes_full_analysis():
    """THE tier-1 gate: trace lint + kernel contracts + file protocol
    over the repository, with only the committed baseline suppressed.
    A finding here means a new hazard (or an unjustified suppression) —
    fix it or baseline it WITH a justification, never skip this test."""
    report = analysis.run_all(root=repo_root())
    assert report.ok, "\n".join(str(f) for f in report.findings)


def test_sweep_stale_temps_bounds_orphans(tmp_path):
    """A SIGKILLed writer's pid-suffixed temp is uniquely named, so no
    retry ever overwrites it — the sweep is what bounds scratch growth.
    Fresh temps (a live writer mid-save) must survive the sweep."""
    from tsspark_tpu.utils.atomic import sweep_stale_temps

    stale = tmp_path / ".chunk_000000_000512.npz.tmp.12345"
    stale.write_bytes(b"dead writer payload")
    os.utime(stale, (1.0, 1.0))  # ancient mtime
    fresh = tmp_path / ".chunk_000512_001024.npz.tmp.12346"
    fresh.write_bytes(b"live writer payload")
    regular = tmp_path / "chunk_000000_000512.npz"
    regular.write_bytes(b"completed result")
    os.utime(regular, (1.0, 1.0))  # old but NOT a temp: must survive

    removed = sweep_stale_temps(str(tmp_path))
    assert removed == 1
    assert not stale.exists()
    assert fresh.exists() and regular.exists()
