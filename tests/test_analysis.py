"""The static-analysis gate (tsspark_tpu.analysis, docs/ANALYSIS.md).

Two layers: each checker must CATCH its seeded-violation fixture (a
checker that silently passes everything is worse than no checker), and
the full pass over this repo must be clean — the tier-1 gate every
subsequent PR runs under.
"""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu import analysis
from tsspark_tpu.analysis import (
    concur,
    contracts,
    fileproto,
    protomodel,
    tracelint,
)
from tsspark_tpu.analysis import report as analysis_report
from tsspark_tpu.analysis.config import (
    AnalysisSettings, KernelMatrix, load_settings, repo_root,
)
from tsspark_tpu.analysis.findings import Finding, apply_suppressions
from tsspark_tpu.utils.atomic import atomic_write, atomic_write_text


# ---------------------------------------------------------------------------
# trace-safety lint: seeded violations
# ---------------------------------------------------------------------------

_BAD_MODULE = textwrap.dedent(
    '''
    import functools
    import jax
    import jax.numpy as jnp
    import numpy as np


    @functools.partial(jax.jit, static_argnames=("depth",))
    def kernel(x, y, depth):
        if x > 0:                       # trace-branch
            y = y + 1.0
        z = float(y)                    # host-sync (builtin)
        w = np.asarray(x)               # host-sync (numpy pull)
        v = x.item()                    # host-sync (method)
        u = jnp.zeros((3,), np.float64) # f64-dtype
        return y + z + w + v + u.sum()


    def helper(x, y=[]):                # static-hash (mutable default)
        return x


    @functools.partial(jax.jit, static_argnames=("ghost",))
    def misnamed(x):                    # static-hash (ghost static)
        return x


    def rejitter(x):
        f = jax.jit(lambda t: t + 1)    # static-hash (jit of lambda)
        return f(x)


    def flip():
        jax.config.update("jax_enable_x64", True)  # f64-dtype (x64 flip)
    '''
)


@pytest.fixture()
def bad_module(tmp_path):
    p = tmp_path / "badmod.py"
    p.write_text(_BAD_MODULE)
    return str(tmp_path), str(p)


def _rules(findings):
    return {f.rule for f in findings}


def test_tracelint_catches_seeded_violations(bad_module):
    root, path = bad_module
    found = tracelint.lint_paths([path], root)
    rules = _rules(found)
    assert "trace-branch" in rules
    assert "host-sync" in rules
    assert "f64-dtype" in rules
    assert "static-hash" in rules
    # Each seeded hazard is caught individually, not via one noisy rule.
    msgs = "\n".join(f.message for f in found)
    assert "float()" in msgs
    assert "np.asarray" in msgs
    assert ".item()" in msgs
    assert "mutable default" in msgs
    assert "ghost" in msgs
    assert "lambda" in msgs
    assert "jax_enable_x64" in msgs


def test_tracelint_inline_suppression(tmp_path):
    src = textwrap.dedent(
        """
        import jax

        @jax.jit
        def kernel(x):
            if x > 0:  # lint-ok[trace-branch]: fixture justification
                return x
            return -x
        """
    )
    p = tmp_path / "ok.py"
    p.write_text(src)
    found = tracelint.lint_paths([str(p)], str(tmp_path))
    assert not found
    # The same code WITHOUT the justification comment is flagged.
    p.write_text(src.replace(
        "  # lint-ok[trace-branch]: fixture justification", ""
    ))
    assert _rules(tracelint.lint_paths([str(p)], str(tmp_path))) == {
        "trace-branch"
    }


def test_tracelint_static_params_not_flagged(tmp_path):
    # Branching on a static argument (or shape/None-ness of a traced
    # one) is trace-safe and must NOT be flagged: the gate stays
    # credible only while it is quiet on correct idioms.
    src = textwrap.dedent(
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("config",))
        def kernel(x, theta0, config):
            if config.growth == "logistic":
                x = x + 1.0
            if theta0 is None:
                theta0 = x
            if x.shape[0] > 4:
                x = x[:4]
            return x + theta0
        """
    )
    p = tmp_path / "good.py"
    p.write_text(src)
    assert not tracelint.lint_paths([str(p)], str(tmp_path))


def test_baseline_suppression_applies():
    f = Finding("host-sync", "tsspark_tpu/x.py", 12, "fn", "msg")
    settings = AnalysisSettings(
        suppressions=(
            "host-sync @ tsspark_tpu/x.py::fn -- fixture justification",
        )
    )
    kept, suppressed = apply_suppressions((f,), settings)
    assert not kept and suppressed == (f,)
    with pytest.raises(ValueError):
        AnalysisSettings(suppressions=("garbage",)).suppression_keys()
    # A baseline entry WITHOUT its justification clause is rejected at
    # load: an exception with no recorded reason is a rubber stamp.
    with pytest.raises(ValueError, match="justification"):
        AnalysisSettings(
            suppressions=("host-sync @ tsspark_tpu/x.py::fn",)
        ).suppression_keys()


# ---------------------------------------------------------------------------
# contract checker: seeded violations
# ---------------------------------------------------------------------------

_ONE_CASE = KernelMatrix(
    batch_sizes=(4,), lengths=(16,), n_changepoints=(0,),
    num_regressors=(0,), mesh_shapes=(),
)


def test_contracts_catch_f64_leak():
    bad = contracts.KernelContract(
        "bad.f64",
        lambda case: jax.eval_shape(
            lambda x: x.astype(jnp.float64), contracts._sds((case.b,))
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"f64-leak"}


def test_contracts_catch_shape_violation():
    bad = contracts.KernelContract(
        "bad.shape",
        lambda case: jax.eval_shape(
            lambda x: x[None], contracts._sds((case.b,))
        ),
        lambda case, out: contracts._expect(
            out, (case.b,), "float32", "out"
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"contract-shape"}


def test_contracts_catch_trace_failure():
    bad = contracts.KernelContract(
        "bad.trace",
        lambda case: jax.eval_shape(
            lambda x: x.reshape((3, 5, 7)), contracts._sds((case.b,))
        ),
    )
    found = contracts.check_kernels(_ONE_CASE, kernels=[bad])
    assert _rules(found) == {"contract-trace"}


def test_contracts_x64_mode_is_what_catches_drift():
    # The seeded f64 cast is INVISIBLE with x64 off (jax truncates it
    # to f32) — the checker must trace in x64 mode or the gate is
    # vacuous.  This pins that mode choice.
    def run(case):
        return jax.eval_shape(
            lambda x: x.astype(jnp.float64), contracts._sds((case.b,))
        )

    out = run(contracts.ShapeCase(4, 16, 0, 0))
    assert str(out.dtype) == "float32"  # x64 off: silently truncated
    found = contracts.check_kernels(
        _ONE_CASE, kernels=[contracts.KernelContract("bad", run)]
    )
    assert _rules(found) == {"f64-leak"}


# ---------------------------------------------------------------------------
# file-protocol race checker: seeded violations
# ---------------------------------------------------------------------------

def test_fileproto_catches_non_atomic_write(tmp_path):
    src = textwrap.dedent(
        """
        import numpy as np

        def bad_writer(out_dir, state):
            np.savez(out_dir + "/chunk_000000_000256.npz", **state)

        def bad_sentinel(out_dir):
            with open(out_dir + "/phase2_done", "w") as fh:
                fh.write("ok")
        """
    )
    rel = "tsspark_tpu/badproto.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    found = fileproto.check_write_sites(str(tmp_path), modules=[rel])
    assert _rules(found) == {"non-atomic-write"}
    assert len(found) == 2
    assert any("chunk-result" in f.message for f in found)
    assert any("phase2-sentinel" in f.message for f in found)


def test_fileproto_accepts_atomic_idioms(tmp_path):
    src = textwrap.dedent(
        """
        import os
        import numpy as np
        from tsspark_tpu.utils.atomic import atomic_write

        def save_chunk_atomic(out_dir, arrays):
            atomic_write(out_dir + "/chunk_000000_000256.npz",
                         lambda fh: np.savez(fh, **arrays))

        def manual_idiom(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        """
    )
    rel = "tsspark_tpu/okproto.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    assert not fileproto.check_write_sites(str(tmp_path), modules=[rel])


def test_fileproto_flags_unregistered_artifact(tmp_path):
    src = textwrap.dedent(
        """
        def mystery(out_dir):
            with open(out_dir + "/mystery_state.bin", "w") as fh:
                fh.write("?")
        """
    )
    rel = "tsspark_tpu/mystery.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    found = fileproto.check_write_sites(str(tmp_path), modules=[rel])
    assert len(found) == 1
    assert found[0].rule == "non-atomic-write"


def test_io_routing_catches_unrouted_durable_writes(tmp_path):
    """Seeded violations of the storage-fault-domain routing rule: a
    direct utils.atomic import, a raw os.replace, and a raw write-mode
    open() each fire ``io-routing``; the append-mode lock idiom stays
    exempt."""
    src = textwrap.dedent(
        """
        import os
        from tsspark_tpu.utils.atomic import atomic_write

        def sideload(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, path)

        def heartbeat(path):
            with open(path, "a") as fh:
                fh.write("alive\\n")
        """
    )
    rel = "tsspark_tpu/plane/unrouted.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text(src)
    found = fileproto.check_io_routing(str(tmp_path), modules=[rel])
    assert _rules(found) == {"io-routing"}
    msgs = [f.message for f in found]
    assert any("utils.atomic" in m for m in msgs)
    assert any("os.replace" in m for m in msgs)
    assert any("open" in m for m in msgs)
    # Exactly three: the append-mode heartbeat did NOT fire.
    assert len(found) == 3
    assert all(f.qualname in ("<module>", "sideload") for f in found)


def test_io_routing_live_tree_is_clean():
    """Every in-scope module of the real tree routes its durable
    writes through tsspark_tpu.io — the routing rule holds with no
    baseline suppressions."""
    root = os.path.dirname(os.path.dirname(fileproto.__file__))
    repo = os.path.dirname(root)
    assert fileproto.check_io_routing(repo) == []


def test_claim_model_catches_overlapping_planner():
    def broken_plan(done, lo, hi, chunk):
        # Ignores completed coverage: refits everything in the window.
        return [(c_lo, min(c_lo + chunk, hi))
                for c_lo in range(lo, hi, chunk)]

    found = fileproto.check_claim_invariants(plan_fn=broken_plan)
    assert "claim-overlap" in _rules(found)
    assert any("overlaps completed coverage" in f.message for f in found)


def test_claim_model_catches_hole_leaving_planner():
    def lazy_plan(done, lo, hi, chunk):
        from tsspark_tpu.orchestrate import plan_chunks

        return plan_chunks(done, lo, hi, chunk)[:-1]  # drops a claim

    found = fileproto.check_claim_invariants(plan_fn=lazy_plan)
    assert any("do not tile" in f.message for f in found)


def test_real_claim_protocol_is_clean():
    assert not fileproto.check_claim_invariants()
    assert not fileproto.check_completed_ranges_order()


# ---------------------------------------------------------------------------
# tracelint closure precision: the qualified-callee join
# ---------------------------------------------------------------------------

def test_tracelint_qualified_callees_no_name_collision(tmp_path):
    """Two same-named functions in different modules: only the one the
    jit root actually imports is traced (the DatasetSpec.key ->
    cache_key rename class — a simple-name join would lint BOTH and
    flag host code as traced)."""
    (tmp_path / "mod_a.py").write_text(
        "def helper(x):\n    return x + 1.0\n"
    )
    (tmp_path / "mod_b.py").write_text(
        # A host-sync IF traced; it must stay out of the closure.
        "def helper(x):\n    return float(x)\n"
    )
    (tmp_path / "rootmod.py").write_text(textwrap.dedent(
        """
        import jax
        from mod_a import helper

        @jax.jit
        def kernel(x):
            return helper(x)
        """
    ))
    paths = sorted(str(p) for p in tmp_path.glob("*.py"))
    assert not tracelint.lint_paths(paths, str(tmp_path))
    # Control: the QUALIFIED callee is still traced — a violation in
    # the imported module's helper IS caught.
    (tmp_path / "mod_a.py").write_text(
        "def helper(x):\n    return float(x)\n"
    )
    found = tracelint.lint_paths(paths, str(tmp_path))
    assert {(f.path, f.rule) for f in found} == {
        ("mod_a.py", "host-sync")
    }


def test_tracelint_reexported_callee_still_traced(tmp_path):
    """A from-import through a re-exporting package __init__ must fall
    back to the simple-name join, not silently drop the edge — the
    qualified-callee precision must never UN-lint traced code."""
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "from mypkg.impl import helper\n"
    )
    (pkg / "impl.py").write_text(
        "def helper(x):\n    return float(x)\n"
    )
    (tmp_path / "rootmod.py").write_text(textwrap.dedent(
        """
        import jax
        from mypkg import helper

        @jax.jit
        def kernel(x):
            return helper(x)
        """
    ))
    paths = sorted(
        str(p) for p in tmp_path.rglob("*.py")
    )
    found = tracelint.lint_paths(paths, str(tmp_path))
    assert {(f.path, f.rule) for f in found} == {
        (os.path.join("mypkg", "impl.py"), "host-sync")
    }


def test_count_inline_waivers_ignores_doc_mentions(tmp_path):
    """A docstring MENTIONING the waiver syntax is documentation, not a
    waiver — only comment tokens count toward the creep metric."""
    (tmp_path / "mod.py").write_text(textwrap.dedent(
        '''
        """Docs: use ``# lint-ok[rule]: reason`` to waive."""

        def f(x):
            return x  # lint-ok[host-sync]: a real waiver
        '''
    ))
    counts = analysis_report.count_inline_waivers(str(tmp_path))
    assert counts == {"host-sync": 1}


def test_tracelint_local_variable_not_a_callee_reference(tmp_path):
    """A local DATA variable passed as an argument must not join a
    same-named package function into the traced closure (`span = t1 -
    t0` once pulled obs.context.span under the lint)."""
    (tmp_path / "obsmod.py").write_text(
        "def span(x):\n    return float(x)\n"
    )
    (tmp_path / "kern.py").write_text(textwrap.dedent(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(t0, t1):
            span = t1 - t0
            return jnp.maximum(span, 1e-6)
        """
    ))
    paths = sorted(str(p) for p in tmp_path.glob("*.py"))
    assert not tracelint.lint_paths(paths, str(tmp_path))


# ---------------------------------------------------------------------------
# concurrency gate: seeded violations (one rule per fixture)
# ---------------------------------------------------------------------------

_RACY_COUNTER = textwrap.dedent(
    '''
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self.wrong_version = 0
            self._watch = None

        def start(self):
            self._watch = threading.Thread(target=self._loop,
                                           daemon=True)
            self._watch.start()

        def _loop(self):
            try:
                with self._lock:
                    self.wrong_version += 1
            except Exception:
                pass

        def note(self):
            self.wrong_version += 1   # racy: no lock

        def stop(self):
            self._watch.join()
    '''
)

_BLOCKING_UNDER_LOCK = textwrap.dedent(
    '''
    import threading
    import time

    class Front:
        def __init__(self):
            self._lock = threading.Lock()

        def respawn(self):
            with self._lock:
                time.sleep(2.0)
    '''
)

_UNJOINED_THREAD = textwrap.dedent(
    '''
    import threading

    def worker():
        try:
            work()
        except Exception:
            pass

    def spawn():
        threading.Thread(target=worker).start()
    '''
)

_ESCAPING_TARGET = textwrap.dedent(
    '''
    import threading

    def worker():
        raise RuntimeError("boom")

    def spawn():
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    '''
)

_MMAP_SCATTER = textwrap.dedent(
    '''
    import numpy as np

    def bad(path, rows, vals):
        mm = np.load(path, mmap_mode="r")
        mm[rows] = vals
        return mm

    def good(path, rows, vals):
        out = np.array(np.load(path, mmap_mode="r"))
        out[rows] = vals
        return out
    '''
)


def _concur_on(tmp_path, src: str):
    p = tmp_path / "fixture.py"
    p.write_text(src)
    return concur.check_paths([str(p)], str(tmp_path))


def test_concur_catches_racy_counter(tmp_path):
    found = _concur_on(tmp_path, _RACY_COUNTER)
    assert _rules(found) == {"lock-guard"}
    assert len(found) == 1
    assert found[0].qualname == "Pool.note"
    assert "wrong_version" in found[0].message


def test_concur_catches_blocking_call_under_lock(tmp_path):
    found = _concur_on(tmp_path, _BLOCKING_UNDER_LOCK)
    assert _rules(found) == {"lock-blocking"}
    assert len(found) == 1
    assert "time.sleep" in found[0].message


def test_concur_catches_unjoined_thread(tmp_path):
    found = _concur_on(tmp_path, _UNJOINED_THREAD)
    assert _rules(found) == {"thread-join"}
    assert len(found) == 1


def test_concur_catches_escaping_thread_target(tmp_path):
    found = _concur_on(tmp_path, _ESCAPING_TARGET)
    assert _rules(found) == {"thread-exc"}
    assert len(found) == 1
    assert found[0].qualname == "worker"


def test_concur_catches_mmap_view_scatter(tmp_path):
    found = _concur_on(tmp_path, _MMAP_SCATTER)
    assert _rules(found) == {"mmap-alias"}
    assert len(found) == 1
    assert found[0].qualname == "bad"   # the laundered copy is clean


def test_concur_inline_waiver(tmp_path):
    waived = _MMAP_SCATTER.replace(
        "mm[rows] = vals",
        "mm[rows] = vals  # lint-ok[mmap-alias]: fixture justification",
    )
    assert not _concur_on(tmp_path, waived)


def test_concur_condition_guarded_counter_still_linted(tmp_path):
    # A Condition IS a mutex when held via `with`: a racy counter in a
    # Condition-only producer/consumer class must not slip the gate.
    src = _RACY_COUNTER.replace("threading.Lock()",
                                "threading.Condition()")
    found = _concur_on(tmp_path, src)
    assert _rules(found) == {"lock-guard"}
    assert found[0].qualname == "Pool.note"


def test_concur_path_join_under_lock_not_flagged(tmp_path):
    src = textwrap.dedent(
        '''
        import os
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def path_of(self, d):
                with self._lock:
                    return os.path.join(d, "state.json")
        '''
    )
    assert not _concur_on(tmp_path, src)


def test_concur_unbounded_event_wait_under_lock_flagged(tmp_path):
    # Bare .wait() on a known non-Condition self attr is an UNBOUNDED
    # block under the lock — strictly worse than a timed one.
    src = textwrap.dedent(
        '''
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()

            def drain(self):
                with self._lock:
                    self._done.wait()
        '''
    )
    found = _concur_on(tmp_path, src)
    assert _rules(found) == {"lock-blocking"}


def test_concur_condition_wait_not_flagged(tmp_path):
    # Condition.wait RELEASES the lock — the canonical producer/
    # consumer idiom must stay quiet or the rule is unusable.
    src = textwrap.dedent(
        '''
        import threading

        class Q:
            def __init__(self):
                self._cond_lock = threading.Condition()

            def take(self):
                with self._cond_lock:
                    self._cond_lock.wait(0.2)
        '''
    )
    assert not _concur_on(tmp_path, src)


# ---------------------------------------------------------------------------
# happens-before model checker: seeded violations
# ---------------------------------------------------------------------------

_SENTINEL_FIRST = textwrap.dedent(
    '''
    from tsspark_tpu.utils.atomic import atomic_write

    def land(out_dir, data):
        atomic_write(out_dir + "/ok.json", lambda fh: fh.write("{}"))
        atomic_write(out_dir + "/payload.bin",
                     lambda fh: fh.write(data))
    '''
)

_PAYLOAD_FIRST = textwrap.dedent(
    '''
    from tsspark_tpu.utils.atomic import atomic_write

    def land(out_dir, data):
        atomic_write(out_dir + "/payload.bin",
                     lambda fh: fh.write(data))
        atomic_write(out_dir + "/ok.json", lambda fh: fh.write("{}"))
    '''
)


def _fixture_protocol(edges=()):
    return protomodel.ProtocolSpec(
        "fixture", "mod.py", "land",
        steps=(
            protomodel.StepSpec("payload", "tok:payload.bin",
                                reader="resumer redoes it"),
            protomodel.StepSpec("ok", "tok:ok.json", role="gate",
                                certifies=("payload",)),
        ),
        edges=edges,
    )


def test_protomodel_catches_sentinel_before_payload(tmp_path):
    (tmp_path / "mod.py").write_text(_SENTINEL_FIRST)
    found = protomodel.check_protocols(str(tmp_path),
                                       [_fixture_protocol()])
    assert _rules(found) == {"hb-order"}
    # The correct order is clean.
    (tmp_path / "mod.py").write_text(_PAYLOAD_FIRST)
    assert not protomodel.check_protocols(str(tmp_path),
                                          [_fixture_protocol()])


def test_protomodel_killpoint_sweep_catches_weak_edges(tmp_path):
    """Edges that leave the gate unordered against its payload admit a
    linearization where a kill right after the gate exposes a payload
    that never landed — the sweep must find it statically."""
    (tmp_path / "mod.py").write_text(_PAYLOAD_FIRST)
    loose = protomodel.ProtocolSpec(
        "fixture-loose", "mod.py", "land",
        steps=(
            protomodel.StepSpec("payload", "tok:payload.bin",
                                reader="resumer redoes it"),
            protomodel.StepSpec("extra", "tok:payload.bin",
                                reader="resumer redoes it"),
            protomodel.StepSpec("ok", "tok:ok.json", role="gate",
                                certifies=("payload", "extra")),
        ),
        # Only payload<extra declared: the gate floats freely.
        edges=(("payload", "extra"),),
    )
    found = protomodel.check_protocols(str(tmp_path), [loose])
    assert "hb-unsafe" in _rules(found)


def test_protomodel_rejects_inconsistent_model(tmp_path):
    (tmp_path / "mod.py").write_text(_PAYLOAD_FIRST)
    bad = protomodel.ProtocolSpec(
        "fixture-bad", "mod.py", "land",
        steps=(
            protomodel.StepSpec("payload", "tok:payload.bin",
                                reader=""),  # no resumer story
            protomodel.StepSpec("ok", "tok:ok.json", role="gate",
                                certifies=("ghost",)),
        ),
    )
    found = protomodel.check_protocols(str(tmp_path), [bad])
    assert _rules(found) == {"hb-model"}
    msgs = "\n".join(f.message for f in found)
    assert "ghost" in msgs and "reader" in msgs


def test_protomodel_live_registry_is_clean():
    assert not protomodel.check_protocols(repo_root())


def test_protomodel_detects_model_drift(tmp_path):
    # A declared step that matches nothing in the writer is drift, not
    # silence: the model must fail loudly when the code moves on.
    (tmp_path / "mod.py").write_text(_PAYLOAD_FIRST)
    drifted = protomodel.ProtocolSpec(
        "fixture-drift", "mod.py", "land",
        steps=(
            protomodel.StepSpec("payload", "tok:renamed.bin",
                                reader="r"),
            protomodel.StepSpec("ok", "tok:ok.json", role="gate",
                                certifies=("payload",)),
        ),
    )
    found = protomodel.check_protocols(str(tmp_path), [drifted])
    assert _rules(found) == {"hb-missing"}


# ---------------------------------------------------------------------------
# the ANALYSIS_* gate artifact + history row
# ---------------------------------------------------------------------------

def test_analysis_report_roundtrip_and_history_row(tmp_path):
    import json as json_mod

    from tsspark_tpu.obs import history

    rep_obj = analysis.AnalysisReport((), (), (("trace", 0),
                                              ("concur", 2)))
    rep = analysis_report.build_report(
        rep_obj, AnalysisSettings(), repo_root(), 1.5
    )
    assert rep["kind"] == "analysis-gate" and rep["ok"]
    assert rep["checkers"] == {"trace": 0, "concur": 2}
    # The live tree carries real inline waivers (each with a reason).
    assert rep["waivers_inline"] >= 1
    path = analysis_report.write_report(rep, out_dir=str(tmp_path))
    with open(path) as fh:
        d = json_mod.load(fh)
    hp = str(tmp_path / "RUNHISTORY.jsonl")
    row, appended = history.ingest(d, hp, source=path)
    assert appended and row["kind"] == "analysis"
    assert row["workload"] == "analysis_full"
    assert row["metrics"]["raw_concur"] == 2
    assert row["metrics"]["ok"] == 1
    # Idempotent by content identity: re-ingesting is a no-op.
    _row2, appended2 = history.ingest(d, hp, source=path)
    assert not appended2


def test_changed_scope_helper():
    from tsspark_tpu.analysis.__main__ import changed_package_paths

    paths = changed_package_paths(repo_root(), "HEAD")
    assert isinstance(paths, list)
    assert all(p.endswith(".py") and os.path.exists(p) for p in paths)
    with pytest.raises(SystemExit):
        changed_package_paths(repo_root(), "no-such-ref-xyz")


# ---------------------------------------------------------------------------
# the shared atomic helper
# ---------------------------------------------------------------------------

def test_atomic_write_roundtrip_and_cleanup(tmp_path):
    target = str(tmp_path / "artifact.npz")
    arrays = {"a": np.arange(5), "b": np.ones((2, 2))}
    atomic_write(target, lambda fh: np.savez(fh, **arrays))
    z = np.load(target)
    np.testing.assert_array_equal(z["a"], arrays["a"])

    atomic_write_text(str(tmp_path / "sentinel"), "ok\n")
    assert (tmp_path / "sentinel").read_text() == "ok\n"

    # A writer crash leaves NEITHER a torn target nor a stray temp.
    with pytest.raises(RuntimeError):
        atomic_write(str(tmp_path / "never.npz"),
                     lambda fh: (_ for _ in ()).throw(RuntimeError("x")))
    leftovers = sorted(os.listdir(tmp_path))
    assert "never.npz" not in leftovers
    assert not [f for f in leftovers if ".tmp" in f]


# ---------------------------------------------------------------------------
# the gate itself: this repo must be clean
# ---------------------------------------------------------------------------

def test_settings_load_from_pyproject():
    settings = load_settings()
    assert isinstance(settings.kernel_matrix.batch_sizes, tuple)
    settings.suppression_keys()  # every committed entry parses


def test_repo_passes_full_analysis():
    """THE tier-1 gate: trace lint + kernel contracts + file protocol
    over the repository, with only the committed baseline suppressed.
    A finding here means a new hazard (or an unjustified suppression) —
    fix it or baseline it WITH a justification, never skip this test."""
    report = analysis.run_all(root=repo_root())
    assert report.ok, "\n".join(str(f) for f in report.findings)


def test_sweep_stale_temps_bounds_orphans(tmp_path):
    """A SIGKILLed writer's pid-suffixed temp is uniquely named, so no
    retry ever overwrites it — the sweep is what bounds scratch growth.
    Fresh temps (a live writer mid-save) must survive the sweep."""
    from tsspark_tpu.utils.atomic import sweep_stale_temps

    stale = tmp_path / ".chunk_000000_000512.npz.tmp.12345"
    stale.write_bytes(b"dead writer payload")
    os.utime(stale, (1.0, 1.0))  # ancient mtime
    fresh = tmp_path / ".chunk_000512_001024.npz.tmp.12346"
    fresh.write_bytes(b"live writer payload")
    regular = tmp_path / "chunk_000000_000512.npz"
    regular.write_bytes(b"completed result")
    os.utime(regular, (1.0, 1.0))  # old but NOT a temp: must survive

    removed = sweep_stale_temps(str(tmp_path))
    assert removed == 1
    assert not stale.exists()
    assert fresh.exists() and regular.exists()


# ---------------------------------------------------------------------------
# effect inference & path budgets (analysis/effects.py)
# ---------------------------------------------------------------------------

def _effects_pkg(tmp_path, files):
    """A throwaway package tree: {relname: source} under
    tmp_path/tsspark_tpu, returning the fixture root."""
    pkg = tmp_path / "tsspark_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _budget(name, roots, forbid, allow_via=()):
    from tsspark_tpu.analysis import effects

    return effects.EffectsConfig(paths=(effects.PathBudget(
        name=name, roots=tuple(roots), forbid=tuple(forbid),
        allow_via=tuple(allow_via),
    ),))


def test_effects_dispatch_on_thread_budget(tmp_path):
    """The serve-threads claim: a heartbeat helper sneaking a jnp op
    onto the maintenance thread trips the no-dispatch budget, and the
    finding carries the call chain from the root."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {"pool.py": '''
        import jax.numpy as jnp

        def _heartbeat(self):
            _refresh_gauge()

        def _refresh_gauge():
            return jnp.zeros((2,)).sum()
    '''})
    found = effects.check_effects(root, config=_budget(
        "threads", ["tsspark_tpu/pool.py::_heartbeat"],
        ["jax-dispatch"],
    ))
    assert [f.rule for f in found] == ["effect-budget"]
    assert found[0].qualname == "_refresh_gauge"
    assert "_heartbeat" in found[0].message  # the chain names the root


def test_effects_raw_write_on_respond_path(tmp_path):
    """open(..., "w") reachable from a respond root fires; the same
    site under an inline waiver is suppressed (and consumed)."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {"serve.py": '''
        def respond(req):
            return _log_request(req)

        def _log_request(req):
            with open("/tmp/requests.log", "a") as fh:
                fh.write(str(req))
    '''})
    cfg = _budget("respond", ["tsspark_tpu/serve.py::respond"],
                  ["raw-fs-write"])
    found = effects.check_effects(root, config=cfg)
    assert [f.rule for f in found] == ["effect-budget"]
    assert found[0].qualname == "_log_request"

    root2 = _effects_pkg(tmp_path / "waived", {"serve.py": '''
        def respond(req):
            return _log_request(req)

        def _log_request(req):
            with open("/tmp/requests.log", "a") as fh:  # lint-ok[effect-budget]: test-only sink
                fh.write(str(req))
    '''})
    assert not effects.check_effects(root2, config=cfg)


def test_effects_allow_via_cuts_path(tmp_path):
    """A declared cut point (the spill-artifact idiom) excuses the
    effects BEYOND it, and only through it."""
    from tsspark_tpu.analysis import effects

    src = {"sched.py": '''
        import os

        def idle_tick(self):
            ensure_spill("scratch")

        def ensure_spill(scratch):
            os.makedirs(scratch)
    '''}
    roots = ["tsspark_tpu/sched.py::idle_tick"]
    found = effects.check_effects(
        _effects_pkg(tmp_path, src),
        config=_budget("idle", roots, ["raw-fs-write"]),
    )
    assert [f.rule for f in found] == ["effect-budget"]
    found = effects.check_effects(
        _effects_pkg(tmp_path / "cut", src),
        config=_budget("idle", roots, ["raw-fs-write"],
                       allow_via=["tsspark_tpu/sched.py::ensure_spill"]),
    )
    assert not found


def test_effects_env_unregistered_and_unused(tmp_path):
    """Every TSSPARK_* read needs an EnvSpec row — including reads
    through an imported module's constant — and a spec nothing reads
    is itself a finding (specs die with the read they cover)."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {
        "consts.py": "ENV_VAR = 'TSSPARK_VIA_CONST'\n",
        "mod.py": '''
            import os

            from tsspark_tpu import consts

            def configured():
                a = os.environ.get("TSSPARK_DIRECT")
                b = os.environ.get(consts.ENV_VAR)
                return a, b
        ''',
    })
    found = effects.check_effects(root, config=effects.EffectsConfig())
    assert sorted(f.message.split("'")[1] for f in found
                  if f.rule == "env-unregistered") == [
        "TSSPARK_DIRECT", "TSSPARK_VIA_CONST",
    ]

    spec = effects.EnvSpec(var="TSSPARK_DIRECT",
                           owner="tsspark_tpu/mod.py", inherit=True)
    ghost = effects.EnvSpec(var="TSSPARK_NEVER_READ",
                            owner="tsspark_tpu/mod.py", inherit=False)
    found = effects.check_effects(
        root, config=effects.EffectsConfig(env=(spec, ghost)),
    )
    rules = {f.rule for f in found}
    assert "env-unused" in rules  # the ghost spec
    assert all(f.qualname == "TSSPARK_NEVER_READ" for f in found
               if f.rule == "env-unused")


def test_effects_spawn_drops_inherited_spec(tmp_path):
    """A spawn site passing env= must provably seed from os.environ —
    a from-scratch dict silently drops every inherited spec.  Both the
    dict(os.environ) idiom and the _child_env-builder idiom pass."""
    from tsspark_tpu.analysis import effects

    spec = (effects.EnvSpec(var="TSSPARK_FAULTS",
                            owner="tsspark_tpu/f.py", inherit=True),)
    bad = _effects_pkg(tmp_path, {
        "f.py": "import os\nF = os.environ.get('TSSPARK_FAULTS')\n",
        "spawn.py": '''
            import subprocess

            def launch(cmd):
                env = {"PATH": "/usr/bin"}
                return subprocess.Popen(cmd, env=env)
        ''',
    })
    found = effects.check_effects(
        bad, config=effects.EffectsConfig(env=spec),
    )
    assert "env-propagation" in {f.rule for f in found}
    assert any("TSSPARK_FAULTS" in f.message for f in found
               if f.rule == "env-propagation")

    good = _effects_pkg(tmp_path / "good", {
        "f.py": "import os\nF = os.environ.get('TSSPARK_FAULTS')\n",
        "spawn.py": '''
            import os
            import subprocess

            def _child_env():
                env = dict(os.environ)
                env["EXTRA"] = "1"
                return env

            def launch_inline(cmd):
                env = dict(os.environ)
                return subprocess.Popen(cmd, env=env)

            def launch_builder(cmd):
                return subprocess.Popen(cmd, env=_child_env())

            def launch_inheriting(cmd):
                return subprocess.Popen(cmd)
        ''',
    })
    found = effects.check_effects(
        good, config=effects.EffectsConfig(env=spec),
    )
    assert "env-propagation" not in {f.rule for f in found}


def test_effects_fault_scope(tmp_path):
    """faults.inject in a module outside the declared fault_modules
    set fires; declaring the module clears it; a declared module with
    no inject site is itself stale."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {"rogue.py": '''
        from tsspark_tpu.resilience import faults

        def risky():
            faults.inject("rogue_point")
    '''})
    found = effects.check_effects(root, config=effects.EffectsConfig())
    assert "fault-scope" in {f.rule for f in found}

    found = effects.check_effects(root, config=effects.EffectsConfig(
        fault_modules=("tsspark_tpu/rogue.py",),
    ))
    assert "fault-scope" not in {f.rule for f in found}

    found = effects.check_effects(root, config=effects.EffectsConfig(
        fault_modules=("tsspark_tpu/rogue.py",
                       "tsspark_tpu/gone.py"),
    ))
    assert any(f.rule == "effect-model" and "gone.py" in f.qualname
               for f in found)


def test_effects_config_validation(tmp_path):
    """A typo'd budget must raise at load, and a root matching no
    function must surface as effect-model — a budget silently checking
    nothing passes vacuously."""
    from tsspark_tpu.analysis import effects

    (tmp_path / "pyproject.toml").write_text(textwrap.dedent('''
        [[tool.tsspark.analysis.effects.paths]]
        name = "bad"
        roots = ["tsspark_tpu/x.py::f"]
        forbid = ["jax-dispatcb"]
    '''))
    with pytest.raises(ValueError):
        effects.load_config(str(tmp_path))

    root = _effects_pkg(tmp_path, {"x.py": "def f():\n    pass\n"})
    found = effects.check_effects(root, config=_budget(
        "ghost", ["tsspark_tpu/x.py::no_such_fn"], ["spawn"],
    ))
    assert [f.rule for f in found] == ["effect-model"]


def test_effects_transitive_signature(tmp_path):
    """The inferred signature unions effects bottom-up over the call
    graph; an unrelated same-named nested function does not leak in."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {"m.py": '''
        import os
        import subprocess

        def top():
            mid()

        def mid():
            subprocess.run(["true"])

        def clean():
            def loop():
                return 1
            return loop()

        def other():
            def loop():
                os.makedirs("x")
            return loop()
    '''})
    g = effects.scan_package(root)
    top = g.transitive_effects(("tsspark_tpu/m.py", "top"))
    assert "spawn" in top and "raw-fs-write" not in top
    clean = g.transitive_effects(("tsspark_tpu/m.py", "clean"))
    assert clean == set()  # other()'s loop must not join clean()'s


def test_effects_pyproject_budgets_declared():
    """The ISSUE's acceptance claim: the committed pyproject declares
    the serve hot-read-path and maintenance-thread budgets, and the
    inherited env specs the spawn sites must forward."""
    from tsspark_tpu.analysis import effects

    cfg = effects.load_config(repo_root())
    budgets = {p.name: p for p in cfg.paths}
    respond = budgets["serve-respond"]
    assert {"jax-compile", "durable-write", "spawn"} <= set(
        respond.forbid
    )
    assert any("_respond_forecast" in r for r in respond.roots)
    threads = budgets["serve-threads"]
    assert {"jax-dispatch", "jax-compile"} <= set(threads.forbid)
    assert any("_heartbeat" in r for r in threads.roots)
    assert "sched-idle" in budgets and "registry-read" in budgets
    env = {s.var: s for s in cfg.env}
    for var in ("TSSPARK_FAULTS", "TSSPARK_TRACE",
                "TSSPARK_DISK_BUDGET_BYTES"):
        assert env[var].inherit, f"{var} must be marked inherited"
    assert cfg.fault_modules  # the kill-point surface is closed


def test_analysis_slo_budget_present():
    """The gate self-SLO: the analysis RUNHISTORY family is sentinel-
    gated like bench/serve/chaos — zero unwaived findings, bounded
    wall."""
    from tsspark_tpu.obs import regress

    budget = regress.load_slo(repo_root())["budgets"]["analysis"]
    assert budget["findings"]["direction"] == "lower"
    assert budget["findings"]["max_rise_abs"] == 0.0
    assert budget["wall_s"]["direction"] == "lower"
    assert regress.DEFAULT_SLO["budgets"]["analysis"] == budget


def test_effects_live_tree_clean():
    """The effects gate over this repository: the committed budgets
    hold with zero unwaived findings (the fast, contracts-free slice
    of test_repo_passes_full_analysis)."""
    from tsspark_tpu.analysis import effects

    found = effects.check_effects(repo_root())
    assert not found, "\n".join(str(f) for f in found)


def test_effects_changed_scope_limits_site_rules(tmp_path):
    """--changed semantics: per-site rules narrow to the touched
    modules, the path budgets still run whole."""
    from tsspark_tpu.analysis import effects

    root = _effects_pkg(tmp_path, {
        "a.py": '''
            import os

            def read_a():
                return os.environ.get("TSSPARK_UNREG_A")
        ''',
        "b.py": '''
            import os

            def write_b():
                os.makedirs("x")

            def root_b():
                write_b()
        ''',
    })
    cfg = _budget("b", ["tsspark_tpu/b.py::root_b"], ["raw-fs-write"])
    found = effects.check_effects(
        root, config=cfg,
        scope_paths=[os.path.join(root, "tsspark_tpu", "b.py")],
    )
    rules = [f.rule for f in found]
    assert "effect-budget" in rules       # budget checked whole
    assert "env-unregistered" not in rules  # a.py out of scope
    found = effects.check_effects(root, config=cfg)
    assert "env-unregistered" in {f.rule for f in found}


# ---------------------------------------------------------------------------
# stale-waiver detection (analysis/waivers.py)
# ---------------------------------------------------------------------------

def test_stale_waiver_fires_and_consumed_passes(tmp_path):
    from tsspark_tpu.analysis import waivers

    root = _effects_pkg(tmp_path, {"mod.py": '''
        def f():
            x = 1  # lint-ok[trace-branch]: excuses nothing anymore
            y = 2  # lint-ok[lock-guard]: this one is still consumed
            return x + y
    '''})
    pkg = os.path.join(root, "tsspark_tpu")
    consumed = {("tsspark_tpu/mod.py", 4, "lock-guard")}
    found = waivers.check_stale(pkg, root, consumed, [], [])
    assert [f.rule for f in found] == ["stale-waiver"]
    assert found[0].line == 3 and "trace-branch" in found[0].message

    # An all-consumed tree is clean.
    consumed.add(("tsspark_tpu/mod.py", 3, "trace-branch"))
    assert not waivers.check_stale(pkg, root, consumed, [], [])


def test_stale_baseline_suppression_fires(tmp_path):
    from tsspark_tpu.analysis import waivers

    root = _effects_pkg(tmp_path, {"mod.py": "def f():\n    pass\n"})
    pkg = os.path.join(root, "tsspark_tpu")
    live = Finding("host-sync", "tsspark_tpu/mod.py", 1, "f", "x")
    keys = [("host-sync", "tsspark_tpu/mod.py", "f"),
            ("host-sync", "tsspark_tpu/mod.py", "ghost_fn")]
    found = waivers.check_stale(pkg, root, set(), keys, [live])
    assert [f.rule for f in found] == ["stale-waiver"]
    assert found[0].qualname == "ghost_fn"


def test_waiver_hits_recorded_by_line_ok(tmp_path):
    """The instrumentation contract: a waiver that suppresses a real
    finding lands in WAIVER_HITS; lint_paths on a waived violation is
    exactly that."""
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent('''
        import jax

        @jax.jit
        def k(x):
            if x > 0:  # lint-ok[trace-branch]: fixture waiver
                x = x + 1
            return x
    '''))
    tracelint.reset_waiver_hits()
    found = tracelint.lint_paths([str(p)], str(tmp_path))
    assert not [f for f in found if f.rule == "trace-branch"]
    assert any(rule == "trace-branch"
               for _p, _l, rule in tracelint.WAIVER_HITS)


def test_run_all_full_pass_reports_stale_count():
    """The tier-1 wiring: a full run_all carries the stale sweep in
    its counts (zero on the live tree — waivers die with their code)."""
    report = analysis.run_all(root=repo_root())
    counts = dict(report.counts)
    assert "effects" in counts and "stale" in counts
    assert counts["stale"] == 0
