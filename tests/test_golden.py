"""Golden-value model-math tests: hand-derived constants, never code-derived.

The CPU parity oracle (backends/cpu.py) optimizes the SAME loss code as the
TPU path, so sMAPE parity validates the solver but not the model math
(round-3 verdict, Missing #3).  These fixtures break that loop: every
expected value below is derived by hand in the adjacent comment, directly
from the public Prophet model definition the reference implements
(``tsspark.fit.prophet``, BASELINE.json:5; source unavailable — SURVEY.md
§0), and asserted against the code.  Nothing here calls the code under test
to produce its own expectation.
"""

import numpy as np
import jax.numpy as jnp
import numpy.testing as npt

from tsspark_tpu.config import ProphetConfig
from tsspark_tpu.models.prophet import trend
from tsspark_tpu.models.prophet.design import FitData
from tsspark_tpu.models.prophet.loss import neg_log_posterior
from tsspark_tpu.models.prophet.seasonality import fourier_features


def test_piecewise_linear_golden():
    # g(t) = k*t + m + sum_j delta_j * relu(t - s_j)
    # k=0.5, m=1.0, delta=(0.2, -0.4), s=(0.3, 0.6):
    #   t=0.00: 0.5*0.00 + 1 + 0        + 0           = 1.000
    #   t=0.25: 0.5*0.25 + 1 + 0        + 0           = 1.125
    #   t=0.50: 0.5*0.50 + 1 + 0.2*0.20 + 0           = 1.290
    #   t=0.75: 0.5*0.75 + 1 + 0.2*0.45 - 0.4*0.15    = 1.405
    #   t=1.00: 0.5*1.00 + 1 + 0.2*0.70 - 0.4*0.40    = 1.480
    t = jnp.array([[0.0, 0.25, 0.5, 0.75, 1.0]])
    g = trend.piecewise_linear(
        t,
        k=jnp.array([0.5]),
        m=jnp.array([1.0]),
        delta=jnp.array([[0.2, -0.4]]),
        s=jnp.array([[0.3, 0.6]]),
    )
    npt.assert_allclose(
        np.asarray(g)[0], [1.0, 1.125, 1.29, 1.405, 1.48], rtol=1e-6
    )


def test_step_weighted_sum_boundary_golden():
    # sum_j v_j * 1[t >= s_j], v=(1, 10), s=(0.3, 0.6).  The changepoint is
    # active AT its own timestamp (searchsorted side="right" convention):
    #   t=0.29 -> 0;  t=0.30 -> 1;  t=0.59 -> 1;  t=0.60 -> 11
    t = jnp.array([[0.29, 0.30, 0.59, 0.60]])
    out = trend.step_weighted_sum(
        jnp.array([[1.0, 10.0]]), t, jnp.array([[0.3, 0.6]])
    )
    npt.assert_allclose(np.asarray(out)[0], [0.0, 1.0, 1.0, 11.0], atol=1e-6)


def test_fourier_features_golden():
    # period=7, order=2; columns are [sin(2pi t/7), cos(2pi t/7),
    # sin(4pi t/7), cos(4pi t/7)]:
    #   t=0.00: [sin 0,      cos 0,      sin 0,    cos 0  ] = [ 0, 1,  0,  1]
    #   t=1.75: [sin(pi/2),  cos(pi/2),  sin(pi),  cos(pi)] = [ 1, 0,  0, -1]
    #   t=3.50: [sin(pi),    cos(pi),    sin(2pi), cos(2pi)]= [ 0,-1,  0,  1]
    feats = fourier_features(np.array([0.0, 1.75, 3.5]), period=7.0, order=2)
    want = np.array([
        [0.0, 1.0, 0.0, 1.0],
        [1.0, 0.0, 0.0, -1.0],
        [0.0, -1.0, 0.0, 1.0],
    ])
    npt.assert_allclose(np.asarray(feats), want, atol=2e-7)


def test_logistic_gamma_golden():
    # Public Prophet offset recursion, one changepoint:
    #   gamma_1 = (s_1 - m - 0) * (1 - k / (k + delta_1))
    # k=1, m=0.4, delta=0.5, s=0.5:
    #   gamma_1 = (0.5 - 0.4) * (1 - 1/1.5) = 0.1 * (1/3) = 1/30
    gamma = trend._logistic_gamma(
        k=jnp.array([1.0]),
        m=jnp.array([0.4]),
        delta=jnp.array([[0.5]]),
        s=jnp.array([[0.5]]),
    )
    npt.assert_allclose(np.asarray(gamma)[0], [1.0 / 30.0], rtol=1e-6)


def test_logistic_trend_golden():
    # g(t) = cap * sigmoid((k + A delta) * (t - (m + A gamma)))
    # k=1, m=0.4, delta=(0.5,), s=(0.5,), cap=2, gamma_1 = 1/30 (above):
    #   t=0.25 (< s): 2*sigmoid(1.0*(0.25-0.4))      = 2*sigmoid(-0.15)
    #       e^0.15 = 1.16183424; 1/(1+1.16183424) = 0.46257015
    #       -> 0.92514030
    #   t=0.50 (= s, changepoint active): rate=1.5, offset=0.4+1/30
    #       2*sigmoid(1.5*(0.5-0.43333333)) = 2*sigmoid(0.1)
    #       e^-0.1 = 0.90483742; 1/1.90483742 = 0.52497919 -> 1.04995837
    #   t=1.00: 2*sigmoid(1.5*(1.0-0.43333333)) = 2*sigmoid(0.85)
    #       e^-0.85 = 0.42741493; 1/1.42741493 = 0.70056714 -> 1.40113428
    # Continuity at the changepoint: the left limit 2*sigmoid(1.0*(0.5-0.4))
    # = 2*sigmoid(0.1) equals the right value — that is what gamma is for.
    t = jnp.array([[0.25, 0.5, 1.0]])
    g = trend.logistic(
        t,
        cap=jnp.full((1, 3), 2.0),
        k=jnp.array([1.0]),
        m=jnp.array([0.4]),
        delta=jnp.array([[0.5]]),
        s=jnp.array([[0.5]]),
    )
    npt.assert_allclose(
        np.asarray(g)[0], [0.92514030, 1.04995837, 1.40113428], rtol=1e-6
    )


def _bare_fit_data(t, y, s, n_cp):
    t = np.asarray(t, np.float32)
    y = np.asarray(y, np.float32)
    s = np.asarray(s, np.float32)
    b, t_len = y.shape
    return FitData(
        t=jnp.asarray(t),
        y=jnp.asarray(y),
        mask=jnp.ones((b, t_len), jnp.float32),
        s=jnp.asarray(s).reshape(b, n_cp),
        cap=jnp.ones((b, t_len), jnp.float32),
        X_season=jnp.zeros((t_len, 0), jnp.float32),
        X_reg=jnp.zeros((b, t_len, 0), jnp.float32),
        prior_scales=jnp.zeros((0,), jnp.float32),
        mult_mask=jnp.zeros((0,), jnp.float32),
    )


def test_neg_log_posterior_golden_no_changepoints():
    # Config: linear growth, no seasonality/regressors/changepoints.
    # Defaults: k_prior_scale=5, m_prior_scale=5, sigma_prior_scale=0.5
    # (config.py).  theta = [k=0.2, m=0.1, log_sigma=0].
    #
    # sigma = SIGMA_FLOOR + exp(0) = 1.00001          (loss.py _SIGMA_FLOOR)
    # yhat  = k*t + m = [0.1, 0.3];  y = [0.5, 0.7];  resid = [0.4, 0.4]
    # nll   = 0.5 * 0.32 / sigma^2 + 2 * ln(sigma)
    #       = 0.16 / 1.0000200001 + 2 * 9.99995e-6
    #       = 0.15999680 + 0.00002000 = 0.16001680
    # prior = 0.5*(0.2/5)^2 + 0.5*(0.1/5)^2 + 0.5*(1.00001/0.5)^2
    #       = 0.0008 + 0.0002 + 0.5*4.00008000 = 0.0010 + 2.00004000
    #       = 2.00106000
    # total = 2.16107680
    cfg = ProphetConfig(seasonalities=(), n_changepoints=0)
    data = _bare_fit_data(
        t=[[0.0, 1.0]], y=[[0.5, 0.7]], s=[[]], n_cp=0
    )
    theta = jnp.array([[0.2, 0.1, 0.0]])
    val = float(neg_log_posterior(theta, data, cfg)[0])
    npt.assert_allclose(val, 2.16107680, rtol=1e-5)


def test_neg_log_posterior_golden_laplace_prior():
    # Same skeleton plus two changepoints, delta=(0.3, -0.2), s=(0.5, 0.75),
    # changepoint_prior_scale=0.05 (default).
    #
    # yhat(t=1) gains 0.3*relu(1-0.5) - 0.2*relu(1-0.75) = 0.15 - 0.05 = 0.1
    #   -> yhat = [0.1, 0.4]; resid = [0.4, 0.3]; sum resid^2 = 0.25
    # nll  = 0.5*0.25/1.0000200001 + 2*ln(1.00001)
    #      = 0.12499750 + 0.00002000 = 0.12501750
    # The Laplace kink is pseudo-Huber smoothed (loss.py _smooth_abs,
    # eps=1e-4): smooth_abs(x) = sqrt(x^2 + 1e-8) - 1e-4
    #   smooth_abs(0.3)  = 0.30000002 - 0.0001 = 0.29990002
    #   smooth_abs(-0.2) = 0.20000002 - 0.0001 = 0.19990002
    #   laplace = (0.29990002 + 0.19990002) / 0.05 = 9.99600083
    # gaussian priors (as above) = 2.00106000
    # total = 0.12501750 + 2.00106000 + 9.99600083 = 12.12207833
    cfg = ProphetConfig(seasonalities=(), n_changepoints=2)
    data = _bare_fit_data(
        t=[[0.0, 1.0]], y=[[0.5, 0.7]], s=[[0.5, 0.75]], n_cp=2
    )
    theta = jnp.array([[0.2, 0.1, 0.0, 0.3, -0.2]])
    val = float(neg_log_posterior(theta, data, cfg)[0])
    npt.assert_allclose(val, 12.12207833, rtol=1e-5)


def test_uniform_changepoints_golden():
    # n=4 changepoints over changepoint_range=0.8 of span [0, 1]:
    # fractions (1..4)/4 * 0.8 = [0.2, 0.4, 0.6, 0.8]
    s = trend.uniform_changepoints(
        np.array([0.0]), np.array([1.0]), 4, 0.8
    )
    npt.assert_allclose(np.asarray(s)[0], [0.2, 0.4, 0.6, 0.8], rtol=1e-6)
