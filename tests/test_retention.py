"""Retention safety under the degradation ladder (referenced from
tsspark_tpu/io/ladder.py): eager reaping and budget-refused publishes
may drop retained *history*, never the active version, a pinned plan's
cycle, or anything outside the cycle namespace."""

import json
import os
import random

import numpy as np
import jax.numpy as jnp
import pytest

from tsspark_tpu import refit
from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu.io import DiskFullError, atomic_write_text
from tsspark_tpu.io import budget as iobudget
from tsspark_tpu.serve import ParamRegistry

CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),),
    n_changepoints=3,
)
SOLVER = SolverConfig(max_iters=10)


def _mk_cycle(scratch, b, s, payload="x" * 64):
    plan = {"base_stamp": b, "plan_stamp": s}
    cdir = refit.cycle_paths(scratch, plan)[0]
    os.makedirs(os.path.join(cdir, "delta_data"), exist_ok=True)
    atomic_write_text(os.path.join(cdir, "delta_data", "rows.bin"),
                      payload)
    return cdir


def test_reap_cycles_property_spares_keep_and_non_cycle_paths(tmp_path):
    """Randomized trials: whatever the mix of cycle dirs, kept dirs,
    and bystander files, reap removes exactly the unkept ``cycle_*``
    dirs and nothing else."""
    rng = random.Random(1302)
    for trial in range(8):
        scratch = str(tmp_path / f"scratch{trial}")
        os.makedirs(scratch)
        # Bystanders that must survive any reap: the plan record, the
        # sched state, a registry-looking subdir, loose files.
        atomic_write_text(os.path.join(scratch, "refit_plan.json"),
                          json.dumps({"base_stamp": 1}))
        atomic_write_text(os.path.join(scratch, "sched_state.json"),
                          "{}")
        os.makedirs(os.path.join(scratch, "registry", "v000001"))
        atomic_write_text(
            os.path.join(scratch, "registry", "v000001", "m.json"),
            "{}")
        cycles = [_mk_cycle(scratch, b, b + 1)
                  for b in rng.sample(range(1, 500),
                                      rng.randrange(1, 7))]
        keep = [c for c in cycles if rng.random() < 0.5]
        refit.reap_cycles(scratch, keep=tuple(keep))
        survivors = {n for n in os.listdir(scratch)
                     if n.startswith("cycle_")}
        assert survivors == {os.path.basename(k) for k in keep}
        for k in keep:  # kept dirs intact, not just present
            assert os.path.exists(
                os.path.join(k, "delta_data", "rows.bin"))
        assert os.path.exists(
            os.path.join(scratch, "refit_plan.json"))
        assert os.path.exists(
            os.path.join(scratch, "registry", "v000001", "m.json"))


def test_reap_missing_scratch_is_a_noop(tmp_path):
    refit.reap_cycles(str(tmp_path / "never_made"))


def test_budget_refused_publish_never_disturbs_active_version(
        tmp_path, monkeypatch):
    """Disk pressure refuses NEW versions; it must not eat the one
    being served.  Arm an exhausted budget over a live registry, watch
    the publish fail typed, then verify the active version still loads
    bitwise-intact."""
    rng = np.random.default_rng(3)
    t = np.arange(96.0)
    y = (10 + 0.02 * t[None, :] + np.sin(2 * np.pi * t[None, :] / 7)
         + rng.normal(0, 0.1, (4, 96)))
    backend = get_backend("tpu", CFG, SOLVER)
    state = backend.fit(t, jnp.asarray(y))
    ids = [f"s{i}" for i in range(4)]
    root = str(tmp_path / "registry")
    reg = ParamRegistry(root, CFG)
    v1 = reg.publish(state, ids)
    before = {
        os.path.relpath(os.path.join(d, f), root)
        for d, _s, fs in os.walk(root) for f in fs
    }
    ref = reg.load()
    used = iobudget.DiskBudget(root).used_bytes()
    monkeypatch.setenv(iobudget.ENV_BUDGET_ROOT, root)
    monkeypatch.setenv(iobudget.ENV_BUDGET_BYTES, str(used))
    with pytest.raises(DiskFullError):
        reg.publish(state._replace(theta=state.theta * 1.01), ids)
    monkeypatch.delenv(iobudget.ENV_BUDGET_ROOT)
    monkeypatch.delenv(iobudget.ENV_BUDGET_BYTES)
    after = {
        os.path.relpath(os.path.join(d, f), root)
        for d, _s, fs in os.walk(root) for f in fs
    }
    # Nothing that existed before the refused publish was removed.
    assert before <= after
    snap = reg.load()
    assert snap.version == v1 and snap.fallback_from is None
    np.testing.assert_array_equal(
        np.asarray(snap.state.theta), np.asarray(ref.state.theta))
