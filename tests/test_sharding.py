"""Sharded fit over the virtual 8-device CPU mesh: results must match the
single-device fit, for pure series-sharding and for (series x time) meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    ShardingConfig,
    SolverConfig,
)
from tsspark_tpu.data import datasets
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.params import init_theta
from tsspark_tpu.ops import lbfgs
from tsspark_tpu.models.prophet.loss import value_and_grad_batch
from tsspark_tpu.parallel import mesh as mesh_mod
from tsspark_tpu.parallel import sharding


CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
)
SOLVER = SolverConfig(max_iters=60)


@pytest.fixture(scope="module")
def batch_data():
    batch = datasets.m4_hourly_like(n_series=11, max_len=280, seed=3)
    data, _ = prepare_fit_data(batch.ds, jnp.asarray(batch.y), CFG)
    theta0 = init_theta(CFG, data.y, data.mask, data.t)
    return data, theta0


def test_requires_8_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


def test_series_sharded_fit_matches_single_device(batch_data):
    data, theta0 = batch_data
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    res = sharding.fit_sharded(data, theta0, CFG, SOLVER, m)
    assert res.theta.shape == theta0.shape  # padding stripped (11 -> 16 -> 11)
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )


def test_series_time_mesh_fit(batch_data):
    data, theta0 = batch_data
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    m = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=2)
    res = sharding.fit_sharded(
        data, theta0, CFG, SOLVER, m, ShardingConfig(time_axis="time")
    )
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )


def test_mesh_validation():
    with pytest.raises(ValueError):
        mesh_mod.make_mesh(n_series_shards=3, n_time_shards=3)


def test_global_batch_feeds_sharded_fit():
    """multihost.global_batch (the per-host collect->shard step) must
    produce globally-sharded arrays that fit identically to host data.
    Single-process here; multi-process uses the same
    make_array_from_process_local_data contract."""
    from tsspark_tpu.parallel import multihost

    batch = datasets.m4_hourly_like(n_series=16, max_len=280, seed=5)
    data, _ = prepare_fit_data(batch.ds, jnp.asarray(batch.y), CFG)
    theta0 = init_theta(CFG, data.y, data.mask, data.t)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    cfg_sh = ShardingConfig()
    gdata = multihost.global_batch(data, m, cfg_sh)
    # Every leaf is sharded over the mesh per the declared specs.
    assert gdata.y.sharding.mesh.shape == m.shape
    assert gdata.y.sharding.spec == sharding.data_shardings(m, data, cfg_sh).y
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    res = sharding.fit_sharded(gdata, theta0, CFG, SOLVER, m)
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )
