"""Sharded fit over the virtual 8-device CPU mesh: results must match the
single-device fit, for pure series-sharding and for (series x time) meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tsspark_tpu.config import (
    ProphetConfig,
    SeasonalityConfig,
    ShardingConfig,
    SolverConfig,
)
from tsspark_tpu.data import datasets
from tsspark_tpu.models.prophet.design import prepare_fit_data
from tsspark_tpu.models.prophet.params import init_theta
from tsspark_tpu.ops import lbfgs
from tsspark_tpu.models.prophet.loss import value_and_grad_batch
from tsspark_tpu.parallel import mesh as mesh_mod
from tsspark_tpu.parallel import sharding


CFG = ProphetConfig(
    seasonalities=(SeasonalityConfig("weekly", 7.0, 2),), n_changepoints=4
)
SOLVER = SolverConfig(max_iters=60)


@pytest.fixture(scope="module")
def batch_data():
    batch = datasets.m4_hourly_like(n_series=11, max_len=280, seed=3)
    data, _ = prepare_fit_data(batch.ds, jnp.asarray(batch.y), CFG)
    theta0 = init_theta(CFG, data.y, data.mask, data.t)
    return data, theta0


def test_requires_8_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


def test_series_sharded_fit_matches_single_device(batch_data):
    data, theta0 = batch_data
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    res = sharding.fit_sharded(data, theta0, CFG, SOLVER, m)
    assert res.theta.shape == theta0.shape  # padding stripped (11 -> 16 -> 11)
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )


def test_series_time_mesh_fit(batch_data):
    data, theta0 = batch_data
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    m = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=2)
    res = sharding.fit_sharded(
        data, theta0, CFG, SOLVER, m, ShardingConfig(time_axis="time")
    )
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )


def test_mesh_validation():
    with pytest.raises(ValueError):
        mesh_mod.make_mesh(n_series_shards=3, n_time_shards=3)


def test_global_batch_feeds_sharded_fit():
    """multihost.global_batch (the per-host collect->shard step) must
    produce globally-sharded arrays that fit identically to host data.
    Single-process here; multi-process uses the same
    make_array_from_process_local_data contract."""
    from tsspark_tpu.parallel import multihost

    batch = datasets.m4_hourly_like(n_series=16, max_len=280, seed=5)
    data, _ = prepare_fit_data(batch.ds, jnp.asarray(batch.y), CFG)
    theta0 = init_theta(CFG, data.y, data.mask, data.t)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    cfg_sh = ShardingConfig()
    gdata = multihost.global_batch(data, m, cfg_sh)
    # Every leaf is sharded over the mesh per the declared specs.
    assert gdata.y.sharding.mesh.shape == m.shape
    assert gdata.y.sharding.spec == sharding.data_shardings(m, data, cfg_sh).y
    ref = lbfgs.minimize(
        lambda th: value_and_grad_batch(th, data, CFG), theta0, SOLVER
    )
    res = sharding.fit_sharded(gdata, theta0, CFG, SOLVER, m)
    np.testing.assert_allclose(
        np.asarray(res.f), np.asarray(ref.f), rtol=2e-3, atol=2e-3
    )


def test_tpu_backend_mesh_routing():
    """TpuBackend(mesh=...) routes fits through the sharded program and
    lands on results equivalent to the unsharded backend — the public
    multi-chip path (collect -> shard -> fit -> scatter) behind the same
    fit signature."""
    from tsspark_tpu.backends.tpu import TpuBackend

    rng = np.random.default_rng(7)
    n, t_len = 11, 200
    ds = np.arange(t_len, dtype=np.float64) + 19000.0
    y = (
        5.0 + 0.02 * np.arange(t_len) + np.sin(2 * np.pi * np.arange(t_len) / 7.0)
        + rng.normal(0, 0.15, (n, t_len))
    )
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    plain = TpuBackend(CFG, SOLVER).fit(ds, y)
    # Routing proof: the mesh fit must actually go through the sharded
    # program — fit_sharded_packed for this packable batch, fit_sharded
    # otherwise (results alone can't tell — the single-device fit is the
    # oracle).
    calls = []
    orig_u = sharding.fit_sharded
    orig_p = sharding.fit_sharded_packed

    def counting_u(*a, **k):
        calls.append("plain")
        return orig_u(*a, **k)

    def counting_p(*a, **k):
        calls.append("packed")
        return orig_p(*a, **k)

    sharding.fit_sharded = counting_u
    sharding.fit_sharded_packed = counting_p
    try:
        shard = TpuBackend(CFG, SOLVER, mesh=m).fit(ds, y)
    finally:
        sharding.fit_sharded = orig_u
        sharding.fit_sharded_packed = orig_p
    assert calls, "mesh fit did not route through the sharded program"
    assert np.asarray(shard.theta).shape == np.asarray(plain.theta).shape
    # Same optimum quality: one-sided loss comparison at f32 tolerance
    # (the sharded trajectory may differ in reduction order).
    scale = np.maximum(np.abs(np.asarray(plain.loss)), 1.0)
    assert float(np.max(
        (np.asarray(shard.loss) - np.asarray(plain.loss)) / scale
    )) < 2e-3
    # Scaling meta rides through for predict.
    np.testing.assert_allclose(
        np.asarray(shard.meta.y_scale), np.asarray(plain.meta.y_scale)
    )


def _trend_sine_batch(b, t_len, seed):
    """Shared synthetic long-series generator for the time-sharded
    numerics tests: linear trend + weekly sine + iid noise."""
    rng = np.random.default_rng(seed)
    ds = np.arange(t_len, dtype=np.float64)
    y = (
        5.0 + 0.5 * ds / t_len + np.sin(2 * np.pi * ds / 7.0)
        + rng.normal(0, 0.1, (b, t_len))
    )
    return ds, y


def test_time_sharded_eval_ulp_parity():
    """Single-evaluation loss/grad on a time-sharded mesh must match the
    single-device evaluation to f32-ulp level (~2e-7 measured).  This is
    the primitive the whole sequence-parallel numerics story rests on:
    XLA's partitioned time reductions introduce only reduction-ORDER
    noise, not a systematic deviation — mid-trajectory solver drift is
    discrete line-search chaos amplifying these ulp seeds, not a
    gradient defect (docs/SEQUENCE_PARALLEL_NUMERICS.md)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tsspark_tpu.models.prophet.loss import value_and_grad_batch
    from tsspark_tpu.models.prophet.params import init_theta

    ds, y = _trend_sine_batch(b=8, t_len=1024, seed=2)
    data, _ = prepare_fit_data(jnp.asarray(ds), jnp.asarray(y), CFG)
    theta0 = init_theta(CFG, data.y, data.mask, data.t)
    f1, g1 = jax.jit(
        lambda th, d: value_and_grad_batch(th, d, CFG)
    )(theta0, data)

    m = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=2)
    scfg = ShardingConfig(time_axis="time")
    specs = sharding.data_shardings(m, data, scfg)
    data_sh = jax.device_put(data, jax.tree.map(
        lambda sp: NamedSharding(m, sp), specs,
        is_leaf=lambda x: isinstance(x, P),
    ))
    th_sh = jax.device_put(theta0, NamedSharding(m, P("series", None)))
    f2, g2 = jax.jit(
        lambda th, d: value_and_grad_batch(th, d, CFG)
    )(th_sh, data_sh)

    f_scale = max(float(jnp.max(jnp.abs(f1))), 1.0)
    g_scale = max(float(jnp.max(jnp.abs(g1))), 1.0)
    assert float(jnp.max(jnp.abs(f2 - f1))) / f_scale < 2e-6
    assert float(jnp.max(jnp.abs(g2 - g1))) / g_scale < 2e-6


def test_time_sharded_converged_loss_parity_long_series():
    """Long-series regime (the one time-sharding exists for): converged
    endpoints may sit at different points of the flat Laplace valley
    (theta parity is NOT promised at this scale — measured 1.8e-3), but
    the sharded solve's LOSS must match the single-device optimum
    one-sidedly at f32 tolerance (measured 5.9e-6)."""
    from tsspark_tpu.models.prophet.model import fit_core

    ds, y = _trend_sine_batch(b=16, t_len=512, seed=4)
    data, _ = prepare_fit_data(jnp.asarray(ds), jnp.asarray(y), CFG)
    solver = SolverConfig(max_iters=96, precond="gn_diag")
    ref = fit_core(data, None, CFG, solver)
    m = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=2)
    res = sharding.fit_sharded(
        data, None, CFG, solver, m, ShardingConfig(time_axis="time")
    )
    f_scale = max(float(jnp.max(jnp.abs(ref.f))), 1.0)
    d_worse = float(jnp.max(res.f - ref.f)) / f_scale
    assert d_worse < 5e-5, d_worse


def test_packed_unpack_bit_identical_under_mesh():
    """The packed transit is LOSSLESS under a mesh: unpacking the sharded
    PackedFitData reproduces the single-device unpack bit-for-bit (every
    unpack op is elementwise/broadcast, so partitioning cannot change a
    single value — the whole multi-chip packed-feed story rests on this)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tsspark_tpu.models.prophet.design import (
        pack_fit_data,
        unpack_fit_data,
    )

    ds, y = _trend_sine_batch(b=8, t_len=256, seed=6)
    mask = np.ones_like(y)
    mask[0, 200:] = 0.0
    data, meta = prepare_fit_data(
        jnp.asarray(ds), jnp.asarray(y), CFG, mask=jnp.asarray(mask),
        as_numpy=True,
    )
    packed, u8 = pack_fit_data(data, meta, ds, collapse_cap=True)
    ref = jax.jit(
        unpack_fit_data, static_argnames=("reg_u8_cols",)
    )(jax.tree.map(jnp.asarray, packed), reg_u8_cols=u8)

    m = mesh_mod.make_mesh(n_series_shards=4, n_time_shards=2)
    scfg = ShardingConfig(time_axis="time")
    pspecs = sharding.packed_shardings(m, packed, scfg)
    packed_sh = jax.device_put(packed, jax.tree.map(
        lambda sp: NamedSharding(m, sp), pspecs,
        is_leaf=lambda x: isinstance(x, P),
    ))
    un = jax.jit(
        unpack_fit_data, static_argnames=("reg_u8_cols",)
    )(packed_sh, reg_u8_cols=u8)
    for name in ref._fields:
        a = np.asarray(getattr(ref, name))
        b_ = np.asarray(getattr(un, name))
        np.testing.assert_array_equal(a, b_, err_msg=name)


def test_fit_sharded_packed_matches_plain_sharded():
    """fit_sharded_packed parity, two gates per layout:

    1. BIT-IDENTICAL to the single-device packed fit on the pure
       series-parallel 8x1 layout — partitioning along B touches no
       per-series reduction, so the mesh feed must not change one bit.
    2. Same optimum as the PLAIN sharded fit at f32 solver tolerance on
       both layouts (the packed t reconstruction differs by ~1 ulp from
       the host-built t, so exact equality is not defined here)."""
    from tsspark_tpu.models.prophet.design import pack_fit_data
    from tsspark_tpu.models.prophet.model import fit_core_packed

    ds, y = _trend_sine_batch(b=16, t_len=256, seed=8)
    data, meta = prepare_fit_data(
        jnp.asarray(ds), jnp.asarray(y), CFG, as_numpy=True
    )
    packed, u8 = pack_fit_data(data, meta, ds, collapse_cap=True)
    theta_sd, stats_sd = fit_core_packed(
        jax.tree.map(jnp.asarray, packed), None, CFG, SOLVER,
        reg_u8_cols=u8,
    )

    for n_s, n_t in ((8, 1), (4, 2)):
        m = mesh_mod.make_mesh(n_series_shards=n_s, n_time_shards=n_t)
        scfg = ShardingConfig(time_axis="time")
        ref = sharding.fit_sharded(data, None, CFG, SOLVER, m, scfg)
        res = sharding.fit_sharded_packed(
            packed, u8, None, CFG, SOLVER, m, scfg
        )
        assert np.asarray(res.theta).shape == np.asarray(ref.theta).shape
        scale = np.maximum(np.abs(np.asarray(ref.f)), 1.0)
        d = float(np.max((np.asarray(res.f) - np.asarray(ref.f)) / scale))
        assert d < 2e-3, (n_s, n_t, d)
        if n_t == 1:
            np.testing.assert_array_equal(
                np.asarray(res.theta), np.asarray(theta_sd)
            )
            np.testing.assert_array_equal(
                np.asarray(res.f), np.asarray(stats_sd)[0]
            )


def test_fit_sharded_packed_pads_ragged_batch():
    """A batch NOT divisible by the series-shard count exercises
    fit_sharded_packed's NaN-inert-row padding branch: results for the
    real rows must match the unpadded plain sharded fit, and padded rows
    must never leak (shape check)."""
    from tsspark_tpu.models.prophet.design import pack_fit_data

    ds, y = _trend_sine_batch(b=11, t_len=256, seed=12)
    data, meta = prepare_fit_data(
        jnp.asarray(ds), jnp.asarray(y), CFG, as_numpy=True
    )
    packed, u8 = pack_fit_data(data, meta, ds, collapse_cap=True)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    scfg = ShardingConfig(time_axis="time")
    ref = sharding.fit_sharded(data, None, CFG, SOLVER, m, scfg)
    res = sharding.fit_sharded_packed(packed, u8, None, CFG, SOLVER, m, scfg)
    assert np.asarray(res.theta).shape[0] == 11
    assert bool(np.asarray(res.converged).all())
    scale = np.maximum(np.abs(np.asarray(ref.f)), 1.0)
    d = float(np.max((np.asarray(res.f) - np.asarray(ref.f)) / scale))
    assert d < 2e-3, d


def test_tpu_backend_mesh_routes_packed():
    """TpuBackend(mesh=...) on a packable batch (shared grid, exact 0/1
    mask) must take the packed transit, not the plain sharded feed."""
    from tsspark_tpu.backends.tpu import TpuBackend

    ds, y = _trend_sine_batch(b=8, t_len=200, seed=10)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    calls = {"packed": 0, "plain": 0}
    orig_p, orig_u = sharding.fit_sharded_packed, sharding.fit_sharded

    def cp(*a, **k):
        calls["packed"] += 1
        return orig_p(*a, **k)

    def cu(*a, **k):
        calls["plain"] += 1
        return orig_u(*a, **k)

    sharding.fit_sharded_packed, sharding.fit_sharded = cp, cu
    try:
        state = TpuBackend(CFG, SOLVER, mesh=m).fit(ds, y)
    finally:
        sharding.fit_sharded_packed = orig_p
        sharding.fit_sharded = orig_u
    assert calls["packed"] >= 1 and calls["plain"] == 0, calls
    assert bool(np.isfinite(np.asarray(state.loss)).all())


def test_mesh_axis_names_override_position():
    """A user mesh declared ("time", "series") must not get the axes
    swapped by the default ShardingConfig: conventional axis NAMES win
    over position (ADVICE r4)."""
    from tsspark_tpu.backends.tpu import TpuBackend

    rng = np.random.default_rng(9)
    n, t_len = 8, 200
    ds = np.arange(t_len, dtype=np.float64) + 19000.0
    y = 4.0 + 0.01 * np.arange(t_len) + rng.normal(0, 0.1, (n, t_len))
    devs = np.array(jax.devices()).reshape(2, 4)
    m = jax.sharding.Mesh(devs, ("time", "series"))
    captured = {}
    orig_u = sharding.fit_sharded
    orig_p = sharding.fit_sharded_packed

    def capture_u(data, th, cfg, solver, mesh, shard_cfg, *a, **k):
        captured["cfg"] = shard_cfg
        return orig_u(data, th, cfg, solver, mesh, shard_cfg, *a, **k)

    def capture_p(packed, u8, th, cfg, solver, mesh, shard_cfg, *a, **k):
        captured["cfg"] = shard_cfg
        return orig_p(packed, u8, th, cfg, solver, mesh, shard_cfg,
                      *a, **k)

    sharding.fit_sharded = capture_u
    sharding.fit_sharded_packed = capture_p
    try:
        TpuBackend(CFG, SOLVER, mesh=m).fit(ds, y)
        assert captured["cfg"].series_axis == "series"
        assert captured["cfg"].time_axis == "time"
        # Symmetric case: only "time" is conventionally named — it must
        # stay the time axis even when listed first.
        m2 = jax.sharding.Mesh(devs, ("time", "batch"))
        TpuBackend(CFG, SOLVER, mesh=m2).fit(ds, y)
        assert captured["cfg"].series_axis == "batch"
        assert captured["cfg"].time_axis == "time"
    finally:
        sharding.fit_sharded = orig_u
        sharding.fit_sharded_packed = orig_p


def test_forecaster_mesh_end_to_end():
    """Forecaster(backend='tpu', mesh=...) — DataFrame in, sharded fit,
    forecast out."""
    import pandas as pd

    import tsspark_tpu as tt

    rng = np.random.default_rng(1)
    n = 240
    ds = pd.date_range("2023-01-01", periods=n, freq="D")
    rows = []
    for sid in range(5):
        y = 5 + sid + 0.01 * np.arange(n) + rng.normal(0, 0.1, n)
        rows.append(pd.DataFrame({"series_id": f"s{sid}", "ds": ds, "y": y}))
    df = pd.concat(rows, ignore_index=True)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    f = tt.Forecaster(CFG, backend="tpu", mesh=m).fit(df)
    fc = f.predict(horizon=7)
    assert np.isfinite(fc["yhat"].to_numpy()).all()
    assert len(fc) == 5 * 7


def test_mesh_with_length_bucketing():
    """mesh= and length_buckets compose: bucket sub-fits inherit the mesh
    (sliced time windows through the sharded program) and match the
    unsharded bucketed fit."""
    from tsspark_tpu.backends.tpu import TpuBackend

    rng = np.random.default_rng(11)
    n, t_len = 48, 512
    ds = np.arange(t_len, dtype=np.float64) + 19000.0
    y = (
        4.0 + 0.01 * np.arange(t_len)
        + np.sin(2 * np.pi * np.arange(t_len) / 7.0)
        + rng.normal(0, 0.1, (n, t_len))
    )
    mask = np.ones((n, t_len), np.float32)
    # Right-aligned ragged history: half the series observe only the last
    # 160 steps -> the bucket planner slices their time window.
    mask[: n // 2, : t_len - 160] = 0.0
    y = np.where(mask > 0, y, 0.0)
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    plain = TpuBackend(CFG, SOLVER, length_buckets=2).fit(ds, y, mask=mask)
    shard = TpuBackend(CFG, SOLVER, length_buckets=2, mesh=m).fit(
        ds, y, mask=mask
    )
    scale = np.maximum(np.abs(np.asarray(plain.loss)), 1.0)
    worse = float(np.max(
        (np.asarray(shard.loss) - np.asarray(plain.loss)) / scale
    ))
    assert worse < 2e-3, worse
    assert np.isfinite(np.asarray(shard.theta)).all()


def test_resolve_time_axis_prefers_time_on_3_axis_mesh():
    """ADVICE r5: with time_axis unset, an axis literally NAMED "time"
    must win over the first non-series axis — on ("series", "x", "time")
    the positional fallback would lay time-major leaves on "x" and leave
    the declared time axis unused."""
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()).reshape(2, 2, 2)
    mesh3 = Mesh(devs, axis_names=("series", "x", "time"))
    cfg = ShardingConfig(series_axis="series", time_axis=None)
    assert sharding._resolve_time_axis(mesh3, cfg) == "time"
    # An explicit declaration still wins over the conventional name.
    assert sharding._resolve_time_axis(
        mesh3, ShardingConfig(series_axis="series", time_axis="x")
    ) == "x"
    # No "time" axis: first non-series fallback is unchanged.
    mesh2 = Mesh(devs.reshape(4, 2), axis_names=("series", "seq"))
    assert sharding._resolve_time_axis(
        mesh2, ShardingConfig(series_axis="series", time_axis=None)
    ) == "seq"
    # And the spec builders agree with the resolution end to end: the
    # (B, T) leaves carry ("series", ..., "time") on the 3-axis mesh.
    from tsspark_tpu.models.prophet.design import FitData

    fake = FitData(
        t=np.zeros((8, 16)), y=np.zeros((8, 16)), mask=np.zeros((8, 16)),
        s=np.zeros((8, 1)), cap=np.zeros((8, 16)),
        X_season=np.zeros((16, 4)), X_reg=np.zeros((8, 16, 0)),
        prior_scales=np.zeros(4), mult_mask=np.zeros(4),
    )
    specs = sharding.data_shardings(mesh3, fake, cfg)
    assert tuple(specs.y) == ("series", "time")


def test_mesh_chunked_fit_matches_single_device_chunked():
    """Mesh-scale chunked behavior (VERDICT Next #8): a >= 4-chunk batch
    through TpuBackend(mesh=..., chunk_size=...) must equal the
    single-device chunked path — chunking and sharding compose, with no
    per-chunk routing drift (every chunk rides the sharded program, and
    the chunk boundaries land on the same rows)."""
    from tsspark_tpu.backends.tpu import TpuBackend

    batch = datasets.m4_hourly_like(n_series=64, max_len=240, seed=11)
    ds, y = batch.ds, batch.y
    m = mesh_mod.make_mesh(n_series_shards=8, n_time_shards=1)
    ref = TpuBackend(CFG, SOLVER, chunk_size=16).fit(ds, y)
    shard = TpuBackend(CFG, SOLVER, chunk_size=16, mesh=m).fit(ds, y)
    # 64 series / chunk 16 = 4 chunks on both paths.
    assert np.asarray(shard.theta).shape == np.asarray(ref.theta).shape
    assert np.asarray(shard.loss).shape == (64,)
    scale = np.maximum(np.abs(np.asarray(ref.loss)), 1.0)
    np.testing.assert_allclose(
        np.asarray(shard.loss) / scale, np.asarray(ref.loss) / scale,
        rtol=0, atol=2e-3,
    )
    assert np.isfinite(np.asarray(shard.theta)).all()
    # The scaling meta must be bit-identical: chunk-local prep sees the
    # same rows in the same order on both paths.
    np.testing.assert_array_equal(
        np.asarray(shard.meta.y_scale), np.asarray(ref.meta.y_scale)
    )
