"""Mesh-resident single-program fit path (``orchestrate --resident``).

The chunk-file protocol (``tsspark_tpu.orchestrate``) is correct and
crash-safe, but every chunk pays a process spawn, a host->device
transfer negotiated from scratch, and a prep-file landing.  When a
``jax.sharding.Mesh`` is available — real accelerator devices, or the
CPU virtual-device mesh the test/bench harness forces — the whole fleet
of series can instead run as ONE accelerator-resident program stream:

* **same program, sharded** — every wave dispatches
  ``parallel.sharding.fit_resident_core``, whose traced body is EXACTLY
  ``fit_core_packed``'s (the chunk workers' program) with inputs
  ``device_put`` under the resident partition rules
  (``resident_partition_rules`` -> ``match_partition_rules`` ->
  ``make_shard_and_gather_fns``).  Per-series math is shard-local
  (series-axis partitioning only), so per-series results are BITWISE
  the file protocol's — ``tests/test_resident.py`` pins it on the
  virtual 8-device mesh, full run and crash-resume both.
* **plane-fed** — claims gate on the data plane's landed shard coverage
  (``data.plane.ready_coverage``) and read the column memmaps directly;
  there are no per-chunk prep files (the memmap layout IS the prep
  input, PR 9).
* **checkpointed through the same protocol** — every wave's result
  lands through ``save_chunk_atomic`` under the same lease fencing, so
  resilience, crash-resume, exactly-once coverage, and
  ``publish_fit_state`` hold unchanged; a killed resident run resumes
  from its last landed flush exactly like a killed chunk worker
  (the ``resident-kill`` chaos class drives this).
* **fallback** — a meshless box (one device, or no JAX runtime) warns
  ONCE and degrades to the chunk-file protocol automatically
  (``run_resilient``): the file protocol remains the fault-domain
  fallback, never a separate code path to keep alive by hand.

Throughput levers carried over from the file protocol: the online
width autotuner (``perf.ChunkAutotuner``, here tuning the per-wave
shard width), the adaptive phase-1 depth policy
(``backends.tpu.tune_phase1_depth`` — ONE definition for both paths),
and async dispatch (a bounded in-flight pipeline; host prep and flush
overlap device compute).  Warm-start buffer DONATION was tried and
reverted: under pipelined overlap it corrupted shard results on the
forced-host multi-device backend — see ``fit_resident_core``'s
docstring for the measured evidence before re-adding it.

NOTE (nproc=1 boxes): on the CPU virtual-device mesh the win is the
removed per-chunk process spawn + JAX re-init + prep-file landing, not
parallel silicon — read CPU numbers as protocol overhead removed, and
see docs/PERF.md "Mesh-resident fit".
"""

from __future__ import annotations

import json
import os
import sys
import time
import warnings
from collections import deque
from typing import Callable, Optional

from tsspark_tpu import orchestrate
from tsspark_tpu.obs import context as obs
from tsspark_tpu.resilience import faults, integrity
from tsspark_tpu.io import (
    atomic_write,
    atomic_write_text,
    sweep_stale_temps,
)

#: The resident flush-state artifact: one small JSON replaced atomically
#: per flush, recording how far the resident program has landed (wave
#: index, coverage, mesh shape) — the on-disk progress signal an
#: operator (or the chaos harness) reads without parsing chunk files.
RESIDENT_STATE_FILE = "resident.json"

#: Minimum series rows per shard for a resident dispatch.  MEASURED, not
#: aesthetic: at 1 row per shard XLA picks a different reduction
#: strategy for the per-row time-axis reductions than the single-device
#: program uses, and the f32 accumulation-order difference diverges
#: whole trajectories — the bitwise-parity gate caught it on the chaos
#: profile's width-8 waves over 8 devices.  At >= 2 rows per shard every
#: tested shape is bitwise the single-device program.  Waves narrower
#: than ``2 * n_devices`` therefore run on a SUB-mesh
#: (``_shards_for_width``) instead of padding: padding the batch is not
#: an option either — the 8-real+8-inert 16-row program computes
#: different bits for the real rows than the 8-row program (batch width
#: is not per-row invariant under phase-1 geometry on this backend).
MIN_ROWS_PER_SHARD = 2

# One-shot flag for the meshless degradation warning: a fleet of calls
# on a meshless box must not warn per call (same pattern as the
# resilient-gate warnings in backends/tpu.py).
_MESHLESS_WARNED = False


def force_virtual_host_mesh(n: int = 8) -> None:
    """Force an ``n``-device virtual CPU mesh via ``XLA_FLAGS``
    (idempotent; an existing device-count setting wins).  Must run
    before JAX creates its backend.  THE one definition for every
    CPU-pinned entry point that needs the mesh — ``bench --resident``,
    the chaos CLI, the analysis gate — so the harnesses' "virtual
    8-device mesh" can never silently diverge (tests/conftest.py
    bootstraps the same flag before the package is importable)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def usable_mesh(min_devices: int = 2):
    """A 1-D series mesh over every local device, or None when the
    runtime cannot host a resident sharded program (fewer than
    ``min_devices`` devices, or JAX device init fails — e.g. a wedged
    accelerator runtime).  None means: use the file protocol."""
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return None
    if len(devices) < min_devices:
        return None
    from tsspark_tpu.parallel import mesh as mesh_mod

    return mesh_mod.make_mesh(
        n_series_shards=len(devices), n_time_shards=1, devices=devices
    )


def _shards_for_width(width: int, n_devices: int) -> int:
    """Series-shard count for one resident wave: the largest power of
    two that divides ``width``, fits the device count, and keeps at
    least :data:`MIN_ROWS_PER_SHARD` rows on every shard (see that
    constant for the measured parity rationale)."""
    k = 1
    while (k * 2 <= n_devices and width % (k * 2) == 0
           and width // (k * 2) >= MIN_ROWS_PER_SHARD):
        k *= 2
    return k


def _write_resident_state(out_dir: str, payload: dict) -> None:
    """Replace the resident flush-state artifact atomically (a watcher
    — or a successor run — never parses a torn record)."""
    atomic_write(
        os.path.join(out_dir, RESIDENT_STATE_FILE),
        lambda fh: json.dump(payload, fh), mode="w",
    )


def _times_row(out_dir: str, row: dict) -> None:
    """One times.jsonl row (same append-only diagnostics log the chunk
    workers write; readers tolerate a torn last line)."""
    with open(os.path.join(out_dir, "times.jsonl"), "a") as fh:
        fh.write(json.dumps(row) + "\n")


def run_resident(
    *,
    data_dir: str,
    out_dir: str,
    series: int,
    chunk: int = 1024,
    phase1_iters: int = 12,
    no_phase1_tune: bool = False,
    autotune: bool = False,
    pipeline_depth: int = 2,
    deadline: Optional[float] = None,
    reserve: Callable[[], float] = lambda: 10.0,
    mesh=None,
    state: Optional[dict] = None,
    fallback_opts: Optional[dict] = None,
    theta0_fn: Optional[Callable[[int, int], "object"]] = None,
) -> dict:
    """Run the whole fit as one mesh-resident sharded program stream.

    Drop-in peer of ``orchestrate.run_resilient`` over the same scratch
    protocol: ``data_dir`` is a spill dir or plane dataset, ``out_dir``
    accumulates the same ``chunk_*.npz`` coverage (a run killed at any
    point resumes from its landed flushes, here or via the file
    protocol — the two paths' artifacts are interchangeable).  Returns
    the mutated ``state`` dict with ``complete`` and ``fit_path``
    (``"resident"``, or ``"fileproto"`` after the meshless fallback).

    ``chunk`` is the claim width (the autotuner's cap with
    ``autotune=True``); ``pipeline_depth`` bounds in-flight waves —
    each completed wave is flushed to its chunk file before more than
    ``pipeline_depth`` dispatches queue, so the on-device -> checkpoint
    cadence is per wave, not end-of-run.

    ``fallback_opts``: extra ``run_resilient`` keywords for the
    meshless degradation (probe_budget_s, on_idle, progress_timeout,
    max_fruitless_retries, ...) — a wedged-accelerator box falls back
    WITH the caller's probe-budget protections, not the library
    defaults (bench.py forwards its usual resilience wiring here).

    ``theta0_fn``: optional warm start — ``fn(lo, hi)`` returns a host
    ``(hi - lo, n_params)`` float32 init for the wave's REAL rows (pad
    rows are zero-filled here); phase 1 then dispatches with
    ``use_theta0`` ON instead of the ridge init.  The delta-refit
    engine (``tsspark_tpu.refit``) gathers these rows per wave off the
    active snapshot plane's theta memmap.  ``use_theta0`` is a DYNAMIC
    traced arg, so warm and cold waves share one compiled program, and
    ``theta0_fn=None`` leaves the cold path bit-for-bit untouched (the
    bitwise-parity contract).  The init buffer is placed with
    ``device_put`` and NOT donated — the recorded PR 11 constraint:
    donation under pipelined overlap corrupts shard results on the
    forced-host multi-device backend.  The meshless fallback runs COLD
    (the chunk-file workers have no warm-start input); correctness is
    unchanged, only the warm-start perf lever is lost.
    """
    global _MESHLESS_WARNED
    if state is None:
        state = {}
    state.setdefault("retries", 0)
    mesh = mesh if mesh is not None else usable_mesh()
    if mesh is None:
        if not _MESHLESS_WARNED:
            _MESHLESS_WARNED = True
            warnings.warn(
                "run_resident: no usable device mesh on this box (one "
                "device, or JAX runtime init failed); degrading to the "
                "chunk-file protocol (orchestrate.run_resilient) — the "
                "fault-domain fallback.  Results are identical; the "
                "resident path's per-wave speedup is not.",
                RuntimeWarning, stacklevel=2,
            )
        kwargs = dict(
            data_dir=data_dir, out_dir=out_dir, series=series, chunk=chunk,
            min_chunk=min(orchestrate.MIN_CHUNK, chunk), segment=0,
            phase1_iters=phase1_iters, no_phase1_tune=no_phase1_tune,
            autotune=autotune, deadline=deadline, reserve=reserve,
            state=state,
        )
        kwargs.update(fallback_opts or {})
        out = orchestrate.run_resilient(**kwargs)
        out["fit_path"] = "fileproto"
        return out
    # Bounded recovery loop, the resident analog of run_resilient's
    # respawn loop: a round that ends with coverage incomplete (an
    # integrity sweep re-queued a torn chunk, a fenced wave discarded
    # its result, a drained ingest) is re-entered — _resident_body is
    # fully resumable — as long as it LANDED something; a zero-progress
    # round means the blocker is external (dead ingest, budget) and
    # looping would spin.
    rounds = 0
    while True:
        before = tuple(sorted(orchestrate.completed_ranges(out_dir)))
        rc = _resident_body(
            data_dir=data_dir, out_dir=out_dir, series=series, chunk=chunk,
            phase1_iters=phase1_iters, no_phase1_tune=no_phase1_tune,
            autotune=autotune, pipeline_depth=pipeline_depth,
            deadline=deadline, reserve=reserve, mesh=mesh, state=state,
            theta0_fn=theta0_fn,
        )
        complete = (rc == 0 and not orchestrate.missing_ranges(
            orchestrate.completed_ranges(out_dir), series
        ) and os.path.exists(os.path.join(out_dir, "phase2_done")))
        if complete or rc != 0:
            break  # done, or budget reached (landed coverage persists)
        rounds += 1
        state["retries"] = rounds
        # RANGE-SET change detection, not a count: a round that lands N
        # waves while its integrity sweep quarantines N torn ranges
        # keeps the count but changes the set — exactly the round that
        # must be re-entered to refit the quarantined coverage.
        changed = tuple(sorted(
            orchestrate.completed_ranges(out_dir)
        )) != before
        if not changed or rounds > 8:
            break
    state["fit_path"] = "resident"
    state["complete"] = complete
    return state


def _resident_body(*, data_dir, out_dir, series, chunk, phase1_iters,
                   no_phase1_tune, autotune, pipeline_depth, deadline,
                   reserve, mesh, state, theta0_fn=None) -> int:
    jax = orchestrate._setup_jax_child()
    import numpy as np

    from tsspark_tpu.backends.tpu import (
        difficulty_order,
        patch_state,
        phase1_dynamic_args,
        phase2_dynamic_args,
        tune_phase1_depth,
    )
    from tsspark_tpu.data import plane as data_plane
    from tsspark_tpu.models.prophet.design import pack_fit_data
    from tsspark_tpu.models.prophet.model import (
        ProphetModel,
        fitstate_from_packed,
    )
    from tsspark_tpu.parallel import sharding as sharding_mod
    from tsspark_tpu.perf import ChunkAutotuner, CompileWatch
    from tsspark_tpu.resilience.report import STATUS_QUARANTINED

    t_run0 = time.time()
    os.makedirs(out_dir, exist_ok=True)
    sweep_stale_temps(out_dir)
    integrity.sweep_chunks(out_dir)
    model_config, solver_config = orchestrate.load_run_config(out_dir)
    ds, d = orchestrate._load_data(data_dir)
    y, mask, reg = d["y"], d["mask"], d["reg"]
    cap, floor = d["cap"], d["floor"]
    model_ = ProphetModel(model_config, solver_config)
    n_params = model_config.num_params
    collapse_cap = model_config.growth != "logistic"
    max_iters = solver_config.max_iters
    two_phase = 0 < phase1_iters < max_iters
    series_axis = mesh.axis_names[0]
    n_shards = int(mesh.shape[series_axis])
    mesh_devices = list(mesh.devices.ravel())
    ingest_stall_s = float(os.environ.get("TSSPARK_INGEST_STALL_S", "30"))

    hb_path = os.path.join(out_dir, "heartbeat")

    def heartbeat():
        atomic_write_text(hb_path, str(time.time()))

    # The u8 indicator split: decide_u8_split is THE shared decision
    # (landed-coverage gating + self-produce) — a static argument of the
    # compiled program, so resident and file-protocol runs of the same
    # data always agree (their bitwise-parity precondition).
    u8_cols = orchestrate.decide_u8_split(
        data_dir, reg, series, heartbeat=heartbeat,
        stall_s=ingest_stall_s,
    )

    # Shard-width autotuner: the same pow-2 hill climber the chunk
    # workers use, persisted in the same autotune.json — here the size
    # is the per-WAVE resident width, floored at the device count
    # (tuner ``multiple``) so steady-state waves span the full mesh;
    # narrower widths the ladder still emits run on a sub-mesh
    # (_shards_for_width) rather than padding.
    tuner = None
    if autotune:
        tuner = ChunkAutotuner.load(
            os.path.join(out_dir, "autotune.json"),
            cap=chunk, floor=min(chunk, 128), multiple=n_shards,
        )
    compile_watch = CompileWatch((sharding_mod.fit_resident_core,))

    # Sub-mesh ladder: a wave narrower than 2 * n_shards runs on fewer
    # devices (MIN_ROWS_PER_SHARD — the measured bitwise-parity floor),
    # never padded.  Meshes/shard-fns are cached per shard count;
    # partition rules are built per payload shape family (X_season rank
    # decides the shared-vs-per-series rule, a per-dataset constant).
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    _meshes = {n_shards: mesh}
    shard_fns_cache: dict = {}
    _theta_shardings: dict = {}

    def mesh_for(k: int):
        if k not in _meshes:
            _meshes[k] = Mesh(
                np.asarray(mesh_devices[:k]).reshape(k, 1),
                mesh.axis_names,
            )
        return _meshes[k]

    def theta_sharding(k: int):
        if k not in _theta_shardings:
            _theta_shardings[k] = NamedSharding(
                mesh_for(k), P(series_axis, None)
            )
        return _theta_shardings[k]

    def shard_payload(packed, k: int):
        per_series = packed.X_season.ndim == 3
        key = (k, per_series)
        if key not in shard_fns_cache:
            specs = sharding_mod.match_partition_rules(
                sharding_mod.resident_partition_rules(
                    series_axis, per_series
                ),
                packed,
            )
            shard_fns_cache[key] = sharding_mod.make_shard_and_gather_fns(
                mesh_for(k), specs
            )[0]
        return jax.tree.map(
            lambda f, a: f(a), shard_fns_cache[key], packed
        )

    _zeros_theta: dict = {}

    def theta_zeros(width: int, k: int):
        # Host zeros cached per width, placed sharded per wave.  NOT
        # donated — see fit_resident_core's docstring: donation under
        # pipelined overlap corrupted shard results on this backend.
        if width not in _zeros_theta:
            _zeros_theta[width] = np.zeros((width, n_params), np.float32)
        return jax.device_put(_zeros_theta[width], theta_sharding(k))

    def theta_init(lo, hi, width, k):
        """The wave's init buffer: zeros (ridge init — the cold path,
        bit-for-bit the PR 11 program) or the caller's warm rows padded
        to the wave width (same placement, same no-donation rule)."""
        if theta0_fn is None:
            return theta_zeros(width, k)
        host = np.zeros((width, n_params), np.float32)
        host[:hi - lo] = np.asarray(theta0_fn(lo, hi), np.float32)
        return jax.device_put(host, theta_sharding(k))

    def prep(lo, hi, width):
        """Pack rows [lo, hi) padded to ``width`` — the chunk workers'
        exact prep (shared `_pad_chunk_rows`/`_chunk_mask`), reading the
        plane memmaps directly: no prep files, no spill copies."""
        rows = lambda a, fill=0.0: orchestrate._pad_chunk_rows(
            a, lo, hi, width, fill
        )
        y_c = rows(y)
        data, meta = model_.prepare(
            ds, y_c,
            mask=orchestrate._chunk_mask(y_c, mask, lo, hi, width),
            regressors=rows(reg), cap=rows(cap, fill=1.0),
            floor=rows(floor), as_numpy=True,
        )
        packed, _ = pack_fit_data(data, meta, ds, reg_u8_cols=u8_cols,
                                  collapse_cap=collapse_cap)
        return lo, hi, width, hi - lo, packed, meta

    # ---- claims: the chunk-file protocol's plan/lease machinery.
    # ---- Mirrors fit_worker's next_claim (orchestrate.py) minus the
    # ---- stolen-span bookkeeping; the claim invariants (plan_chunks
    # ---- disjointness, lease fencing, ready-coverage gating,
    # ---- stall-bounded self-produce) are THE SAME — change both. ----
    claimed: list = []
    held_leases: set = set()
    lease_token = f"resident.{os.getpid()}.{int(t_run0 * 1e3)}"
    claim_spans: dict = {}

    def next_claim(block: bool = True):
        waited = 0.0
        while True:
            width = tuner.next_size() if tuner is not None else chunk
            ready = data_plane.ready_coverage(data_dir, series)
            todo = orchestrate.plan_chunks(
                orchestrate.completed_ranges(out_dir) + claimed,
                0, series, width,
            )
            if ready is not None:
                todo = [(l2, h2) for l2, h2 in todo
                        if data_plane.covers(ready, l2, h2)]
            for lo2, hi2 in todo:
                claim_sid = obs.new_id() if obs.active() else None
                if not orchestrate.claim_lease(out_dir, lo2, hi2,
                                               lease_token,
                                               span_id=claim_sid):
                    continue
                claimed.append((lo2, hi2))
                held_leases.add((lo2, hi2))
                if claim_sid is not None:
                    claim_spans[(lo2, hi2)] = claim_sid
                    obs.record("chunk.claim", time.time(), 0.0,
                               span_id=claim_sid, lo=lo2, hi=hi2,
                               width=width, resident=True)
                return lo2, hi2, width
            if ready is None or not data_plane.ingest_pending(
                data_dir, series
            ):
                return None
            if not block:
                return None
            if deadline is not None and \
                    deadline - time.time() < reserve():
                # Unlike the file protocol (whose PARENT enforces the
                # deadline by killing the child), this wait runs in the
                # caller's process — it must not sleep out an ingest
                # stall past the reserve.
                return None
            heartbeat()
            time.sleep(0.5)
            waited += 0.5
            if waited >= ingest_stall_s:
                waited = 0.0
                if not data_plane.produce_next_missing(data_dir):
                    return None

    # ---- phase 1: pipelined resident waves ---------------------------
    depth = {"v": phase1_iters if two_phase else max_iters,
             "tuned": not two_phase or bool(no_phase1_tune)}
    crash_after = int(os.environ.get("TSSPARK_TEST_CRASH_AFTER", "0"))
    n_flushed = 0
    last_flush_t = {"t": t_run0}
    device_str = str(jax.devices()[0])

    def flush_wave(wave, tune: bool = True) -> Optional[object]:
        """Block on one in-flight wave and land it through the chunk
        protocol (the on-device -> checkpoint flush): lease fence ->
        save_chunk_atomic -> release, plus the same spans/metrics/
        times.jsonl telemetry the chunk workers emit.

        ``tune=False`` on DRAIN flushes (end-of-run / budget-stop tail):
        draining back-to-back pops measures milliseconds of
        flush-to-flush wall for waves that finished long ago, and
        feeding those phantom ~1000x series/s samples to the autotuner
        would persist a fake optimum into autotune.json."""
        nonlocal n_flushed
        (lo, hi, width, b_real, meta, theta, stats, compiled, t_sub,
         k_sh) = wave
        theta = np.asarray(theta)[:b_real]
        stats = np.asarray(stats)[:, :b_real]
        heartbeat()
        state_w = fitstate_from_packed(
            theta, stats,
            jax.tree.map(lambda a: np.asarray(a)[:b_real], meta),
        )
        now = time.time()
        wall = max(now - last_flush_t["t"], 1e-9)
        last_flush_t["t"] = now
        if not orchestrate.holds_lease(out_dir, lo, hi, lease_token):
            print(
                f"[resident] lease on [{lo}, {hi}) lost; discarding this "
                f"wave's result (fenced)", file=sys.stderr,
            )
            obs.event("fenced", lo=lo, hi=hi, resident=True)
            return None
        t_save0 = time.time()
        corrupted = orchestrate.save_chunk_atomic(out_dir, lo, hi, state_w)
        orchestrate.release_lease(out_dir, lo, hi, lease_token)
        held_leases.discard((lo, hi))
        if obs.active():
            fit_sid = obs.record(
                "chunk.fit", t_sub, t_save0 - t_sub,
                parent_id=claim_spans.get((lo, hi)),
                lo=lo, hi=hi, width=width, live=hi - lo,
                compile_miss=bool(compiled), resident=True,
            )
            obs.record("chunk.land", t_save0, time.time() - t_save0,
                       parent_id=fit_sid, lo=lo, hi=hi,
                       **({"corrupted": True} if corrupted else {}))
            orchestrate._metrics_chunk(hi - lo, wall)
        if tune and tuner is not None and hi - lo == width:
            tuner.record(width, hi - lo, wall, compile_miss=compiled)
        n_flushed += 1
        done_now = orchestrate.completed_ranges(out_dir)
        _write_resident_state(out_dir, {
            "unix": round(time.time(), 3), "wave": n_flushed,
            "landed": sum(h - l for l, h in done_now),
            "series": series, "mesh": [n_shards, 1],
            "width": width, "path": "resident",
        })
        _times_row(out_dir, {
            "lo": lo, "hi": hi, "fit_s": round(wall, 3),
            "chunk": chunk, "width": width, "live": hi - lo,
            "series_per_s": round((hi - lo) / wall, 2),
            "compile_miss": bool(compiled),
            "t": round(time.time() - t_run0, 2),
            "device": device_str, "path": "resident",
            "shards": k_sh,
        })
        # Chaos hook: the resident-kill fault class arms this point
        # (mode "exit" kills the program mid-flush-stream; the next run
        # resumes from the landed coverage above).
        faults.inject("resident_flush", lo=lo, hi=hi)
        if crash_after and n_flushed >= crash_after:
            os._exit(17)  # simulated mid-run resident death
        return state_w

    from concurrent.futures import ThreadPoolExecutor

    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            pending: deque = deque()    # prep futures
            inflight: deque = deque()   # dispatched waves awaiting flush

            def submit_prep(block=False) -> bool:
                c = next_claim(block=block)
                if c is None:
                    return False
                lo2, hi2, w2 = c
                pending.append(pool.submit(prep, lo2, hi2, w2))
                return True

            def dispatch(fut):
                lo, hi, width, b_real, packed, meta = fut.result()
                faults.inject("fit_chunk", lo=lo, hi=hi)
                t_sub = time.time()
                k = _shards_for_width(width, n_shards)
                snap = compile_watch.size()
                sharded = shard_payload(packed, k)
                theta, stats = sharding_mod.fit_resident_core(
                    sharded, theta_init(lo, hi, width, k), model_config,
                    solver_config, reg_u8_cols=u8_cols,
                    **phase1_dynamic_args(depth["v"],
                                          theta0_fn is not None,
                                          packed=True),
                )
                compiled = compile_watch.size() > snap
                return (lo, hi, width, b_real, meta, theta, stats,
                        compiled, t_sub, k)

            for i in range(pipeline_depth + 1):
                if not submit_prep(block=(i == 0)):
                    break
            while pending or inflight:
                if deadline is not None and \
                        deadline - time.time() < reserve():
                    while inflight:
                        flush_wave(inflight.popleft(), tune=False)
                    return 1  # budget reached; landed coverage persists
                if pending:
                    wave = dispatch(pending.popleft())
                    submit_prep()
                    if not depth["tuned"]:
                        # Depth must settle before wave 1 dispatches, so
                        # wave 0 flushes inline (same policy point as
                        # the chunk workers: backends.tpu.
                        # tune_phase1_depth).
                        st0 = flush_wave(wave)
                        if st0 is not None:
                            frac = float(
                                (~np.asarray(st0.converged)).mean()
                            )
                            depth["v"] = tune_phase1_depth(
                                depth["v"], frac, max_iters
                            )
                        depth["tuned"] = True
                    else:
                        inflight.append(wave)
                    while len(inflight) > pipeline_depth:
                        flush_wave(inflight.popleft())
                else:
                    # Pipeline draining (no prep in flight): these waves
                    # finished while earlier flushes ran — their
                    # flush-to-flush wall is not a throughput sample.
                    flush_wave(inflight.popleft(), tune=False)
                if not pending and not inflight:
                    submit_prep(block=True)
    finally:
        # Unflushed claims (budget stop, an exception mid-wave) must not
        # leave LIVE leases behind: this process stays alive, so a
        # fallback/successor run in the same process would be locked out
        # until expiry instead of reclaiming immediately.
        for lo_h, hi_h in sorted(held_leases):
            orchestrate.release_lease(out_dir, lo_h, hi_h, lease_token)
        held_leases.clear()

    # ---- phase 2: compacted stragglers through the same resident
    # ---- program (host gather off the memmaps, sharded dispatch).
    # ---- Mirrors the chunk workers' "host" phase-2 branch
    # ---- (orchestrate._fit_worker_body) — the bitwise-parity tests
    # ---- pin the two; change the gather/pad/patch logic in BOTH. ----
    marker = os.path.join(out_dir, "phase2_done")
    if integrity.sweep_chunks(out_dir):
        return 0  # corrupt ranges re-queued; the caller's rescan refits
    done = orchestrate.completed_ranges(out_dir)
    if orchestrate.missing_ranges(done, series):
        return 0
    if not two_phase:
        if not os.path.exists(marker):
            atomic_write_text(marker, "ok\n")
            obs.record("phase2.done", time.time(), 0.0)
        return 0
    if os.path.exists(marker):
        return 0

    t_p2 = time.time()
    straggler_idx, straggler_theta, straggler_gn = [], [], []
    files = {}
    for lo, hi in done:
        z = dict(np.load(orchestrate._chunk_path(out_dir, lo, hi)))
        files[(lo, hi)] = z
        if z.get("phase2") is not None:
            continue
        bad = np.flatnonzero(
            ~z["converged"] & (z["status"] != STATUS_QUARANTINED)
        )
        straggler_idx.extend(int(lo + i) for i in bad)
        straggler_theta.append(z["theta"][bad])
        straggler_gn.append(z["grad_norm"][bad])
    if straggler_idx:
        heartbeat()
        idx = np.asarray(straggler_idx)
        order = difficulty_order(np.concatenate(straggler_gn))
        idx = idx[order]
        theta_cat = np.concatenate(straggler_theta, axis=0)[order]
        n_s = len(straggler_idx)
        p2_chunk = tuner.best_size if tuner is not None else chunk
        pad = (-n_s) % p2_chunk
        pad_rows = lambda a: np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
        ) if pad else a
        g = lambda a: None if a is None else pad_rows(
            np.ascontiguousarray(a[idx], np.float32)
        )
        y_s = g(y)
        if mask is not None:
            m_s = g(mask)
        else:
            m_s = np.zeros_like(y_s)
            m_s[:idx.size] = np.isfinite(y_s[:idx.size])
        r_s, c_s, f_s = g(reg), g(cap), g(floor)
        init_s = pad_rows(theta_cat.astype(np.float32))
        k2 = _shards_for_width(p2_chunk, n_shards)
        subs = []
        for lo2 in range(0, n_s + pad, p2_chunk):
            hi2 = lo2 + p2_chunk
            sl = lambda a: None if a is None else a[lo2:hi2]
            data2, meta2 = model_.prepare(
                ds, y_s[lo2:hi2], mask=sl(m_s), regressors=sl(r_s),
                cap=sl(c_s), floor=sl(f_s), as_numpy=True,
            )
            packed2, _ = pack_fit_data(
                data2, meta2, ds, reg_u8_cols=u8_cols,
                collapse_cap=collapse_cap,
            )
            init2 = np.asarray(init_s[lo2:hi2], np.float32)
            th2, st2 = sharding_mod.fit_resident_core(
                shard_payload(packed2, k2),
                jax.device_put(init2, theta_sharding(k2)),
                model_config, solver_config, reg_u8_cols=u8_cols,
                **phase2_dynamic_args(solver_config, packed=True),
            )
            jax.block_until_ready(th2)
            heartbeat()
            subs.append(fitstate_from_packed(
                np.asarray(th2), np.asarray(st2), meta2,
            ))
        state2 = jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=0)[:n_s], *subs
        )
        for (lo, hi), z in files.items():
            if z.get("phase2") is not None:
                continue
            in_chunk = np.flatnonzero((idx >= lo) & (idx < hi))
            local = idx[in_chunk] - lo
            chunk_state = orchestrate._state_from_chunk(z)
            sub = jax.tree.map(
                lambda a: np.asarray(a)[in_chunk], state2
            )
            patched = patch_state(chunk_state, local, sub)
            t_patch0 = time.time()
            corrupted = orchestrate.save_chunk_atomic(
                out_dir, lo, hi, patched,
                extra_arrays={"phase2": np.asarray(1)},
            )
            obs.record("chunk.land", t_patch0, time.time() - t_patch0,
                       lo=lo, hi=hi, phase2=True,
                       **({"corrupted": True} if corrupted else {}))
    _times_row(out_dir, {
        "phase2_s": round(time.time() - t_p2, 3),
        "stragglers": len(straggler_idx),
        "phase2_mode": "resident-sharded",
    })
    atomic_write_text(marker, "ok\n")
    obs.record("fit.phase2", t_p2, time.time() - t_p2,
               stragglers=len(straggler_idx), mode="resident-sharded")
    obs.record("phase2.done", time.time(), 0.0)
    return 0


def resident_worker(args) -> int:
    """Child entry point (``python -m tsspark_tpu.orchestrate
    --_resident``): the resident run as a fault-isolatable process the
    chaos harness can kill mid-flush.  Adopts the spawner's trace like
    the chunk workers; a meshless child degrades to the in-process
    chunk-worker body (NOT a fresh subprocess tree — this IS the
    worker)."""
    obs.adopt_env()
    t0 = time.time()
    wspan = obs.open_span("resident.worker", make_current=True,
                          series=args.series, chunk=args.chunk)
    try:
        mesh = usable_mesh()
        if mesh is None:
            # Degrade to the chunk-worker body in THIS process (same
            # coverage protocol; the spawner's watchdog keeps working).
            args.hi = args.hi or args.series
            rc = orchestrate.fit_worker(args)
        else:
            st = run_resident(
                data_dir=args.data, out_dir=args.out, series=args.series,
                chunk=args.chunk, phase1_iters=args.phase1_iters,
                no_phase1_tune=args.no_phase1_tune,
                autotune=getattr(args, "autotune", False), mesh=mesh,
            )
            rc = 0 if st.get("complete") else 1
    except BaseException:
        obs.close_span(wspan, "resident.worker", t0, status="err")
        raise
    obs.close_span(wspan, "resident.worker", t0, rc=rc)
    if obs.active():
        from tsspark_tpu.obs.metrics import DEFAULT

        try:
            DEFAULT.export(
                os.path.join(args.out,
                             f"metrics_resident_{os.getpid()}.json"),
                trace_id=obs.trace_id(),
            )
        except OSError:
            pass
    return rc
