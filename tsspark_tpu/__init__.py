"""tsspark_tpu — a TPU-native time-series forecasting framework.

A from-scratch re-design of the capabilities of ``mageky/time-series-spark``
(Prophet-family decomposable forecasting at scale): instead of fanning
per-series CPU fits out through Spark ``mapPartitions`` UDFs, the design
matrix build and the L-BFGS MAP solve are batched JAX programs sharded over
TPU meshes, behind a ``ForecastBackend`` plugin registry
(see BASELINE.json:5 for the driver north star; the reference source itself
was unavailable — SURVEY.md §0).

Quick start::

    import pandas as pd
    from tsspark_tpu import Forecaster, ProphetConfig

    fc = Forecaster(ProphetConfig(), backend="tpu")
    fc.fit(df)                       # long frame: series_id, ds, y
    out = fc.predict(horizon=28)     # long frame with yhat + intervals

The public names below resolve lazily (PEP 562): ``import
tsspark_tpu.serve.replica`` must not drag in pandas/``frame``/``eval``
— a serve replica's spawn wall is pure import time, and the forecast
plane answers its hot reads without ever touching the fit stack, so a
plane-covered replica pays only for the modules it actually serves
from (the ``bench --serveplane`` TTFR numbers measure exactly this
wall; docs/SERVING.md "AOT program bank")."""

import importlib
import importlib.util

# Public name -> defining module.  Resolution imports the module on
# first attribute access and caches the value in the package globals,
# so repeat lookups are plain dict hits.
_EXPORTS = {
    "DAILY": "tsspark_tpu.config",
    "McmcConfig": "tsspark_tpu.config",
    "ProphetConfig": "tsspark_tpu.config",
    "RegressorConfig": "tsspark_tpu.config",
    "SeasonalityConfig": "tsspark_tpu.config",
    "ShardingConfig": "tsspark_tpu.config",
    "SolverConfig": "tsspark_tpu.config",
    "WEEKLY": "tsspark_tpu.config",
    "YEARLY": "tsspark_tpu.config",
    "ForecastBackend": "tsspark_tpu.backends.registry",
    "get_backend": "tsspark_tpu.backends.registry",
    "list_backends": "tsspark_tpu.backends.registry",
    "register_backend": "tsspark_tpu.backends.registry",
    "Forecaster": "tsspark_tpu.frame",
    "cross_validation": "tsspark_tpu.eval.diagnostics",
    "performance_metrics": "tsspark_tpu.eval.diagnostics",
    "Holiday": "tsspark_tpu.models.holidays",
    "add_holidays": "tsspark_tpu.models.holidays",
    "country_holidays": "tsspark_tpu.models.holidays",
    "holidays_from_df": "tsspark_tpu.models.holidays",
    "FitState": "tsspark_tpu.models.prophet.model",
    "McmcState": "tsspark_tpu.models.prophet.model",
    "ProphetModel": "tsspark_tpu.models.prophet.model",
    "auto_seasonalities": "tsspark_tpu.models.prophet.seasonality",
    "FaultPlan": "tsspark_tpu.resilience",
    "ResilienceReport": "tsspark_tpu.resilience",
    "ResilienceWarning": "tsspark_tpu.resilience",
    "RetryPolicy": "tsspark_tpu.resilience",
    "get_report": "tsspark_tpu.resilience",
    "ParamRegistry": "tsspark_tpu.serve",
    "PredictionEngine": "tsspark_tpu.serve",
}

__version__ = "0.4.0"

__all__ = [
    "DAILY",
    "Forecaster",
    "ForecastBackend",
    "FitState",
    "Holiday",
    "McmcConfig",
    "McmcState",
    "add_holidays",
    "auto_seasonalities",
    "country_holidays",
    "holidays_from_df",
    "ProphetConfig",
    "ProphetModel",
    "RegressorConfig",
    "SeasonalityConfig",
    "ShardingConfig",
    "SolverConfig",
    "WEEKLY",
    "YEARLY",
    "FaultPlan",
    "ParamRegistry",
    "PredictionEngine",
    "ResilienceReport",
    "ResilienceWarning",
    "RetryPolicy",
    "cross_validation",
    "get_backend",
    "get_report",
    "list_backends",
    "performance_metrics",
    "register_backend",
]


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is not None:
        value = getattr(importlib.import_module(mod), name)
        globals()[name] = value
        return value
    # `tsspark_tpu.frame`-style attribute access without a prior
    # submodule import: resolve it like the eager init used to, but
    # only when the submodule really exists — a typo must stay an
    # AttributeError, and a broken submodule must raise ITS error.
    if importlib.util.find_spec(f"tsspark_tpu.{name}") is not None:
        return importlib.import_module(f"tsspark_tpu.{name}")
    raise AttributeError(
        f"module 'tsspark_tpu' has no attribute {name!r}"
    )


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
