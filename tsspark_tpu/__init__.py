"""tsspark_tpu — a TPU-native time-series forecasting framework.

A from-scratch re-design of the capabilities of ``mageky/time-series-spark``
(Prophet-family decomposable forecasting at scale): instead of fanning
per-series CPU fits out through Spark ``mapPartitions`` UDFs, the design
matrix build and the L-BFGS MAP solve are batched JAX programs sharded over
TPU meshes, behind a ``ForecastBackend`` plugin registry
(see BASELINE.json:5 for the driver north star; the reference source itself
was unavailable — SURVEY.md §0).

Quick start::

    import pandas as pd
    from tsspark_tpu import Forecaster, ProphetConfig

    fc = Forecaster(ProphetConfig(), backend="tpu")
    fc.fit(df)                       # long frame: series_id, ds, y
    out = fc.predict(horizon=28)     # long frame with yhat + intervals
"""

from tsspark_tpu.config import (
    DAILY,
    McmcConfig,
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    ShardingConfig,
    SolverConfig,
    WEEKLY,
    YEARLY,
)
from tsspark_tpu.backends.registry import (
    ForecastBackend,
    get_backend,
    list_backends,
    register_backend,
)
from tsspark_tpu.frame import Forecaster
from tsspark_tpu.eval.diagnostics import cross_validation, performance_metrics
from tsspark_tpu.models.holidays import (
    Holiday,
    add_holidays,
    country_holidays,
    holidays_from_df,
)
from tsspark_tpu.models.prophet.model import FitState, McmcState, ProphetModel
from tsspark_tpu.models.prophet.seasonality import auto_seasonalities
from tsspark_tpu.resilience import (
    FaultPlan,
    ResilienceReport,
    ResilienceWarning,
    RetryPolicy,
    get_report,
)
from tsspark_tpu.serve import ParamRegistry, PredictionEngine

__version__ = "0.4.0"

__all__ = [
    "DAILY",
    "Forecaster",
    "ForecastBackend",
    "FitState",
    "Holiday",
    "McmcConfig",
    "McmcState",
    "add_holidays",
    "auto_seasonalities",
    "country_holidays",
    "holidays_from_df",
    "ProphetConfig",
    "ProphetModel",
    "RegressorConfig",
    "SeasonalityConfig",
    "ShardingConfig",
    "SolverConfig",
    "WEEKLY",
    "YEARLY",
    "FaultPlan",
    "ParamRegistry",
    "PredictionEngine",
    "ResilienceReport",
    "ResilienceWarning",
    "RetryPolicy",
    "cross_validation",
    "get_backend",
    "get_report",
    "list_backends",
    "performance_metrics",
    "register_backend",
]
