"""Process-local metrics registry: counters, gauges, pow-2 histograms.

Naming convention (docs/OBSERVABILITY.md): ``tsspark_<subsystem>_<what>
_<unit>`` — ``tsspark_serve_request_seconds``, ``tsspark_fit_chunks_
total``.  Labels are a small dict baked into the handle at registration
(``counter("...", result="shed")``), so the hot path is one attribute
increment with no formatting.

Histograms bucket on the pow-2 ladder — the same shape discipline the
engine's coalescing buckets and the fit path's compaction widths walk
(``parallel.sharding``) — as ``{exponent: count}`` with exact
sum/count/min/max alongside, so a snapshot stays a few dozen ints no
matter how many observations land.

Export: ``MetricsRegistry.export`` writes an atomic JSON snapshot
(``metrics_*.json`` next to the run's other artifacts; the run ledger
joins them by trace id), and ``to_prometheus`` renders the standard
text exposition format for scrape-style consumers
(``python -m tsspark_tpu.obs prom``).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from tsspark_tpu.utils.atomic import atomic_write

_LabelKey = Tuple[Tuple[str, str], ...]


def _labelkey(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter.  Handle methods take a lock: ``value += n``
    is load/add/store bytecode the GIL can interleave, and the engine's
    background pump thread shares handles with submitting threads."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def _reset(self) -> None:
        with self._lock:
            self.value = 0


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        # A single store is atomic under the GIL; no lock needed.
        # (A lint-ok[host-sync] waiver lived here while tracelint
        # joined call graphs by simple name — `.at[i].set(...)` in
        # traced code collided with this method.  The qualified-name
        # closure removed the collision class, so the waiver is gone.)
        self.value = float(v)

    def _reset(self) -> None:
        self.value = 0.0


#: Exponent clamp: 2**-30 s ≈ 1 ns and 2**30 ≈ 34 years/1G — everything
#: this package measures fits far inside.
_EXP_MIN, _EXP_MAX = -30, 30


class Histogram:
    """Pow-2-bucketed histogram: bucket ``e`` counts observations with
    ``2**(e-1) < v <= 2**e`` (zero/negative land in the bottom)."""

    __slots__ = ("buckets", "count", "total", "vmin", "vmax", "_lock")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        if v > 0.0:
            e = min(max(math.ceil(math.log2(v)), _EXP_MIN), _EXP_MAX)
        else:
            e = _EXP_MIN
        with self._lock:
            self.buckets[e] = self.buckets.get(e, 0) + 1
            self.count += 1
            self.total += v
            self.vmin = v if self.vmin is None else min(self.vmin, v)
            self.vmax = v if self.vmax is None else max(self.vmax, v)

    def _reset(self) -> None:
        with self._lock:
            self.buckets.clear()
            self.count = 0
            self.total = 0.0
            self.vmin = self.vmax = None

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate: the bucket boundary (2**e) at or above
        the q-th observation."""
        if not self.count:
            return None
        target = q * self.count
        seen = 0
        for e in sorted(self.buckets):
            seen += self.buckets[e]
            if seen >= target:
                return 2.0 ** e
        return 2.0 ** max(self.buckets)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "buckets": {str(e): n for e, n in sorted(self.buckets.items())},
            "count": self.count,
            "sum": round(self.total, 6),
            "min": self.vmin, "max": self.vmax,
        }


class MetricsRegistry:
    """Named metric handles, one registry per process (``DEFAULT``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    def counter(self, name: str, **labels: Any) -> Counter:
        key = (name, _labelkey(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
        return c

    def gauge(self, name: str, **labels: Any) -> Gauge:
        key = (name, _labelkey(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge()
        return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
        return h

    def reset(self) -> None:
        """Zero every metric IN PLACE (handles cached by subsystems —
        the engine resolves its counters once at init — stay live).
        Per-run exporters (the chaos harness, the serve loadgen) call
        this at run start so a second run in the same process does not
        export the first run's counts under its own trace id."""
        with self._lock:
            handles = (list(self._counters.values())
                       + list(self._gauges.values())
                       + list(self._hists.values()))
        for h in handles:
            h._reset()

    # -- export ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = [
                {"name": n, "labels": dict(lk), "value": c.value}
                for (n, lk), c in sorted(self._counters.items())
            ]
            gauges = [
                {"name": n, "labels": dict(lk), "value": g.value}
                for (n, lk), g in sorted(self._gauges.items())
            ]
            hists = [
                {"name": n, **h.to_dict()}
                for n, h in sorted(self._hists.items())
            ]
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def export(self, path: str,
               trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Atomic snapshot file (readers never see a torn JSON); the
        trace id keys it into the run ledger."""
        snap = {
            "kind": "metrics-snapshot",
            "unix": round(time.time(), 3),
            "trace_id": trace_id,
            "pid": os.getpid(),
            "metrics": self.snapshot(),
        }
        atomic_write(path, lambda fh: json.dump(snap, fh, indent=1),
                     mode="w")
        return snap

    def to_prometheus(self) -> str:
        """Prometheus text exposition of the current state."""
        return prometheus_text(self.snapshot())


def prometheus_text(metrics: Dict[str, Any]) -> str:
    """Render a ``snapshot()``-shaped dict as Prometheus text (also
    accepts the ``metrics`` block of an exported snapshot file)."""
    lines = []
    for c in metrics.get("counters", ()):
        lines.append(f"# TYPE {c['name']} counter")
        lab = ",".join(f'{k}="{v}"' for k, v in
                       sorted(c.get("labels", {}).items()))
        lines.append(
            f"{c['name']}{{{lab}}} {c['value']}" if lab
            else f"{c['name']} {c['value']}"
        )
    for g in metrics.get("gauges", ()):
        lines.append(f"# TYPE {g['name']} gauge")
        lab = ",".join(f'{k}="{v}"' for k, v in
                       sorted((g.get("labels") or {}).items()))
        lines.append(
            f"{g['name']}{{{lab}}} {g['value']}" if lab
            else f"{g['name']} {g['value']}"
        )
    for h in metrics.get("histograms", ()):
        name = h["name"]
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for e in sorted(int(k) for k in h.get("buckets", {})):
            cum += h["buckets"][str(e)]
            lines.append(f'{name}_bucket{{le="{2.0 ** e:g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{name}_sum {h['sum']}")
        lines.append(f"{name}_count {h['count']}")
    return "\n".join(lines) + "\n"


#: The process's registry.  Subsystems grab handles at init and bump
#: them unconditionally — a handle costs one int add, and the snapshot
#: is only exported when a caller asks for it.
DEFAULT = MetricsRegistry()
