"""The run ledger: one artifact joining a run's every observation.

``build_ledger`` walks a run's scratch tree and joins, under one trace
id: the span log(s) (``spans.jsonl`` — orchestrate claims/fits/lands,
registry publish/activate/load, streaming batches, engine requests and
dispatches, fault events), exported metrics snapshots
(``metrics_*.json``), and the orchestrate workers' per-chunk perf rows
(``times.jsonl`` — the PerfRecorder-shaped telemetry ``bench.py``
summarizes).  ``BENCH_*``/``SERVE_*``/``CHAOS_*`` reports stamped with
the same trace id are embedded by reference (kind + headline), so the
historical artifact formats join without a schema break.

Derived views:

* **span tree + orphan check** — every span's parent must resolve (the
  crash-safe ``open`` records written at span begin are what keeps a
  SIGKILLed worker's children parented);
* **MTTR from spans alone** — each ``fault`` event to the next healthy
  signal, with the same per-class semantics the chaos harness measures
  off claim-file mtimes (``derive_mttr``), so the two must agree;
* **RED summary** — per span name: rate, errors, duration percentiles.

``write_ledger`` persists it atomically as ``RUNLEDGER_<unix>.json``.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tsspark_tpu.obs import context
from tsspark_tpu.utils.atomic import atomic_write

#: Span names that count as the pipeline being "healthy again" after a
#: fault (the signals the chaos harness's mtime-based MTTR scan reads
#: off disk: a chunk landing, the phase-2 sentinel, a registry load
#: serving, a streaming batch absorbed, a request answered).
HEALTHY_SPANS = ("chunk.land", "phase2.done", "registry.load",
                 "stream.batch", "serve.request")

#: Classes whose recovery is defined as the END of their stage (the
#: harness measures stream faults against the streaming stage's end,
#: not the next batch — a mid-stream fault is only "recovered" once the
#: stream drains cleanly).
_STAGE_END_CLASSES = ("stream-fault",)


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------


def _walk_files(root: str, match) -> List[str]:
    out = []
    for dirpath, _dirs, names in os.walk(root):
        for name in sorted(names):
            if match(name):
                out.append(os.path.join(dirpath, name))
    return out


def collect_records(root: str) -> List[Dict[str, Any]]:
    """All span/event records under ``root`` (every ``spans.jsonl``)."""
    recs: List[Dict[str, Any]] = []
    if os.path.isfile(root):
        return context.read_records(root)
    for path in _walk_files(root, lambda n: n == context.SPANS_FILE):
        recs.extend(context.read_records(path))
    return recs


def merge_spans(records: Sequence[Dict[str, Any]]
                ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(spans, events): completion records win over their own ``open``
    record (same span id); a span only ever opened stays ``open`` —
    the honest record of a process killed mid-span."""
    spans: Dict[str, Dict[str, Any]] = {}
    events: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("kind") == "event":
            events.append(rec)
            continue
        if rec.get("kind") != "span" or not rec.get("span_id"):
            continue
        sid = rec["span_id"]
        prev = spans.get(sid)
        if prev is None:
            spans[sid] = dict(rec)
        elif prev.get("status") == "open" and rec.get("status") != "open":
            # Completion record: keep the open record's parent (the
            # close side omits it — only the begin site knows it).
            if rec.get("parent_id") is None:
                rec = dict(rec, parent_id=prev.get("parent_id"))
            spans[sid] = dict(rec)
    out = sorted(spans.values(), key=lambda s: (s.get("t0") or 0.0))
    events.sort(key=lambda e: e.get("t") or 0.0)
    return out, events


def orphan_spans(spans: Sequence[Dict[str, Any]]) -> List[str]:
    """Span ids whose parent id resolves to no span in the ledger
    (parentless roots are fine — ``parent_id: null``)."""
    ids = {s["span_id"] for s in spans}
    return sorted(
        s["span_id"] for s in spans
        if s.get("parent_id") and s["parent_id"] not in ids
    )


def _span_end(s: Dict[str, Any]) -> Optional[float]:
    if s.get("t0") is None or s.get("dur_s") is None:
        return None
    return float(s["t0"]) + float(s["dur_s"])


# ---------------------------------------------------------------------------
# MTTR from spans alone
# ---------------------------------------------------------------------------


def derive_mttr(spans: Sequence[Dict[str, Any]],
                events: Sequence[Dict[str, Any]]
                ) -> Dict[str, Optional[float]]:
    """Per-fault-class MTTR read off the trace: worst, over that class's
    ``fault`` events, of the gap to the next healthy signal.

    Semantics mirror the chaos harness's claim-file-mtime measurement
    (``chaos.invariants``) so the two agree to within write latency:

    * direct-mode faults pair with their explicit ``recovered`` event;
    * stage-end classes recover at their enclosing stage span's end;
    * everything else recovers at the first healthy span
      (``HEALTHY_SPANS``, status ok, not itself fault-tainted) ending
      after the fault inside the same stage window, with the stage end
      as the fallback when nothing healthy followed.
    """
    stages = [s for s in spans if s.get("name", "").startswith("stage.")
              and _span_end(s) is not None]
    healthy = [
        (_span_end(s), s) for s in spans
        if s.get("name") in HEALTHY_SPANS and s.get("status") == "ok"
        and not (s.get("attrs") or {}).get("corrupted")
        and _span_end(s) is not None
    ]
    healthy.sort(key=lambda p: p[0])
    recovered: Dict[str, List[float]] = {}
    faults: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        tag = (ev.get("attrs") or {}).get("tag")
        if not tag or ev.get("t") is None:
            continue
        if ev.get("name") == "fault":
            faults.setdefault(tag, []).append(ev)
        elif ev.get("name") == "recovered":
            recovered.setdefault(tag, []).append(float(ev["t"]))

    def stage_window(t: float) -> Optional[Tuple[float, float]]:
        best = None
        for s in stages:
            t0, t1 = float(s["t0"]), _span_end(s)
            if t0 <= t <= t1 and (best is None
                                  or t1 - t0 < best[1] - best[0]):
                best = (t0, t1)
        return best

    def first_healthy(t: float, end: Optional[float]
                      ) -> Optional[float]:
        """Earliest healthy-span end after ``t`` inside the window —
        with chunk lands deduplicated to the LAST land per range inside
        it: a phase-2 patch (or a corruption refit) rewrites its chunk
        file, so an on-disk mtime scan only ever sees a range's final
        land, and the span measure must count the same signal.  The
        window scoping also keeps the fault-free reference run's lands
        (same ranges, different stage) out of the storm's recovery."""
        last_land: Dict[Any, float] = {}
        others: List[float] = []
        for e, s in healthy:
            if end is not None and e > end + 0.5:
                continue
            if s.get("name") == "chunk.land":
                a = s.get("attrs") or {}
                key = (a.get("lo"), a.get("hi"))
                last_land[key] = max(last_land.get(key, 0.0), e)
            else:
                others.append(e)
        cands = [e for e in list(last_land.values()) + others if e > t]
        return min(cands) if cands else None

    out: Dict[str, Optional[float]] = {}
    for cls, evs in faults.items():
        worst: Optional[float] = 0.0
        for ev in evs:
            t = float(ev["t"])
            mode = (ev.get("attrs") or {}).get("mode")
            nxt: Optional[float] = None
            if mode == "direct" or recovered.get(cls):
                nxt = next((r for r in sorted(recovered.get(cls, ()))
                            if r > t), None)
            else:
                win = stage_window(t)
                end = win[1] if win else None
                if cls not in _STAGE_END_CLASSES:
                    nxt = first_healthy(t, end)
                if nxt is None:
                    nxt = end if end is not None and end > t else None
            if nxt is None:
                worst = None
                break
            worst = max(worst, nxt - t)
        out[cls] = worst
    return out


# ---------------------------------------------------------------------------
# RED summary
# ---------------------------------------------------------------------------


def red_summary(spans: Sequence[Dict[str, Any]]) -> Dict[str, Dict]:
    """Rate / Errors / Duration per span name (the SLO view)."""
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(s)
    out: Dict[str, Dict] = {}
    for name, group in sorted(by_name.items()):
        durs = sorted(float(s["dur_s"]) for s in group
                      if s.get("dur_s") is not None)
        t0s = [float(s["t0"]) for s in group if s.get("t0") is not None]
        window = (max(t0s) - min(t0s)) if len(t0s) > 1 else 0.0

        def pct(q: float) -> Optional[float]:
            if not durs:
                return None
            # Nearest-rank: ceil(q*n)-1, not int(q*n) — the latter is
            # one rank high whenever q*n is integral (p99 of 100
            # samples would read as the max).  round() first: float
            # q*n lands a hair above the integer (0.99*100 -> 99.0…01)
            # and a bare ceil would re-introduce the off-by-one.
            i = min(len(durs) - 1,
                    max(0, math.ceil(round(q * len(durs), 9)) - 1))
            return round(durs[i] * 1e3, 3)

        out[name] = {
            "n": len(group),
            "err": sum(1 for s in group if s.get("status") == "err"),
            "open": sum(1 for s in group if s.get("status") == "open"),
            "rate_per_s": (round(len(group) / window, 2) if window > 0
                           else None),
            "p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "max_ms": (round(durs[-1] * 1e3, 3) if durs else None),
            "total_s": round(sum(durs), 4),
        }
    return out


def milestones(spans: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """First occurrence of each pipeline landmark (chunk claim -> fit ->
    land -> publish -> activate -> first cache-hit forecast)."""
    firsts: Dict[str, float] = {}
    for s in spans:
        name, t0 = s.get("name"), s.get("t0")
        if name is None or t0 is None:
            continue
        key = None
        if name in ("chunk.claim", "chunk.fit", "chunk.land",
                    "registry.publish", "registry.activate"):
            key = name
        elif (name == "serve.request" and s.get("status") == "ok"
                and (s.get("attrs") or {}).get("cached", 0)):
            key = "serve.first_cache_hit"
        elif name == "serve.request" and s.get("status") == "ok":
            key = "serve.first_forecast"
        if key is not None and key not in firsts:
            firsts[key] = float(t0)
    return firsts


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------


def _collect_times(root: str) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for path in _walk_files(root, lambda n: n == "times.jsonl"):
        try:
            with open(path) as fh:
                for line in fh:
                    if line.strip():
                        try:
                            rows.append(json.loads(line))
                        except ValueError:
                            pass  # torn tail of a killed worker
        except OSError:
            continue
    return rows


def _collect_metrics(root: str) -> List[Dict[str, Any]]:
    snaps: List[Dict[str, Any]] = []
    for path in _walk_files(
        root, lambda n: n.startswith("metrics_") and n.endswith(".json")
    ):
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(snap, dict) and snap.get("kind") == "metrics-snapshot":
            snaps.append(snap)
    return snaps


def build_ledger(root: str,
                 reports: Sequence[Dict[str, Any]] = (),
                 trace: Optional[str] = None) -> Dict[str, Any]:
    """Assemble the run ledger for the run recorded under ``root`` (a
    scratch tree holding ``spans.jsonl`` files, or one span log).

    ``reports``: already-parsed BENCH/SERVE/CHAOS dicts to join (only
    their headline is embedded).  ``trace``: restrict to one trace id
    (default: the dominant one in the span log).
    """
    from tsspark_tpu.perf.recorder import summarize_times

    records = collect_records(root)
    if trace is None:
        counts: Dict[str, int] = {}
        for r in records:
            t = r.get("trace_id")
            if t:
                counts[t] = counts.get(t, 0) + 1
        trace = max(counts, key=counts.get) if counts else None
    records = [r for r in records if r.get("trace_id") == trace]
    spans, events = merge_spans(records)
    times = _collect_times(root) if os.path.isdir(root) else []
    report_refs = []
    for rep in reports:
        if not isinstance(rep, dict):
            continue
        report_refs.append({
            "kind": rep.get("kind"),
            "unix": rep.get("unix"),
            "trace_id": rep.get("trace_id"),
            "ok": rep.get("ok"),
            "joined": rep.get("trace_id") == trace,
        })
    ends = [e for e in (_span_end(s) for s in spans) if e is not None]
    t0s = [s["t0"] for s in spans if s.get("t0") is not None]
    return {
        "kind": "run-ledger",
        "unix": round(time.time(), 3),
        "trace_id": trace,
        "t0": min(t0s) if t0s else None,
        "wall_s": (round(max(ends) - min(t0s), 3)
                   if ends and t0s else None),
        "processes": sorted({s.get("pid") for s in spans
                             if s.get("pid") is not None}),
        "spans": spans,
        "events": events,
        "orphan_spans": orphan_spans(spans),
        "mttr_s": {k: (None if v is None else round(v, 3))
                   for k, v in sorted(derive_mttr(spans, events).items())},
        "red": red_summary(spans),
        "milestones": {k: round(v, 3)
                       for k, v in milestones(spans).items()},
        "perf": summarize_times(times) if times else None,
        "metrics": _collect_metrics(root) if os.path.isdir(root) else [],
        "reports": report_refs,
    }


def write_ledger(ledger: Dict[str, Any],
                 path: Optional[str] = None) -> str:
    """Persist a ledger as ``RUNLEDGER_<unix>.json`` (atomic, like every
    other report artifact)."""
    out = path or f"RUNLEDGER_{int(ledger.get('unix', time.time()))}.json"
    atomic_write(out, lambda fh: json.dump(ledger, fh, indent=1),
                 mode="w")
    return out
