"""Live SLO watch: tail an in-flight run's scratch and gate it NOW.

``python -m tsspark_tpu.obs watch <scratch>`` re-reads the run's
``spans.jsonl`` (crash-safe append log — tailing it is always safe) and
its newest ``metrics_*.json`` snapshot every tick, derives the live
state — current stage, series landed and trailing-window series/s,
serve queue depth / shed rate / breaker state, live request p99 — and
evaluates the SAME SLO budgets the post-run sentinel applies
(``obs.regress`` over ``pyproject [tool.tsspark.slo]``) against the
run-history baselines, continuously.

A breach is recorded back into the run's own trace as an
``slo.breach`` event (same spans.jsonl, same trace id — deduplicated
per metric), so it lands in the run ledger next to the spans that
caused it; the watcher needs no signal channel to the watched process.

Works against any traced scratch: an orchestrate/bench out dir, a
chaos storm scratch, or a serve daemon's registry dir (pair with
``--metrics-every`` so the daemon exports snapshots periodically).
Device-free: never imports JAX.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from tsspark_tpu.obs import context as obs
from tsspark_tpu.obs import history, ledger, regress

#: Trailing window for the live series/s estimate.
RATE_WINDOW_S = 60.0


def _dominant_trace(records: Sequence[Dict[str, Any]]) -> Optional[str]:
    counts: Dict[str, int] = {}
    for r in records:
        t = r.get("trace_id")
        if t:
            counts[t] = counts.get(t, 0) + 1
    return max(counts, key=counts.get) if counts else None


def _newest_metrics(scratch: str) -> Optional[Dict[str, Any]]:
    """Newest exported metrics snapshot under ``scratch`` (recursive —
    the serve daemon exports next to its registry)."""
    best, best_unix = None, -1.0
    for path in glob.glob(os.path.join(scratch, "**", "metrics_*.json"),
                          recursive=True):
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except (OSError, ValueError):
            continue  # mid-replace or torn: next tick sees it whole
        if not (isinstance(snap, dict)
                and snap.get("kind") == "metrics-snapshot"):
            continue
        unix = snap.get("unix") or 0.0
        if unix >= best_unix:
            best, best_unix = snap, unix
    return best


def _scratch_device(scratch: str) -> Optional[str]:
    """The watched run's device, read off its workers' ``times.jsonl``
    rows (the fit workers stamp one per chunk) — scopes the live
    baseline to the right device class so full-scale TPU history never
    gates a CPU smoke run."""
    dev = None
    for path in glob.glob(os.path.join(scratch, "**", "times.jsonl"),
                          recursive=True):
        try:
            with open(path) as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a live writer
                    if isinstance(rec, dict) and rec.get("device"):
                        dev = rec["device"]
        except OSError:
            continue
    return dev


def _metric_lookup(snap: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Flatten a snapshot into {name[/label=value]: number}."""
    out: Dict[str, float] = {}
    metrics = (snap or {}).get("metrics") or {}
    for c in metrics.get("counters", ()):
        labels = c.get("labels") or {}
        suffix = "".join(f"/{k}={v}" for k, v in sorted(labels.items()))
        out[f"{c['name']}{suffix}"] = c.get("value", 0)
    for g in metrics.get("gauges", ()):
        labels = g.get("labels") or {}
        suffix = "".join(f"/{k}={v}" for k, v in sorted(labels.items()))
        out[f"{g['name']}{suffix}"] = g.get("value", 0.0)
    return out


def observe_run(scratch: str,
                history_rows: Sequence[Dict[str, Any]] = (),
                slo: Optional[Dict[str, Any]] = None,
                now: Optional[float] = None) -> Dict[str, Any]:
    """One observation of the in-flight run (pure read; no side
    effects).  ``now`` pins the rate-window clock for tests.

    (Named ``observe_run``, not ``status``: the trace lint's jit
    call-graph closure joins functions by simple callee name, and
    ``status`` is a callee inside the traced solver — a collision would
    drag this whole host-side module into traced scope.)"""
    slo = slo or regress.load_slo()
    records = ledger.collect_records(scratch)
    trace = _dominant_trace(records)
    records = [r for r in records if r.get("trace_id") == trace]
    spans, events = ledger.merge_spans(records)

    # Current stage: the latest still-open span wins (depth-first runs
    # leave their whole open ancestry; last t0 = innermost); fall back
    # to the latest completed span's name.
    open_spans = [s for s in spans if s.get("status") == "open"
                  and s.get("t0") is not None]
    stage = None
    if open_spans:
        stage = max(open_spans, key=lambda s: s["t0"]).get("name")
    elif spans:
        stage = spans[-1].get("name")

    # Landed coverage + trailing-window throughput off chunk.land spans
    # (dedup to the last land per range — phase-2 patches rewrite).
    last_land: Dict[Any, Dict[str, Any]] = {}
    for s in spans:
        if s.get("name") != "chunk.land" or s.get("status") != "ok":
            continue
        end = ledger._span_end(s)
        if end is None:
            continue
        a = s.get("attrs") or {}
        key = (a.get("lo"), a.get("hi"))
        prev = last_land.get(key)
        if prev is None or end > prev["end"]:
            last_land[key] = {"end": end, "lo": a.get("lo"),
                              "hi": a.get("hi")}
    series_done = sum(
        (d["hi"] - d["lo"]) for d in last_land.values()
        if isinstance(d["lo"], int) and isinstance(d["hi"], int)
    )
    ends = [d["end"] for d in last_land.values()]
    t_ref = now if now is not None else (max(ends) if ends else None)
    series_per_s = None
    if t_ref is not None and ends:
        t0s = [s["t0"] for s in spans if s.get("t0") is not None]
        lo_t = max(min(t0s or [t_ref]), t_ref - RATE_WINDOW_S)
        window = max(t_ref - lo_t, 1e-6)
        in_window = sum(
            (d["hi"] - d["lo"]) for d in last_land.values()
            if lo_t <= d["end"] <= t_ref
            and isinstance(d["lo"], int) and isinstance(d["hi"], int)
        )
        series_per_s = round(in_window / window, 2)

    # Serve-side live state: metric snapshot + request spans.
    snap = _newest_metrics(scratch)
    flat = _metric_lookup(snap)
    queue_depth = flat.get("tsspark_serve_queue_depth")
    breaker_open = flat.get("tsspark_serve_breaker_open")
    carried = flat.get("tsspark_serve_cache_carried")
    shed = flat.get("tsspark_serve_requests_total/result=shed", 0)
    done = flat.get("tsspark_serve_requests_total/result=completed", 0)
    total = shed + done
    shed_rate = round(shed / total, 4) if total else None
    # Live p99 over the TRAILING window only (same discipline as the
    # series/s estimate): a cumulative percentile would dilute a
    # latency regression that develops mid-run past noticing.
    req = [(ledger._span_end(s), s) for s in spans
           if s.get("name") == "serve.request"
           and ledger._span_end(s) is not None]
    p99_ms = None
    if req:
        t_last = max(e for e, _s in req)
        recent = [s for e, s in req if e >= t_last - RATE_WINDOW_S]
        p99_ms = ledger.red_summary(recent)["serve.request"]["p99_ms"]

    # Live data-to-forecast freshness off the scheduler's
    # refit.freshness spans (t0 = the delta's land time, dur = land ->
    # first-served): trailing-window p95, same discipline as the p99.
    fr = [(ledger._span_end(s), s.get("dur_s")) for s in spans
          if s.get("name") == "refit.freshness"
          and ledger._span_end(s) is not None
          and isinstance(s.get("dur_s"), (int, float))]
    freshness_p95_s = None
    if fr:
        t_last = max(e for e, _d in fr)
        recent_fr = [d for e, d in fr if e >= t_last - RATE_WINDOW_S]
        if recent_fr:
            import numpy as _np

            freshness_p95_s = round(
                float(_np.percentile(_np.asarray(recent_fr), 95)), 4
            )

    # Live land->alert freshness off the alert stream's
    # alerts.freshness spans (t0 = the delta's land time, dur = land ->
    # sink ack of its last alert), plus the stream's own counters from
    # the metric snapshot — the alerts row of the dashboard.
    al = [(ledger._span_end(s), s.get("dur_s")) for s in spans
          if s.get("name") == "alerts.freshness"
          and ledger._span_end(s) is not None
          and isinstance(s.get("dur_s"), (int, float))]
    alerts_p95_s = None
    if al:
        t_last = max(e for e, _d in al)
        recent_al = [d for e, d in al if e >= t_last - RATE_WINDOW_S]
        if recent_al:
            import numpy as _np

            alerts_p95_s = round(
                float(_np.percentile(_np.asarray(recent_al), 95)), 4
            )
    alerts_fired = flat.get("tsspark_alerts_fired_total")
    alerts_suppressed = flat.get("tsspark_alerts_suppressed_total")
    alerts_queued = flat.get("tsspark_alerts_queued")
    alerts_breaker = flat.get("tsspark_alerts_breaker_open")

    # The live row(s), judged by the same sentinel machinery the
    # post-run gate uses — one pseudo-row per family so bench budgets
    # gate throughput and serve budgets gate the read path.
    breaches: List[Dict[str, Any]] = []
    live_rows = []
    device = _scratch_device(scratch)
    dev_class = history.device_class(device)
    if series_per_s is not None:
        live_rows.append({"kind": "bench", "row_id": "live:bench",
                          "device_class": dev_class,
                          "metrics": {"series_per_s": series_per_s}})
    serve_metrics: Dict[str, float] = {}
    if shed_rate is not None:
        serve_metrics["shed_rate"] = shed_rate
    if p99_ms is not None:
        serve_metrics["p99_ms"] = p99_ms
    if serve_metrics:
        live_rows.append({"kind": "serve", "row_id": "live:serve",
                          "device_class": dev_class,
                          "metrics": serve_metrics})
    if freshness_p95_s is not None:
        live_rows.append({
            "kind": "freshness", "row_id": "live:freshness",
            "device_class": dev_class,
            "metrics": {"freshness_p95_s": freshness_p95_s},
        })
    if alerts_p95_s is not None:
        live_rows.append({
            "kind": "alerts", "row_id": "live:alerts",
            "device_class": dev_class,
            "metrics": {"alerts_p95_s": alerts_p95_s},
        })
    verdicts = []
    for live in live_rows:
        v = regress.evaluate(live, history_rows, slo=slo)
        verdicts.append(v)
        breaches.extend(c for c in v["checks"] if not c["ok"])
    return {
        "scratch": scratch,
        "trace_id": trace,
        "stage": stage,
        "n_spans": len(spans),
        "open_spans": len(open_spans),
        "events": len(events),
        "series_done": series_done,
        "series_per_s": series_per_s,
        "queue_depth": queue_depth,
        "shed_rate": shed_rate,
        "breaker": (None if breaker_open is None
                    else ("open" if breaker_open >= 1.0 else "closed")),
        "p99_ms": p99_ms,
        "carried": carried,
        "freshness_p95_s": freshness_p95_s,
        "alerts_p95_s": alerts_p95_s,
        "alerts_fired": alerts_fired,
        "alerts_suppressed": alerts_suppressed,
        "alerts_queued": alerts_queued,
        "alerts_breaker": (None if alerts_breaker is None
                           else ("open" if alerts_breaker >= 1.0
                                 else "closed")),
        "breaches": breaches,
        "verdicts": verdicts,
    }


def _spans_path(scratch: str) -> Optional[str]:
    """The run's span log (first one under the scratch) — breach events
    append THERE so the ledger joins them."""
    if os.path.isfile(scratch):
        return scratch
    cands = sorted(glob.glob(
        os.path.join(scratch, "**", obs.SPANS_FILE), recursive=True
    ))
    return cands[0] if cands else None


def record_breach(scratch: str, trace: Optional[str],
                  check: Dict[str, Any]) -> bool:
    """Append one ``slo.breach`` event to the watched run's own trace
    (no-op when the scratch has no span log yet)."""
    path = _spans_path(scratch)
    if path is None:
        return False
    prev = obs.start_run(path, trace_id=trace)
    try:
        obs.event("slo.breach", source="watch", metric=check["metric"],
                  value=check["value"], bound=check["bound"],
                  median=check["median"], direction=check["direction"])
    finally:
        obs.end_run(prev)
    return True


def format_line(st: Dict[str, Any]) -> str:
    bits = [f"stage={st['stage'] or '-'}"]
    if st["series_done"]:
        bits.append(f"done={st['series_done']}")
    if st["series_per_s"] is not None:
        bits.append(f"series/s={st['series_per_s']}")
    if st["queue_depth"] is not None:
        bits.append(f"queue={int(st['queue_depth'])}")
    if st["shed_rate"] is not None:
        bits.append(f"shed_rate={st['shed_rate']}")
    if st["breaker"] is not None:
        bits.append(f"breaker={st['breaker']}")
    if st["p99_ms"] is not None:
        bits.append(f"p99={st['p99_ms']}ms")
    if st.get("carried") is not None:
        bits.append(f"carried={int(st['carried'])}")
    if st.get("freshness_p95_s") is not None:
        bits.append(f"fresh_p95={st['freshness_p95_s']}s")
    if st.get("alerts_p95_s") is not None:
        bits.append(f"alert_p95={st['alerts_p95_s']}s")
    if st.get("alerts_fired") is not None:
        bits.append(f"alerts={int(st['alerts_fired'])}"
                    f"/{int(st.get('alerts_suppressed') or 0)}supp"
                    f"/{int(st.get('alerts_queued') or 0)}q")
    if st.get("alerts_breaker") is not None:
        bits.append(f"alert_sink={st['alerts_breaker']}")
    if st["breaches"]:
        worst = ", ".join(
            f"{c['metric']}={c['value']} vs bound {c['bound']}"
            for c in st["breaches"]
        )
        bits.append(f"SLO:BREACH({worst})")
    else:
        bits.append("SLO:ok")
    return f"[watch +{st.get('t_offset_s', 0):.0f}s] " + " ".join(bits)


def watch(scratch: str,
          history_path: str = history.HISTORY_FILE,
          interval_s: float = 2.0,
          duration_s: Optional[float] = None,
          once: bool = False,
          emit=print) -> int:
    """Tail ``scratch`` until ``duration_s`` elapses (forever when
    None; one pass with ``once``).  Returns 1 iff any SLO breached."""
    slo = regress.load_slo()
    rows = (history.read_history(history_path)
            if os.path.exists(history_path) else [])
    t_start = time.monotonic()
    recorded: set = set()
    any_breach = False
    while True:
        st = observe_run(scratch, rows, slo=slo)
        st["t_offset_s"] = time.monotonic() - t_start
        emit(format_line(st))
        for check in st["breaches"]:
            any_breach = True
            if check["metric"] not in recorded:
                recorded.add(check["metric"])
                record_breach(scratch, st["trace_id"], check)
        if once:
            break
        if (duration_s is not None
                and time.monotonic() - t_start >= duration_s):
            break
        time.sleep(interval_s)
    return 1 if any_breach else 0
