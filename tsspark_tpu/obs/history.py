"""Cross-run history index: one normalized row per run artifact.

PR 6 gave every run a trace, metrics, and a ledger — but each run still
died alone: BENCH/SERVE/CHAOS/EVAL artifacts sat side by side with no
machine-readable trajectory joining them, so a throughput or MTTR
regression was only caught if a human diffed JSON by hand.  This module
is the temporal half of observability: every report artifact the
package emits is normalized into ONE flat row schema and appended to
``RUNHISTORY.jsonl`` — trace id, git rev, NUMERICS_REV, config
fingerprint, device class, workload key, and a flat metric map
(series/s, first-flush, compile misses, serve p50/p95/p99 + shed/hit
rate, per-fault-class MTTR, sMAPE/parity deltas).

Contracts (same discipline as the span log):

* **append-only + crash-safe** — rows go down through
  ``utils.atomic.append_line`` (one ``O_APPEND`` write per row), and
  readers tolerate a torn final line;
* **idempotent by trace id** — a row's identity is
  ``<kind>:<trace_id>`` (content hash when the artifact predates trace
  stamping); re-ingesting the same artifact is a no-op, so every
  entrypoint can self-ingest unconditionally;
* **device-free** — never imports JAX (the ``python -m tsspark_tpu.obs
  history`` CLI must run against a wedged machine).

``backfill`` ingests the committed round artifacts (BENCH_r01–r06,
EVAL_*, plus any SERVE/CHAOS/RUNLEDGER files present) so the trajectory
starts with the project's recorded past, not an empty file.  The
regression sentinel (``obs.regress``) reads this index for its rolling
baselines.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import subprocess
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tsspark_tpu.obs.context import read_records
from tsspark_tpu.utils.atomic import append_line

#: File name convention for the cross-run index (one per working dir,
#: next to the BENCH_*/SERVE_*/CHAOS_* artifacts it normalizes).
HISTORY_FILE = "RUNHISTORY.jsonl"

#: Artifact families the backfill scans for (filename prefixes).
FAMILIES = ("BENCH_", "SERVE_", "CHAOS_", "EVAL_", "RUNLEDGER_",
            "SCALE_", "ANALYSIS_")

_git_rev_cache: Dict[str, Optional[str]] = {}


def git_rev(root: Optional[str] = None) -> Optional[str]:
    """Short git revision of ``root`` (default: the checkout this
    package is imported from — a run's cwd is usually a scratch dir,
    but the code that produced the artifact lives here); None outside a
    checkout.  Cached per root — report emitters stamp it once per run."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
    key = os.path.abspath(root)
    if key not in _git_rev_cache:
        rev = None
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short=10", "HEAD"],
                cwd=key, capture_output=True, text=True, timeout=10,
            )
            if out.returncode == 0:
                rev = out.stdout.strip() or None
        except (OSError, subprocess.SubprocessError):
            pass
        _git_rev_cache[key] = rev
    return _git_rev_cache[key]


def device_class(device: Optional[str]) -> Optional[str]:
    """Coarse accelerator class for baseline comparability: numbers off
    a TPU run must never gate a CPU-degraded run (or vice versa)."""
    if not device:
        return None
    d = str(device).lower()
    if "tpu" in d:
        return "tpu"
    if "cpu" in d:
        return "cpu"
    if "gpu" in d or "cuda" in d:
        return "gpu"
    return None


def _put(metrics: Dict[str, float], name: str, value: Any) -> None:
    """Admit only finite numbers (bools as 0/1) into the flat map."""
    if isinstance(value, bool):
        metrics[name] = int(value)
    elif isinstance(value, (int, float)) and value == value:  # not NaN
        metrics[name] = value


# ---------------------------------------------------------------------------
# per-family normalizers
# ---------------------------------------------------------------------------


def _bench_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    rc = None
    parsed: Optional[Dict[str, Any]] = rep
    if "cmd" in rep and "parsed" in rep:
        # Driver wrapper (BENCH_r01–r05): {"n", "cmd", "rc", "tail",
        # "parsed"} — the summary line lives under "parsed" (null when
        # the run never printed one; the row still records the rc so a
        # crashed round is a visible point on the trajectory).
        rc = rep.get("rc")
        parsed = rep.get("parsed")
    m: Dict[str, float] = {}
    if rc is not None:
        _put(m, "rc", rc)
    if not isinstance(parsed, dict):
        return {"kind": "bench", "trace_id": None, "unix": None,
                "workload": None, "device": None, "numerics_rev": None,
                "config_fingerprint": None, "git_rev": None, "metrics": m}
    extra = parsed.get("extra") or {}
    perf = extra.get("perf") or {}
    _put(m, "fit_wall_s", parsed.get("value"))
    for k in ("series_done", "datagen_s", "datagen_share",
              "ingest_wall_s", "ingest_overlap_s", "wall_s",
              "smape_insample_mean", "converged_frac", "phase2_s",
              "worker_retries", "complete"):
        _put(m, k, extra.get(k))
    # Throughput only exists when series actually landed: a wedged run
    # reports series_per_s=0.0 meaning "never ran", and admitting that
    # into the row would drag the sentinel's rolling median to 0 —
    # making the throughput budget vacuous (BENCH_r03-r05 are exactly
    # such rows in the committed trajectory).
    if extra.get("series_done"):
        _put(m, "series_per_s", extra.get("series_per_s"))
        # Path-scoped throughput (the mesh-resident fit's own SLO
        # metric): only stamped by resident-path runs, so its rolling
        # baseline is resident-only by construction.
        _put(m, "resident_series_per_s",
             extra.get("resident_series_per_s"))
    for k in ("first_flush_s", "compile_misses", "n_chunks"):
        _put(m, k, perf.get(k))
    # Delta-refit rows (bench --delta; tsspark_tpu.refit): cycle
    # throughput over the CHANGED set, the cycle wall as a fraction of
    # the same run's measured cold fit+publish wall, and the flip-window
    # cache carry-forward — budgeted in [tool.tsspark.slo.bench].
    for k in ("delta_series_per_s", "delta_wall_frac", "cache_carried",
              "flip_hit_rate"):
        _put(m, k, extra.get(k))
    # The fit path rides the workload key: resident and chunk-file runs
    # of the same shape are DIFFERENT workloads to the regression
    # sentinel — their throughput baselines must never mix.  Only the
    # NON-default path is suffixed: fileproto rows keep the historical
    # key, so the default path's entire committed baseline history stays
    # live instead of being orphaned by a rename.
    workload = parsed.get("metric")
    fit_path = extra.get("fit_path")
    if workload and fit_path and fit_path != "fileproto":
        workload = f"{workload}+{fit_path}"
    # Delta cycles additionally scope on the churn fraction: a 1%-churn
    # cycle's wall must never baseline a 30%-churn cycle's (and the
    # delta metric name already keeps them clear of cold-fit rows).
    delta_churn = extra.get("delta_churn")
    if workload and delta_churn is not None:
        workload = f"{workload}+delta{delta_churn}"
    return {
        "kind": "bench",
        "trace_id": extra.get("trace_id"),
        "unix": parsed.get("unix"),
        "workload": workload,
        "device": extra.get("device"),
        "numerics_rev": extra.get("numerics_rev"),
        "config_fingerprint": extra.get("config_fingerprint"),
        "git_rev": extra.get("git_rev"),
        "metrics": m,
    }


def _serve_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    eng = rep.get("engine") or {}
    lat = eng.get("latency_ms") or {}
    occ = eng.get("batch_occupancy") or {}
    cache = rep.get("cache") or {}
    pool = rep.get("pool") or {}
    m: Dict[str, float] = {}
    for k in ("p50", "p95", "p99", "mean", "max"):
        _put(m, f"{k}_ms", lat.get(k))
    for k in ("requests_per_s", "wall_s"):
        _put(m, k, rep.get(k))
    for k in ("submitted", "completed", "shed", "failed", "rejected",
              "fast_failed"):
        _put(m, k, eng.get(k))
    submitted = eng.get("submitted")
    if isinstance(submitted, (int, float)) and submitted:
        _put(m, "shed_rate",
             round(float(eng.get("shed", 0)) / submitted, 4))
    _put(m, "hit_rate", cache.get("hit_rate"))
    _put(m, "mean_fill", occ.get("mean_fill"))
    if pool:
        # Replica-pool loadgen rows (docs/SERVING.md "Replica pool"):
        # aggregate throughput, failover count, and the flip-window p99
        # are the pool's SLO metrics ([tool.tsspark.slo.serve]).
        _put(m, "agg_requests_per_s", rep.get("requests_per_s"))
        _put(m, "failovers", pool.get("failovers"))
        _put(m, "respawns", pool.get("respawns"))
        _put(m, "wrong_version", pool.get("wrong_version"))
        _put(m, "flip_p99_ms", (pool.get("flip") or {}).get("p99_ms"))
        for slot, st in sorted((pool.get("per_replica") or {}).items()):
            if isinstance(st, dict):
                _put(m, f"replica{slot}_shed", st.get("shed"))
    plane = rep.get("plane") or {}
    if plane:
        # Forecast-plane serve rows (bench --serveplane; docs/SERVING.md
        # "Forecast plane"): plane hit rate and the zero-dispatch read
        # p99 are SLO metrics ([tool.tsspark.slo.serve]); throughputs
        # and TTFR ride along as trajectory context.
        _put(m, "plane_hit_rate", plane.get("plane_hit_rate"))
        _put(m, "plane_read_p99_ms",
             (plane.get("read_latency_ms") or {}).get("p99"))
        _put(m, "plane_requests_per_s",
             (plane.get("hot_read") or {}).get("plane_rps"))
        _put(m, "dispatch_requests_per_s",
             (plane.get("hot_read") or {}).get("dispatch_rps"))
        _put(m, "plane_publish_s", plane.get("publish_s"))
        _put(m, "ttfr_cold_s", (plane.get("ttfr") or {}).get("cold_s"))
        _put(m, "ttfr_aot_warm_s",
             (plane.get("ttfr") or {}).get("aot_warm_s"))
    workload = (f"loadgen_{rep.get('n_requests')}"
                f"x{rep.get('n_series')}")
    if pool:
        workload = f"pool{pool.get('replicas')}_{workload}"
    if plane:
        # Its own baseline family: a plane row's throughput/latency mix
        # (cache-disabled hot reads) must never judge — or be judged
        # by — an ordinary loadgen row.
        workload = f"serveplane_{workload}"
    return {
        "kind": "serve",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": workload,
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _serveplane_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Forecast-plane serve rows (bench --serveplane): the ordinary
    serve normalization re-kinded into its OWN row family.  A plane
    row's metric mix (cache-disabled hot reads, TTFR probes, publish
    walls) is a different experiment from an ordinary loadgen — giving
    it a family gives it its own trajectory block and its own SLO
    section ([tool.tsspark.slo.serveplane]) instead of riding serve's."""
    return dict(_serve_row(rep), kind="serveplane")


def _calibration_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Uncertainty-tier calibration rows (bench --uncertainty;
    uncertainty/calibrate.py).  The headline is coverage_abs_gap —
    |empirical - nominal| coverage of the served intervals on held-out
    data, the one metric that catches a silently mis-calibrated
    posterior — plus the ADVI fit throughput, the quantile plane's
    zero-dispatch read p99, and the NUTS gold audit's divergence.
    Budgeted in [tool.tsspark.slo.calibration]."""
    cal = rep.get("calibration") or {}
    m: Dict[str, float] = {}
    for k in ("coverage_abs_gap", "fit_s", "advi_fit_s",
              "advi_series_per_s", "publish_s", "nbytes",
              "qread_p99_ms", "draws"):
        _put(m, k, cal.get(k))
    _put(m, "wall_s", rep.get("wall_s"))
    _put(m, "mode_advi", cal.get("mode") == "advi")
    for hb, b in sorted((cal.get("buckets") or {}).items()):
        if isinstance(b, dict):
            _put(m, f"coverage_abs_gap_h{hb}", b.get("coverage_abs_gap"))
    gold = cal.get("gold") or {}
    for k in ("qdiv_max", "qdiv_mean", "rhat_max", "ess_min",
              "hmc_divergences"):
        _put(m, k, gold.get(k))
    return {
        "kind": "calibration",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": (f"calibration_{rep.get('n_series')}"
                     f"x{rep.get('holdout')}"),
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _scale_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Scale-ladder rung rows (bench --scale; tsspark_tpu.bench_scale).
    The rung name IS part of the workload key: a 1M-series row must
    never baseline against a smoke row — the same flat-namespace
    discipline PR 11 gave the fit-path suffix."""
    fit = rep.get("fit") or {}
    pub = rep.get("publish") or {}
    serve = rep.get("serve") or {}
    mem = serve.get("mem") or {}
    lat = serve.get("latency_ms") or {}
    flip = serve.get("flip") or {}
    cmp_ = serve.get("rss_compare") or {}
    m: Dict[str, float] = {}
    _put(m, "complete", rep.get("complete"))
    _put(m, "wall_s", rep.get("wall_s"))
    _put(m, "ingest_s", (rep.get("ingest") or {}).get("ingest_s"))
    _put(m, "fit_s", fit.get("fit_s"))
    if fit.get("series_done"):
        _put(m, "series_per_s", fit.get("series_per_s"))
    _put(m, "publish_s", pub.get("publish_s"))
    _put(m, "snapshot_mb", pub.get("snapshot_mb"))
    _put(m, "time_to_first_request_s",
         serve.get("time_to_first_request_s"))
    _put(m, "agg_requests_per_s", serve.get("agg_requests_per_s"))
    _put(m, "p50_ms", lat.get("p50"))
    _put(m, "p99_ms", lat.get("p99"))
    _put(m, "flip_p99_ms", flip.get("p99_ms"))
    _put(m, "rss_mb_per_replica", mem.get("rss_mb_per_replica"))
    _put(m, "pss_mb_per_replica", mem.get("pss_mb_per_replica"))
    _put(m, "rss_anon_mb_per_replica",
         mem.get("rss_anon_mb_per_replica"))
    _put(m, "snap_pss_total_mb", mem.get("snap_pss_total_mb"))
    _put(m, "rss_reduction_x", cmp_.get("rss_reduction_x"))
    _put(m, "wrong_version", serve.get("wrong_version"))
    return {
        "kind": "scale",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": f"scale_{rep.get('rung')}",
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _freshness_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Freshness-stream rows (bench --freshness; tsspark_tpu.sched).
    The workload key carries the rung, churn, AND loop mode: a
    pipelined stream must never baseline a serialized one — the p95
    gap between them is exactly the metric the bench exists to show."""
    m: Dict[str, float] = {}
    for k in ("freshness_p50_s", "freshness_p95_s",
              "freshness_mean_s", "freshness_vs_cold_frac",
              "cycle_overhead_frac", "spec_hit_rate", "cycles",
              "wrong_version", "probe_failures", "cold_wall_s",
              "complete", "wall_s"):
        _put(m, k, rep.get(k))
    churn = rep.get("churn")
    churn_key = (f"c{int(round(float(churn) * 1000)):04d}"
                 if isinstance(churn, (int, float)) else "c?")
    return {
        "kind": "freshness",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": (f"freshness_{rep.get('rung')}_{churn_key}"
                     f"+{rep.get('mode')}"),
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _alerts_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Alert-stream rows (bench --alerts; tsspark_tpu.alerts).  The
    workload key carries the rung, churn, AND scoring mode: interval
    runs (quantile plane published) must never baseline zscore
    fallback runs — their latency profiles differ by the qplane read
    path itself."""
    m: Dict[str, float] = {}
    for k in ("alerts_p50_s", "alerts_p95_s", "alerts_mean_s",
              "delivered_frac", "fired", "suppressed", "delivered",
              "deduped", "queued", "breaker_opens", "cold_wall_s",
              "complete", "wall_s"):
        _put(m, k, rep.get(k))
    churn = rep.get("churn")
    churn_key = (f"c{int(round(float(churn) * 1000)):04d}"
                 if isinstance(churn, (int, float)) else "c?")
    return {
        "kind": "alerts",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": (f"alerts_{rep.get('rung')}_{churn_key}"
                     f"+{rep.get('mode')}"),
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _analysis_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    """Static-analysis gate rows (python -m tsspark_tpu.analysis;
    analysis/report.py).  The gate's drift metrics — waiver creep,
    suppressed-finding growth, gate runtime — become trajectory points
    so a PR that quietly doubles the waiver count is as visible as one
    that halves throughput.  Only FULL gate runs write the artifact
    (the CLI skips it for --changed/partial runs, whose counts are not
    comparable), so every row here shares one workload key."""
    m: Dict[str, float] = {}
    for k in ("ok", "findings", "suppressed", "waivers_inline",
              "waivers_baseline", "wall_s"):
        _put(m, k, rep.get(k))
    for name, n in sorted((rep.get("checkers") or {}).items()):
        _put(m, f"raw_{name}", n)
    return {
        "kind": "analysis",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": "analysis_full",
        "device": None,
        "numerics_rev": None,
        "config_fingerprint": None,
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _chaos_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    m: Dict[str, float] = {}
    _put(m, "ok", rep.get("ok"))
    invs = rep.get("invariants") or {}
    _put(m, "invariant_fails",
         sum(1 for v in invs.values()
             if isinstance(v, dict) and not v.get("ok")))
    for cls, v in sorted((rep.get("mttr_s") or {}).items()):
        _put(m, f"mttr_{cls}", v)
    # Storage fault-domain accounting (the report's ``io`` section):
    # write/error/fault counters and budget/ladder gauges per storm.
    for name, v in sorted((rep.get("io") or {}).items()):
        _put(m, name.replace("tsspark_", ""), v)
    return {
        "kind": "chaos",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": f"storm_{rep.get('profile')}",
        "device": rep.get("device"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _eval_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    m: Dict[str, float] = {}
    for name, c in sorted((rep.get("configs") or {}).items()):
        if not isinstance(c, dict):
            continue
        for k in ("smape_holdout_cpu", "smape_holdout_tpu",
                  "delta_holdout_max_abs", "fit_seconds_tpu"):
            _put(m, f"{name}.{k}", c.get(k))
        dist = c.get("delta_holdout_dist") or {}
        _put(m, f"{name}.delta_holdout_p50", dist.get("p50"))
    return {
        "kind": "eval",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": f"parity_scale{rep.get('scale')}",
        "device": rep.get("platform"),
        "numerics_rev": rep.get("numerics_rev"),
        "config_fingerprint": rep.get("config_fingerprint"),
        "git_rev": rep.get("git_rev"),
        "metrics": m,
    }


def _ledger_row(rep: Dict[str, Any]) -> Dict[str, Any]:
    m: Dict[str, float] = {}
    _put(m, "wall_s", rep.get("wall_s"))
    _put(m, "n_spans", len(rep.get("spans") or ()))
    _put(m, "n_processes", len(rep.get("processes") or ()))
    _put(m, "orphan_spans", len(rep.get("orphan_spans") or ()))
    for cls, v in sorted((rep.get("mttr_s") or {}).items()):
        _put(m, f"mttr_{cls}", v)
    red = (rep.get("red") or {}).get("serve.request") or {}
    _put(m, "serve_request_p99_ms", red.get("p99_ms"))
    return {
        "kind": "ledger",
        "trace_id": rep.get("trace_id"),
        "unix": rep.get("unix"),
        "workload": None,
        "device": None,
        "numerics_rev": None,
        "config_fingerprint": None,
        "git_rev": None,
        "metrics": m,
    }


def classify(rep: Dict[str, Any]) -> Optional[str]:
    """Artifact family of a parsed report dict; None when it is not an
    ingestible run artifact (e.g. a REGRESSION verdict — verdicts must
    never feed back into the baselines that produced them)."""
    kind = rep.get("kind")
    if kind == "serve-loadgen":
        # Plane-bearing loadgen reports (bench --serveplane) are their
        # own family: different experiment, different baselines.
        return "serveplane" if rep.get("plane") else "serve"
    if kind == "calibration-eval":
        return "calibration"
    if kind == "scale-ladder":
        return "scale"
    if kind == "freshness-bench":
        return "freshness"
    if kind == "alerts-bench":
        return "alerts"
    if kind == "analysis-gate":
        return "analysis"
    if kind == "chaos-storm":
        return "chaos"
    if kind == "run-ledger":
        return "ledger"
    if kind == "eval-parity" or "configs" in rep:
        return "eval"
    if kind == "regression-verdict":
        return None
    if "metric" in rep and "extra" in rep:
        return "bench"
    if "cmd" in rep and "parsed" in rep:
        return "bench"
    return None


_ROW_BUILDERS = {
    "bench": _bench_row,
    "serve": _serve_row,
    "serveplane": _serveplane_row,
    "calibration": _calibration_row,
    "scale": _scale_row,
    "freshness": _freshness_row,
    "alerts": _alerts_row,
    "analysis": _analysis_row,
    "chaos": _chaos_row,
    "eval": _eval_row,
    "ledger": _ledger_row,
}


def row_from_report(rep: Dict[str, Any],
                    source: Optional[str] = None) -> Optional[Dict]:
    """Normalize one parsed artifact into a history row (None when the
    dict is no known artifact family)."""
    kind = classify(rep) if isinstance(rep, dict) else None
    if kind is None:
        return None
    row = _ROW_BUILDERS[kind](rep)
    if row["trace_id"]:
        row_id = f"{kind}:{row['trace_id']}"
    else:
        # Pre-PR-6 artifacts carry no trace id: content-hash identity
        # keeps re-ingesting the same committed file a no-op.
        digest = hashlib.sha1(
            json.dumps(rep, sort_keys=True, default=str).encode()
        ).hexdigest()[:12]
        row_id = f"{kind}:sha-{digest}"
    row["row_id"] = row_id
    row["source"] = os.path.basename(source) if source else None
    row["device_class"] = device_class(row.get("device"))
    row["ingested_unix"] = round(time.time(), 3)
    return row


# ---------------------------------------------------------------------------
# the index: read / ingest / backfill
# ---------------------------------------------------------------------------


def read_history(path: str = HISTORY_FILE) -> List[Dict[str, Any]]:
    """All rows of the index, unique by ``row_id`` in first-ingest
    order — a LATER line with the same id amends the earlier one (how
    the sentinel retrofits its ``breached`` flag onto a row that was
    backfilled before being judged).  Torn final line and non-row junk
    tolerated — the append contract allows a writer killed mid-write to
    tear its own last line."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in read_records(path):
        if isinstance(r, dict) and r.get("row_id"):
            # Re-assignment keeps the first occurrence's position.
            out[r["row_id"]] = r
    return list(out.values())


def append_row(row: Dict[str, Any],
               history_path: str = HISTORY_FILE,
               amend: bool = False) -> bool:
    """Append one prebuilt row; False when its ``row_id`` is already
    indexed (the idempotency that lets entrypoints self-ingest
    unconditionally).  ``amend`` appends anyway when the stored row's
    ``breached`` flag differs — the reader's last-wins dedupe makes the
    flagged version authoritative."""
    prev = next((r for r in read_history(history_path)
                 if r.get("row_id") == row["row_id"]), None)
    if prev is not None and not (
        amend and prev.get("breached") != row.get("breached")
    ):
        return False
    append_line(history_path, json.dumps(row))
    return True


def ingest(rep: Dict[str, Any], history_path: str = HISTORY_FILE,
           source: Optional[str] = None
           ) -> Tuple[Optional[Dict], bool]:
    """Normalize + append one report; returns ``(row, appended)``.
    Idempotent: a row whose ``row_id`` is already indexed is skipped."""
    row = row_from_report(rep, source=source)
    if row is None:
        return None, False
    return row, append_row(row, history_path)


def ingest_path(path: str, history_path: str = HISTORY_FILE
                ) -> Tuple[Optional[Dict], bool]:
    """Ingest one artifact file (unparseable/unknown files skipped)."""
    try:
        with open(path) as fh:
            rep = json.load(fh)
    except (OSError, ValueError):
        return None, False
    if not isinstance(rep, dict):
        return None, False
    return ingest(rep, history_path, source=path)


_ROUND_RE = re.compile(r"_r(\d+)")


def _backfill_sort_key(path: str, rep: Dict[str, Any]):
    """Committed round artifacts (``*_r01`` …) order by round number;
    unix-stamped artifacts by their timestamp; mtime as the tiebreak —
    so the backfilled trajectory reads in run order, not glob order."""
    mround = _ROUND_RE.search(os.path.basename(path))
    unix = rep.get("unix")
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = 0.0
    return (
        int(mround.group(1)) if mround else 10 ** 9,
        unix if isinstance(unix, (int, float)) else mtime,
        os.path.basename(path),
    )


def backfill(root: str = ".",
             history_path: Optional[str] = None) -> Dict[str, Any]:
    """Ingest every artifact of a known family under ``root`` (flat
    glob — artifacts live next to the index).  Returns a summary."""
    history_path = history_path or os.path.join(root, HISTORY_FILE)
    candidates: List[Tuple[Tuple, str, Dict]] = []
    for fam in FAMILIES:
        for path in glob.glob(os.path.join(root, f"{fam}*.json")):
            try:
                with open(path) as fh:
                    rep = json.load(fh)
            except (OSError, ValueError):
                continue
            if isinstance(rep, dict):
                candidates.append((_backfill_sort_key(path, rep),
                                   path, rep))
    candidates.sort(key=lambda c: c[0])
    ingested, skipped = [], []
    for _key, path, rep in candidates:
        row, appended = ingest(rep, history_path, source=path)
        if row is None:
            continue
        (ingested if appended else skipped).append(
            os.path.basename(path)
        )
    return {"history": history_path, "ingested": ingested,
            "skipped": skipped, "rows": len(read_history(history_path))}


# ---------------------------------------------------------------------------
# trajectory rendering
# ---------------------------------------------------------------------------

#: Headline metrics per family, in display order (missing ones elided).
_TRAJECTORY_COLUMNS = {
    "bench": ("series_per_s", "first_flush_s", "datagen_s",
              "datagen_share", "smape_insample_mean", "series_done",
              "complete", "rc"),
    "serve": ("requests_per_s", "p50_ms", "p99_ms", "shed_rate",
              "hit_rate", "agg_requests_per_s", "failovers",
              "flip_p99_ms"),
    "serveplane": ("plane_hit_rate", "plane_read_p99_ms",
                   "plane_requests_per_s", "dispatch_requests_per_s",
                   "plane_publish_s", "ttfr_cold_s",
                   "ttfr_aot_warm_s"),
    "calibration": ("coverage_abs_gap", "mode_advi",
                    "advi_series_per_s", "qread_p99_ms", "qdiv_max",
                    "rhat_max", "hmc_divergences"),
    "scale": ("series_per_s", "agg_requests_per_s",
              "time_to_first_request_s", "flip_p99_ms",
              "rss_mb_per_replica", "rss_reduction_x", "complete"),
    "freshness": ("freshness_p50_s", "freshness_p95_s",
                  "freshness_vs_cold_frac", "cycle_overhead_frac",
                  "spec_hit_rate", "wrong_version", "complete"),
    "alerts": ("alerts_p50_s", "alerts_p95_s", "delivered_frac",
               "fired", "suppressed", "deduped", "breaker_opens",
               "complete"),
    "analysis": ("ok", "findings", "suppressed", "waivers_inline",
                 "waivers_baseline", "wall_s"),
    "chaos": ("ok", "invariant_fails"),
    "eval": ("config3_m5.smape_holdout_cpu",
             "config3_m5.delta_holdout_p50",
             "config2_m4_hourly.delta_holdout_p50"),
    "ledger": ("wall_s", "n_spans", "n_processes", "orphan_spans"),
}


def _fmt_row(row: Dict[str, Any], columns: Sequence[str]) -> str:
    name = row.get("source") or row["row_id"]
    bits = [f"{name:<28}"]
    bits.append(f"dev={row.get('device_class') or '?':<4}")
    if row.get("numerics_rev") is not None:
        bits.append(f"rev={row['numerics_rev']}")
    if row.get("git_rev"):
        bits.append(f"git={row['git_rev']}")
    metrics = row.get("metrics") or {}
    shown = 0
    for col in columns:
        if col in metrics:
            bits.append(f"{col}={metrics[col]}")
            shown += 1
    if not shown and row["kind"] == "chaos":
        # mttr columns are per-class; show the worst one.
        mttrs = {k: v for k, v in metrics.items()
                 if k.startswith("mttr_")}
        if mttrs:
            worst = max(mttrs, key=lambda k: mttrs[k])
            bits.append(f"{worst}={mttrs[worst]}")
    if not metrics:
        bits.append("(no parsed summary)")
    return "  " + " ".join(bits)


def trajectory(rows: Sequence[Dict[str, Any]]) -> List[str]:
    """Human-readable trajectory: one line per row, grouped by family
    in ingest order (the roadmap's 'bench trajectory' block)."""
    lines: List[str] = []
    for kind in ("bench", "eval", "serve", "serveplane", "calibration",
                 "scale", "freshness", "alerts", "analysis", "chaos",
                 "ledger"):
        group = [r for r in rows if r.get("kind") == kind]
        if not group:
            continue
        lines.append(f"{kind} trajectory ({len(group)} runs):")
        for row in group:
            extra = _TRAJECTORY_COLUMNS.get(kind, ())
            lines.append(_fmt_row(row, extra))
        # Per-family chaos rows also carry per-class MTTR columns; the
        # sentinel (obs.regress) budgets them individually.
    return lines
