"""``python -m tsspark_tpu.obs`` — render a run's observability story.

Subcommands::

    report [target]   end-to-end timeline + RED/SLO summary.  ``target``
                      is a RUNLEDGER_*.json, a directory holding
                      spans.jsonl files (a run scratch), or omitted —
                      then the newest RUNLEDGER_*.json in the cwd.
                      ``--chrome-trace OUT`` instead exports the spans
                      as Chrome/Perfetto trace-event JSON (open at
                      ui.perfetto.dev) for timeline debugging.
    ledger <dir> [-o OUT]   build + write a RUNLEDGER from a scratch dir
    prom <target>     Prometheus text from a metrics_*.json snapshot or
                      a ledger's embedded snapshots
    history [root]    cross-run trajectory from RUNHISTORY.jsonl;
                      ``--backfill`` ingests the committed BENCH/SERVE/
                      CHAOS/EVAL/RUNLEDGER artifacts under ``root``
    sentinel <artifact>   ingest one report + judge it against the
                      rolling history baseline (exit 1 on breach)
    watch <scratch>   tail an in-flight run's spans + metric snapshots
                      and evaluate the SLO budgets live

Device-free: never imports JAX (same contract as ``-m tsspark_tpu.perf``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def _load_ledger(target: Optional[str]) -> Dict:
    from tsspark_tpu.obs import ledger as ledger_mod

    if target is None:
        cands = sorted(glob.glob("RUNLEDGER_*.json"),
                       key=lambda p: os.path.getmtime(p))
        if not cands:
            raise SystemExit(
                "no RUNLEDGER_*.json in the cwd; pass a ledger file or "
                "a run scratch directory"
            )
        target = cands[-1]
    if os.path.isdir(target):
        return ledger_mod.build_ledger(target)
    with open(target) as fh:
        d = json.load(fh)
    if d.get("kind") != "run-ledger":
        raise SystemExit(f"{target}: not a run ledger (kind={d.get('kind')})")
    return d


def _fmt_dur(dur) -> str:
    if dur is None:
        return "…open"
    if dur >= 1.0:
        return f"{dur:.2f}s"
    return f"{dur * 1e3:.1f}ms"


def _render_timeline(ledger: Dict, max_rows: int) -> List[str]:
    spans = ledger.get("spans", [])
    t_base = ledger.get("t0") or 0.0
    children: Dict[Optional[str], List[Dict]] = {}
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        parent = s.get("parent_id")
        if parent is not None and parent not in by_id:
            parent = None  # orphan: render at the root, flagged below
        children.setdefault(parent, []).append(s)
    for group in children.values():
        group.sort(key=lambda s: s.get("t0") or 0.0)
    lines: List[str] = []

    def walk(parent: Optional[str], depth: int) -> None:
        for s in children.get(parent, ()):
            if len(lines) >= max_rows:
                return
            attrs = s.get("attrs") or {}
            bits = " ".join(
                f"{k}={v}" for k, v in sorted(attrs.items())
                if isinstance(v, (int, float, str, bool))
            )
            mark = " !" if s.get("status") == "err" else ""
            lines.append(
                f"  [{(s.get('t0') or 0.0) - t_base:9.3f}s] "
                f"{'  ' * depth}{s.get('name')} "
                f"({_fmt_dur(s.get('dur_s'))}) pid={s.get('pid')}"
                f"{(' ' + bits) if bits else ''}{mark}"
            )
            walk(s["span_id"], depth + 1)

    walk(None, 0)
    if len(lines) >= max_rows:
        lines.append(f"  ... ({len(spans)} spans total; --max-rows to "
                     f"see more)")
    return lines


def _chrome_trace(ledger: Dict, path: str) -> str:
    """Export a ledger's spans/events as Chrome trace-event JSON
    (``ph: X`` complete events; still-open spans extend to the trace
    end so a SIGKILLed worker's span is visible, not invisible)."""
    from tsspark_tpu.utils.atomic import atomic_write

    t_base = ledger.get("t0") or 0.0
    # Trace end covers open spans' starts and event timestamps too: a
    # run that wedges at the end has its latest activity in exactly
    # those records, and computing the end off closed spans alone would
    # render the wedged worker's span as a zero-width sliver.
    marks = [
        s["t0"] + s["dur_s"] for s in ledger.get("spans", ())
        if s.get("t0") is not None and s.get("dur_s") is not None
    ] + [
        s["t0"] for s in ledger.get("spans", ())
        if s.get("t0") is not None
    ] + [
        e["t"] for e in ledger.get("events", ()) if e.get("t") is not None
    ]
    t_end = max(marks) if marks else t_base
    evs: List[Dict] = []
    for s in ledger.get("spans", ()):
        t0 = s.get("t0")
        if t0 is None:
            continue
        dur = s.get("dur_s")
        if dur is None:
            # Open span: extend to the trace end, floored at 1 ms so
            # even the LAST thing that happened stays visible.
            dur = max(1e-3, t_end - t0)
        args_d = {
            k: v for k, v in (s.get("attrs") or {}).items()
            if isinstance(v, (int, float, str, bool))
        }
        args_d["span_id"] = s.get("span_id")
        args_d["status"] = s.get("status")
        name = s.get("name") or "?"
        evs.append({
            "name": name, "cat": name.split(".")[0], "ph": "X",
            "ts": round((t0 - t_base) * 1e6, 1),
            "dur": round(dur * 1e6, 1),
            "pid": s.get("pid") or 0, "tid": s.get("pid") or 0,
            "args": args_d,
        })
    for e in ledger.get("events", ()):
        evs.append({
            "name": e.get("name") or "?", "cat": "event", "ph": "i",
            "s": "p",
            "ts": round(((e.get("t") or t_base) - t_base) * 1e6, 1),
            "pid": e.get("pid") or 0, "tid": e.get("pid") or 0,
            "args": e.get("attrs") or {},
        })
    payload = {
        "traceEvents": evs,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": ledger.get("trace_id")},
    }
    atomic_write(path, lambda fh: json.dump(payload, fh), mode="w")
    return path


def _report(args) -> int:
    ledger = _load_ledger(args.target)
    if getattr(args, "chrome_trace", None):
        out = _chrome_trace(ledger, args.chrome_trace)
        print(f"chrome trace: {len(ledger.get('spans', []))} spans, "
              f"{len(ledger.get('events', []))} events, trace "
              f"{ledger.get('trace_id')} -> {out} "
              "(open at ui.perfetto.dev or chrome://tracing)")
        return 0
    t_base = ledger.get("t0") or 0.0
    print(
        f"run ledger: trace {ledger.get('trace_id')} | "
        f"{len(ledger.get('spans', []))} spans across "
        f"{len(ledger.get('processes', []))} process(es) | "
        f"wall {ledger.get('wall_s')}s"
    )
    orphans = ledger.get("orphan_spans", [])
    print(f"orphan spans: {len(orphans)}"
          + (f"  {orphans[:8]}" if orphans else ""))
    ms = ledger.get("milestones") or {}
    if ms:
        print("milestones (s from trace start):")
        for k, v in sorted(ms.items(), key=lambda kv: kv[1]):
            print(f"  {v - t_base:9.3f}  {k}")
    print("timeline:")
    for line in _render_timeline(ledger, args.max_rows):
        print(line)
    red = ledger.get("red") or {}
    if red:
        print("RED summary (per span name):")
        for name, r in sorted(red.items()):
            rate = f"{r['rate_per_s']}/s" if r.get("rate_per_s") else "-"
            print(
                f"  {name:<22} n={r['n']:<6} err={r['err']:<4} "
                f"open={r.get('open', 0):<3} rate={rate:<10} "
                f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms "
                f"max={r['max_ms']}ms"
            )
    mttr = {k: v for k, v in (ledger.get("mttr_s") or {}).items()}
    if mttr:
        print("MTTR from spans (per fault class):")
        for cls, v in sorted(mttr.items()):
            print(f"  {cls:<18} "
                  + (f"{v}s" if v is not None else "NO RECOVERY"))
    reports = ledger.get("reports") or []
    if reports:
        print("joined reports:")
        for r in reports:
            print(f"  {r.get('kind')} trace={r.get('trace_id')} "
                  f"ok={r.get('ok')} joined={r.get('joined')}")
    return 0


def _ledger(args) -> int:
    from tsspark_tpu.obs import ledger as ledger_mod

    ledger = ledger_mod.build_ledger(args.dir)
    out = ledger_mod.write_ledger(ledger, args.out)
    print(
        f"run ledger: {len(ledger['spans'])} spans, "
        f"{len(ledger['events'])} events, trace "
        f"{ledger['trace_id']} -> {out}"
    )
    return 0


def _prom(args) -> int:
    from tsspark_tpu.obs.metrics import prometheus_text

    with open(args.target) as fh:
        d = json.load(fh)
    if d.get("kind") == "metrics-snapshot":
        sys.stdout.write(prometheus_text(d.get("metrics", {})))
        return 0
    if d.get("kind") == "run-ledger":
        for snap in d.get("metrics", []):
            sys.stdout.write(prometheus_text(snap.get("metrics", {})))
        return 0
    raise SystemExit(f"{args.target}: neither a metrics snapshot nor a "
                     "run ledger")


def _history(args) -> int:
    from tsspark_tpu.obs import history as hist

    hpath = args.history or os.path.join(args.root, hist.HISTORY_FILE)
    if args.backfill:
        summary = hist.backfill(args.root, hpath)
        print(f"backfill: +{len(summary['ingested'])} row(s), "
              f"{len(summary['skipped'])} already indexed -> "
              f"{summary['history']}")
    for path in args.ingest or ():
        row, appended = hist.ingest_path(path, hpath)
        if appended:
            state = "ingested"
        elif row is not None:
            state = "already indexed"
        elif not os.path.exists(path):
            state = "missing file"
        else:
            state = "not a known artifact family"
        print(f"ingest {path}: {state}")
    rows = hist.read_history(hpath)
    print(f"run history: {len(rows)} row(s) ({hpath})")
    for line in hist.trajectory(rows):
        print(line)
    return 0


def _sentinel(args) -> int:
    from tsspark_tpu.obs import regress

    try:
        with open(args.artifact) as fh:
            rep = json.load(fh)
    except (OSError, ValueError) as e:
        raise SystemExit(f"{args.artifact}: {e}")
    verdict = regress.sentinel_report(
        rep, history_path=args.history, source=args.artifact,
        out=args.out,
    )
    if verdict is None:
        raise SystemExit(
            f"{args.artifact}: not an ingestible run artifact"
        )
    print(regress.summarize(verdict))
    return 0 if verdict["ok"] else 1


def _watch(args) -> int:
    from tsspark_tpu.obs import watch as watch_mod

    return watch_mod.watch(
        args.scratch, history_path=args.history,
        interval_s=args.interval, duration_s=args.duration,
        once=args.once,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.obs",
        description="observability reports (docs/OBSERVABILITY.md)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_rep = sub.add_parser("report", help="timeline + RED/SLO summary")
    p_rep.add_argument("target", nargs="?", default=None)
    p_rep.add_argument("--max-rows", type=int, default=200)
    p_rep.add_argument("--chrome-trace", default=None, metavar="OUT",
                       help="export spans as Chrome/Perfetto "
                       "trace-event JSON instead of the text report")
    p_led = sub.add_parser("ledger", help="build a RUNLEDGER from a dir")
    p_led.add_argument("dir")
    p_led.add_argument("-o", "--out", default=None)
    p_prom = sub.add_parser("prom", help="Prometheus text dump")
    p_prom.add_argument("target")
    p_hist = sub.add_parser(
        "history", help="cross-run trajectory (RUNHISTORY.jsonl)"
    )
    p_hist.add_argument("root", nargs="?", default=".")
    p_hist.add_argument("--backfill", action="store_true",
                        help="ingest the BENCH/SERVE/CHAOS/EVAL/"
                        "RUNLEDGER artifacts under root first")
    p_hist.add_argument("--history", default=None,
                        help="index path (default: "
                        "<root>/RUNHISTORY.jsonl)")
    p_hist.add_argument("--ingest", action="append", default=None,
                        metavar="FILE",
                        help="additionally ingest this artifact "
                        "(repeatable)")
    p_sent = sub.add_parser(
        "sentinel", help="judge one artifact vs the rolling baseline"
    )
    p_sent.add_argument("artifact")
    p_sent.add_argument("--history", default="RUNHISTORY.jsonl")
    p_sent.add_argument("--out", default=None,
                        help="verdict path (default: "
                        "REGRESSION_<unix>.json)")
    p_watch = sub.add_parser(
        "watch", help="live SLO watch over an in-flight run scratch"
    )
    p_watch.add_argument("scratch")
    p_watch.add_argument("--history", default="RUNHISTORY.jsonl")
    p_watch.add_argument("--interval", type=float, default=2.0)
    p_watch.add_argument("--duration", type=float, default=None)
    p_watch.add_argument("--once", action="store_true",
                         help="one evaluation pass, then exit")
    args = ap.parse_args(argv)
    return {
        "report": _report, "ledger": _ledger, "prom": _prom,
        "history": _history, "sentinel": _sentinel, "watch": _watch,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
