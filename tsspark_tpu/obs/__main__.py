"""``python -m tsspark_tpu.obs`` — render a run's observability story.

Subcommands::

    report [target]   end-to-end timeline + RED/SLO summary.  ``target``
                      is a RUNLEDGER_*.json, a directory holding
                      spans.jsonl files (a run scratch), or omitted —
                      then the newest RUNLEDGER_*.json in the cwd.
    ledger <dir> [-o OUT]   build + write a RUNLEDGER from a scratch dir
    prom <target>     Prometheus text from a metrics_*.json snapshot or
                      a ledger's embedded snapshots

Device-free: never imports JAX (same contract as ``-m tsspark_tpu.perf``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def _load_ledger(target: Optional[str]) -> Dict:
    from tsspark_tpu.obs import ledger as ledger_mod

    if target is None:
        cands = sorted(glob.glob("RUNLEDGER_*.json"),
                       key=lambda p: os.path.getmtime(p))
        if not cands:
            raise SystemExit(
                "no RUNLEDGER_*.json in the cwd; pass a ledger file or "
                "a run scratch directory"
            )
        target = cands[-1]
    if os.path.isdir(target):
        return ledger_mod.build_ledger(target)
    with open(target) as fh:
        d = json.load(fh)
    if d.get("kind") != "run-ledger":
        raise SystemExit(f"{target}: not a run ledger (kind={d.get('kind')})")
    return d


def _fmt_dur(dur) -> str:
    if dur is None:
        return "…open"
    if dur >= 1.0:
        return f"{dur:.2f}s"
    return f"{dur * 1e3:.1f}ms"


def _render_timeline(ledger: Dict, max_rows: int) -> List[str]:
    spans = ledger.get("spans", [])
    t_base = ledger.get("t0") or 0.0
    children: Dict[Optional[str], List[Dict]] = {}
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        parent = s.get("parent_id")
        if parent is not None and parent not in by_id:
            parent = None  # orphan: render at the root, flagged below
        children.setdefault(parent, []).append(s)
    for group in children.values():
        group.sort(key=lambda s: s.get("t0") or 0.0)
    lines: List[str] = []

    def walk(parent: Optional[str], depth: int) -> None:
        for s in children.get(parent, ()):
            if len(lines) >= max_rows:
                return
            attrs = s.get("attrs") or {}
            bits = " ".join(
                f"{k}={v}" for k, v in sorted(attrs.items())
                if isinstance(v, (int, float, str, bool))
            )
            mark = " !" if s.get("status") == "err" else ""
            lines.append(
                f"  [{(s.get('t0') or 0.0) - t_base:9.3f}s] "
                f"{'  ' * depth}{s.get('name')} "
                f"({_fmt_dur(s.get('dur_s'))}) pid={s.get('pid')}"
                f"{(' ' + bits) if bits else ''}{mark}"
            )
            walk(s["span_id"], depth + 1)

    walk(None, 0)
    if len(lines) >= max_rows:
        lines.append(f"  ... ({len(spans)} spans total; --max-rows to "
                     f"see more)")
    return lines


def _report(args) -> int:
    ledger = _load_ledger(args.target)
    t_base = ledger.get("t0") or 0.0
    print(
        f"run ledger: trace {ledger.get('trace_id')} | "
        f"{len(ledger.get('spans', []))} spans across "
        f"{len(ledger.get('processes', []))} process(es) | "
        f"wall {ledger.get('wall_s')}s"
    )
    orphans = ledger.get("orphan_spans", [])
    print(f"orphan spans: {len(orphans)}"
          + (f"  {orphans[:8]}" if orphans else ""))
    ms = ledger.get("milestones") or {}
    if ms:
        print("milestones (s from trace start):")
        for k, v in sorted(ms.items(), key=lambda kv: kv[1]):
            print(f"  {v - t_base:9.3f}  {k}")
    print("timeline:")
    for line in _render_timeline(ledger, args.max_rows):
        print(line)
    red = ledger.get("red") or {}
    if red:
        print("RED summary (per span name):")
        for name, r in sorted(red.items()):
            rate = f"{r['rate_per_s']}/s" if r.get("rate_per_s") else "-"
            print(
                f"  {name:<22} n={r['n']:<6} err={r['err']:<4} "
                f"open={r.get('open', 0):<3} rate={rate:<10} "
                f"p50={r['p50_ms']}ms p99={r['p99_ms']}ms "
                f"max={r['max_ms']}ms"
            )
    mttr = {k: v for k, v in (ledger.get("mttr_s") or {}).items()}
    if mttr:
        print("MTTR from spans (per fault class):")
        for cls, v in sorted(mttr.items()):
            print(f"  {cls:<18} "
                  + (f"{v}s" if v is not None else "NO RECOVERY"))
    reports = ledger.get("reports") or []
    if reports:
        print("joined reports:")
        for r in reports:
            print(f"  {r.get('kind')} trace={r.get('trace_id')} "
                  f"ok={r.get('ok')} joined={r.get('joined')}")
    return 0


def _ledger(args) -> int:
    from tsspark_tpu.obs import ledger as ledger_mod

    ledger = ledger_mod.build_ledger(args.dir)
    out = ledger_mod.write_ledger(ledger, args.out)
    print(
        f"run ledger: {len(ledger['spans'])} spans, "
        f"{len(ledger['events'])} events, trace "
        f"{ledger['trace_id']} -> {out}"
    )
    return 0


def _prom(args) -> int:
    from tsspark_tpu.obs.metrics import prometheus_text

    with open(args.target) as fh:
        d = json.load(fh)
    if d.get("kind") == "metrics-snapshot":
        sys.stdout.write(prometheus_text(d.get("metrics", {})))
        return 0
    if d.get("kind") == "run-ledger":
        for snap in d.get("metrics", []):
            sys.stdout.write(prometheus_text(snap.get("metrics", {})))
        return 0
    raise SystemExit(f"{args.target}: neither a metrics snapshot nor a "
                     "run ledger")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tsspark_tpu.obs",
        description="observability reports (docs/OBSERVABILITY.md)",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_rep = sub.add_parser("report", help="timeline + RED/SLO summary")
    p_rep.add_argument("target", nargs="?", default=None)
    p_rep.add_argument("--max-rows", type=int, default=200)
    p_led = sub.add_parser("ledger", help="build a RUNLEDGER from a dir")
    p_led.add_argument("dir")
    p_led.add_argument("-o", "--out", default=None)
    p_prom = sub.add_parser("prom", help="Prometheus text dump")
    p_prom.add_argument("target")
    args = ap.parse_args(argv)
    return {"report": _report, "ledger": _ledger, "prom": _prom}[args.cmd](
        args
    )


if __name__ == "__main__":
    sys.exit(main())
