"""Regression sentinel: rolling robust SLOs over the run history.

Every artifact-producing entrypoint (``bench.py``, ``python -m
tsspark_tpu.serve --loadgen``, ``python -m tsspark_tpu.chaos``) ends by
handing its report here: the report is ingested into the history index
(``obs.history``), compared against a rolling robust baseline —
median/MAD over the last K *comparable* rows: same artifact kind,
device class, NUMERICS_REV, and workload key — under per-metric budgets
declared in ``pyproject.toml [tool.tsspark.slo]``, and the verdict is
persisted as ``REGRESSION_<unix>.json``.  A breach makes the
entrypoint exit nonzero, so a perf or MTTR regression fails the run
that introduced it instead of waiting for a human to diff JSON.

Budget semantics, per metric (``direction`` = "higher" | "lower"):

* the *budget bound* comes from ``max_drop_frac``/``max_drop_abs``
  (higher-is-better) or ``max_rise_frac``/``max_rise_abs`` (lower-is-
  better) off the baseline median, plus optional ``slack_abs`` so tiny
  absolute values (a 0.2 s MTTR) don't trip fractional budgets on
  noise;
* the *noise bound* is ``mad_k`` scaled MADs from the median
  (1.4826·MAD ≈ one robust sigma);
* a value breaches only when it is worse than BOTH — robust to a noisy
  baseline, yet an identical re-run is always green and a 3× collapse
  is always red (pinned in tests/test_history.py).

Device-free: never imports JAX (same contract as ``obs.history``).
"""

from __future__ import annotations

import fnmatch
import json
import os
import statistics
import time
from typing import Any, Dict, List, Optional, Sequence

from tsspark_tpu.obs import history
from tsspark_tpu.utils.atomic import atomic_write

#: MAD -> robust sigma scale (normal consistency constant).
_MAD_SIGMA = 1.4826

#: Fallbacks when pyproject has no ``[tool.tsspark.slo]`` (kept in sync
#: with the committed table there — pyproject is the reviewed source of
#: truth; these only cover running outside a checkout).
DEFAULT_SLO: Dict[str, Any] = {
    "window": 8,
    "min_history": 1,
    "mad_k": 4.0,
    "budgets": {
        "bench": {
            "series_per_s": {"direction": "higher",
                             "max_drop_frac": 0.5},
            "resident_series_per_s": {"direction": "higher",
                                      "max_drop_frac": 0.5},
            "first_flush_s": {"direction": "lower",
                              "max_rise_frac": 1.5, "slack_abs": 5.0},
            "compile_misses": {"direction": "lower",
                               "max_rise_abs": 8},
            "datagen_s": {"direction": "lower", "max_rise_frac": 1.0,
                          "slack_abs": 10.0},
            "datagen_share": {"direction": "lower",
                              "max_rise_abs": 0.10,
                              "slack_abs": 0.02},
            "smape_insample_mean": {"direction": "lower",
                                    "max_rise_frac": 0.05},
            "delta_series_per_s": {"direction": "higher",
                                   "max_drop_frac": 0.5},
            "delta_wall_frac": {"direction": "lower",
                                "max_rise_frac": 0.5,
                                "slack_abs": 0.05},
        },
        "serve": {
            "p50_ms": {"direction": "lower", "max_rise_frac": 1.0,
                       "slack_abs": 2.0},
            "p99_ms": {"direction": "lower", "max_rise_frac": 1.0,
                       "slack_abs": 5.0},
            "requests_per_s": {"direction": "higher",
                               "max_drop_frac": 0.5},
            "shed_rate": {"direction": "lower", "max_rise_abs": 0.05},
            "hit_rate": {"direction": "higher", "max_drop_abs": 0.15},
            "agg_requests_per_s": {"direction": "higher",
                                   "max_drop_frac": 0.5},
            "failovers": {"direction": "lower", "max_rise_abs": 8},
            "flip_p99_ms": {"direction": "lower", "max_rise_frac": 1.0,
                            "slack_abs": 50.0},
            "plane_hit_rate": {"direction": "higher",
                               "max_drop_abs": 0.15},
            "plane_read_p99_ms": {"direction": "lower",
                                  "max_rise_frac": 1.0,
                                  "slack_abs": 2.0},
        },
        "serveplane": {
            "plane_hit_rate": {"direction": "higher",
                               "max_drop_abs": 0.15},
            "plane_read_p99_ms": {"direction": "lower",
                                  "max_rise_frac": 1.0,
                                  "slack_abs": 2.0},
            "plane_requests_per_s": {"direction": "higher",
                                     "max_drop_frac": 0.5},
            "ttfr_aot_warm_s": {"direction": "lower",
                                "max_rise_frac": 1.0,
                                "slack_abs": 5.0},
        },
        "calibration": {
            "coverage_abs_gap": {"direction": "lower",
                                 "max_rise_abs": 0.10,
                                 "slack_abs": 0.05},
            "advi_series_per_s": {"direction": "higher",
                                  "max_drop_frac": 0.5},
            "qread_p99_ms": {"direction": "lower",
                             "max_rise_frac": 1.0, "slack_abs": 2.0},
            "qdiv_max": {"direction": "lower", "max_rise_frac": 1.0,
                         "slack_abs": 1.0},
        },
        "scale": {
            "rss_mb_per_replica": {"direction": "lower",
                                   "max_rise_frac": 0.5,
                                   "slack_abs": 128.0},
            "agg_requests_per_s": {"direction": "higher",
                                   "max_drop_frac": 0.5},
            "time_to_first_request_s": {"direction": "lower",
                                        "max_rise_frac": 1.0,
                                        "slack_abs": 5.0},
            "flip_p99_ms": {"direction": "lower",
                            "max_rise_frac": 1.0,
                            "slack_abs": 50.0},
            "series_per_s": {"direction": "higher",
                             "max_drop_frac": 0.5},
        },
        "freshness": {
            "freshness_p95_s": {"direction": "lower",
                                "max_rise_frac": 1.0,
                                "slack_abs": 2.0},
            "cycle_overhead_frac": {"direction": "lower",
                                    "max_rise_abs": 0.25,
                                    "slack_abs": 0.05},
            "spec_hit_rate": {"direction": "higher",
                              "max_drop_abs": 0.5},
        },
        "alerts": {
            "alerts_p95_s": {"direction": "lower",
                             "max_rise_frac": 1.0,
                             "slack_abs": 2.0},
            "delivered_frac": {"direction": "higher",
                               "max_drop_abs": 0.25},
        },
        "chaos": {
            "ok": {"direction": "higher", "max_drop_abs": 0.5},
            "mttr_*": {"direction": "lower", "max_rise_frac": 1.0,
                       "slack_abs": 2.0},
        },
        "eval": {
            "*.delta_holdout_p50": {"direction": "lower",
                                    "max_rise_abs": 0.05},
            "*.smape_holdout_tpu": {"direction": "lower",
                                    "max_rise_frac": 0.05,
                                    "slack_abs": 0.2},
        },
        "analysis": {
            "findings": {"direction": "lower", "max_rise_abs": 0.0},
            "wall_s": {"direction": "lower", "max_rise_frac": 1.0,
                       "slack_abs": 30.0},
        },
    },
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def load_slo(root: Optional[str] = None) -> Dict[str, Any]:
    """SLO config: ``[tool.tsspark.slo]`` from ``root``'s (default: the
    checkout's, else the cwd's) pyproject, merged over the defaults.
    Per-kind tables merge per metric — overriding one budget does not
    drop the rest."""
    slo = {
        "window": DEFAULT_SLO["window"],
        "min_history": DEFAULT_SLO["min_history"],
        "mad_k": DEFAULT_SLO["mad_k"],
        "budgets": {k: dict(v)
                    for k, v in DEFAULT_SLO["budgets"].items()},
    }
    roots = [root] if root else [_repo_root(), os.getcwd()]
    raw: Dict[str, Any] = {}
    for r in roots:
        path = os.path.join(r, "pyproject.toml")
        if not os.path.exists(path):
            continue
        try:
            try:
                import tomllib as toml_mod  # Python >= 3.11
            except ImportError:
                import tomli as toml_mod
            with open(path, "rb") as fh:
                raw = (toml_mod.load(fh).get("tool", {})
                       .get("tsspark", {}).get("slo", {}))
        except Exception:
            raw = {}
        if raw:
            break
    for key in ("window", "min_history", "mad_k"):
        if isinstance(raw.get(key), (int, float)):
            slo[key] = raw[key]
    for kind, table in raw.items():
        if kind in ("window", "min_history", "mad_k"):
            continue
        if isinstance(table, dict):
            merged = dict(slo["budgets"].get(kind, {}))
            for metric, budget in table.items():
                if isinstance(budget, dict):
                    merged[metric] = budget
            slo["budgets"][kind] = merged
    return slo


# ---------------------------------------------------------------------------
# baseline selection + evaluation
# ---------------------------------------------------------------------------


def comparable(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    """Two rows may share a baseline: same kind, and device class /
    NUMERICS_REV / workload equal wherever both sides recorded them
    (pre-PR-8 artifacts carry None — a wildcard, so the backfilled past
    still seeds baselines)."""
    if a.get("kind") != b.get("kind"):
        return False
    for key in ("device_class", "numerics_rev", "workload"):
        va, vb = a.get(key), b.get(key)
        if va is not None and vb is not None and va != vb:
            return False
    return True


def _bound(direction: str, med: float, sigma: float,
           budget: Dict[str, Any], mad_k: float) -> float:
    """The effective threshold: worse than BOTH the declared budget and
    the noise band.  Multiple declared budget forms combine loosely
    (the sentinel must be conservative — it exits runs nonzero)."""
    budget_bounds: List[float] = []
    if direction == "higher":
        if "max_drop_frac" in budget:
            budget_bounds.append(med * (1.0 - budget["max_drop_frac"]))
        if "max_drop_abs" in budget:
            budget_bounds.append(med - budget["max_drop_abs"])
        if not budget_bounds:
            budget_bounds.append(med)
        b = min(budget_bounds) - budget.get("slack_abs", 0.0)
        return min(b, med - mad_k * sigma)
    if "max_rise_frac" in budget:
        budget_bounds.append(med * (1.0 + budget["max_rise_frac"]))
    if "max_rise_abs" in budget:
        budget_bounds.append(med + budget["max_rise_abs"])
    if not budget_bounds:
        budget_bounds.append(med)
    b = max(budget_bounds) + budget.get("slack_abs", 0.0)
    return max(b, med + mad_k * sigma)


def evaluate(row: Dict[str, Any],
             history_rows: Sequence[Dict[str, Any]],
             slo: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Judge one history row against its rolling baseline; returns the
    verdict dict (``write_verdict`` for the file form)."""
    slo = slo or load_slo()
    window = int(slo["window"])
    min_history = int(slo["min_history"])
    # Rows that themselves breached are no baseline: a persistent
    # regression re-ingested run after run would otherwise drag the
    # median down until the unfixed regression judges green.
    base = [r for r in history_rows
            if r.get("row_id") != row.get("row_id")
            and not r.get("breached")
            and comparable(r, row)][-window:]
    budgets: Dict[str, Dict] = slo["budgets"].get(row.get("kind"), {})
    metrics: Dict[str, Any] = row.get("metrics") or {}
    checks: List[Dict[str, Any]] = []
    breaches: List[str] = []
    skipped: List[str] = []
    for pattern in sorted(budgets):
        budget = budgets[pattern]
        if any(c in pattern for c in "*?["):
            names = sorted(fnmatch.filter(metrics, pattern))
        else:
            names = [pattern]
        for name in names:
            value = metrics.get(name)
            series = [r["metrics"][name] for r in base
                      if isinstance((r.get("metrics") or {}).get(name),
                                    (int, float))]
            if not isinstance(value, (int, float)):
                skipped.append(name)
                continue
            if len(series) < min_history:
                skipped.append(name)
                continue
            med = float(statistics.median(series))
            mad = float(statistics.median(
                abs(x - med) for x in series
            ))
            sigma = _MAD_SIGMA * mad
            direction = budget.get("direction", "higher")
            mad_k = float(budget.get("mad_k", slo["mad_k"]))
            bound = _bound(direction, med, sigma, budget, mad_k)
            ok = (value >= bound if direction == "higher"
                  else value <= bound)
            checks.append({
                "metric": name, "value": value,
                "median": round(med, 6), "mad": round(mad, 6),
                "n_baseline": len(series),
                "direction": direction,
                "bound": round(bound, 6), "ok": ok,
            })
            if not ok:
                breaches.append(name)
    return {
        "kind": "regression-verdict",
        "unix": round(time.time(), 3),
        "trace_id": row.get("trace_id"),
        "row_id": row.get("row_id"),
        "row_kind": row.get("kind"),
        "source": row.get("source"),
        "workload": row.get("workload"),
        "git_rev": row.get("git_rev") or history.git_rev(),
        "baseline": {
            "n": len(base), "window": window,
            "row_ids": [r.get("row_id") for r in base],
        },
        "checks": checks,
        "breaches": breaches,
        "skipped": sorted(set(skipped)),
        "ok": not breaches,
    }


def write_verdict(verdict: Dict[str, Any],
                  path: Optional[str] = None) -> str:
    """Persist a verdict as ``REGRESSION_<unix>.json`` (atomic, like
    every other report artifact)."""
    out = path or f"REGRESSION_{int(verdict.get('unix', time.time()))}.json"
    atomic_write(out, lambda fh: json.dump(verdict, fh, indent=1),
                 mode="w")
    return out


# ---------------------------------------------------------------------------
# the entrypoint post-step
# ---------------------------------------------------------------------------


def sentinel_report(rep: Dict[str, Any],
                    history_path: str = history.HISTORY_FILE,
                    source: Optional[str] = None,
                    out: Optional[str] = None,
                    slo: Optional[Dict[str, Any]] = None
                    ) -> Optional[Dict[str, Any]]:
    """The self-gate every artifact-producing entrypoint calls: ingest
    ``rep`` into the history (idempotent), judge it against the rows
    that PRECEDED it, write the ``REGRESSION_*.json`` verdict.  Returns
    the verdict (with ``path`` filled in), or None when ``rep`` is not
    an ingestible artifact.  Never raises for a malformed report — the
    caller decides what a breach does to its exit code."""
    before = history.read_history(history_path)
    row = history.row_from_report(rep, source=source)
    if row is None:
        return None
    verdict = evaluate(row, before, slo=slo)
    if not verdict["ok"]:
        # The verdict travels WITH the row: ``evaluate`` skips breached
        # rows when baselining, so a regressed run never normalizes
        # the very baseline that would have to catch it.  ``amend``
        # covers the row having reached the index unjudged first (a
        # backfill, or an entrypoint run with the sentinel opted out).
        row["breached"] = verdict["breaches"]
    history.append_row(row, history_path, amend=not verdict["ok"])
    verdict["history"] = history_path
    verdict["path"] = write_verdict(verdict, out)
    return verdict


def summarize(verdict: Dict[str, Any]) -> str:
    """One operator-facing line per verdict (entrypoints print it)."""
    if verdict["ok"]:
        judged = [c["metric"] for c in verdict["checks"]]
        basis = verdict["baseline"]["n"]
        return (f"sentinel OK: {len(judged)} metric(s) within budget "
                f"vs {basis}-run baseline -> {verdict.get('path')}")
    bits = []
    for c in verdict["checks"]:
        if not c["ok"]:
            cmp_ = "<" if c["direction"] == "higher" else ">"
            bits.append(f"{c['metric']}={c['value']} {cmp_} "
                        f"bound {c['bound']} (median {c['median']})")
    return ("sentinel REGRESSION: " + "; ".join(bits)
            + f" -> {verdict.get('path')}")
