"""Trace/span context: one trace id across every process of a run.

The Spark reference reads a run's story off the Spark UI's stage
timeline; this package's equivalent is a ``spans.jsonl`` file that every
process of a run appends to.  A *run* binds a (trace_id, spans_path)
pair process-globally (``start_run``); the *current span* rides a
contextvar so nested instrumentation parents correctly; and the binding
crosses process boundaries through the ``TSSPARK_TRACE`` environment
variable (``inject_env`` in the spawner, ``adopt_env`` at the child's
entry) and through the serve daemon's JSONL request envelopes
(``remote_context``).

Records are appended crash-safely via ``utils.atomic.append_line`` (one
``O_APPEND`` write per line — concurrent writer processes never
interleave), so a SIGKILLed worker loses at most its own last line.
Long-lived spans are written TWICE: an ``open`` record at begin
(``open_span``) and a completion record with the same span id at end —
a process killed mid-span still leaves the open record behind, so its
children never become orphans in the ledger.

With no run bound, every function here is a no-op costing one ``None``
check — production fits that never asked for tracing pay nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

from tsspark_tpu.utils.atomic import append_line

ENV_VAR = "TSSPARK_TRACE"

#: File name convention for the per-run span log (one per run dir).
SPANS_FILE = "spans.jsonl"


class Run:
    """A process-global run binding: trace id + span-log path."""

    __slots__ = ("trace_id", "spans_path")

    def __init__(self, trace_id: str, spans_path: Optional[str]):
        self.trace_id = trace_id
        self.spans_path = spans_path

    def write(self, rec: Dict[str, Any]) -> None:
        if self.spans_path is None:
            return
        try:
            append_line(self.spans_path, json.dumps(rec))
        except OSError:
            pass  # observability must never take the workload down


_RUN: Optional[Run] = None
# Current span id (parent for children).  A contextvar, not a global:
# the engine's background pump thread and the orchestrator's writer
# thread must not clobber the main thread's position in the tree.
_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "tsspark_obs_span", default=None
)
# Trace override for remote envelopes (serve daemon request lines).
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "tsspark_obs_trace", default=None
)


def new_id() -> str:
    """Random 12-hex id (span or trace)."""
    return os.urandom(6).hex()


def active() -> bool:
    return _RUN is not None


def trace_id() -> Optional[str]:
    over = _TRACE.get()
    if over is not None:
        return over
    return _RUN.trace_id if _RUN is not None else None


def current_span_id() -> Optional[str]:
    return _SPAN.get()


def current_ids() -> Optional[Dict[str, str]]:
    """{"trace_id", "span_id"} when a span is active (the structured
    logger stamps these onto every event), else None."""
    sid = _SPAN.get()
    if sid is None or not active():
        return None
    return {"trace_id": trace_id(), "span_id": sid}


def start_run(spans_path: Optional[str] = None,
              trace_id: Optional[str] = None) -> Optional[Run]:
    """Bind a run for this process; returns the PREVIOUS binding so a
    caller that nests runs (tests, the chaos harness inside a traced
    session) can restore it with ``end_run``."""
    global _RUN
    prev = _RUN
    if spans_path is not None:
        d = os.path.dirname(os.path.abspath(spans_path))
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            spans_path = None
    _RUN = Run(trace_id or new_id(), spans_path)
    # Fresh run, fresh tree: a span position left over from a previous
    # binding (or an adopted parent from a finished run) must not
    # become this run's phantom root parent.
    _SPAN.set(None)
    return prev


def end_run(prev: Optional[Run] = None) -> None:
    """Restore the previous binding (or unbind)."""
    global _RUN
    _RUN = prev


def inject_env(env: Dict[str, str],
               parent_id: Optional[str] = None) -> None:
    """Propagate the active run into a child process's environment.
    ``parent_id`` overrides the current span as the child's parent
    (spawners that allocate a per-attempt span pass it explicitly)."""
    if _RUN is None:
        return
    env[ENV_VAR] = json.dumps({
        "trace_id": _RUN.trace_id,
        "parent_span_id": parent_id or _SPAN.get(),
        "spans_path": _RUN.spans_path,
    })


def adopt_env() -> bool:
    """Child-process entry: bind the run the spawner injected (no-op
    when none was).  The injected parent span becomes the current span,
    so everything this process records parents across the boundary."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return False
    try:
        d = json.loads(spec)
    except ValueError:
        return False
    start_run(spans_path=d.get("spans_path"),
              trace_id=d.get("trace_id"))
    if d.get("parent_span_id"):
        _SPAN.set(d["parent_span_id"])
    return True


@contextlib.contextmanager
def remote_context(trace: Optional[str],
                   parent_span_id: Optional[str]) -> Iterator[None]:
    """Adopt a REMOTE caller's trace for the duration of one request
    (the serve daemon's JSONL envelope: ``{"trace": {"trace_id": ...,
    "parent_span_id": ...}}``).  Records written inside carry the
    caller's trace id and parent to its span."""
    if not active() or not trace:
        yield
        return
    t_tok = _TRACE.set(trace)
    s_tok = _SPAN.set(parent_span_id)
    try:
        yield
    finally:
        _SPAN.reset(s_tok)
        _TRACE.reset(t_tok)


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def _span_rec(name: str, span_id: str, parent_id: Optional[str],
              t0: float, dur_s: Optional[float], status: str,
              attrs: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "kind": "span", "trace_id": trace_id(), "span_id": span_id,
        "parent_id": parent_id, "name": name,
        "t0": round(t0, 6),
        "dur_s": None if dur_s is None else round(dur_s, 6),
        "status": status, "pid": os.getpid(),
        "attrs": attrs,
    }


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[Optional[str]]:
    """Record a span around a block; yields the span id (None when no
    run is bound).  Exceptions mark the span ``err`` and propagate."""
    if _RUN is None:
        yield None
        return
    sid = new_id()
    parent = _SPAN.get()
    tok = _SPAN.set(sid)
    t0 = time.time()
    m0 = time.monotonic()
    status = "ok"
    try:
        yield sid
    except BaseException:
        status = "err"
        raise
    finally:
        _SPAN.reset(tok)
        _RUN.write(_span_rec(name, sid, parent, t0,
                             time.monotonic() - m0, status, attrs))


def record(name: str, t0: float, dur_s: float, *,
           span_id: Optional[str] = None,
           parent_id: Optional[str] = None,
           status: str = "ok", **attrs: Any) -> Optional[str]:
    """Record a completed span with caller-supplied timings (for sites
    that already own the clock: the fit worker's chunk wall, the
    engine's request latency).  Returns the span id."""
    if _RUN is None:
        return None
    sid = span_id or new_id()
    if parent_id is None:
        parent_id = _SPAN.get()
    _RUN.write(_span_rec(name, sid, parent_id, t0, dur_s, status, attrs))
    return sid


def open_span(name: str, *, span_id: Optional[str] = None,
              parent_id: Optional[str] = None,
              make_current: bool = False, **attrs: Any) -> Optional[str]:
    """Write an ``open`` record NOW (crash-safe parent: a process killed
    mid-span leaves this behind, so children never orphan).  Close with
    ``close_span`` using the returned id."""
    if _RUN is None:
        return None
    sid = span_id or new_id()
    if parent_id is None:
        parent_id = _SPAN.get()
    _RUN.write(_span_rec(name, sid, parent_id, time.time(), None,
                         "open", attrs))
    if make_current:
        _SPAN.set(sid)
    return sid


def close_span(span_id: Optional[str], name: str, t0: float, *,
               status: str = "ok", **attrs: Any) -> None:
    """Completion record for an ``open_span`` (same span id; the ledger
    keeps the completed record)."""
    if _RUN is None or span_id is None:
        return
    _RUN.write(_span_rec(name, span_id, None, t0, time.time() - t0,
                         status, attrs))


def event(name: str, **attrs: Any) -> None:
    """Point annotation on the current span (fault firings, recovery
    marks).  Standalone when no span is active — still joined by trace."""
    if _RUN is None:
        return
    _RUN.write({
        "kind": "event", "trace_id": trace_id(), "span_id": _SPAN.get(),
        "name": name, "t": round(time.time(), 6), "pid": os.getpid(),
        "attrs": attrs,
    })


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------


def read_records(path: str) -> List[Dict[str, Any]]:
    """All records of one span log (torn last line tolerated — the
    append contract allows a writer killed mid-write to tear it)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out
