"""Unified observability: cross-process tracing, metrics, run ledger.

Three layers (docs/OBSERVABILITY.md):

* ``obs.context`` — trace/span context on a contextvar, propagated to
  child processes through the spawn environment and appended
  crash-safely to a per-run ``spans.jsonl``;
* ``obs.metrics`` — a process-local registry of counters/gauges/pow-2
  histograms with atomic snapshot export and a Prometheus text mode;
* ``obs.ledger`` — the ``RUNLEDGER_*.json`` joiner: spans, metric
  snapshots, perf telemetry, and stamped reports under one trace id,
  with MTTR, RED, and orphan checks derived from the trace.

``python -m tsspark_tpu.obs report`` renders the end-to-end timeline.
"""

from tsspark_tpu.obs.context import (  # noqa: F401
    active,
    adopt_env,
    close_span,
    current_ids,
    current_span_id,
    end_run,
    event,
    inject_env,
    new_id,
    open_span,
    record,
    remote_context,
    span,
    start_run,
    trace_id,
)
from tsspark_tpu.obs.ledger import (  # noqa: F401
    build_ledger,
    derive_mttr,
    write_ledger,
)
from tsspark_tpu.obs.metrics import (  # noqa: F401
    DEFAULT as METRICS,
    MetricsRegistry,
    prometheus_text,
)
