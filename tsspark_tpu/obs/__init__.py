"""Unified observability: tracing, metrics, ledger, history, SLOs.

Per-run layers (docs/OBSERVABILITY.md):

* ``obs.context`` — trace/span context on a contextvar, propagated to
  child processes through the spawn environment and appended
  crash-safely to a per-run ``spans.jsonl``;
* ``obs.metrics`` — a process-local registry of counters/gauges/pow-2
  histograms with atomic snapshot export and a Prometheus text mode;
* ``obs.ledger`` — the ``RUNLEDGER_*.json`` joiner: spans, metric
  snapshots, perf telemetry, and stamped reports under one trace id,
  with MTTR, RED, and orphan checks derived from the trace.

Cross-run layers (docs/OBSERVABILITY.md, "Trajectory & SLOs"):

* ``obs.history`` — the append-only ``RUNHISTORY.jsonl`` index: every
  BENCH/SERVE/CHAOS/EVAL/RUNLEDGER artifact normalized into one flat
  row schema, idempotent by trace id;
* ``obs.regress`` — the regression sentinel: rolling robust baselines
  (median/MAD over comparable rows) under ``pyproject
  [tool.tsspark.slo]`` budgets, ``REGRESSION_*.json`` verdicts, and
  nonzero exits wired into every artifact-producing entrypoint;
* ``obs.watch`` — live SLO watch over an in-flight run's scratch.

``python -m tsspark_tpu.obs report`` renders the end-to-end timeline;
``... history --backfill`` the cross-run trajectory; ``... watch`` the
live view.
"""

from tsspark_tpu.obs.context import (  # noqa: F401
    active,
    adopt_env,
    close_span,
    current_ids,
    current_span_id,
    end_run,
    event,
    inject_env,
    new_id,
    open_span,
    record,
    remote_context,
    span,
    start_run,
    trace_id,
)
from tsspark_tpu.obs.history import (  # noqa: F401
    HISTORY_FILE,
    git_rev,
    ingest,
    read_history,
)
from tsspark_tpu.obs.ledger import (  # noqa: F401
    build_ledger,
    derive_mttr,
    write_ledger,
)
from tsspark_tpu.obs.regress import (  # noqa: F401
    evaluate,
    load_slo,
    sentinel_report,
)
from tsspark_tpu.obs.metrics import (  # noqa: F401
    DEFAULT as METRICS,
    MetricsRegistry,
    prometheus_text,
)
