"""Optional Spark adapter: drive the TPU backend from a Spark DataFrame.

The reference runs per-series fits *inside* Spark executors (a
``mapPartitions`` UDF per partition, BASELINE.json:5).  On TPU the economics
invert: one chip fits tens of thousands of series per second, so shipping
model code to executors buys nothing — the adapter instead implements the
driver-side collapse the north star prescribes (collect -> shard -> fit ->
scatter):

  1. collect the long DataFrame to the driver (toPandas, Arrow-backed),
  2. run the batched fit/predict through the normal Forecaster,
  3. hand the forecast frame back as a Spark DataFrame (createDataFrame).

PySpark is NOT installed in this image; the adapter is import-gated and the
test suite exercises it with a duck-typed fake (tests/test_spark_cli.py).
Anything exposing ``toPandas()`` and a ``sparkSession.createDataFrame(pdf)``
works — real pyspark included.
"""

from __future__ import annotations

from typing import Any, Optional

import pandas as pd

from tsspark_tpu.frame import Forecaster


def _require_to_pandas(sdf: Any) -> pd.DataFrame:
    to_pandas = getattr(sdf, "toPandas", None)
    if to_pandas is None:
        raise TypeError(
            f"expected a Spark DataFrame (needs .toPandas()), got {type(sdf)!r}"
        )
    return to_pandas()


def _spark_session(sdf: Any):
    session = getattr(sdf, "sparkSession", None) or getattr(sdf, "sql_ctx", None)
    if session is None:
        raise TypeError(
            "cannot locate a SparkSession on the input DataFrame "
            "(.sparkSession / .sql_ctx)"
        )
    return session


class SparkForecaster:
    """Fit/predict over Spark DataFrames with a TPU-batched driver-side core.

    Example (on a real cluster)::

        sfc = SparkForecaster(Forecaster(cfg, backend="tpu"))
        sfc.fit(spark_df)                      # long: series_id, ds, y
        out = sfc.predict(horizon=28)          # Spark DataFrame back
    """

    def __init__(self, forecaster: Forecaster):
        self.forecaster = forecaster
        self._session = None

    def fit(self, sdf: Any) -> "SparkForecaster":
        pdf = _require_to_pandas(sdf)
        self._session = _spark_session(sdf)
        self.forecaster.fit(pdf)
        return self

    def predict(
        self,
        horizon: Optional[int] = None,
        future_sdf: Optional[Any] = None,
        include_history: bool = False,
    ) -> Any:
        if self._session is None:
            raise RuntimeError("predict before fit")
        future_pdf = (
            _require_to_pandas(future_sdf) if future_sdf is not None else None
        )
        out = self.forecaster.predict(
            horizon=horizon, future_df=future_pdf,
            include_history=include_history,
        )
        return self._session.createDataFrame(out)


def forecast_spark(
    sdf: Any,
    forecaster: Forecaster,
    horizon: Optional[int] = None,
    include_history: bool = False,
) -> Any:
    """One-shot convenience: fit on ``sdf`` and return the forecast frame."""
    return (
        SparkForecaster(forecaster)
        .fit(sdf)
        .predict(horizon, include_history=include_history)
    )
