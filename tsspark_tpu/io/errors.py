"""Typed storage errors: a failing disk must never look like a missing file.

``data/plane.py`` historically swallowed ``OSError`` wholesale on its
read/cleanup paths, which is correct for the ENOENT family (an absent
artifact IS the protocol's "not landed yet" signal) but catastrophic for
ENOSPC/EIO/EROFS — a dying disk silently degrades into "dataset looks
empty, regenerate it".  Every durable-I/O site classifies through here:
the ENOENT family stays a soft "missing", real media failures surface as
typed subclasses callers can count, alert on, and feed the degradation
ladder.

All storage errors subclass ``OSError`` with the original errno
preserved, so pre-existing ``except OSError`` handlers keep working —
the classification ADDS information, it never changes reachability.
"""

from __future__ import annotations

import errno
import os

#: errnos that mean "the artifact is not there" — the protocol-normal
#: case every reader already treats as absence, never a disk failure.
_MISSING_ERRNOS = frozenset({
    errno.ENOENT, errno.ENOTDIR, errno.ESTALE,
})


class StorageError(OSError):
    """A durable-I/O operation failed for a reason that is NOT absence:
    the media, filesystem, or quota misbehaved."""


class DiskFullError(StorageError):
    """ENOSPC / EDQUOT: no space (or quota) left on the device."""


class DiskIOError(StorageError):
    """EIO: the device reported a hard I/O error."""


class ReadOnlyError(StorageError):
    """EROFS: the filesystem went read-only under us (the kernel's
    last-resort response to a failing device)."""


class ShortWriteError(StorageError):
    """A write persisted fewer bytes than were handed to it and the
    site detected the tear before publishing."""


class BackpressureError(RuntimeError):
    """The degradation ladder refused new ingest work: disk headroom is
    below the pause threshold.  Deliberately NOT an ``OSError`` — this
    is flow control, not a failure, and must never be swallowed by a
    ``missing-file`` handler."""

    def __init__(self, state: str, headroom: float):
        super().__init__(
            f"delta ingestion paused by degradation ladder "
            f"(state={state}, headroom={headroom:.3f})"
        )
        self.state = state
        self.headroom = headroom


_ERRNO_CLASS = {
    errno.ENOSPC: DiskFullError,
    errno.EDQUOT: DiskFullError,
    errno.EIO: DiskIOError,
    errno.EROFS: ReadOnlyError,
}


def is_missing(e: BaseException) -> bool:
    """True when ``e`` means "the file is not there" (protocol-normal
    absence), False for everything else — in particular every real disk
    failure."""
    return (isinstance(e, OSError)
            and e.errno in _MISSING_ERRNOS)


def classify_os_error(e: OSError) -> OSError:
    """Map an ``OSError`` to its typed storage subclass (ENOSPC →
    ``DiskFullError``, EIO → ``DiskIOError``, EROFS →
    ``ReadOnlyError``); anything else — including the ENOENT family —
    comes back unchanged.  The returned error carries the original
    errno and message, so ``except OSError`` and errno dispatch both
    keep working."""
    if isinstance(e, StorageError):
        return e
    cls = _ERRNO_CLASS.get(e.errno)
    if cls is None:
        return e
    err = cls(e.errno, os.strerror(e.errno) if e.errno else str(e))
    err.filename = getattr(e, "filename", None)
    err.__cause__ = e
    try:
        # Real disk failures are COUNTED, not just raised — the alert
        # surface a swallowed OSError never had.
        from tsspark_tpu.obs.metrics import DEFAULT as METRICS

        METRICS.counter("tsspark_io_disk_errors_total").inc()
        METRICS.counter(
            f"tsspark_io_disk_error_{cls.__name__}_total").inc()
    except Exception:
        pass
    return err


def reraise_classified(e: OSError):
    """Raise ``e`` as its typed storage subclass (or as itself when it
    needs no mapping) — the one-liner every narrowed ``except OSError``
    site ends with after handling the missing case."""
    ce = classify_os_error(e)
    if ce is e:
        raise e
    raise ce from e
