"""``DiskBudget``: byte accounting per storage root, consulted before
every version-producing write.

The always-on refit loop publishes versions forever; on a real
deployment the first fault it meets is a full disk.  A budget bounds
one storage root (a registry, a scratch dir) to a byte ceiling and
reports *headroom* — the fraction of room left, taking the tighter of
the configured budget and the filesystem's real free space — which the
degradation ladder (``tsspark_tpu.io.ladder``) turns into shed/reap/
pause/stale decisions.

Arming is environment-driven so child processes (refit publishers,
replicas) inherit the same budget the parent armed, exactly like
``TSSPARK_FAULTS``:

  TSSPARK_DISK_BUDGET_BYTES  byte ceiling for the budgeted root
  TSSPARK_DISK_BUDGET_ROOT   the root it governs (required with BYTES)

Unarmed, ``active()`` is a single environ lookup returning None and the
durable-I/O layer skips the gate entirely.
"""

from __future__ import annotations

import errno as _errno
import os
from typing import Dict, Optional

from tsspark_tpu.io.errors import DiskFullError

ENV_BUDGET_BYTES = "TSSPARK_DISK_BUDGET_BYTES"
ENV_BUDGET_ROOT = "TSSPARK_DISK_BUDGET_ROOT"


class DiskBudget:
    """Byte budget for one storage root.

    ``headroom()`` is the governing gauge: fraction of room left in
    [0, 1], the min of budget headroom (1 - used/budget) and the
    filesystem's real free fraction.  ``check(nbytes)`` raises
    ``DiskFullError`` when a prospective write of ``nbytes`` would
    overrun — same errno a real ENOSPC carries, so callers classify
    both identically."""

    def __init__(self, root: str, budget_bytes: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self._m_headroom = None
        self._m_used = None

    # -- accounting --------------------------------------------------------

    def used_bytes(self) -> int:
        """Bytes currently under the root (hardlinked copy-forward
        columns count once per inode would be ideal; the walk counts
        per-path, which over-counts links — the CONSERVATIVE direction
        for a budget)."""
        total = 0
        for d, _sub, names in os.walk(self.root):
            for name in names:
                try:
                    total += os.lstat(os.path.join(d, name)).st_size
                except OSError:
                    continue  # racing unlink (a reaper, a temp sweep)
        return total

    def fs_headroom(self) -> float:
        """The filesystem's own free fraction under the root."""
        try:
            st = os.statvfs(self.root)
        except OSError:
            return 1.0  # root not there yet: nothing written, all room
        if st.f_blocks <= 0:
            return 1.0
        return max(0.0, min(1.0, st.f_bavail / st.f_blocks))

    def headroom(self) -> float:
        """Fraction of room left in [0, 1] — min of budget and real
        filesystem headroom.  Also publishes the ``io.*`` gauges."""
        fs = self.fs_headroom()
        if self.budget_bytes and self.budget_bytes > 0:
            used = self.used_bytes()
            frac = max(0.0, min(1.0, 1.0 - used / self.budget_bytes))
        else:
            used = None
            frac = 1.0
        h = min(fs, frac)
        self._publish_gauges(h, used)
        return h

    def check(self, nbytes: int = 0, what: str = "") -> None:
        """Gate a prospective write of ``nbytes`` under this root;
        raises ``DiskFullError`` (errno ENOSPC) on overrun."""
        if not self.budget_bytes or self.budget_bytes <= 0:
            return
        used = self.used_bytes()
        if used + max(0, int(nbytes)) > self.budget_bytes:
            self._publish_gauges(
                max(0.0, 1.0 - used / self.budget_bytes), used)
            raise DiskFullError(
                _errno.ENOSPC,
                f"disk budget exhausted for {self.root} "
                f"({used}+{nbytes} > {self.budget_bytes} bytes"
                + (f"; {what}" if what else "") + ")",
            )

    def governs(self, path: str) -> bool:
        """True when ``path`` lives under the budgeted root."""
        p = os.path.abspath(path)
        return p == self.root or p.startswith(self.root + os.sep)

    # -- obs ----------------------------------------------------------------

    def _publish_gauges(self, headroom: float,
                        used: Optional[int]) -> None:
        try:
            from tsspark_tpu.obs.metrics import DEFAULT as METRICS

            if self._m_headroom is None:
                self._m_headroom = METRICS.gauge(
                    "tsspark_io_budget_headroom")
                self._m_used = METRICS.gauge(
                    "tsspark_io_budget_used_bytes")
            self._m_headroom.set(float(headroom))
            if used is not None:
                self._m_used.set(float(used))
        except Exception:
            pass  # obs must never break an I/O path


_active_cache: Dict[str, Optional[DiskBudget]] = {}


def active() -> Optional[DiskBudget]:
    """The environment-armed budget for this process tree, or None.
    Cached per (root, bytes) env pair — the unarmed path is one
    environ lookup."""
    spec = os.environ.get(ENV_BUDGET_BYTES)
    if not spec:
        return None
    root = os.environ.get(ENV_BUDGET_ROOT)
    if not root:
        return None
    key = f"{root}\x00{spec}"
    if key not in _active_cache:
        try:
            _active_cache[key] = DiskBudget(root, int(spec))
        except (ValueError, TypeError):
            _active_cache[key] = None  # malformed: fail open
    return _active_cache[key]


def arm(root: str, budget_bytes: int,
        env: Optional[Dict[str, str]] = None) -> DiskBudget:
    """Arm a budget for this process tree (``os.environ`` default) —
    the test/chaos entry point, mirroring ``FaultPlan.install``."""
    target = os.environ if env is None else env
    target[ENV_BUDGET_BYTES] = str(int(budget_bytes))
    target[ENV_BUDGET_ROOT] = os.path.abspath(root)
    return DiskBudget(root, budget_bytes)
