"""THE durable-I/O choke point: every plane, registry, chunk, plan, and
patch writer routes here.

This wraps ``tsspark_tpu.utils.atomic``'s write-temp-then-rename idiom
with the three things a raw helper cannot give:

  * **Named fault injection** — ``io_write`` / ``io_fsync`` /
    ``io_rename`` / ``io_link`` / ``io_mmap`` points from
    ``resilience/faults.py``, so ENOSPC, EIO, short writes, lost
    fsyncs, and slow media are injectable ONCE, for every artifact
    family, past and future.  Storage rules may be path-scoped
    (``path="manifest.json"``) to aim at one family.
  * **Typed error classification** — real disk failures surface as
    ``tsspark_tpu.io.errors`` subclasses (still ``OSError``s), never
    masquerading as missing files.
  * **Accounting** — ``io.*`` latency/byte metrics, and the
    environment-armed ``DiskBudget`` consulted before every
    version-producing write under its root.

The effect gate (``analysis/effects.py``) treats this module (with
``utils/atomic.py``) as the durable choke point: raw filesystem writes
HERE classify as ``durable-write``, anywhere else as ``raw-fs-write``
— so a path budget forbidding durable writes catches bypasses and
sanctioned writes alike, attributed correctly.

The wrappers keep the exact NAMES of the ``utils.atomic`` helpers
(``atomic_write``, ``atomic_write_text``, ``append_line``,
``sweep_stale_temps``) so the ``fileproto`` static checker's
atomic-helper recognition holds at every call site unchanged.

Unlike the raw helper, ``atomic_write`` here fsyncs the temp before the
rename — the publish is a real durability barrier, and the ``io_fsync``
point sits exactly where a lost fsync would bite.
"""

from __future__ import annotations

import errno as _errno
import os
import shutil
import time
from typing import Callable, Optional

import numpy as np

from tsspark_tpu.io import budget as _budget
from tsspark_tpu.io.errors import classify_os_error
from tsspark_tpu.resilience import faults
from tsspark_tpu.utils.atomic import (  # noqa: F401  (re-exported)
    STALE_TEMP_S,
    _tmp_path,
    sweep_stale_temps,
)
from tsspark_tpu.utils.atomic import append_line as _raw_append_line

#: Named injection points (see resilience/faults.py docstring).
IO_WRITE = "io_write"
IO_FSYNC = "io_fsync"
IO_RENAME = "io_rename"
IO_LINK = "io_link"
IO_MMAP = "io_mmap"

#: errnos where a hardlink legitimately degrades to a copy (filesystem
#: capability, not media failure) — anything else must propagate, or an
#: injected EIO would be silently healed by the fallback.
_LINK_FALLBACK_ERRNOS = frozenset(
    getattr(_errno, name)
    for name in ("EXDEV", "EPERM", "EMLINK", "EOPNOTSUPP", "ENOTSUP")
    if hasattr(_errno, name)
)

_m = {"init": False}


def _metrics():
    """Lazy ``io.*`` instrument cache (obs must never break I/O)."""
    if not _m["init"]:
        try:
            from tsspark_tpu.obs.metrics import DEFAULT as METRICS

            _m["writes"] = METRICS.counter("tsspark_io_writes_total")
            _m["bytes"] = METRICS.counter("tsspark_io_write_bytes_total")
            _m["write_s"] = METRICS.histogram("tsspark_io_write_seconds")
            _m["fsync_s"] = METRICS.histogram("tsspark_io_fsync_seconds")
        except Exception:
            _m["writes"] = _m["bytes"] = None
            _m["write_s"] = _m["fsync_s"] = None
        _m["init"] = True
    return _m


def _reraise_classified(e: OSError) -> None:
    """Re-raise ``e`` as its typed storage subclass (or as-is when it
    needs no mapping).  Call only from an ``except OSError`` block."""
    ce = classify_os_error(e)
    if ce is e:
        raise
    raise ce from e


def _gate_budget(path: str) -> None:
    """Consult the environment-armed ``DiskBudget`` before a
    version-producing write under its root."""
    b = _budget.active()
    if b is not None and b.governs(path):
        b.check(0, what=os.path.basename(path))


def atomic_write(path: str, write_fn: Callable, mode: str = "wb", *,
                 lo: Optional[int] = None,
                 hi: Optional[int] = None) -> None:
    """Durable atomic publish of ``path``: budget gate, temp write,
    fsync barrier, rename — each step a named fault point.  Same
    contract as ``utils.atomic.atomic_write`` plus durability and
    classified errors; ``lo``/``hi`` scope series-targeted fault rules
    exactly as at the fit points."""
    t0 = time.perf_counter()
    tmp = _tmp_path(path)
    nbytes = 0
    try:
        try:
            _gate_budget(path)
            faults.inject(IO_WRITE, lo=lo, hi=hi, path=path)
            with open(tmp, mode) as fh:
                write_fn(fh)
                fh.flush()
                frac = faults.short_write(IO_WRITE, path, lo=lo, hi=hi)
                if frac is not None:
                    # The torn artifact still publishes: an unchecked
                    # write(2) return looks exactly like success, and
                    # only the CRC-sentinel read path may catch it.
                    fh.truncate(max(0, int(fh.tell() * frac)))
                t1 = time.perf_counter()
                faults.inject(IO_FSYNC, lo=lo, hi=hi, path=path)
                os.fsync(fh.fileno())
                m = _metrics()
                if m["fsync_s"] is not None:
                    m["fsync_s"].observe(time.perf_counter() - t1)
            nbytes = os.path.getsize(tmp)
            faults.inject(IO_RENAME, lo=lo, hi=hi, path=path)
            faults.lost_fsync(IO_FSYNC, path, lo=lo, hi=hi)
            os.replace(tmp, path)
        except OSError as e:
            _reraise_classified(e)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    m = _metrics()
    if m["writes"] is not None:
        m["writes"].inc()
        m["bytes"].inc(nbytes)
        m["write_s"].observe(time.perf_counter() - t0)


def atomic_write_text(path: str, text: str) -> None:
    """Durable atomic text-file write (sentinels, fingerprints,
    manifests)."""
    atomic_write(path, lambda fh: fh.write(text), mode="w")


def append_line(path: str, line: str) -> None:
    """Crash-safe single-``os.write`` append (same contract as
    ``utils.atomic.append_line``) behind the ``io_write`` fault point
    and classified errors."""
    try:
        faults.inject(IO_WRITE, path=path)
        _raw_append_line(path, line)
    except OSError as e:
        _reraise_classified(e)


def hardlink(src: str, dst: str) -> None:
    """``os.link`` behind the ``io_link`` fault point; classified
    errors."""
    try:
        faults.inject(IO_LINK, path=dst)
        os.link(src, dst)
    except OSError as e:
        _reraise_classified(e)


def link_or_copy(src: str, dst: str) -> None:
    """Hardlink ``src`` → ``dst``, degrading to a byte copy ONLY for
    capability errnos (cross-device, no-hardlink filesystems).  Real
    media failures — including injected ones — propagate; a copy
    fallback that swallowed EIO would un-test the fault."""
    try:
        hardlink(src, dst)
    except OSError as e:
        if getattr(e, "errno", None) not in _LINK_FALLBACK_ERRNOS:
            raise
        try:
            shutil.copy2(src, dst)
        except OSError as e2:
            _reraise_classified(e2)


def open_memmap(path: str, *, mode: str = "r", dtype=None, shape=None,
                lo: Optional[int] = None,
                hi: Optional[int] = None):
    """``np.lib.format.open_memmap`` behind the ``io_mmap`` fault point
    (attach AND create flavors); classified errors."""
    try:
        faults.inject(IO_MMAP, lo=lo, hi=hi, path=path)
        if mode in ("w+",):
            _gate_budget(path)
        if dtype is None and shape is None:
            return np.lib.format.open_memmap(path, mode=mode)
        return np.lib.format.open_memmap(
            path, mode=mode, dtype=dtype, shape=shape)
    except OSError as e:
        _reraise_classified(e)


def attach_array(path: str, *, mmap_mode: str = "r"):
    """``np.load(..., mmap_mode=...)`` behind the ``io_mmap`` fault
    point — the read-side attach every plane viewer uses."""
    try:
        faults.inject(IO_MMAP, path=path)
        return np.load(path, mmap_mode=mmap_mode)
    except OSError as e:
        _reraise_classified(e)


def fsync_dir(dirpath: str) -> None:
    """Directory-entry durability barrier (publish-rename visibility on
    a crash); best-effort on filesystems that refuse O_RDONLY dir
    fsync."""
    faults.inject(IO_FSYNC, path=dirpath)
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
