"""Disk-pressure degradation ladder: shed → reap → pause → stale-serve.

A service meeting disk pressure must degrade in the order that
sacrifices the least first.  Driven by ``DiskBudget.headroom()``:

  state          enter below   gives up
  -----          -----------   --------
  normal         —             nothing
  shed_spec      0.40          speculative warm refit prep (sched skips
                               ``_refresh_speculation``; cheapest, pure
                               cache loss)
  reap           0.25          retained history beyond the safety floor
                               (``refit.reap_cycles`` runs eagerly; never
                               the active version or a pinned plan's base
                               — see tests/test_retention.py)
  pause_ingest   0.10          freshness: ``land_delta`` raises
                               ``BackpressureError``, upstream sources
                               hold their deltas
  stale_serve    0.05          recency honesty: the pool keeps serving
                               the last good version but flags responses
                               and ``stats()`` as stale

Transitions are recomputed from headroom on every ``state()`` call with
upward hysteresis (climbing back toward normal requires clearing the
entry threshold by ``hysteresis``), so a root oscillating around one
threshold does not flap the ladder.

Module-level helpers (``current_state``, ``gate_ingest``,
``stale_serving``) resolve the environment-armed budget so call sites in
``data/plane.py`` / ``sched.py`` / ``serve/pool.py`` stay one-liners and
cost one environ lookup when no budget is armed.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from tsspark_tpu.io import budget as _budget
from tsspark_tpu.io.errors import BackpressureError

#: Ladder states, mildest first; index = severity rank.
LADDER_STATES = ("normal", "shed_spec", "reap", "pause_ingest",
                 "stale_serve")

#: Default entry thresholds (headroom fraction BELOW which the state is
#: entered), aligned with LADDER_STATES[1:].
DEFAULT_THRESHOLDS = (0.40, 0.25, 0.10, 0.05)


class DegradationLadder:
    """Headroom → ladder state, with upward hysteresis."""

    def __init__(self, budget: _budget.DiskBudget, *,
                 thresholds=DEFAULT_THRESHOLDS,
                 hysteresis: float = 0.02):
        if len(thresholds) != len(LADDER_STATES) - 1:
            raise ValueError("one threshold per non-normal state")
        if list(thresholds) != sorted(thresholds, reverse=True):
            raise ValueError("thresholds must descend with severity")
        self.budget = budget
        self.thresholds = tuple(float(t) for t in thresholds)
        self.hysteresis = float(hysteresis)
        self._rank = 0
        self._lock = threading.Lock()
        self._m_state = None

    def _rank_for(self, headroom: float) -> int:
        rank = 0
        for i, t in enumerate(self.thresholds):
            if headroom < t:
                rank = i + 1
        return rank

    def state(self) -> str:
        """Recompute and return the current state.  Worsening applies
        immediately; improving requires clearing the previous state's
        entry threshold by the hysteresis margin."""
        h = self.budget.headroom()
        raw = self._rank_for(h)
        with self._lock:
            if raw >= self._rank:
                self._rank = raw
            else:
                # Improving: only step down when headroom clears the
                # CURRENT state's entry threshold with margin.
                enter = self.thresholds[self._rank - 1]
                if h >= enter + self.hysteresis:
                    self._rank = raw
            rank = self._rank
        self._publish_gauge(rank)
        return LADDER_STATES[rank]

    def rank(self) -> int:
        """Severity index of ``state()`` (0 = normal)."""
        return LADDER_STATES.index(self.state())

    def allows(self, action: str) -> bool:
        """Flow-control queries the wired subsystems ask:
        ``speculate`` (sched warm prep), ``ingest`` (delta landing)."""
        r = self.rank()
        if action == "speculate":
            return r < LADDER_STATES.index("shed_spec")
        if action == "ingest":
            return r < LADDER_STATES.index("pause_ingest")
        raise ValueError(f"unknown ladder action {action!r}")

    def should_reap(self) -> bool:
        return self.rank() >= LADDER_STATES.index("reap")

    def stale_serve(self) -> bool:
        return self.rank() >= LADDER_STATES.index("stale_serve")

    def _publish_gauge(self, rank: int) -> None:
        try:
            from tsspark_tpu.obs.metrics import DEFAULT as METRICS

            if self._m_state is None:
                self._m_state = METRICS.gauge("tsspark_io_ladder_state")
            self._m_state.set(float(rank))
        except Exception:
            pass


_ladders: Dict[str, DegradationLadder] = {}
_ladders_lock = threading.Lock()


def active_ladder(root: Optional[str] = None
                  ) -> Optional[DegradationLadder]:
    """The ladder over the environment-armed budget, or None when no
    budget is armed (the common, zero-cost case).  ``root``: when
    given, only return the ladder if the budget governs that path —
    pressure on the registry root must not pause an unrelated data
    root."""
    b = _budget.active()
    if b is None:
        return None
    if root is not None and not b.governs(root):
        return None
    key = f"{b.root}\x00{b.budget_bytes}"
    with _ladders_lock:
        lad = _ladders.get(key)
        if lad is None:
            lad = DegradationLadder(b)
            _ladders[key] = lad
    return lad


def current_state(root: Optional[str] = None) -> str:
    """Ladder state for ``root`` ("normal" when nothing is armed)."""
    lad = active_ladder(root)
    return "normal" if lad is None else lad.state()


def gate_ingest(root: str) -> None:
    """Backpressure gate for delta landing: raises
    ``BackpressureError`` at ``pause_ingest`` or worse."""
    lad = active_ladder(root)
    if lad is None:
        return
    if not lad.allows("ingest"):
        raise BackpressureError(lad.state(), lad.budget.headroom())


def stale_serving(root: Optional[str] = None) -> bool:
    """True when responses from ``root``'s registry should carry the
    staleness flag."""
    lad = active_ladder(root)
    return lad is not None and lad.stale_serve()
