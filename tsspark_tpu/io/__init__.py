"""``tsspark_tpu.io`` — the storage fault domain's front door.

One durable-I/O layer (``durable``) every plane, registry, chunk, plan,
and patch writer routes through; typed storage errors (``errors``) so a
failing disk never reads as a missing file; a per-root byte budget
(``budget``) consulted before version-producing writes; and the
disk-pressure degradation ladder (``ladder``) the scheduler, ingest
path, and serving pool consult.  See docs/RESILIENCE.md § Storage fault
domain.
"""

from tsspark_tpu.io.budget import DiskBudget
from tsspark_tpu.io.durable import (
    IO_FSYNC,
    IO_LINK,
    IO_MMAP,
    IO_RENAME,
    IO_WRITE,
    append_line,
    atomic_write,
    atomic_write_text,
    attach_array,
    fsync_dir,
    hardlink,
    link_or_copy,
    open_memmap,
    sweep_stale_temps,
)
from tsspark_tpu.io.errors import (
    BackpressureError,
    DiskFullError,
    DiskIOError,
    ReadOnlyError,
    ShortWriteError,
    StorageError,
    classify_os_error,
    is_missing,
    reraise_classified,
)
from tsspark_tpu.io.ladder import (
    LADDER_STATES,
    DegradationLadder,
    active_ladder,
    current_state,
    gate_ingest,
    stale_serving,
)

__all__ = [
    "IO_FSYNC", "IO_LINK", "IO_MMAP", "IO_RENAME", "IO_WRITE",
    "append_line", "atomic_write", "atomic_write_text", "attach_array",
    "fsync_dir", "hardlink", "link_or_copy", "open_memmap",
    "sweep_stale_temps",
    "BackpressureError", "DiskFullError", "DiskIOError",
    "ReadOnlyError", "ShortWriteError", "StorageError",
    "classify_os_error", "is_missing", "reraise_classified",
    "DiskBudget",
    "LADDER_STATES", "DegradationLadder", "active_ladder",
    "current_state", "gate_ingest", "stale_serving",
]
