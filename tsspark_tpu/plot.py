"""Plotting: forecast and component figures (matplotlib, import-gated).

Mirrors the Prophet-family plotting surface the reference's users expect:
``plot_forecast`` (history + yhat + interval band per series) and
``plot_components`` (trend with interval, one panel per seasonality /
regressor block).  Works off the long forecast frame a
:class:`~tsspark_tpu.frame.Forecaster` produces, or raw arrays via the
``*_arrays`` variants — no refit needed to plot.

matplotlib is present in this image but kept a soft dependency: importing
this module without it raises only when a plot function is called.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import pandas as pd


def _mpl():
    try:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        return plt
    except ImportError as e:  # pragma: no cover - matplotlib is in the image
        raise ImportError(
            "plotting needs matplotlib; it is not installed"
        ) from e


def plot_forecast(
    forecast_df: pd.DataFrame,
    history_df: Optional[pd.DataFrame] = None,
    series_id: Optional[str] = None,
    id_col: str = "series_id",
    ds_col: str = "ds",
    y_col: str = "y",
    ax=None,
    figsize=(10, 4),
):
    """History dots + forecast line + uncertainty band for one series.

    Args:
      forecast_df: long frame with ds/yhat (+yhat_lower/yhat_upper).
      history_df: optional long frame with the training observations.
      series_id: which series to plot (default: the first in forecast_df).
    """
    plt = _mpl()
    sid = series_id if series_id is not None else forecast_df[id_col].iloc[0]
    fc = forecast_df[forecast_df[id_col] == sid]
    if fc.empty:
        raise ValueError(f"series {sid!r} not present in forecast frame")
    if ax is None:
        _, ax = plt.subplots(figsize=figsize)

    if history_df is not None:
        h = history_df[history_df[id_col] == sid]
        ax.plot(h[ds_col], h[y_col], "k.", markersize=3, alpha=0.6,
                label="observed")
    ax.plot(fc[ds_col], fc["yhat"], color="#0072B2", label="forecast")
    if {"yhat_lower", "yhat_upper"} <= set(fc.columns):
        ax.fill_between(
            fc[ds_col], fc["yhat_lower"], fc["yhat_upper"],
            color="#0072B2", alpha=0.2, linewidth=0, label="interval",
        )
    ax.set_title(str(sid))
    ax.set_xlabel(ds_col)
    ax.set_ylabel(y_col)
    ax.legend(loc="best", fontsize=8)
    ax.figure.autofmt_xdate()
    return ax


def add_changepoints_to_plot(
    ax,
    forecaster,
    series_id: Optional[str] = None,
    threshold: float = 0.01,
    color: str = "r",
):
    """Overlay significant changepoints on a forecast axis (Prophet's
    ``add_changepoints_to_plot``).

    Draws a dashed vertical line at every fit-time changepoint whose rate
    adjustment |delta| exceeds ``threshold`` for the given series.

    Args:
      ax: the axis returned by :func:`plot_forecast`.
      forecaster: a fitted :class:`~tsspark_tpu.frame.Forecaster`.
      series_id: which series (default: the first fitted one).
    """
    cps = forecaster.changepoints_df(series_id)
    for _, row in cps[cps["abs_delta"] > threshold].iterrows():
        ax.axvline(row["ds"], ls="--", lw=1, color=color, alpha=0.6)
    return ax


def plot_components(
    components: Dict[str, np.ndarray],
    ds,
    series_index: int = 0,
    names: Optional[Sequence[str]] = None,
    figsize=(10, 2.2),
):
    """One panel per component block for one series.

    Args:
      components: name -> (B, T) arrays, e.g. from ``Forecaster.components``
        or ``ProphetModel.components`` (plus "trend"/interval keys from a
        forecast dict — anything (B, T) works).
      ds: (T,) x-axis values (days or datetimes).
      series_index: row of the batch to plot.
      names: subset/order of component names (default: all, trend first).
    """
    plt = _mpl()
    keys = list(components)
    if names is None:
        names = sorted(
            (k for k in keys if not k.endswith(("_lower", "_upper"))),
            key=lambda k: (k != "trend", k),
        )
    fig, axes = plt.subplots(
        len(names), 1, figsize=(figsize[0], figsize[1] * len(names)),
        sharex=True, squeeze=False,
    )
    for ax, name in zip(axes[:, 0], names):
        arr = np.asarray(components[name])
        ax.plot(ds, arr[series_index], color="#0072B2")
        lo, hi = f"{name}_lower", f"{name}_upper"
        if lo in components and hi in components:
            ax.fill_between(
                ds, np.asarray(components[lo])[series_index],
                np.asarray(components[hi])[series_index],
                color="#0072B2", alpha=0.2, linewidth=0,
            )
        ax.set_ylabel(name, fontsize=9)
    axes[-1, 0].set_xlabel("ds")
    fig.autofmt_xdate()
    fig.tight_layout()
    return fig


def plot_cross_validation_metric(
    cv_df: pd.DataFrame,
    metric: str = "smape",
    rolling_window: float = 0.1,
    ds_col: str = "ds",
    y_col: str = "y",
    ax=None,
    figsize=(10, 4),
):
    """Per-point metric scatter + rolling-mean curve over forecast horizon.

    Mirrors ``prophet.plot.plot_cross_validation_metric``: dots are the raw
    per-(series, cutoff, ds) errors from a :func:`cross_validation` frame,
    the line is the horizon-rolling aggregate from
    :func:`performance_metrics`.  Both are computed from the same
    ``point_metrics`` definitions, so they cannot drift apart; the dots for
    ``rmse``/``mdape`` show their per-point bases (|err| / APE).
    """
    from tsspark_tpu.eval.diagnostics import (
        _ALL_METRICS, performance_metrics, point_metrics,
    )

    if metric not in _ALL_METRICS:
        raise ValueError(
            f"unknown metric {metric!r}; choose from {_ALL_METRICS}"
        )
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=figsize)
    d = cv_df.copy()
    d["horizon"] = d[ds_col] - d["cutoff"]
    point = point_metrics(d, (metric,), y_col=y_col)
    base = {"rmse": "mae", "mdape": "mape"}.get(metric, metric)
    ax.plot(d["horizon"], point[base], ".", alpha=0.3, markersize=3,
            color="gray")
    pm = performance_metrics(
        cv_df, rolling_window=rolling_window, metrics=(metric,),
        ds_col=ds_col, y_col=y_col,
    )
    ax.plot(pm["horizon"], pm[metric], color="#0072B2", linewidth=2)
    ax.set_xlabel("horizon")
    ax.set_ylabel(metric)
    return ax
