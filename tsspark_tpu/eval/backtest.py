"""Backtesting: simulated historical forecasts (Prophet-style CV), batched.

Prophet's cross_validation fits one model per (series, cutoff) pair in a
Python loop.  The TPU-native formulation treats every (series, cutoff) pair
as one row of a single padded batch — the history mask hides observations
after the cutoff — so the entire backtest is ONE batched MAP solve plus one
batched predict, regardless of how many cutoffs are requested.

Returns long-format forecasts per cutoff plus an aggregated metric table,
mirroring prophet.diagnostics.{cross_validation,performance_metrics}.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import ProphetConfig, SolverConfig
from tsspark_tpu.eval import metrics as metrics_mod


def make_cutoffs(
    ds: np.ndarray, horizon: float, period: float, initial: float
) -> np.ndarray:
    """Cutoff times: every ``period`` days, newest first window ending so the
    last horizon fits, oldest cutoff at least ``initial`` days of history."""
    ds = np.asarray(ds, float)
    last = ds.max()
    first = ds.min()
    cutoffs = []
    c = last - horizon
    while c >= first + initial:
        cutoffs.append(c)
        c -= period
    if not cutoffs:
        raise ValueError(
            f"no valid cutoffs: span {last - first} days, initial {initial}, "
            f"horizon {horizon}"
        )
    return np.asarray(sorted(cutoffs))


def cross_validation(
    ds: np.ndarray,
    y: np.ndarray,
    config: ProphetConfig,
    horizon: float,
    period: Optional[float] = None,
    initial: Optional[float] = None,
    solver_config: SolverConfig = SolverConfig(),
    backend: str = "tpu",
    mask: Optional[np.ndarray] = None,
    regressors: Optional[np.ndarray] = None,
    cap: Optional[np.ndarray] = None,
    **backend_kwargs,
) -> Dict[str, np.ndarray]:
    """Simulated historical forecasts for a (B, T) batch.

    Args:
      ds: (T,) shared calendar grid (days).
      y:  (B, T) observations.
      horizon: forecast horizon in days.
      period: spacing between cutoffs (default horizon / 2).
      initial: minimum training history (default 3 * horizon).

    Returns dict with:
      "cutoffs" (C,), "ds" (H_t,) evaluation grid per cutoff is implicit:
      "y_true", "yhat" (B, C, H_t) with NaN outside each horizon window,
      plus per-(series,cutoff) metric arrays "smape", "mae", "rmse",
      "coverage" of shape (B, C).
    """
    ds = np.asarray(ds, float)
    y = np.asarray(y, float)
    b, t_len = y.shape
    period = horizon / 2.0 if period is None else period
    initial = 3.0 * horizon if initial is None else initial
    cutoffs = make_cutoffs(ds, horizon, period, initial)
    c = len(cutoffs)

    base_mask = np.isfinite(y).astype(np.float32)
    if mask is not None:
        base_mask *= np.asarray(mask, np.float32)

    # Expand to (B*C) rows: row (i, j) = series i with history <= cutoff j.
    hist_mask = (ds[None, :] <= cutoffs[:, None]).astype(np.float32)  # (C, T)
    big_mask = (base_mask[:, None, :] * hist_mask[None, :, :]).reshape(
        b * c, t_len
    )
    big_y = np.repeat(y, c, axis=0)
    rep = lambda a: None if a is None else np.repeat(np.asarray(a), c, axis=0)

    bk = get_backend(backend, config, solver_config, **backend_kwargs)
    state = bk.fit(
        jnp.asarray(ds),
        jnp.asarray(np.nan_to_num(big_y)),
        mask=jnp.asarray(big_mask),
        regressors=None if regressors is None else jnp.asarray(rep(regressors)),
        cap=None if cap is None else jnp.asarray(rep(cap)),
    )

    # Evaluate every row on the full grid once; slice horizon windows after.
    fc = bk.predict(
        state,
        jnp.asarray(ds),
        regressors=None if regressors is None else jnp.asarray(rep(regressors)),
        cap=None if cap is None else jnp.asarray(rep(cap)),
        seed=0,
    )
    yhat = np.asarray(fc["yhat"]).reshape(b, c, t_len)
    lower = np.asarray(fc.get("yhat_lower", fc["yhat"])).reshape(b, c, t_len)
    upper = np.asarray(fc.get("yhat_upper", fc["yhat"])).reshape(b, c, t_len)

    # Horizon windows: cutoff < ds <= cutoff + horizon, observed only.
    win = (
        (ds[None, :] > cutoffs[:, None])
        & (ds[None, :] <= cutoffs[:, None] + horizon)
    ).astype(np.float32)  # (C, T)
    eval_mask = base_mask[:, None, :] * win[None, :, :]  # (B, C, T)

    y_b = np.nan_to_num(y)[:, None, :]
    out = {
        "cutoffs": cutoffs,
        "grid": ds,
        "eval_mask": eval_mask,
        "y_true": y_b * eval_mask,
        "yhat": yhat,
        "yhat_lower": lower,
        "yhat_upper": upper,
        "smape": np.asarray(metrics_mod.smape(y_b, yhat, mask=eval_mask)),
        "mae": np.asarray(metrics_mod.mae(y_b, yhat, mask=eval_mask)),
        "rmse": np.asarray(metrics_mod.rmse(y_b, yhat, mask=eval_mask)),
        "coverage": np.asarray(
            metrics_mod.coverage(y_b, lower, upper, mask=eval_mask)
        ),
    }
    return out


def performance_metrics(cv: Dict[str, np.ndarray]) -> Dict[str, float]:
    """Aggregate a cross_validation result into scalar headline metrics."""
    return {
        "smape_mean": float(np.mean(cv["smape"])),
        "mae_mean": float(np.mean(cv["mae"])),
        "rmse_mean": float(np.mean(cv["rmse"])),
        "coverage_mean": float(np.mean(cv["coverage"])),
        "n_windows": int(cv["smape"].size),
    }
