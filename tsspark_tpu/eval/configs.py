"""Runners for the five driver evaluation configs (BASELINE.json:6-12).

Each runner builds its dataset (synthetic stand-ins — zero-egress machine,
see data/datasets.py), fits through the requested backend, and returns
headline metrics.  ``scale`` shrinks datasets for smoke runs; 1.0 is the
full driver-defined size.

Usage:  python -m tsspark_tpu.eval.configs [config_number|all] [--scale S]
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import (
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu import data as datasets
from tsspark_tpu.eval import metrics
from tsspark_tpu.streaming.driver import StreamingForecaster
from tsspark_tpu.streaming.source import InMemorySource


def _fit_and_score(cfg, batch, backend, solver, holdout_frac=0.1, **fit_kw):
    """Fit on the head of each series, sMAPE on (a) train and (b) holdout."""
    t_len = batch.y.shape[1]
    split = int(t_len * (1 - holdout_frac))
    sl = lambda a: None if a is None else jnp.asarray(a[:, :split])
    bk = get_backend(backend, cfg, solver)

    t0 = time.time()
    state = bk.fit(
        jnp.asarray(batch.ds[:split]),
        jnp.asarray(np.nan_to_num(batch.y[:, :split])),
        mask=jnp.asarray(batch.mask[:, :split]),
        cap=sl(batch.cap),
        regressors=None if batch.regressors is None
        else jnp.asarray(batch.regressors[:, :split]),
        **fit_kw,
    )
    jax.block_until_ready(state.theta)
    fit_s = time.time() - t0

    fc = bk.predict(
        state,
        jnp.asarray(batch.ds),
        cap=None if batch.cap is None else jnp.asarray(batch.cap),
        regressors=None if batch.regressors is None
        else jnp.asarray(batch.regressors),
        num_samples=0,
    )
    y = jnp.asarray(np.nan_to_num(batch.y))
    m_train = jnp.asarray(batch.mask).at[:, split:].set(0.0)
    m_hold = jnp.asarray(batch.mask).at[:, :split].set(0.0)
    return {
        "fit_seconds": round(fit_s, 3),
        "n_series": int(batch.y.shape[0]),
        "n_timesteps": int(split),
        "smape_train": round(float(metrics.smape(y, fc["yhat"], m_train).mean()), 3),
        "smape_holdout": round(float(metrics.smape(y, fc["yhat"], m_hold).mean()), 3),
        "converged_frac": round(float(np.asarray(state.converged).mean()), 3),
    }


def config1_peyton(backend="tpu", scale=1.0) -> Dict:
    """Additive fit, single daily series (CPU-backend reference config)."""
    batch = datasets.peyton_manning_like(n_days=max(200, int(2905 * scale)))
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 10),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        n_changepoints=25,
    )
    return _fit_and_score(cfg, batch, backend, SolverConfig(max_iters=200))


def config2_m4_hourly(backend="tpu", scale=1.0) -> Dict:
    """Batched additive fit, weekly+daily seasonality, 414 hourly series."""
    batch = datasets.m4_hourly_like(n_series=max(4, int(414 * scale)))
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("daily", 1.0, 4),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        n_changepoints=10,
    )
    return _fit_and_score(cfg, batch, backend, SolverConfig(max_iters=150))


def config3_m5(backend="tpu", scale=1.0) -> Dict:
    """M5 retail with holiday + external regressors (the headline config)."""
    batch = datasets.m5_like(n_series=max(8, int(30490 * scale)))
    cfg = ProphetConfig(
        seasonalities=(
            SeasonalityConfig("yearly", 365.25, 8),
            SeasonalityConfig("weekly", 7.0, 3),
        ),
        regressors=(
            RegressorConfig("holiday", standardize=False),
            RegressorConfig("price"),
            RegressorConfig("promo", standardize=False),
        ),
        n_changepoints=25,
    )
    return _fit_and_score(cfg, batch, backend, SolverConfig(max_iters=120))


def config4_wiki_logistic(backend="tpu", scale=1.0) -> Dict:
    """Logistic growth with capacity, multiplicative seasonality."""
    batch = datasets.wiki_logistic_like(n_series=max(2, int(8 * scale)))
    cfg = ProphetConfig(
        growth="logistic",
        seasonalities=(
            SeasonalityConfig("weekly", 7.0, 3, mode="multiplicative"),
        ),
        n_changepoints=15,
    )
    return _fit_and_score(cfg, batch, backend, SolverConfig(max_iters=200))


def config5_streaming(backend="tpu", scale=1.0) -> Dict:
    """Kafka-style micro-batch incremental refit with warm starts.

    Records the full streaming story (round-4 verdict, Missing #5):
    per-micro-batch refit latency, warm-vs-cold start quality AND
    latency on the identical batch schedule, and at-least-once
    semantics under a simulated crash (the last micro-batch redelivered
    un-committed — the refit must be idempotent)."""
    import pandas as pd

    n_days = max(150, int(730 * scale))
    n_series = max(2, int(50 * scale))
    rng = np.random.default_rng(11)
    frames = []
    for i in range(n_series):
        t = np.arange(n_days, dtype=float)
        y = (
            20 * (i + 1)
            + 0.05 * t
            + 3 * np.sin(2 * np.pi * t / 7)
            + rng.normal(0, 0.5, n_days)
        )
        frames.append(pd.DataFrame({"series_id": f"s{i}", "ds": t, "y": y}))
    df = pd.concat(frames)
    warm_len = int(n_days * 0.7)
    micro = int(n_days * 0.1)
    batches = [df[df.ds < warm_len]] + [
        df[(df.ds >= warm_len + k * micro) & (df.ds < warm_len + (k + 1) * micro)]
        for k in range(3)
    ]
    batches = [b for b in batches if len(b)]
    cfg = ProphetConfig(
        seasonalities=(SeasonalityConfig("weekly", 7.0, 3),), n_changepoints=10
    )
    sids = [f"s{i}" for i in range(n_series)]

    def forecast_smape(sf):
        fc = sf.forecast(sids, horizon=14, num_samples=0)
        t = fc.ds.to_numpy().reshape(n_series, 14)
        sid = np.arange(n_series)[:, None] + 1
        want = 20 * sid + 0.05 * t + 3 * np.sin(2 * np.pi * t / 7)
        return float(np.mean(np.asarray(metrics.smape(
            jnp.asarray(want),
            jnp.asarray(fc.yhat.to_numpy().reshape(n_series, 14)),
        ))))

    def lat(stats):
        b = np.asarray(stats.batch_seconds)
        return {
            "per_batch_s": [round(float(x), 3) for x in b],
            "mean_s": round(float(b.mean()), 3),
            "p50_s": round(float(np.median(b)), 3),
            "max_s": round(float(b.max()), 3),
        }

    # Throwaway pass to populate the jit cache: each micro-batch's union
    # grid has its own (B, T) shape, so the FIRST schedule pays a compile
    # per batch.  Without this, whichever of the warm/cold runs goes
    # first absorbs every compile and the latency comparison measures the
    # cache, not the solver (observed: 13.1 s vs 0.3 s "speedup" that was
    # 100% compilation).
    StreamingForecaster(
        cfg, SolverConfig(max_iters=60), backend=backend
    ).run(InMemorySource(batches))

    sf = StreamingForecaster(cfg, SolverConfig(max_iters=60), backend=backend)
    t0 = time.time()
    stats = sf.run(InMemorySource(batches))
    total_s = time.time() - t0
    smape_fc = forecast_smape(sf)
    # Snapshot BEFORE the crash-replay below mutates sf.stats in place.
    n_batches = stats.micro_batches
    n_warm, n_cold = stats.warm_starts, stats.cold_starts
    latency = lat(stats)

    # Warm-vs-cold on the IDENTICAL schedule: same batches, warm-start
    # transfer disabled, so every refit pays the ridge-init path.  The
    # steady-state comparison is the incremental batches (index >= 1) —
    # batch 0 is a cold start in both runs by construction.
    sf_cold = StreamingForecaster(
        cfg, SolverConfig(max_iters=60), backend=backend, warm_start=False,
    )
    stats_cold = sf_cold.run(InMemorySource(batches))
    smape_cold = forecast_smape(sf_cold)
    steady = np.asarray(stats.batch_seconds[1:n_batches])
    steady_cold = np.asarray(stats_cold.batch_seconds[1:])

    # At-least-once under crash: redeliver the final micro-batch as an
    # un-committed replay (offset never acknowledged -> the source hands
    # it out again).  The history store dedups and the refit re-lands the
    # same parameters, so forecasts must not move.
    sf.process(batches[-1])
    smape_replay = forecast_smape(sf)
    fc_delta = abs(smape_replay - smape_fc)

    return {
        "micro_batches": n_batches,
        "warm_starts": n_warm,
        "cold_starts": n_cold,
        "total_seconds": round(total_s, 3),
        "smape_forecast": round(smape_fc, 3),
        "n_series": n_series,
        "refit_latency": latency,
        "warm_vs_cold": {
            "smape_warm": round(smape_fc, 3),
            "smape_cold": round(smape_cold, 3),
            "steady_latency_warm_mean_s": round(float(steady.mean()), 3)
            if steady.size else None,
            "steady_latency_cold_mean_s": round(float(steady_cold.mean()), 3)
            if steady_cold.size else None,
            "cold_starts_forced": stats_cold.cold_starts,
        },
        "crash_replay": {
            "redelivered_batches": 1,
            "smape_delta_after_replay": round(fc_delta, 6),
            "idempotent": bool(fc_delta < 1e-3),
        },
    }


RUNNERS = {
    "1": config1_peyton,
    "2": config2_m4_hourly,
    "3": config3_m5,
    "4": config4_wiki_logistic,
    "5": config5_streaming,
}


def main():
    from tsspark_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    ap = argparse.ArgumentParser()
    ap.add_argument("which", nargs="?", default="all")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--backend", default="tpu")
    args = ap.parse_args()
    keys = list(RUNNERS) if args.which == "all" else [args.which]
    out = {}
    for k in keys:
        out[f"config{k}"] = RUNNERS[k](backend=args.backend, scale=args.scale)
        print(json.dumps({f"config{k}": out[f"config{k}"]}))


if __name__ == "__main__":
    main()
