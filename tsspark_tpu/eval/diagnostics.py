"""Prophet-style diagnostics on long DataFrames: CV + metric tables.

The reference exposes the Prophet-family diagnostics surface
(``cross_validation`` / ``performance_metrics`` over DataFrames; the
array-level batched engine lives in eval/backtest.py — every
(series, cutoff) pair is one row of a single batched fit, instead of the
reference's per-cutoff refits fanned out over Spark executors).

``cross_validation`` returns the familiar long frame
[series_id, ds, cutoff, y, yhat, yhat_lower, yhat_upper];
``performance_metrics`` aggregates it into a horizon-indexed table with
Prophet's rolling-window smoothing (mse, rmse, mae, mape, mdape, smape,
coverage).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np
import pandas as pd

from tsspark_tpu.eval import backtest
from tsspark_tpu.frame import Forecaster, _days_to_ts, pivot_long

HorizonLike = Union[float, int, str, pd.Timedelta]


def _to_days(value: HorizonLike, name: str) -> float:
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        days = float(value)
    else:
        days = float(pd.Timedelta(value) / pd.Timedelta(days=1))
    if days <= 0:
        raise ValueError(f"{name} must be positive, got {value!r}")
    return days


def cross_validation(
    forecaster: Forecaster,
    df: pd.DataFrame,
    horizon: HorizonLike,
    period: Optional[HorizonLike] = None,
    initial: Optional[HorizonLike] = None,
) -> pd.DataFrame:
    """Simulated historical forecasts for every series in a long frame.

    Args:
      forecaster: an (unfitted is fine) Forecaster carrying the model config,
        backend choice, holiday calendars, and column conventions.
      df: long frame with the forecaster's id/ds/y (+regressor/cap) columns.
      horizon: forecast horizon — days or anything ``pd.Timedelta`` accepts.
      period: spacing between cutoffs (default horizon / 2).
      initial: minimum training history (default 3 * horizon).

    Returns a long frame [series_id, ds, cutoff, y, yhat, yhat_lower,
    yhat_upper] with one row per (series, cutoff, horizon step) that has an
    observed truth value — the same shape prophet.diagnostics.cross_validation
    produces, for all series at once.
    """
    fc = forecaster
    h_days = _to_days(horizon, "horizon")
    p_days = h_days / 2.0 if period is None else _to_days(period, "period")
    i_days = 3.0 * h_days if initial is None else _to_days(initial, "initial")

    was_datetime = not np.issubdtype(df[fc.ds_col].dtype, np.number)
    batch = pivot_long(
        df, fc.id_col, fc.ds_col, fc.y_col, cap_col=fc.cap_col,
        floor_col=fc.floor_col, regressor_cols=fc.regressor_cols,
    )
    b = batch.y.shape[0]
    # auto_seasonality resolves from the full observed calendar, exactly as
    # a fit() on this frame would (the per-cutoff fits below share a config).
    fc._resolve_auto_seasonality(batch.ds)
    reg = fc._combined_regressors(batch.ds, batch.regressors, b)

    cv = backtest.cross_validation(
        batch.ds, batch.y, fc.config,
        horizon=h_days, period=p_days, initial=i_days,
        solver_config=fc.backend.solver_config,
        backend=fc.backend.name,
        regressors=reg, cap=batch.cap,
    )

    sel = cv["eval_mask"] > 0  # (B, C, T)
    i_idx, j_idx, k_idx = np.nonzero(sel)
    ds_days = cv["grid"][k_idx]
    cut_days = cv["cutoffs"][j_idx]
    out = pd.DataFrame({
        fc.id_col: batch.series_ids[i_idx],
        fc.ds_col: _days_to_ts(ds_days) if was_datetime else ds_days,
        "cutoff": _days_to_ts(cut_days) if was_datetime else cut_days,
        fc.y_col: batch.y[i_idx, k_idx],
        "yhat": cv["yhat"][i_idx, j_idx, k_idx],
        "yhat_lower": cv["yhat_lower"][i_idx, j_idx, k_idx],
        "yhat_upper": cv["yhat_upper"][i_idx, j_idx, k_idx],
    })
    return out.sort_values([fc.id_col, "cutoff", fc.ds_col]).reset_index(
        drop=True
    )


_ALL_METRICS = ("mse", "rmse", "mae", "mape", "mdape", "smape", "coverage")


def point_metrics(
    d: pd.DataFrame, metrics: Sequence[str] = _ALL_METRICS,
    y_col: str = "y",
) -> pd.DataFrame:
    """Per-row metric values for a cross_validation-shaped frame.

    The single source of the per-point metric definitions — both the
    horizon-aggregated table (:func:`performance_metrics`) and the raw
    scatter in ``plot.plot_cross_validation_metric`` are built from it, so
    conventions (sMAPE denominator, eps, coverage inclusivity) cannot drift
    apart.  ``rmse`` aggregates from ``mse``; ``mdape`` from ``mape``.
    """
    y = d[y_col].to_numpy(float)
    yhat = d["yhat"].to_numpy(float)
    err = y - yhat
    eps = 1e-12
    point = pd.DataFrame(index=d.index)
    point["mse"] = err**2
    point["mae"] = np.abs(err)
    point["mape"] = np.abs(err) / np.maximum(np.abs(y), eps)
    point["mdape"] = point["mape"]
    point["smape"] = 2.0 * np.abs(err) / np.maximum(
        np.abs(y) + np.abs(yhat), eps
    )
    if "coverage" in metrics:
        point["coverage"] = (
            (y >= d["yhat_lower"].to_numpy(float))
            & (y <= d["yhat_upper"].to_numpy(float))
        ).astype(float)
    return point


def performance_metrics(
    cv_df: pd.DataFrame,
    rolling_window: float = 0.1,
    metrics: Sequence[str] = _ALL_METRICS,
    y_col: str = "y",
    ds_col: str = "ds",
) -> pd.DataFrame:
    """Horizon-indexed accuracy table from a cross_validation frame.

    Mirrors prophet.diagnostics.performance_metrics: rows are sorted by
    forecast horizon (ds - cutoff) and each metric is smoothed with a
    trailing window covering ``rolling_window`` of all rows (so the table
    answers "how accurate are forecasts h days out", denoised).  With
    ``rolling_window=0`` every horizon step reports its own exact average.
    """
    unknown = set(metrics) - set(_ALL_METRICS)
    if unknown:
        raise ValueError(f"unknown metrics {sorted(unknown)}; "
                         f"choose from {_ALL_METRICS}")
    d = cv_df.copy()
    d["horizon"] = d[ds_col] - d["cutoff"]
    d = d.sort_values("horizon", kind="stable").reset_index(drop=True)
    point = point_metrics(d, metrics, y_col=y_col)

    if rolling_window <= 0:
        # Exact per-horizon aggregation, no smoothing.
        point["horizon"] = d["horizon"]
        g = point.groupby("horizon", sort=True)
        out = pd.DataFrame({"horizon": list(g.groups)})
        for m in metrics:
            if m == "rmse":
                out[m] = np.sqrt(g["mse"].mean().to_numpy())
            elif m == "mdape":
                out[m] = g["mdape"].median().to_numpy()
            else:
                out[m] = g[m].mean().to_numpy()
        return out

    n = len(d)
    w = max(1, int(np.ceil(rolling_window * n)))
    out = pd.DataFrame({"horizon": d["horizon"]})
    for m in metrics:
        if m == "rmse":
            out[m] = np.sqrt(point["mse"].rolling(w, min_periods=w).mean())
        elif m == "mdape":
            out[m] = point["mdape"].rolling(w, min_periods=w).median()
        else:
            out[m] = point[m].rolling(w, min_periods=w).mean()
    out = out.iloc[w - 1:]
    # One row per distinct horizon (the trailing window ending at its last row).
    out = out.groupby("horizon", sort=True).tail(1).reset_index(drop=True)
    return out
