"""CPU-vs-TPU parity audit over the driver evaluation configs.

The driver criterion is "sMAPE parity vs CPU" (BASELINE.json:2): the batched
TPU solver must reproduce the per-series scipy oracle's accuracy, not just
run fast.  This module fits eval configs 1-4 (eval/configs.py) through BOTH
backends on identical data and reports per-config in-sample/holdout sMAPE
for each backend plus the per-series worst deviation — the artifact the
round reviews (EVAL_r*.json) are built from.

The CPU oracle is a per-series Python loop, so ``scale`` keeps its cost
bounded; parity is a per-series property, so a representative subsample is
as informative as the full batch.

Usage:  python -m tsspark_tpu.eval.parity [--scale S] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict

import numpy as np
import jax
import jax.numpy as jnp

from tsspark_tpu.backends.registry import get_backend
from tsspark_tpu.config import (
    ProphetConfig,
    RegressorConfig,
    SeasonalityConfig,
    SolverConfig,
)
from tsspark_tpu import data as datasets
from tsspark_tpu.eval import metrics


def _config3():
    """Eval config 3 (M5 retail: holiday regressors + external features)."""
    return (
        ProphetConfig(
            seasonalities=(
                SeasonalityConfig("yearly", 365.25, 8),
                SeasonalityConfig("weekly", 7.0, 3),
            ),
            regressors=(
                RegressorConfig("holiday", standardize=False),
                RegressorConfig("price"),
                RegressorConfig("promo", standardize=False),
            ),
            n_changepoints=25,
        ),
        SolverConfig(max_iters=120),
    )


def _case_configs(scale: float):
    """The four fit configs (5 is streaming; its parity is covered by the
    warm-start tests) with datasets sized for a tractable scipy oracle."""
    cfg3, solver3 = _config3()
    return {
        "config1_peyton": (
            datasets.peyton_manning_like(n_days=max(400, int(2905 * scale))),
            ProphetConfig(
                seasonalities=(
                    SeasonalityConfig("yearly", 365.25, 10),
                    SeasonalityConfig("weekly", 7.0, 3),
                ),
                n_changepoints=25,
            ),
            SolverConfig(max_iters=200),
        ),
        "config2_m4_hourly": (
            datasets.m4_hourly_like(n_series=max(8, int(414 * scale))),
            ProphetConfig(
                seasonalities=(
                    SeasonalityConfig("daily", 1.0, 4),
                    SeasonalityConfig("weekly", 7.0, 3),
                ),
                n_changepoints=10,
            ),
            SolverConfig(max_iters=150),
        ),
        "config3_m5": (
            datasets.m5_like(n_series=max(16, int(30490 * scale))),
            cfg3,
            solver3,
        ),
        "config4_wiki_logistic": (
            datasets.wiki_logistic_like(n_series=max(4, int(8 * scale * 8))),
            ProphetConfig(
                growth="logistic",
                seasonalities=(
                    SeasonalityConfig("weekly", 7.0, 3, mode="multiplicative"),
                ),
                n_changepoints=15,
            ),
            SolverConfig(max_iters=200),
        ),
    }


def _smape_per_series(cfg, solver, batch, backend: str, holdout_frac=0.1,
                      transfer_chunk: int = 2048, **backend_kwargs):
    """Fit on the train window, score per-series sMAPE on train + holdout.

    BOTH legs stream through host-side series chunks of ``transfer_chunk``:
    at bench scale a single full-batch transfer (y/mask ~210 MB each, the
    (30490, 1941, R) regressors ~640 MB) is far beyond the TPU tunnel's
    observed ~64 MB single-buffer crash envelope (bench.py header), so the
    device must only ever see chunk-sized buffers.  The tail chunk is
    index-padded to the full chunk shape so every dispatch reuses one
    compiled program.
    """
    from tsspark_tpu.backends.tpu import _concat_states, _slice_state

    t_len = batch.y.shape[1]
    split = int(t_len * (1 - holdout_frac))
    bk = get_backend(backend, cfg, solver, **backend_kwargs)
    b = batch.y.shape[0]
    chunk = min(transfer_chunk, b)

    # Tail handling: the jitted backend wrap-pads its tail chunk with
    # duplicate rows so every dispatch reuses ONE compiled program shape
    # (duplicate rows ride the lockstep batch for free and are sliced
    # away); the CPU oracle is a per-series Python loop where duplicates
    # cost full scipy fits and there is no compiled shape to preserve, so
    # it takes the exact tail.
    wrap_tail = backend != "cpu"

    def tail_idx(lo):
        hi = min(lo + chunk, b)
        if wrap_tail and hi - lo < chunk:
            return np.arange(lo, lo + chunk) % b, hi - lo
        return np.arange(lo, hi), hi - lo

    ds_train = jnp.asarray(batch.ds[:split])
    t0 = time.time()
    states = []
    for lo in range(0, b, chunk):
        idx, n_real = tail_idx(lo)
        kw = {}
        if batch.cap is not None:
            kw["cap"] = jnp.asarray(batch.cap[idx][:, :split])
        if batch.regressors is not None:
            kw["regressors"] = jnp.asarray(batch.regressors[idx][:, :split])
        st = bk.fit(
            ds_train,
            jnp.asarray(np.nan_to_num(batch.y[idx][:, :split])),
            mask=jnp.asarray(batch.mask[idx][:, :split]),
            **kw,
        )
        states.append(_slice_state(st, 0, n_real))
    state = states[0] if len(states) == 1 else _concat_states(states)
    jax.block_until_ready(state.theta)
    fit_s = time.time() - t0

    ds_full = jnp.asarray(batch.ds)
    tr, ho = [], []
    for lo in range(0, b, chunk):
        idx, n_real = tail_idx(lo)
        st = jax.tree.map(lambda a: a[idx], state)  # device and host leaves
        pkw = {}
        if batch.cap is not None:
            pkw["cap"] = jnp.asarray(batch.cap[idx])
        if batch.regressors is not None:
            pkw["regressors"] = jnp.asarray(batch.regressors[idx])
        fc = bk.predict(st, ds_full, num_samples=0, **pkw)
        y = jnp.asarray(np.nan_to_num(batch.y[idx]))
        m = jnp.asarray(batch.mask[idx])
        tr.append(np.asarray(
            metrics.smape(y, fc["yhat"], m.at[:, split:].set(0.0))
        )[:n_real])
        ho.append(np.asarray(
            metrics.smape(y, fc["yhat"], m.at[:, :split].set(0.0))
        )[:n_real])
    return np.concatenate(tr), np.concatenate(ho), fit_s


def _delta_dist(deltas: np.ndarray) -> Dict:
    """Per-series |delta sMAPE| distribution (the parity gate statistic)."""
    a = np.abs(deltas)
    return {
        "p50": round(float(np.percentile(a, 50)), 4),
        "p95": round(float(np.percentile(a, 95)), 4),
        "max": round(float(a.max()), 4),
    }


def run_parity(scale: float = 0.01, configs=None) -> Dict:
    """``configs``: optional iterable of case names (run_parity's keys)
    to restrict to — scale >= 0.1 audits pay a multi-minute scipy oracle
    per config, and the VERDICT's parity ask (Weak #2) names only
    config2/config3."""
    out = {}
    for name, (batch, cfg, solver) in _case_configs(scale).items():
        if configs and name not in configs:
            continue
        tr_cpu, ho_cpu, s_cpu = _smape_per_series(cfg, solver, batch, "cpu")
        tr_tpu, ho_tpu, s_tpu = _smape_per_series(cfg, solver, batch, "tpu")
        out[name] = {
            "n_series": int(batch.y.shape[0]),
            "smape_train_cpu": round(float(tr_cpu.mean()), 4),
            "smape_train_tpu": round(float(tr_tpu.mean()), 4),
            "delta_train_mean": round(float((tr_tpu - tr_cpu).mean()), 4),
            "delta_train_max_abs": round(float(np.abs(tr_tpu - tr_cpu).max()), 4),
            "smape_holdout_cpu": round(float(ho_cpu.mean()), 4),
            "smape_holdout_tpu": round(float(ho_tpu.mean()), 4),
            "delta_holdout_mean": round(float((ho_tpu - ho_cpu).mean()), 4),
            "delta_holdout_max_abs": round(
                float(np.abs(ho_tpu - ho_cpu).max()), 4
            ),
            "delta_holdout_dist": _delta_dist(ho_tpu - ho_cpu),
            "delta_train_dist": _delta_dist(tr_tpu - tr_cpu),
            "fit_seconds_cpu": round(s_cpu, 2),
            "fit_seconds_tpu": round(s_tpu, 2),
        }
    return out


def run_config3_at_scale(
    n_series: int = 30490, oracle_n: int = 512, seed: int = 0,
    chunk_size: int = 2048, iter_segment: int = 24,
) -> Dict:
    """Bench-scale parity for eval config 3: the batched solver fits the FULL
    series batch; the scipy oracle (the cost bound — a per-series Python
    loop) runs on a random subsample, and the per-series holdout |delta
    sMAPE| distribution over that subsample is the gate statistic.

    This answers round-2 weakness #7: small-scale parity audits cannot see
    distribution tails that only appear at bench scale.
    """
    cfg, solver = _config3()
    batch = datasets.m5_like(n_series=n_series)
    # chunk_size bounds BOTH the host->device transfer block and the
    # compiled program batch (the ~64 MB tunnel envelope knob).
    tr_tpu, ho_tpu, s_tpu = _smape_per_series(
        cfg, solver, batch, "tpu", transfer_chunk=chunk_size,
        chunk_size=chunk_size, iter_segment=iter_segment,
    )
    rng = np.random.default_rng(seed)
    idx = np.sort(rng.choice(n_series, size=min(oracle_n, n_series),
                             replace=False))
    sub = batch._replace(
        y=batch.y[idx], mask=batch.mask[idx],
        series_ids=batch.series_ids[idx],
        cap=None if batch.cap is None else batch.cap[idx],
        regressors=None if batch.regressors is None
        else batch.regressors[idx],
    )
    tr_cpu, ho_cpu, s_cpu = _smape_per_series(cfg, solver, sub, "cpu")
    return {
        "n_series_tpu": n_series,
        "n_series_oracle": int(idx.size),
        "smape_holdout_tpu_full": round(float(ho_tpu.mean()), 4),
        "smape_holdout_tpu_sub": round(float(ho_tpu[idx].mean()), 4),
        "smape_holdout_cpu_sub": round(float(ho_cpu.mean()), 4),
        "delta_holdout_dist": _delta_dist(ho_tpu[idx] - ho_cpu),
        "delta_train_dist": _delta_dist(tr_tpu[idx] - tr_cpu),
        "fit_seconds_tpu_full": round(s_tpu, 2),
        "fit_seconds_cpu_sub": round(s_cpu, 2),
    }


def main():
    from tsspark_tpu.utils.platform import honor_env_platforms

    honor_env_platforms()
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--out", default=None)
    ap.add_argument("--configs", action="append", default=None,
                    help="restrict to these config names (repeatable; "
                         "default: all four)")
    ap.add_argument("--config3-full", action="store_true",
                    help="additionally run the bench-scale config-3 parity "
                         "(full TPU batch vs oracle subsample)")
    ap.add_argument("--oracle-n", type=int, default=512)
    args = ap.parse_args()
    import jax

    from tsspark_tpu.config import NUMERICS_REV
    from tsspark_tpu.obs import context as obs
    from tsspark_tpu.obs.history import git_rev

    result = {
        # Cross-run identity (obs.history): parity/calibration rows
        # join RUNHISTORY.jsonl like every other report family, so the
        # sentinel can gate holdout-delta drift across revisions.  The
        # trace id adopts an active run's when one is bound (a traced
        # harness driving parity), else mints a fresh one.
        "kind": "eval-parity",
        "unix": round(time.time(), 3),
        "trace_id": obs.trace_id() or obs.new_id(),
        "git_rev": git_rev(),
        "numerics_rev": NUMERICS_REV,
        "platform": str(jax.devices()[0]),
        "scale": args.scale,
        "configs": run_parity(args.scale, configs=args.configs),
    }
    if args.config3_full:
        result["config3_bench_scale"] = run_config3_at_scale(
            oracle_n=args.oracle_n
        )
    text = json.dumps(result, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")


if __name__ == "__main__":
    main()
