"""Forecast accuracy metrics, masked and batched.

sMAPE is the parity metric named by the driver north star (BASELINE.json:2);
the rest are the standard companions for the M-competition datasets.  All
functions accept (..., T) arrays plus an optional validity mask and reduce
over the trailing time axis, working with numpy or jax arrays.
"""

from __future__ import annotations

import jax.numpy as jnp


def _masked(err, mask):
    if mask is None:
        return err, err.shape[-1]
    return err * mask, jnp.maximum(mask.sum(axis=-1), 1.0)


def smape(y_true, y_pred, mask=None, eps: float = 1e-9):
    """Symmetric MAPE in percent: 200/n * sum |y-yhat| / (|y|+|yhat|)."""
    denom = jnp.abs(y_true) + jnp.abs(y_pred) + eps
    err, n = _masked(jnp.abs(y_true - y_pred) / denom, mask)
    return 200.0 * err.sum(axis=-1) / n


def mae(y_true, y_pred, mask=None):
    err, n = _masked(jnp.abs(y_true - y_pred), mask)
    return err.sum(axis=-1) / n


def rmse(y_true, y_pred, mask=None):
    err, n = _masked((y_true - y_pred) ** 2, mask)
    return jnp.sqrt(err.sum(axis=-1) / n)


def mase(y_true, y_pred, y_train, season: int = 1, mask=None, train_mask=None):
    """MAE scaled by the in-sample seasonal-naive MAE (M4's headline metric)."""
    naive = jnp.abs(y_train[..., season:] - y_train[..., :-season])
    if train_mask is not None:
        m = train_mask[..., season:] * train_mask[..., :-season]
        scale = (naive * m).sum(axis=-1) / jnp.maximum(m.sum(axis=-1), 1.0)
    else:
        scale = naive.mean(axis=-1)
    return mae(y_true, y_pred, mask) / jnp.maximum(scale, 1e-9)


def coverage(y_true, lower, upper, mask=None):
    """Fraction of observations inside [lower, upper]."""
    inside = ((y_true >= lower) & (y_true <= upper)).astype(lower.dtype)
    err, n = _masked(inside, mask)
    return err.sum(axis=-1) / n
